"""Post-hoc DAG analyzers over DagInfo.

Reference parity: tez-tools/analyzers/job-analyzer/.../plugins/ via
AnalyzerDriver — full plugin set: CriticalPathAnalyzer:53,
ShuffleTimeAnalyzer, SkewAnalyzer, SpillAnalyzerImpl, SlowestVertexAnalyzer,
ContainerReuseAnalyzer, HungTaskAnalyzer, TaskConcurrencyAnalyzer,
SlowTaskIdentifier, DagOverviewAnalyzer, InputReadErrorAnalyzer,
LocalityAnalyzer, OneOnOneEdgeAnalyzer, SlowNodeAnalyzer,
TaskAssignmentAnalyzer, TaskAttemptResultStatisticsAnalyzer,
VertexLevelCriticalPathAnalyzer (+ speculation and IO-ratio extras).
"""
from __future__ import annotations

import dataclasses
import json
import sys
from typing import Any, Dict, List, Sequence

from tez_tpu.tools.history_parser import DagInfo, parse_jsonl_files


@dataclasses.dataclass
class AnalyzerResult:
    analyzer: str
    headline: str
    rows: List[Dict[str, Any]]

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class Analyzer:
    name = "abstract"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        raise NotImplementedError


class CriticalPathAnalyzer(Analyzer):
    """Longest chain of vertex (start..finish) spans ordered by start time —
    which vertices bound the DAG wall-clock."""
    name = "critical_path"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        verts = sorted(dag.vertices.values(), key=lambda v: v.start_time)
        total = dag.duration or 1e-9
        for v in verts:
            rows.append({
                "vertex": v.name, "start_offset": v.start_time - dag.start_time,
                "duration": v.duration,
                "fraction_of_dag": round(v.duration / total, 3),
            })
        slowest = max(verts, key=lambda v: v.duration, default=None)
        headline = (f"DAG {dag.name}: {dag.duration:.2f}s; dominant vertex "
                    f"{slowest.name} ({slowest.duration:.2f}s)"
                    if slowest else "empty DAG")
        return AnalyzerResult(self.name, headline, rows)


class ShuffleTimeAnalyzer(Analyzer):
    """Shuffle/merge phase times + bytes per vertex (reference:
    ShuffleTimeAnalyzer over SHUFFLE_PHASE_TIME/MERGE_PHASE_TIME)."""
    name = "shuffle_time"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            tc = v.counters.get("TaskCounter", {})
            if not tc.get("SHUFFLE_BYTES") and not tc.get("SHUFFLE_PHASE_TIME"):
                continue
            rows.append({
                "vertex": v.name,
                "shuffle_bytes": tc.get("SHUFFLE_BYTES", 0),
                "shuffle_phase_ms": tc.get("SHUFFLE_PHASE_TIME", 0),
                "merge_phase_ms": tc.get("MERGE_PHASE_TIME", 0),
                "shuffled_inputs": tc.get("NUM_SHUFFLED_INPUTS", 0),
                "failed_fetches": tc.get("NUM_FAILED_SHUFFLE_INPUTS", 0),
            })
        total = sum(r["shuffle_bytes"] for r in rows)
        return AnalyzerResult(self.name,
                              f"total shuffled: {total} bytes", rows)


class SkewAnalyzer(Analyzer):
    """Attempt-duration skew per vertex (reference: SkewAnalyzer)."""
    name = "skew"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            durations = [t.successful_attempt.duration
                         for t in v.tasks.values()
                         if t.successful_attempt is not None]
            if not durations:
                continue
            mean = sum(durations) / len(durations)
            rows.append({
                "vertex": v.name, "tasks": len(durations),
                "mean_s": round(mean, 3),
                "max_s": round(max(durations), 3),
                "skew_ratio": round(max(durations) / mean, 2) if mean else 0,
            })
        worst = max(rows, key=lambda r: r["skew_ratio"], default=None)
        return AnalyzerResult(
            self.name,
            f"worst skew {worst['skew_ratio']}x in {worst['vertex']}"
            if worst else "no completed tasks", rows)


class SpillAnalyzer(Analyzer):
    """Spilled records / host-spill bytes per vertex (reference:
    SpillAnalyzerImpl)."""
    name = "spill"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            tc = v.counters.get("TaskCounter", {})
            rows.append({
                "vertex": v.name,
                "spilled_records": tc.get("SPILLED_RECORDS", 0),
                "additional_spill_count": tc.get("ADDITIONAL_SPILL_COUNT", 0),
                "host_spill_bytes": tc.get("HOST_SPILL_BYTES", 0),
                "output_bytes": tc.get("OUTPUT_BYTES", 0),
            })
        total = sum(r["host_spill_bytes"] for r in rows)
        return AnalyzerResult(self.name, f"host spill: {total} bytes", rows)


class SlowestVertexAnalyzer(Analyzer):
    name = "slowest_vertex"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = sorted(
            ({"vertex": v.name, "duration_s": round(v.duration, 3),
              "num_tasks": v.num_tasks}
             for v in dag.vertices.values()),
            key=lambda r: -r["duration_s"])
        return AnalyzerResult(
            self.name,
            f"slowest: {rows[0]['vertex']}" if rows else "none", rows)


class ContainerReuseAnalyzer(Analyzer):
    """Tasks per runner (reference: ContainerReuseAnalyzer)."""
    name = "container_reuse"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = [{"container": cid, **info}
                for cid, info in dag.containers.items()]
        total = sum(r.get("tasks_run", 0) for r in rows)
        return AnalyzerResult(
            self.name,
            f"{len(rows)} runners, {total} tasks ("
            f"{total / len(rows):.1f} tasks/runner)" if rows else "no runners",
            rows)


class SpeculationAnalyzer(Analyzer):
    """Attempts beyond the first per task (reference: SpeculationAnalyzer)."""
    name = "speculation"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            for t in v.tasks.values():
                if len(t.attempts) > 1:
                    rows.append({"task": t.task_id,
                                 "vertex": v.name,
                                 "attempts": len(t.attempts),
                                 "states": sorted(a.state for a in
                                                  t.attempts.values())})
        return AnalyzerResult(self.name,
                              f"{len(rows)} tasks with extra attempts", rows)


class HungTaskAnalyzer(Analyzer):
    """Tasks started but never finished (reference: HungTaskAnalyzer)."""
    name = "hung_tasks"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            for t in v.tasks.values():
                if t.start_time and not t.finish_time:
                    rows.append({"task": t.task_id, "vertex": v.name})
        return AnalyzerResult(self.name, f"{len(rows)} hung tasks", rows)


class TaskConcurrencyAnalyzer(Analyzer):
    """Peak/avg concurrently-running attempts over time (reference:
    TaskConcurrencyAnalyzer)."""
    name = "task_concurrency"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        attempts = [a for a in dag.all_attempts() if a.start_time]
        # open intervals (in-progress/crashed DAGs) close at the latest
        # timestamp seen, never at the 0.0 "unset" sentinel
        horizon = max([dag.finish_time] +
                      [a.finish_time for a in attempts] +
                      [a.start_time for a in attempts], default=0.0)
        points = []
        for a in attempts:
            points.append((a.start_time, 1))
            points.append((a.finish_time or horizon, -1))
        points.sort()
        cur = peak = 0
        area = 0.0
        last_t = points[0][0] if points else 0
        for t, d in points:
            area += cur * (t - last_t)
            cur += d
            peak = max(peak, cur)
            last_t = t
        span = dag.duration or 1e-9
        return AnalyzerResult(
            self.name,
            f"peak {peak} concurrent attempts, avg {area / span:.1f}",
            [{"peak": peak, "avg": round(area / span, 2)}])


class SlowTaskAttemptAnalyzer(Analyzer):
    """Slowest attempts across the DAG (reference: SlowTaskIdentifier)."""
    name = "slow_attempts"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        attempts = sorted(dag.all_attempts(), key=lambda a: -a.duration)[:10]
        rows = [{"attempt": a.attempt_id, "vertex": a.vertex_name,
                 "duration_s": round(a.duration, 3), "state": a.state}
                for a in attempts]
        return AnalyzerResult(
            self.name,
            f"slowest attempt {rows[0]['duration_s']}s in "
            f"{rows[0]['vertex']}" if rows else "none", rows)


class InputOutputRatioAnalyzer(Analyzer):
    """Bytes out / bytes in per vertex — where data amplifies or reduces
    (reference: the IO-ratio family of analyzers)."""
    name = "io_ratio"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            tc = v.counters.get("TaskCounter", {})
            inp = tc.get("SHUFFLE_BYTES", 0) or \
                tc.get("INPUT_SPLIT_LENGTH_BYTES", 0)
            out = tc.get("OUTPUT_BYTES", 0)
            if inp or out:
                rows.append({"vertex": v.name, "in_bytes": inp,
                             "out_bytes": out,
                             "ratio": round(out / inp, 3) if inp else None})
        return AnalyzerResult(self.name, f"{len(rows)} vertices with IO",
                              rows)


class DagOverviewAnalyzer(Analyzer):
    """One-row-per-vertex DAG summary (reference: DagOverviewAnalyzer)."""
    name = "dag_overview"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in sorted(dag.vertices.values(), key=lambda v: v.start_time):
            states: Dict[str, int] = {}
            for t in v.tasks.values():
                states[t.state or "RUNNING"] = \
                    states.get(t.state or "RUNNING", 0) + 1
            rows.append({
                "vertex": v.name, "state": v.state, "num_tasks": v.num_tasks,
                "task_states": states,
                "duration_s": round(v.duration, 3),
            })
            first = min((t.start_time for t in v.tasks.values()
                         if t.start_time), default=v.start_time)
            # vertices that never started (upstream failure) have no offset
            rows[-1]["first_task_start_offset"] = \
                round(first - dag.start_time, 3) if first else None
        return AnalyzerResult(
            self.name,
            f"{dag.name}: {dag.state}, {len(rows)} vertices, "
            f"{sum(r['num_tasks'] for r in rows)} tasks, "
            f"{dag.duration:.2f}s", rows)


class InputReadErrorAnalyzer(Analyzer):
    """Fetch failures and output-loss reruns (reference:
    InputReadErrorAnalyzer over INPUT_READ_ERROR events)."""
    name = "input_read_errors"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for a in dag.all_attempts():
            failed = a.counter("TaskCounter", "NUM_FAILED_SHUFFLE_INPUTS")
            output_lost = "output lost" in (a.diagnostics or "")
            if failed or output_lost:
                rows.append({"attempt": a.attempt_id, "vertex": a.vertex_name,
                             "failed_fetches": failed,
                             "output_lost_rerun": output_lost,
                             "state": a.state})
        return AnalyzerResult(
            self.name,
            f"{sum(r['failed_fetches'] for r in rows)} failed fetches, "
            f"{sum(r['output_lost_rerun'] for r in rows)} output-loss reruns",
            rows)


class LocalityAnalyzer(Analyzer):
    """Local vs remote shuffle reads per vertex (reference: LocalityAnalyzer
    over DATA_LOCAL_TASKS; here locality = same-host buffer handoff vs DCN
    fetch, SURVEY.md §2.10)."""
    name = "locality"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            tc = v.counters.get("TaskCounter", {})
            local = tc.get("LOCAL_SHUFFLED_INPUTS", 0)
            total = tc.get("NUM_SHUFFLED_INPUTS", 0)
            if total:
                rows.append({"vertex": v.name, "shuffled_inputs": total,
                             "local_inputs": local,
                             "local_fraction": round(local / total, 3)})
        return AnalyzerResult(
            self.name,
            (f"{sum(r['local_inputs'] for r in rows)}/"
             f"{sum(r['shuffled_inputs'] for r in rows)} inputs read locally"
             if rows else "no shuffled inputs"), rows)


class OneOnOneEdgeAnalyzer(Analyzer):
    """For ONE_TO_ONE edges: did task i of src and dst land on the same
    node (affinity working)? (reference: OneOnOneEdgeAnalyzer)."""
    name = "one_on_one_edges"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        def placement(vertex_name: str) -> Dict[int, str]:
            v = dag.vertex(vertex_name)
            out: Dict[int, str] = {}
            if v is None:
                return out
            for t in v.tasks.values():
                a = t.successful_attempt
                if a is None:
                    continue
                try:
                    idx = int(t.task_id.rsplit("_", 1)[1])
                except (ValueError, IndexError):
                    continue
                where = a.node_id or a.container_id
                if where:          # unknown placement must not count as a
                    out[idx] = where   # colocated ''=='' match
            return out

        rows = []
        for e in dag.edges:
            if e.get("movement") != "ONE_TO_ONE":
                continue
            src, dst = placement(e["src"]), placement(e["dst"])
            common = set(src) & set(dst)
            colocated = sum(1 for i in common if src[i] == dst[i])
            rows.append({"edge": f"{e['src']}->{e['dst']}",
                         "pairs": len(common), "colocated": colocated})
        return AnalyzerResult(
            self.name,
            f"{len(rows)} ONE_TO_ONE edges" if rows
            else "no ONE_TO_ONE edges", rows)


class SlowNodeAnalyzer(Analyzer):
    """Mean attempt duration + failure count per node — is one host slow or
    flaky? (reference: SlowNodeAnalyzer)."""
    name = "slow_nodes"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        per_node: Dict[str, List] = {}
        for a in dag.all_attempts():
            if not a.finish_time:
                continue
            per_node.setdefault(a.node_id or a.container_id or "?",
                                []).append(a)
        rows = []
        for node, atts in sorted(per_node.items()):
            durs = [a.duration for a in atts]
            rows.append({
                "node": node, "attempts": len(atts),
                "mean_s": round(sum(durs) / len(durs), 3),
                "failed": sum(1 for a in atts if a.state == "FAILED"),
            })
        slowest = max(rows, key=lambda r: r["mean_s"], default=None)
        return AnalyzerResult(
            self.name,
            f"slowest node {slowest['node']} (mean {slowest['mean_s']}s)"
            if slowest else "no finished attempts", rows)


class TaskAssignmentAnalyzer(Analyzer):
    """Attempts per node per vertex — assignment spread (reference:
    TaskAssignmentAnalyzer)."""
    name = "task_assignment"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            per_node: Dict[str, int] = {}
            for t in v.tasks.values():
                for a in t.attempts.values():
                    key = a.node_id or a.container_id or "?"
                    per_node[key] = per_node.get(key, 0) + 1
            if per_node:
                rows.append({"vertex": v.name, "per_node": per_node,
                             "nodes_used": len(per_node)})
        return AnalyzerResult(self.name,
                              f"{len(rows)} vertices placed", rows)


class TaskAttemptResultStatisticsAnalyzer(Analyzer):
    """Attempt terminal-state counts per (vertex, node) (reference:
    TaskAttemptResultStatisticsAnalyzer)."""
    name = "attempt_result_stats"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        stats: Dict[tuple, Dict[str, int]] = {}
        for a in dag.all_attempts():
            key = (a.vertex_name, a.node_id or a.container_id or "?")
            bucket = stats.setdefault(key, {})
            state = a.state or "RUNNING"
            bucket[state] = bucket.get(state, 0) + 1
        rows = [{"vertex": v, "node": n, "states": s}
                for (v, n), s in sorted(stats.items())]
        total_failed = sum(s.get("FAILED", 0) for s in stats.values())
        return AnalyzerResult(
            self.name,
            f"{len(rows)} (vertex,node) buckets, {total_failed} failed",
            rows)


class VertexLevelCriticalPathAnalyzer(Analyzer):
    """Longest dependency chain through the DAG's edges weighted by vertex
    durations (reference: VertexLevelCriticalPathAnalyzer; the flat
    CriticalPathAnalyzer above ranks by span only)."""
    name = "vertex_critical_path"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        preds: Dict[str, List[str]] = {}
        for e in dag.edges:
            preds.setdefault(e["dst"], []).append(e["src"])
        names = [v.name for v in dag.vertices.values()]
        durs = {v.name: v.duration for v in dag.vertices.values()}
        memo: Dict[str, tuple] = {}

        def longest(name: str) -> tuple:
            """(total duration, path list) of the heaviest chain ending at
            `name`; cycles are impossible (DAG.verify)."""
            if name in memo:
                return memo[name]
            best = (0.0, [])
            for p in preds.get(name, []):
                cand = longest(p)
                if cand[0] > best[0]:
                    best = cand
            memo[name] = (best[0] + durs.get(name, 0.0), best[1] + [name])
            return memo[name]

        if not names:
            return AnalyzerResult(self.name, "empty DAG", [])
        total, path = max((longest(n) for n in names), key=lambda x: x[0])
        rows = [{"vertex": n, "duration_s": round(durs.get(n, 0.0), 3)}
                for n in path]
        frac = f" ({total / dag.duration:.0%} of DAG)" if dag.duration else \
            " (DAG unfinished)"
        return AnalyzerResult(
            self.name,
            f"critical path {' -> '.join(path)} = {total:.2f}s{frac}", rows)


class NodeHealthAnalyzer(Analyzer):
    """Node blacklist / forced-active transitions correlated with where
    failed attempts ran (reference: SlowNodeAnalyzer's sibling for the
    AMNodeImpl state machine; the chaos harness uses it to attribute
    storms to node flaps)."""
    name = "node_health"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        failed_per_node: Dict[str, int] = {}
        for a in dag.all_attempts():
            if a.state in ("FAILED", "KILLED") and a.node_id:
                failed_per_node[a.node_id] = \
                    failed_per_node.get(a.node_id, 0) + 1
        rows = []
        for ev in dag.node_events:
            rows.append({
                "node": ev["node_id"], "event": ev["event"],
                "node_failures": ev["failures"],
                "offset_s": round(ev["time"] - dag.start_time, 3)
                if dag.start_time else None,
                "failed_attempts_on_node":
                    failed_per_node.get(ev["node_id"], 0)})
        blacklists = sum(1 for r in rows if r["event"] == "BLACKLISTED")
        forced = sum(1 for r in rows if r["event"] == "FORCED_ACTIVE")
        return AnalyzerResult(
            self.name,
            (f"{blacklists} blacklist(s), {forced} forced-active "
             f"transition(s)" if rows else "no node health transitions"),
            rows)


class DeviceHealthAnalyzer(Analyzer):
    """Device-plane failure containment per vertex: host failovers, breaker
    trips/short-circuits, watchdog fires, and OOM split retries from the
    DeviceFailover counter group (async pipeline containment ladder).  A
    vertex with failovers but zero breaker trips rode out isolated faults;
    short-circuits mean the breaker held the device offline for part of
    the run and host-path capacity planning applies."""
    name = "device_health"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            df = v.counters.get("DeviceFailover", {})
            if not df:
                continue
            rows.append({
                "vertex": v.name,
                "failover_spans": df.get("device.failover.spans", 0),
                "failover_groups": df.get("device.failover.groups", 0),
                "drained_on_wedge": df.get("device.failover.drained", 0),
                "watchdog_fires": df.get("device.watchdog.fires", 0),
                "breaker_trips": df.get("device.breaker.trips", 0),
                "breaker_short_circuits":
                    df.get("device.breaker.short_circuits", 0),
                "breaker_recoveries": df.get("device.breaker.recoveries", 0),
                "oom_split_attempts": df.get("device.oom.split_attempts", 0),
                "oom_split_success": df.get("device.oom.split_success", 0),
            })
        spans = sum(r["failover_spans"] for r in rows)
        trips = sum(r["breaker_trips"] for r in rows)
        fires = sum(r["watchdog_fires"] for r in rows)
        headline = "device plane healthy (no containment events)" if not rows \
            else (f"{spans} span(s) failed over to host; "
                  f"{trips} breaker trip(s), {fires} watchdog fire(s)")
        return AnalyzerResult(self.name, headline, rows)


class SpanCriticalPathAnalyzer(Analyzer):
    """Span-based critical path over the live tracing buffer: the longest
    causal chain through the recorded spans (tracing plane, this PR's
    tentpole), naming which vertex/fetch/commit span dominates wall clock.
    Unlike CriticalPathAnalyzer (history timestamps, vertex granularity)
    this sees intra-attempt structure — a fetch stall or merge dominating a
    vertex shows up by name.  Empty when the DAG ran with tracing disarmed."""
    name = "span_critical_path"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        from tez_tpu.common import tracing
        from tez_tpu.tools.trace_export import critical_path_report
        spans = tracing.snapshot()
        # scope to this DAG's trace when its root span is in the buffer
        # (the buffer is process-global and may hold several DAGs)
        dag_traces = {sp.trace_id for sp in spans
                      if sp.cat == "dag" and
                      sp.args.get("dag_id") == str(dag.dag_id)}
        if dag_traces:
            spans = [sp for sp in spans if sp.trace_id in dag_traces]
        if not spans:
            return AnalyzerResult(
                self.name,
                "no spans recorded (run with tez.trace.enabled=True)", [])
        report = critical_path_report(spans)
        dom = report["dominant"]
        chain = report["chain"]
        # dominant VERTEX: attribute each chain span's self time to the
        # nearest enclosing span that names a vertex (the attempt span),
        # then take the vertex holding the most on-path time.  The dag
        # root's own self time (AM scheduling overhead) stays unattributed.
        per_vertex: Dict[str, float] = {}
        cur = ""
        for c in chain:
            cur = c.get("vertex") or cur
            if cur:
                per_vertex[cur] = per_vertex.get(cur, 0) + c.get("self_ms", 0)
        headline = "no dominant span"
        if dom:
            headline = (f"critical chain of {len(chain)} span(s); dominant: "
                        f"{dom['name']} ({dom['duration_ms']:.1f}ms)")
            if per_vertex:
                v, ms = max(per_vertex.items(), key=lambda kv: kv[1])
                headline += f"; dominant vertex: {v} ({ms:.1f}ms on path)"
        return AnalyzerResult(self.name, headline, chain)


ALL_ANALYZERS: Sequence[Analyzer] = (
    SpanCriticalPathAnalyzer(),
    CriticalPathAnalyzer(), ShuffleTimeAnalyzer(), SkewAnalyzer(),
    SpillAnalyzer(), SlowestVertexAnalyzer(), ContainerReuseAnalyzer(),
    SpeculationAnalyzer(), HungTaskAnalyzer(), TaskConcurrencyAnalyzer(),
    SlowTaskAttemptAnalyzer(), InputOutputRatioAnalyzer(),
    DagOverviewAnalyzer(), InputReadErrorAnalyzer(), LocalityAnalyzer(),
    OneOnOneEdgeAnalyzer(), SlowNodeAnalyzer(), NodeHealthAnalyzer(),
    DeviceHealthAnalyzer(),
    TaskAssignmentAnalyzer(), TaskAttemptResultStatisticsAnalyzer(),
    VertexLevelCriticalPathAnalyzer())


def analyze_dag(dag: DagInfo,
                analyzers: Sequence[Analyzer] = ALL_ANALYZERS
                ) -> List[AnalyzerResult]:
    return [a.analyze(dag) for a in analyzers]


def main() -> int:
    """AnalyzerDriver CLI: python -m tez_tpu.tools.analyzers <jsonl...>
    or --cache-dir <dir> [dag_id...] (timeline-cache-backed reads)."""
    if len(sys.argv) < 2:
        print("usage: analyzers <history.jsonl | dir | glob>... | "
              "--cache-dir <dir> [dag_id...]")
        return 2
    if sys.argv[1] == "--cache-dir":
        if len(sys.argv) < 3:
            print("usage: analyzers --cache-dir <dir> [dag_id...]")
            return 2
        from tez_tpu.tools.history_cache import DagInfoCache
        cache = DagInfoCache(sys.argv[2])
        wanted = sys.argv[3:]
        dags = {i: d for i, d in cache.all().items()
                if not wanted or i in wanted}
    else:
        dags = parse_jsonl_files(sys.argv[1:])
    if not dags:
        print("no DAGs found")
        return 1
    for dag in dags.values():
        print(f"=== {dag.dag_id} ({dag.name}) state={dag.state} "
              f"duration={dag.duration:.2f}s ===")
        for result in analyze_dag(dag):
            print(f"[{result.analyzer}] {result.headline}")
            for row in result.rows:
                print("   ", json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
