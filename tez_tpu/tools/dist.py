"""Distribution assembly builder — the tez-dist analog.

The reference ships two assemblies (tez-dist/src/main/assembly/tez-dist.xml
and tez-dist-minimal.xml): the full tarball bundles every runtime module
plus dependencies; the minimal one ships only the framework and expects the
environment (Hadoop there, the Python/JAX toolchain here) to be provided.

`tez-dist [--minimal] [--out DIR]` produces
`<out>/tez-tpu-<version>[-minimal].tar.gz`:

- full: the `tez_tpu` package, native sources AND the compiled
  `libtezhost.so` (built on the fly via `make -C native` when a toolchain
  is present), docs, examples, packaging metadata.
- minimal: the framework package only — no examples, no tools, no docs,
  native as source (built on first use by `ops/native.py`).
"""
from __future__ import annotations

import argparse
import io
import os
import subprocess
import sys
import tarfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# tools/ stays in minimal: the AM web controller imports swimlane/analyzer
# modules at request time, so they are framework, not extras
_MINIMAL_EXCLUDED_PKG_DIRS = ("examples", "models")
_SKIP_NAMES = ("__pycache__", ".pytest_cache")


def _walk_files(root: str, rel_base: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_NAMES]
        for name in sorted(filenames):
            if name.endswith((".pyc", ".pyo")):
                continue
            full = os.path.join(dirpath, name)
            yield full, os.path.join(rel_base, os.path.relpath(full, root))


def _try_build_native() -> str | None:
    native_dir = os.path.join(_REPO, "tez_tpu", "native")
    so = os.path.join(native_dir, "libtezhost.so")
    try:
        subprocess.run(["make", "-C", native_dir], check=True,
                       capture_output=True)
    except Exception as e:  # noqa: BLE001 — toolchain-free hosts ship source-only
        # never ship a possibly-stale binary when the rebuild failed
        print(f"warning: native build failed ({e!r:.120}); "
              "assembly ships native sources only", file=sys.stderr)
        return None
    return so if os.path.exists(so) else None


def build(minimal: bool, out_dir: str) -> str:
    from tez_tpu.version import __version__
    # bench.py + docs/ exist only in a source checkout (native sources now
    # ship inside the wheel, so they no longer distinguish the two)
    if not (os.path.exists(os.path.join(_REPO, "bench.py"))
            and os.path.isdir(os.path.join(_REPO, "docs"))):
        raise SystemExit(
            "tez-dist assembles from a source checkout (docs/, bench.py, "
            f"pyproject.toml beside the package); {_REPO} lacks them — "
            "run it from the repository root")
    name = f"tez-tpu-{__version__}" + ("-minimal" if minimal else "")
    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, name + ".tar.gz")

    # full assemblies bundle a freshly built libtezhost.so; minimal ships
    # native as source only (built on first use by ops/native.py) — and a
    # stale committed .so must never ride along either assembly
    ship_so = (_try_build_native() is not None) if not minimal else False

    members: list[tuple[str, str]] = []
    pkg_root = os.path.join(_REPO, "tez_tpu")
    for full, rel in _walk_files(pkg_root, f"{name}/tez_tpu"):
        parts = os.path.relpath(full, pkg_root).split(os.sep)
        if minimal and parts[0] in _MINIMAL_EXCLUDED_PKG_DIRS:
            continue
        base = os.path.basename(full)
        if parts[0] == "native" and base.endswith((".so", ".tmp")) and \
                not (ship_so and base == "libtezhost.so"):
            continue
        members.append((full, rel))

    if not minimal:
        for extra_dir in ("docs",):
            for full, rel in _walk_files(os.path.join(_REPO, extra_dir),
                                         f"{name}/{extra_dir}"):
                members.append((full, rel))
        for extra in ("bench.py", "README.md"):
            p = os.path.join(_REPO, extra)
            if os.path.exists(p):
                members.append((p, f"{name}/{extra}"))
    pyproject = os.path.join(_REPO, "pyproject.toml")
    if os.path.exists(pyproject):
        members.append((pyproject, f"{name}/pyproject.toml"))

    with tarfile.open(out_path, "w:gz") as tf:
        for full, rel in members:
            tf.add(full, arcname=rel, recursive=False)
        manifest = "\n".join(sorted(rel for _, rel in members)) + "\n"
        info = tarfile.TarInfo(f"{name}/MANIFEST")
        data = manifest.encode()
        info.size = len(data)
        info.mtime = int(time.time())
        tf.addfile(info, io.BytesIO(data))
    return out_path


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Build a tez-tpu distribution tarball (tez-dist analog)")
    parser.add_argument("--minimal", action="store_true",
                        help="framework-only assembly (tez-dist-minimal)")
    parser.add_argument("--out", default=os.path.join(_REPO, "dist"))
    args = parser.parse_args()
    path = build(args.minimal, args.out)
    print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
