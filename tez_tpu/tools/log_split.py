"""Split a combined log stream into per-entity files.

Reference parity: tez-tools tez-log-split — carve an aggregated log (many
tasks interleaved in one file) into one file per task attempt so a single
attempt's story reads linearly.  Works off the attempt ids that NDC tagging
(tez_tpu/common/ndc.py) and thread names put on log lines; lines naming no
attempt go to main.log, and continuation lines (e.g. traceback bodies)
follow the last attributed line.
"""
from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, TextIO

#: attempt_<appTs>_<appSeq>_<dagSeq>_<vertex>_<task>_<attempt>
ATTEMPT_RE = re.compile(r"attempt_\d+_\d+_\d+_\d+_\d+_\d+")

#: a line that starts a new log record (timestamp or level prefix); anything
#: else is a continuation (traceback line, wrapped message)
RECORD_START_RE = re.compile(
    r"^(\d{4}-\d{2}-\d{2}[ T]|\[?(DEBUG|INFO|WARNING|ERROR|CRITICAL)\b)")


MAX_OPEN_HANDLES = 64


def split_log(lines, out_dir: str) -> Dict[str, int]:
    """Write per-attempt files (<attempt_id>.log) + main.log under out_dir.
    Returns {file name: line count}."""
    os.makedirs(out_dir, exist_ok=True)
    handles: Dict[str, TextIO] = {}   # insertion-ordered: LRU-ish eviction
    counts: Dict[str, int] = {}
    current = "main.log"

    def sink(name: str) -> TextIO:
        fh = handles.get(name)
        if fh is None:
            if len(handles) >= MAX_OPEN_HANDLES:
                # a DAG can have more attempts than the fd limit: close the
                # coldest handle and reopen in append mode on next use
                evict = next(iter(handles))
                handles.pop(evict).close()
            mode = "a" if name in counts else "w"
            fh = handles[name] = open(os.path.join(out_dir, name), mode)
        return fh

    try:
        for line in lines:
            m = ATTEMPT_RE.search(line)
            if m is not None:
                current = m.group(0) + ".log"
            elif RECORD_START_RE.match(line):
                current = "main.log"
            # else: continuation line stays with `current`
            sink(current).write(line)
            counts[current] = counts.get(current, 0) + 1
    finally:
        for fh in handles.values():
            fh.close()
    return counts


def main() -> int:
    if len(sys.argv) != 3:
        print("usage: log_split <combined.log> <out-dir>")
        return 2
    with open(sys.argv[1]) as fh:
        counts = split_log(fh, sys.argv[2])
    for name in sorted(counts):
        print(f"{counts[name]:8d}  {name}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
