"""Generate docs/configuration.md from the config registry.

Reference parity: the reference documents TezConfiguration keys via
annotations + generated config docs; here the registry IS the source of
truth (run: python -m tez_tpu.tools.gen_config_docs > docs/configuration.md).
"""
from __future__ import annotations

import sys

from tez_tpu.common.config import Scope, TezConfiguration


def render() -> str:
    lines = [
        "# Configuration reference",
        "",
        "Generated from `tez_tpu.common.config` "
        "(`python -m tez_tpu.tools.gen_config_docs`).  Keys with the "
        "`tez.runtime.` prefix travel inside edge payloads (set them via "
        "the edge config builders); everything else is AM/DAG/client scope.",
        "",
    ]
    by_scope = {s: [] for s in Scope}
    for key in sorted(TezConfiguration.registry(), key=lambda k: k.name):
        by_scope[key.scope].append(key)
    for scope in Scope:
        keys = by_scope[scope]
        if not keys:
            continue
        lines.append(f"## Scope: {scope.value}")
        lines.append("")
        lines.append("| key | default | doc |")
        lines.append("|---|---|---|")
        for k in keys:
            default = repr(k.default)
            doc = (k.doc or "").replace("|", "\\|").replace("\n", " ")
            lines.append(f"| `{k.name}` | `{default}` | {doc} |")
        lines.append("")
    return "\n".join(lines)


def main() -> int:
    sys.stdout.write(render())
    return 0


if __name__ == "__main__":
    sys.exit(main())
