"""Export tez_tpu traces as Chrome/Perfetto ``trace_event`` JSON.

Two sources, one output format (load either in https://ui.perfetto.dev or
chrome://tracing):

1. **Live span buffer** (`tez_tpu.common.tracing`): causally-linked spans
   recorded while a DAG ran with ``tez.trace.enabled`` — per-fetch, per-phase
   timing with trace-id/parent-span-id links in the args.
2. **History journals** (post-mortem): any JSONL history/recovery journal
   parses into DagInfo (tools/history_parser.py) and renders as DAG/vertex/
   attempt spans — this works even after an AM crash, since the recovery
   journal doubles as history.

Also home of the span-based critical-path computation used by the
``span_critical_path`` analyzer: the longest causal chain through the span
graph, reported with per-span self time so the dominant vertex/fetch/commit
is named, not guessed.

CLI:
  python -m tez_tpu.tools.trace_export history1.jsonl [...] -o trace.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

from tez_tpu.common.tracing import Span

_PID = os.getpid()


def _tid(name: str) -> int:
    """Stable small-ish int for a thread (or lane) name."""
    return zlib.crc32(name.encode()) & 0x7FFFFFFF


def _us(t: float) -> int:
    return int(t * 1_000_000)


def spans_to_events(spans: Iterable[Span]) -> List[Dict[str, Any]]:
    """Span objects -> trace_event dicts ("X" complete events; span point
    events and zero-duration instant spans -> "i" instants)."""
    events: List[Dict[str, Any]] = []
    tid_names: Dict[int, str] = {}
    for sp in spans:
        end = sp.end if sp.end is not None else sp.start
        tid = _tid(sp.thread)
        tid_names.setdefault(tid, sp.thread)
        args = dict(sp.args)
        args["trace_id"] = sp.trace_id
        args["span_id"] = sp.span_id
        if sp.parent_id:
            args["parent_span_id"] = sp.parent_id
        if sp.cat == "instant" or end <= sp.start:
            events.append({"name": sp.name, "cat": sp.cat or "span",
                           "ph": "i", "s": "t", "ts": _us(sp.start),
                           "pid": _PID, "tid": tid, "args": args})
        else:
            events.append({"name": sp.name, "cat": sp.cat or "span",
                           "ph": "X", "ts": _us(sp.start),
                           "dur": max(1, _us(end) - _us(sp.start)),
                           "pid": _PID, "tid": tid, "args": args})
        for ts, ename, attrs in sp.events:
            events.append({"name": ename, "cat": "event", "ph": "i",
                           "s": "t", "ts": _us(ts), "pid": _PID, "tid": tid,
                           "args": dict(attrs, span_id=sp.span_id,
                                        trace_id=sp.trace_id)})
    for tid, tname in tid_names.items():
        events.append({"name": "thread_name", "ph": "M", "pid": _PID,
                       "tid": tid, "args": {"name": tname}})
    return events


def spans_to_trace(spans: Iterable[Span]) -> Dict[str, Any]:
    return {"traceEvents": spans_to_events(spans), "displayTimeUnit": "ms"}


def history_to_events(dag: "Any") -> List[Dict[str, Any]]:
    """DagInfo (tools/history_parser) -> trace_event dicts.  Lanes (tids)
    are containers, like the swimlane; vertices and the DAG itself render
    on their own lanes so the phase structure reads at a glance."""
    events: List[Dict[str, Any]] = []

    def lane(name: str) -> int:
        tid = _tid(name)
        events.append({"name": "thread_name", "ph": "M", "pid": _PID,
                       "tid": tid, "args": {"name": name}})
        return tid

    if dag.start_time and dag.finish_time > dag.start_time:
        events.append({"name": f"dag:{dag.name}", "cat": "dag", "ph": "X",
                       "ts": _us(dag.start_time),
                       "dur": max(1, _us(dag.finish_time) -
                                  _us(dag.start_time)),
                       "pid": _PID, "tid": lane("dag"),
                       "args": {"dag_id": dag.dag_id, "state": dag.state}})
    for v in dag.vertices.values():
        if v.start_time and v.finish_time > v.start_time:
            events.append({"name": f"vertex:{v.name}", "cat": "vertex",
                           "ph": "X", "ts": _us(v.start_time),
                           "dur": max(1, _us(v.finish_time) -
                                      _us(v.start_time)),
                           "pid": _PID, "tid": lane(f"vertex:{v.name}"),
                           "args": {"state": v.state,
                                    "num_tasks": v.num_tasks}})
    for a in dag.all_attempts():
        if not a.start_time or a.finish_time <= a.start_time:
            continue
        events.append({"name": f"attempt:{a.attempt_id}", "cat": "task",
                       "ph": "X", "ts": _us(a.start_time),
                       "dur": max(1, _us(a.finish_time) - _us(a.start_time)),
                       "pid": _PID,
                       "tid": lane(a.container_id or a.node_id or "task"),
                       "args": {"vertex": a.vertex_name, "state": a.state,
                                "node": a.node_id}})
    # admission plane (post-PR-11): the queue-wait window between submit
    # and start, plus the session's QUEUED/SHED verdict stream — without
    # this lane a parked DAG's wait was silently absent from the export
    if dag.submit_time and dag.start_time > dag.submit_time:
        events.append({"name": "admission:queue-wait", "cat": "admission",
                       "ph": "X", "ts": _us(dag.submit_time),
                       "dur": max(1, _us(dag.start_time) -
                                  _us(dag.submit_time)),
                       "pid": _PID, "tid": lane("admission"),
                       "args": {"dag_id": dag.dag_id,
                                "tenant": dag.tenant}})
    for ev in dag.admission_events:
        t = ev.get("time", 0.0)
        if not t:
            continue
        events.append({"name": f"admission:{ev.get('event', '?')}",
                       "cat": "admission", "ph": "i", "s": "t",
                       "ts": _us(t), "pid": _PID, "tid": lane("admission"),
                       "args": {k: v for k, v in ev.items() if k != "time"}})
    return events


def history_to_trace(dag: "Any") -> Dict[str, Any]:
    return {"traceEvents": history_to_events(dag), "displayTimeUnit": "ms"}


# --------------------------------------------------------------------------
# Flight-recorder tracks (planes with no span coverage: store, push,
# exchange, admission verdicts, breaker/watchdog, SLO)
# --------------------------------------------------------------------------

def flight_to_events(snap: "Any") -> List[Dict[str, Any]]:
    """FlightSnapshot -> trace_event dicts, one lane per plane.

    Span edges re-render as complete events (useful when the dump is the
    only artifact — no live span buffer post-mortem); every histogram
    observation becomes a complete event on a per-name counter lane (the
    store publish/fetch/demote, push rtt, exchange round, and admission
    queue-wait tracks); typed plane events render as instants on their
    plane's lane.  Timestamps project onto the wall clock through the
    anchor embedded in the snapshot, so these tracks line up with
    history/span tracks from the same process."""
    from tez_tpu.common import clock
    from tez_tpu.obs import flight as fl
    events: List[Dict[str, Any]] = []
    lanes: Dict[int, str] = {}

    def lane(name: str) -> int:
        tid = _tid(name)
        if tid not in lanes:
            lanes[tid] = name
            events.append({"name": "thread_name", "ph": "M", "pid": _PID,
                           "tid": tid, "args": {"name": name}})
        return tid

    anchor = snap.anchor
    for e in snap.events:
        wall = clock.mono_to_wall(e.t_ns, anchor)
        if e.kind == fl.SPAN:
            start = clock.mono_to_wall(e.a, anchor)
            events.append({"name": e.name, "cat": e.scope or "span",
                           "ph": "X", "ts": _us(start),
                           "dur": max(1, e.b // 1000), "pid": _PID,
                           "tid": lane(f"flight:span:{e.scope or 'span'}"),
                           "args": {"seq": e.seq}})
        elif e.kind == fl.COUNTER:
            dur = max(1, e.a)          # a = observed microseconds
            events.append({"name": e.name, "cat": "counter", "ph": "X",
                           "ts": _us(wall) - dur, "dur": dur, "pid": _PID,
                           "tid": lane(f"flight:counter:{e.name}"),
                           "args": {"seq": e.seq, "observed_us": e.a}})
        else:
            events.append({"name": e.name or e.kind_name,
                           "cat": e.kind_name, "ph": "i", "s": "t",
                           "ts": _us(wall), "pid": _PID,
                           "tid": lane(f"flight:{e.kind_name}"),
                           "args": {"seq": e.seq, "scope": e.scope,
                                    "a": e.a, "b": e.b}})
    return events


def flight_to_trace(snap: "Any") -> Dict[str, Any]:
    return {"traceEvents": flight_to_events(snap), "displayTimeUnit": "ms"}


def write_trace(trace: Dict[str, Any], path: str) -> str:
    with open(path, "w") as fh:
        json.dump(trace, fh, default=str)
    return path


# --------------------------------------------------------------------------
# Span-based critical path
# --------------------------------------------------------------------------

def critical_path(spans: List[Span]) -> List[Span]:
    """The longest causal chain: starting from each root span, follow the
    child whose end time is latest (what actually gated the parent's end),
    and return the root->leaf path of the trace that finished last.  Spans
    still open (end is None) participate with their start as end."""
    by_parent: Dict[Optional[str], List[Span]] = {}
    roots: List[Span] = []
    ids = {sp.span_id for sp in spans}
    for sp in spans:
        if sp.parent_id and sp.parent_id in ids:
            by_parent.setdefault(sp.parent_id, []).append(sp)
        else:
            roots.append(sp)
    if not roots:
        return []

    def end_of(sp: Span) -> float:
        return sp.end if sp.end is not None else sp.start

    root = max(roots, key=end_of)
    path = [root]
    cur = root
    while True:
        kids = by_parent.get(cur.span_id)
        if not kids:
            return path
        cur = max(kids, key=end_of)
        path.append(cur)


def dominant_span(path: List[Span]) -> Optional[Span]:
    """The path member with the largest SELF time (own duration minus the
    duration of its on-path child) — the span a perf PR should attack."""
    if not path:
        return None
    best, best_self = None, -1.0
    for i, sp in enumerate(path):
        child_dur = path[i + 1].duration if i + 1 < len(path) else 0.0
        self_t = max(0.0, sp.duration - child_dur)
        if self_t > best_self:
            best, best_self = sp, self_t
    return best


def critical_path_report(spans: List[Span]) -> Dict[str, Any]:
    path = critical_path(spans)
    dom = dominant_span(path)
    def self_ms(i: int) -> float:
        child = path[i + 1].duration if i + 1 < len(path) else 0.0
        return round(max(0.0, path[i].duration - child) * 1000, 3)

    return {
        "chain": [{"name": sp.name, "cat": sp.cat,
                   "duration_ms": round(sp.duration * 1000, 3),
                   "self_ms": self_ms(i),
                   "vertex": sp.args.get("vertex", ""),
                   "span_id": sp.span_id} for i, sp in enumerate(path)],
        "dominant": None if dom is None else {
            "name": dom.name, "cat": dom.cat,
            "vertex": dom.args.get("vertex", ""),
            "span_id": dom.span_id,
            "duration_ms": round(dom.duration * 1000, 3)},
    }


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Export Chrome/Perfetto trace JSON from history "
                    "journals (or the live span buffer via --live).")
    ap.add_argument("journals", nargs="*",
                    help="history/recovery JSONL files")
    ap.add_argument("-o", "--out", default="trace.json")
    ap.add_argument("--dag", default="",
                    help="dag_id to export (default: last one seen)")
    ap.add_argument("--live", action="store_true",
                    help="export the in-process span buffer instead of "
                         "history files")
    ap.add_argument("--flight", nargs="*", default=[], metavar="DUMP",
                    help="flight_*.json dumps whose per-plane tracks "
                         "(store/push/exchange/admission/breaker) are "
                         "merged into the export")
    args = ap.parse_args(argv)
    if args.live:
        from tez_tpu.common import tracing
        trace = spans_to_trace(tracing.snapshot())
    elif args.journals:
        from tez_tpu.tools.history_parser import parse_jsonl_files
        dags = parse_jsonl_files(args.journals)
        if not dags:
            print("no DAGs found in journals", file=sys.stderr)
            return 1
        dag_id = args.dag or sorted(dags)[-1]
        if dag_id not in dags:
            print(f"dag {dag_id} not in {sorted(dags)}", file=sys.stderr)
            return 1
        trace = history_to_trace(dags[dag_id])
    elif args.flight:
        trace = {"traceEvents": [], "displayTimeUnit": "ms"}
    else:
        ap.error("journal files, --flight dumps, or --live required")
    if args.flight:
        from tez_tpu.obs import flight as fl
        for path in args.flight:
            trace["traceEvents"].extend(
                flight_to_events(fl.load_dump(path)))
    write_trace(trace, args.out)
    print(f"wrote {len(trace['traceEvents'])} events to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
