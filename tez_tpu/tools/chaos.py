"""Chaos soak harness: run a canned DAG under a seeded fault storm and
check the output is bit-exact against a fault-free baseline.

Every storm is derived purely from ``--seed``, so any failure is
reproducible with the printed repro line::

    python -m tez_tpu.tools.chaos --seed 1234

Multiple trials (``--trials K``) walk seeds N, N+1, ... and share one
baseline run. The storm menu only contains *recoverable* faults — ones the
framework is expected to absorb (retries, reruns, speculation, container
respawn) — so a divergent or failed run is always a bug, never an
over-aggressive storm.

``--commit-storm`` runs the exactly-once commit scenario instead: a DAG
with a FileOutput data sink is killed between the ledger's
DAG_COMMIT_STARTED and DAG_COMMIT_FINISHED records (a delay fault parks
the publisher mid-commit), a successor AM attempt resumes the ledger, and
the published output must be bit-exact vs a fault-free run — no orphaned
``_temporary`` tree, no double-published part file, ``_SUCCESS`` present.
On divergence the recovery journal is fsck'd so a corrupt ledger is
distinguished from a replay bug.
"""
from __future__ import annotations

import argparse
import os
import random
import shutil
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common import faults
from tez_tpu.common.payload import (InputDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor)
from tez_tpu.dag.dag import DAG, DataSinkDescriptor, Edge, Vertex
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)
from tez_tpu.library.processors import SimpleProcessor

NUM_PRODUCERS = 2
KEYS_PER_TASK = 40

# Recoverable storm menu. Each entry is a single-rule spec fragment; a storm
# is a seeded sample of these joined with ';'. Budgets are deliberately
# small (n=1..2) so compound storms stay inside the framework's retry
# envelopes (task.max.failed.attempts, fetch max_attempts, ...).
STORM_MENU = (
    "shuffle.fetch.read:fail:n=1,exc=io",
    "shuffle.fetch.connect:fail:n=1,exc=conn",
    "shuffle.data:corrupt:n=1",
    "task.run:fail:n=1,exc=runtime",
    "task.run:delay:ms=400,n=1",
    "spill.write:delay:ms=150,n=2",
    "am.heartbeat:delay:ms=250,n=2",
    "am.container.launch:fail:n=1",
)


class ChaosEmitProcessor(SimpleProcessor):
    """Deterministic producer: every task emits the same (key, value) set,
    so the grouped totals downstream are a pure function of the plan."""

    def run(self, inputs, outputs):
        writer = outputs["consumer"].get_writer()
        for i in range(KEYS_PER_TASK):
            writer.write(f"key{i:03d}".encode(), i + 1)


class ChaosCountProcessor(SimpleProcessor):
    """Groups the sorted input and writes 'key total' lines (sorted, so the
    file is bit-exact regardless of fetch interleaving) to result_path."""

    def run(self, inputs, outputs):
        payload = self.context.user_payload.load() or {}
        reader = inputs["producer"].get_reader()
        totals = {k: sum(vs) for k, vs in reader}
        lines = [f"{k.decode()} {v}" for k, v in sorted(totals.items())]
        with open(payload["result_path"], "w") as fh:
            fh.write("\n".join(lines) + "\n")


class ChaosWideEmitProcessor(SimpleProcessor):
    """Store-pressure producer: enough bytes per task that a deliberately
    tiny buffer-store host tier must demote/evict mid-shuffle."""

    WIDE_KEYS = 4000

    def run(self, inputs, outputs):
        writer = outputs["consumer"].get_writer()
        for i in range(self.WIDE_KEYS):
            writer.write(f"key{i:05d}".encode(), i + 1)


class ChaosPushEmitProcessor(SimpleProcessor):
    """Push-storm producer: several io.sort.mb's worth of records per task
    so the pipelined sorter emits a stream of spills — each one an eager
    push for the storm to kill mid-map-wave."""

    PUSH_KEYS = 150_000

    def run(self, inputs, outputs):
        writer = outputs["consumer"].get_writer()
        for i in range(self.PUSH_KEYS):
            writer.write(f"key{i:06d}".encode(), i + 1)


def make_storm(seed: int) -> str:
    """Seeded storm spec: 2-4 distinct recoverable faults."""
    rng = random.Random(seed)
    picks = rng.sample(STORM_MENU, rng.randint(2, 4))
    return ";".join(picks)


def _build_dag(name: str, result_path: str, fault_spec: str = "",
               fault_seed: int = 0, trace: bool = False,
               producer_cls: type = ChaosEmitProcessor) -> DAG:
    producer = Vertex.create("producer", ProcessorDescriptor.create(
        producer_cls), NUM_PRODUCERS)
    consumer = Vertex.create("consumer", ProcessorDescriptor.create(
        ChaosCountProcessor, payload={"result_path": result_path}), 1)
    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "long"}
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=conf))
    dag = DAG.create(name).add_vertex(producer).add_vertex(consumer)
    dag.add_edge(Edge.create(producer, consumer, prop))
    if fault_spec:
        dag.set_conf("tez.test.fault.spec", fault_spec)
        dag.set_conf("tez.test.fault.seed", fault_seed)
    if trace:
        dag.set_conf("tez.trace.enabled", True)
    return dag


def _run_dag(workdir: str, name: str, fault_spec: str = "",
             fault_seed: int = 0, timeout: float = 120.0,
             trace: bool = False, extra_conf: Optional[Dict] = None,
             producer_cls: type = ChaosEmitProcessor,
             counters: Optional[Dict] = None) -> Tuple[str, bytes]:
    """One client + one DAG in a fresh staging dir. Returns (state, result
    bytes); result is b'' if the DAG failed before writing.  Pass a dict as
    ``counters`` to receive the DAG's counter groups summed across tasks."""
    staging = os.path.join(workdir, name, "staging")
    result_path = os.path.join(workdir, name, "result.txt")
    os.makedirs(os.path.dirname(result_path), exist_ok=True)
    conf = {
        "tez.staging-dir": staging,
        "tez.am.local.num-containers": 4,
        # leave headroom for injected task failures
        "tez.am.task.max.failed.attempts": 4,
    }
    conf.update(extra_conf or {})
    client = TezClient.create(name, conf).start()
    try:
        dag = _build_dag(name, result_path, fault_spec, fault_seed,
                         trace=trace, producer_cls=producer_cls)
        dag_client = client.submit_dag(dag)
        status = dag_client.wait_for_completion(timeout=timeout)
        state = status.state.name
        if counters is not None:
            final = dag_client.get_dag_status(with_counters=True)
            if final.counters is not None:
                for group, cs in final.counters.to_dict().items():
                    g = counters.setdefault(group, {})
                    for cname, v in cs.items():
                        g[cname] = g.get(cname, 0) + v
    finally:
        client.stop()
        faults.clear_all()
    data = b""
    if os.path.exists(result_path):
        with open(result_path, "rb") as fh:
            data = fh.read()
    return state, data


def run_trial(seed: int, workdir: str, baseline: Optional[bytes] = None,
              timeout: float = 120.0, trace: bool = False,
              ) -> Tuple[bool, str, str]:
    """Run one seeded storm; returns (ok, spec, detail)."""
    if baseline is None:
        state, baseline = _run_dag(workdir, "baseline", timeout=timeout)
        if state != DAGStatusState.SUCCEEDED.name or not baseline:
            return False, "", f"baseline run failed (state={state})"
    spec = make_storm(seed)
    state, got = _run_dag(workdir, f"storm{seed}", fault_spec=spec,
                          fault_seed=seed, timeout=timeout, trace=trace)
    if state != DAGStatusState.SUCCEEDED.name:
        return False, spec, f"storm DAG finished {state}"
    if got != baseline:
        return False, spec, (f"output diverged from baseline "
                             f"({len(got)} vs {len(baseline)} bytes)")
    return True, spec, "bit-exact vs baseline"


# ---------------------------------------------------------- store pressure

def run_store_pressure(seed: int, workdir: str,
                       timeout: float = 120.0) -> Tuple[bool, str]:
    """Buffer-store eviction-storm scenario. Returns (ok, detail).

    The wide producer pushes ~100KB of shuffle data through a buffer store
    whose tiers are deliberately tiny (host ~50KB, device ~20KB, watermarks
    0.6/0.3), so the watermark enforcer must demote and evict mid-merge —
    while the consumer is actively fetching.  The run must still succeed
    and its output must be bit-exact vs a store-disabled baseline: tier
    churn is allowed to cost I/O, never data."""
    from tez_tpu.store import local_buffer_store, reset_store

    reset_store()          # a leftover full-size store would hide pressure
    try:
        state, baseline = _run_dag(workdir, f"storebase{seed}",
                                   timeout=timeout,
                                   producer_cls=ChaosWideEmitProcessor)
        if state != DAGStatusState.SUCCEEDED.name or not baseline:
            return False, f"store-off baseline failed (state={state})"
        store_conf = {
            "tez.runtime.store.enabled": True,
            "tez.runtime.store.device.capacity-mb": 0.02,
            "tez.runtime.store.host.capacity-mb": 0.05,
            "tez.runtime.store.watermark.high": 0.6,
            "tez.runtime.store.watermark.low": 0.3,
            # reuse off: this scenario measures pressure, not caching
            "tez.runtime.store.lineage.reuse": False,
        }
        state, got = _run_dag(workdir, f"storepress{seed}", timeout=timeout,
                              extra_conf=store_conf,
                              producer_cls=ChaosWideEmitProcessor)
        store = local_buffer_store()
        if store is None:
            return False, "store-enabled run never created the buffer store"
        counters = store.stats()["counters"]
        published = counters.get("store.published", 0)
        churn = {k: v for k, v in counters.items()
                 if (k.startswith("store.demotions.") or
                     k.startswith("store.evictions.")) and v}
        if state != DAGStatusState.SUCCEEDED.name:
            return False, (f"store-pressure DAG finished {state}; "
                           f"churn={churn}")
        if got != baseline:
            return False, (f"output diverged under store pressure "
                           f"({len(got)} vs {len(baseline)} bytes); "
                           f"churn={churn}")
        if published < 1:
            return False, "no output was ever published into the store"
        if not churn:
            return False, (f"tiny tiers never forced a demotion/eviction "
                           f"({published} published) — pressure did not "
                           f"bite; shrink the tiers or widen the producer")
        return True, (f"bit-exact under eviction storm: {published} "
                      f"published, churn={churn}")
    finally:
        reset_store()


# ------------------------------------------------------------- push storm

def run_push_storm(seed: int, workdir: str,
                   timeout: float = 120.0) -> Tuple[bool, str]:
    """Push-transport kill scenario. Returns (ok, detail).

    A multi-spill producer runs with push-based shuffle enabled while a
    seeded ``shuffle.push.send`` pfail storm kills pushers mid-map-wave
    (retries clamped to 1 so a killed push is really dead).  The pull path
    is the correctness backstop: every spill was synchronously registered
    before its push left the building, so the run must still SUCCEED and
    its output must be bit-exact vs a fault-free pull-only baseline.  The
    storm must also demonstrably bite — at least one push rejected AND at
    least one push landed, else the trial proves nothing either way."""
    from tez_tpu.store import local_buffer_store, reset_store

    reset_store()          # a leftover store would hide this run's pushes
    try:
        state, baseline = _run_dag(workdir, f"pushbase{seed}",
                                   timeout=timeout,
                                   extra_conf={"tez.runtime.io.sort.mb": 1},
                                   producer_cls=ChaosPushEmitProcessor)
        if state != DAGStatusState.SUCCEEDED.name or not baseline:
            return False, f"pull-only baseline failed (state={state})"
        push_conf = {
            "tez.runtime.io.sort.mb": 1,       # many spills == many pushes
            "tez.runtime.shuffle.push.enabled": True,
            "tez.runtime.shuffle.push.retries": 1,
            "tez.runtime.store.enabled": True,
            # reuse off: this scenario measures the backstop, not caching
            "tez.runtime.store.lineage.reuse": False,
        }
        spec = "shuffle.push.send:pfail:p=0.5,exc=io"
        counters: Dict = {}
        state, got = _run_dag(workdir, f"pushstorm{seed}", fault_spec=spec,
                              fault_seed=seed, timeout=timeout,
                              extra_conf=push_conf,
                              producer_cls=ChaosPushEmitProcessor,
                              counters=counters)
        task = counters.get("TaskCounter", {})
        pushed = task.get("SHUFFLE_PUSH_BYTES", 0)
        rejected = task.get("SHUFFLE_PUSH_REJECTED", 0)
        if state != DAGStatusState.SUCCEEDED.name:
            return False, (f"push-storm DAG finished {state}; "
                           f"pushed={pushed} rejected={rejected}")
        if got != baseline:
            return False, (f"output diverged under the push storm "
                           f"({len(got)} vs {len(baseline)} bytes); "
                           f"pushed={pushed} rejected={rejected}")
        store = local_buffer_store()
        published = 0
        if store is not None:
            published = store.stats()["counters"].get("store.published", 0)
        if rejected < 1:
            return False, (f"storm never killed a push ({pushed} bytes "
                           f"pushed) — raise p or emit more spills")
        if pushed < 1 or published < 1:
            return False, (f"no push ever landed ({rejected} rejected, "
                           f"{published} published) — the run degenerated "
                           f"to pull-only and proves nothing about push")
        return True, (f"bit-exact on the pull backstop: {pushed} bytes "
                      f"pushed ({published} published), {rejected} push(es) "
                      f"killed by the storm")
    finally:
        reset_store()


# ----------------------------------------------------------- tenant storm

class ChaosTenantEmitProcessor(SimpleProcessor):
    """Tenant-salted producer: each tenant's key space and values are
    disjoint functions of the payload salt, so any cross-tenant mixing in
    the session AM shows up as a bit-level diff, never a coincidence."""

    def run(self, inputs, outputs):
        payload = self.context.user_payload.load() or {}
        salt = int(payload.get("salt", 0))
        writer = outputs["consumer"].get_writer()
        for i in range(KEYS_PER_TASK):
            writer.write(f"t{salt}key{i:03d}".encode(), i + 1 + salt)


#: Recoverable per-DAG faults for the tenant storm: small budgets so every
#: accepted DAG stays inside its retry envelope (the admission faults —
#: am.admit.shed / am.queue.delay — are installed process-wide instead,
#: because they fire before the DAG exists to carry a conf).
TENANT_STORM_MENU = (
    "task.run:fail:n=1,exc=runtime",
    # the delay entry pairs a task-level stall with a device-plane
    # dispatch delay: whichever engine runs the attempt, the round gets a
    # genuine straggler for tools/doctor.py to name in its waterfall
    "task.run:delay:ms=250,n=1;device.dispatch.delay:delay:ms=250,n=2",
    "shuffle.fetch.read:fail:n=1,exc=io",
)


def _build_tenant_dag(name: str, result_path: str, salt: int,
                      tenant: str = "", fault_spec: str = "",
                      fault_seed: int = 0, trace: bool = False) -> DAG:
    producer = Vertex.create("producer", ProcessorDescriptor.create(
        ChaosTenantEmitProcessor, payload={"salt": salt}), NUM_PRODUCERS)
    consumer = Vertex.create("consumer", ProcessorDescriptor.create(
        ChaosCountProcessor, payload={"result_path": result_path}), 1)
    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "long"}
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=conf))
    dag = DAG.create(name).add_vertex(producer).add_vertex(consumer)
    dag.add_edge(Edge.create(producer, consumer, prop))
    if tenant:
        dag.set_conf("tez.dag.tenant", tenant)
    if fault_spec:
        dag.set_conf("tez.test.fault.spec", fault_spec)
        dag.set_conf("tez.test.fault.seed", fault_seed)
    if trace:
        dag.set_conf("tez.trace.enabled", True)
    return dag


def run_tenant_storm(seed: int, workdir: str, timeout: float = 120.0,
                     tenants: int = 3, rounds: int = 3,
                     p95_bound_s: float = 30.0) -> Tuple[bool, str]:
    """Multi-tenant session soak. Returns (ok, detail).

    One resident session AM (max-concurrent-dags=2, queue-size=2) takes
    recurring DAGs from ``tenants`` concurrent submitter threads, each
    round barrier-synchronized so every round is a genuine 3-way admission
    race.  A process-wide ``am.admit.shed`` fault forces the first two
    submissions to SHED (clients must resubmit on the typed RETRY-AFTER)
    and ``am.queue.delay`` stalls the queue consumer mid-promote; on top,
    half the DAGs carry a seeded recoverable task/fetch fault.  The
    contract under all of that:

    - every ACCEPTED DAG completes bit-exact vs its tenant's fault-free
      baseline (shed submissions — resubmitted until accepted — are the
      only losses, and they are typed, never silent);
    - per-tenant store bytes stay attributed to their tenant: no bytes
      under an unknown or anonymous tenant (cross-tenant leak);
    - zero epoch-fence events (two live DAGs in one AM incarnation must
      never fence each other);
    - per-tenant p95 completion latency (tenant.<t>.dag.latency in the
      metrics registry) stays under ``p95_bound_s``.
    """
    from tez_tpu.common import metrics as metrics_mod
    from tez_tpu.common import tracing
    from tez_tpu.store import local_buffer_store, reset_store
    from tez_tpu.utils.backoff import ExponentialBackoff

    reset_store()
    tracing.clear_all()
    metrics_mod.registry().reset()   # p95/queue-wait reads are storm-scoped
    tenant_names = [f"tenant{t}" for t in range(tenants)]

    # fault-free per-tenant baselines, each on its own throwaway AM
    baselines: List[bytes] = []
    for t in range(tenants):
        base = os.path.join(workdir, f"tsbase{seed}-t{t}")
        result_path = os.path.join(base, "result.txt")
        os.makedirs(base, exist_ok=True)
        client = TezClient.create(f"tsbase{t}", {
            "tez.staging-dir": os.path.join(base, "staging"),
            "tez.am.local.num-containers": 4}).start()
        try:
            dag = _build_tenant_dag(f"tsbase{seed}-t{t}", result_path,
                                    salt=t)
            status = client.submit_dag(dag).wait_for_completion(
                timeout=timeout)
        finally:
            client.stop()
        if status.state.name != DAGStatusState.SUCCEEDED.name or \
                not os.path.exists(result_path):
            return False, (f"tenant {t} baseline failed "
                           f"(state={status.state.name})")
        with open(result_path, "rb") as fh:
            baselines.append(fh.read())
    if len(set(baselines)) != tenants:
        return False, "tenant baselines are not pairwise distinct"

    storm_dir = os.path.join(workdir, f"tenantstorm{seed}")
    results_dir = os.path.join(storm_dir, "results")
    os.makedirs(results_dir, exist_ok=True)
    session_conf = {
        "tez.staging-dir": os.path.join(storm_dir, "staging"),
        "tez.am.local.num-containers": 4,
        "tez.am.task.max.failed.attempts": 4,
        "tez.am.session.max-concurrent-dags": 2,
        "tez.am.session.queue-size": 2,
        "tez.am.session.shed.retry-after-ms": 100,
        "tez.am.session.fair-share": True,
        "tez.am.session.tenant.weights":
            ",".join(f"{n}={tenants - i}"
                     for i, n in enumerate(tenant_names)),
        # store on with roomy per-tenant quotas: the storm checks byte
        # ATTRIBUTION (leaks), not quota pressure — store-pressure covers
        # that; lineage reuse exercises the governed result cache across
        # each tenant's recurring rounds
        "tez.runtime.store.enabled": True,
        "tez.runtime.store.quota.device-mb": 8,
        "tez.runtime.store.quota.host-mb": 8,
        "tez.runtime.store.quota.disk-mb": 8,
        "tez.runtime.store.lineage.reuse": True,
        # declarative SLO targets (obs/slo.py): the forced am.admit.shed
        # faults must surface as a typed shed-rate breach in GET /slo,
        # the history journal, and (when --dump-flight armed the
        # recorder) the flight dump — the doctor acceptance path
        "tez.am.slo.shed-rate": 0.01,
        "tez.am.slo.min-count": 2,
        # live ops plane on: make soak is the documented target for
        # `graft top` / GET /doctor/live (docs/telemetry.md), so the
        # storm session serves them on an ephemeral port
        "tez.am.web.enabled": True,
    }
    # admission faults are process-wide: they fire in the AM's submit path
    # and queue consumer, before any DAG-scoped rules exist.  fail:n=2
    # deterministically sheds the first two submissions; delay stalls the
    # consumer mid-promote without killing it.
    faults.install("chaos", faults.parse_spec(
        "am.admit.shed:fail:n=2;am.queue.delay:delay:ms=120,n=3"),
        seed=seed)
    import threading
    errors: List[str] = []
    completed: Dict[str, int] = {n: 0 for n in tenant_names}
    barrier = threading.Barrier(tenants)

    client = TezClient.create(f"tenantstorm{seed}", session_conf,
                              session=True).start()
    web = getattr(client.framework_client.am, "web_ui", None)
    if web is not None:
        # the soak is the documented live target for the ops plane:
        # point `make top URL=...` (or a Prometheus scraper) here
        print(f"live ops plane: python -m tez_tpu.tools.top {web.url}")

    def submitter(t: int) -> None:
        tenant = tenant_names[t]
        rng = random.Random(seed * 7919 + t)
        for r in range(rounds):
            try:
                barrier.wait(timeout=timeout)
            except threading.BrokenBarrierError:
                errors.append(f"{tenant}-r{r}: barrier broken "
                              f"(another tenant thread died)")
                return
            name = f"{tenant}-r{r}"
            result_path = os.path.join(results_dir, f"{name}.txt")
            spec = rng.choice(TENANT_STORM_MENU) \
                if rng.random() < 0.5 else ""
            dag = _build_tenant_dag(name, result_path, salt=t,
                                    tenant=tenant, fault_spec=spec,
                                    fault_seed=seed * 100 + r, trace=True)
            try:
                dc = client.submit_dag_with_retry(
                    dag, retries=10,
                    backoff=ExponentialBackoff(base=0.05, cap=0.5,
                                               jitter=True, rng=rng))
                state = dc.wait_for_completion(timeout=timeout).state.name
            except Exception as e:  # noqa: BLE001 — a loss, reported loudly
                errors.append(f"{name}: {e!r}")
                continue
            if state != DAGStatusState.SUCCEEDED.name:
                errors.append(f"{name}: finished {state} "
                              f"(storm=[{spec or 'none'}])")
                continue
            got = b""
            if os.path.exists(result_path):
                with open(result_path, "rb") as fh:
                    got = fh.read()
            if got != baselines[t]:
                errors.append(f"{name}: output diverged from tenant "
                              f"baseline ({len(got)} vs "
                              f"{len(baselines[t])} bytes)")
                continue
            completed[tenant] += 1

    try:
        threads = [threading.Thread(target=submitter, args=(t,),
                                    name=f"tenant{t}-submitter",
                                    daemon=True)
                   for t in range(tenants)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=timeout * rounds)
        qs = client.queue_status()
        store = local_buffer_store()
        tenant_bytes = store.tenant_bytes() if store is not None else {}
        store_counters = store.stats()["counters"] if store is not None \
            else {}
    finally:
        client.stop()
        faults.clear_all()
        reset_store()

    if errors:
        return False, f"{len(errors)} loss(es): " + "; ".join(errors[:4])
    stats = qs.get("tenants", {})
    shed = sum(ts.get("shed", 0) for ts in stats.values())
    accepted = sum(ts.get("accepted", 0) for ts in stats.values())
    for n in tenant_names:
        ts = stats.get(n, {})
        if completed[n] != rounds or ts.get("completed", 0) != rounds:
            return False, (f"{n}: {completed[n]}/{rounds} rounds verified, "
                           f"AM says completed={ts.get('completed', 0)} — "
                           f"an accepted DAG was lost")
        if ts.get("failed", 0):
            return False, f"{n}: {ts['failed']} DAG(s) failed in the AM"
    if shed < 2:
        return False, (f"only {shed} shed(s) — the am.admit.shed fault "
                       f"(n=2) did not bite")
    if qs.get("queue_depth", 0) or not qs.get("consumer_alive", True):
        return False, (f"session ended dirty: queue_depth="
                       f"{qs.get('queue_depth')} consumer_alive="
                       f"{qs.get('consumer_alive')}")
    # cross-tenant store isolation: every byte the session holds must be
    # attributed to a declared tenant — bytes under "" (anonymous) or an
    # unknown name mean the tenant plumbing leaked somewhere
    unknown = set(tenant_bytes) - set(tenant_names)
    if unknown:
        return False, (f"store bytes leaked outside declared tenants: "
                       f"{sorted(unknown)} in {tenant_bytes}")
    if store_counters.get("store.published", 0) < 1:
        return False, "no output was ever published into the store"
    # epoch fencing: concurrent DAGs share ONE AM incarnation; any fence
    # event means dag-vs-dag state bled into the epoch plane
    spans = tracing.snapshot()
    fences = [s for s in spans if s.name == "fence.stale_epoch"]
    fences += [n for s in spans for _, n, _ in s.events
               if n == "fence.stale_epoch"]
    tracing.clear_all()
    if fences:
        return False, f"{len(fences)} unexpected epoch-fence event(s)"
    hists = metrics_mod.registry().histograms()
    p95s = {}
    for n in tenant_names:
        h = hists.get(f"tenant.{n}.dag.latency")
        if h is None or h.count < rounds:
            return False, (f"{n}: latency histogram missing/short "
                           f"({0 if h is None else h.count}/{rounds})")
        p95s[n] = h.quantile(0.95) / 1000.0
        if p95s[n] > p95_bound_s:
            return False, (f"{n}: p95 latency {p95s[n]:.2f}s over the "
                           f"declared {p95_bound_s:.0f}s bound")
    queue_waits = hists.get("am.admit.queue_wait")
    if queue_waits is None or queue_waits.count < 1:
        return False, ("no submission ever took the QUEUE verdict — the "
                       "barrier-synced rounds never contended")
    p95_txt = " ".join(f"{n}={p95s[n]:.2f}s" for n in tenant_names)
    return True, (f"{accepted} accepted / {shed} shed / "
                  f"{sum(completed.values())} bit-exact over {tenants} "
                  f"tenants x {rounds} rounds; {queue_waits.count} queued "
                  f"(p95 wait {queue_waits.quantile(0.95):.0f}ms); "
                  f"tenant bytes {sorted(tenant_bytes)}; p95 {p95_txt}")


# ----------------------------------------------------------- commit storm

class ChaosSinkCountProcessor(SimpleProcessor):
    """ChaosCountProcessor variant that emits through the vertex's FileOutput
    data sink, so the result is published by the commit protocol (two-phase
    ledger + rename-on-commit) rather than written directly by the task."""

    def run(self, inputs, outputs):
        reader = inputs["producer"].get_reader()
        totals = {k: sum(vs) for k, vs in reader}
        writer = outputs["sink"].get_writer()
        for k, v in sorted(totals.items()):
            writer.write(k.decode(), str(v))


def _build_sink_dag(name: str, out_dir: str, fault_spec: str = "",
                    fault_seed: int = 0, trace: bool = False) -> DAG:
    producer = Vertex.create("producer", ProcessorDescriptor.create(
        ChaosEmitProcessor), NUM_PRODUCERS)
    consumer = Vertex.create("consumer", ProcessorDescriptor.create(
        ChaosSinkCountProcessor), 1)
    consumer.add_data_sink("sink", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": out_dir,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": out_dir})))
    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "long"}
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=conf))
    dag = DAG.create(name).add_vertex(producer).add_vertex(consumer)
    dag.add_edge(Edge.create(producer, consumer, prop))
    if fault_spec:
        dag.set_conf("tez.test.fault.spec", fault_spec)
        dag.set_conf("tez.test.fault.seed", fault_seed)
    if trace:
        dag.set_conf("tez.trace.enabled", True)
    return dag


def read_published(out_dir: str) -> Dict[str, bytes]:
    """Published output-dir contents: {filename: bytes} for every regular
    file (part files + _SUCCESS). Subdirs (e.g. a leftover _temporary tree)
    are reported with a b'<DIR>' sentinel so they always diverge."""
    out: Dict[str, bytes] = {}
    if not os.path.isdir(out_dir):
        return out
    for name in sorted(os.listdir(out_dir)):
        p = os.path.join(out_dir, name)
        if os.path.isfile(p):
            with open(p, "rb") as fh:
                out[name] = fh.read()
        else:
            out[name] = b"<DIR>"
    return out


def _fsck_summary(staging: str, app_id: str) -> str:
    from tez_tpu.tools import journal_fsck
    files = journal_fsck.discover_journals(
        os.path.join(staging, app_id, "recovery"))
    if not files:
        return "no recovery journal found"
    report = journal_fsck.fsck_files(files)
    dags = {d: led.inferred_terminal for d, led in report.dags.items()}
    return (f"journal fsck: {'CLEAN' if report.ok else report.errors}; "
            f"terminal states {dags}")


def run_commit_storm(workdir: str, timeout: float = 120.0,
                     delay_ms: int = 4000, app_id: str = "app_1_cstorm",
                     trace: bool = False) -> Tuple[bool, str]:
    """The exactly-once commit scenario. Returns (ok, detail).

    A ``commit.publish`` delay fault parks attempt 1's publisher after the
    COMMIT_STARTED ledger record; the AM is killed inside that window, so
    attempt 2 finds an open ledger and must resume the commit — and the
    parked publisher, now a zombie from a superseded epoch, must be fenced
    when it wakes instead of double-publishing."""
    from tez_tpu.am.app_master import DAGAppMaster
    from tez_tpu.am.dag_impl import DAGState
    from tez_tpu.am.history import HistoryEventType
    from tez_tpu.common import config as C

    # fault-free baseline
    base_out = os.path.join(workdir, "commit_base", "out")
    client = TezClient.create("commitbase", {
        "tez.staging-dir": os.path.join(workdir, "commit_base", "staging"),
        "tez.am.local.num-containers": 4}).start()
    try:
        status = client.submit_dag(
            _build_sink_dag("commitbase", base_out)).wait_for_completion(
                timeout=timeout)
    finally:
        client.stop()
        faults.clear_all()
    if status.state.name != DAGStatusState.SUCCEEDED.name:
        return False, f"baseline sink DAG failed (state={status.state.name})"
    baseline = read_published(base_out)
    if "_SUCCESS" not in baseline:
        return False, "baseline published no _SUCCESS marker"

    # storm: kill the AM between COMMIT_STARTED and COMMIT_FINISHED
    out_dir = os.path.join(workdir, "commit_storm", "out")
    staging = os.path.join(workdir, "commit_storm", "staging")
    dag = _build_sink_dag(
        "commitstorm", out_dir,
        fault_spec=f"commit.publish:delay:ms={delay_ms},n=1", fault_seed=1,
        trace=trace)
    plan = dag.create_dag_plan()
    conf = C.TezConfiguration({"tez.staging-dir": staging,
                               "tez.am.local.num-containers": 4})
    am1 = DAGAppMaster(app_id, conf, attempt=1)
    am1.start()
    am1.submit_dag(plan)
    deadline = time.time() + timeout
    while time.time() < deadline:
        if am1.logging_service.of_type(HistoryEventType.DAG_COMMIT_STARTED):
            break
        time.sleep(0.02)
    else:
        am1.stop()
        return False, "DAG_COMMIT_STARTED never observed"
    am1.stop()   # crash inside the COMMIT_STARTED..COMMIT_FINISHED window

    am2 = DAGAppMaster(app_id, conf, attempt=2)
    am2.start()
    try:
        recovered = am2.recover_and_resume()
        if recovered is None:
            return False, "successor AM recovered nothing"
        final = am2.wait_for_dag(recovered, timeout=timeout)
        finished = am2.logging_service.of_type(
            HistoryEventType.DAG_COMMIT_FINISHED)
    finally:
        am2.stop()
    if final is not DAGState.SUCCEEDED:
        return False, (f"recovered DAG finished {final}; "
                       f"{_fsck_summary(staging, app_id)}")
    if not finished:
        return False, "resumed commit never journaled DAG_COMMIT_FINISHED"
    got = read_published(out_dir)
    if "_temporary" in got:
        return False, (f"orphaned _temporary tree left in output dir; "
                       f"{_fsck_summary(staging, app_id)}")
    if got != baseline:
        return False, (f"published output diverged from baseline "
                       f"({sorted(got)} vs {sorted(baseline)}); "
                       f"{_fsck_summary(staging, app_id)}")
    return True, (f"bit-exact after mid-commit AM kill "
                  f"({len(got) - 1} part file(s) + _SUCCESS)")


def run_am_kill(seed: int, workdir: str,
                timeout: float = 120.0) -> Tuple[bool, str]:
    """AM crash-survival scenario (``make chaos-ha``). Returns (ok, detail).

    Leg 1 — admission-queue replay + re-attach.  A session AM
    (max-concurrent-dags=1) takes tenant A's DAG mid-run (a seeded
    ``task.run`` delay keeps it running) while tenants B and C park in
    the admission queue; with >=2 submissions queued and one mid-run the
    AM is SIGKILLed (``crash()`` — no graceful resolution, no terminal
    records).  Parked submitters must observe a typed
    :class:`AMCrashedError`.  The client then ``reattach()``s: the
    successor incarnation replays the journal, resubmits A under its old
    dag_id, and requeues B and C from their unresolved ``DAG_QUEUED``
    records.  Every DAG — mid-run and queued alike — must complete
    bit-exact vs its tenant's fault-free baseline, with exactly two
    ``DAG_REQUEUED_ON_RECOVERY`` records journaled.  A zombie heartbeat
    stamped with the dead incarnation's epoch must be fenced
    (``should_die``) and journaled as ``ATTEMPT_FENCED``.

    Leg 2 — coded push replicas.  A multi-spill push DAG runs with
    ``tez.runtime.shuffle.push.replicas=2`` while a seeded
    ``store.replica.lost`` fault declares a fetch's primary copies dead;
    the consumer must reconstruct from the buddy replica
    (``store.replica.failover``) with ZERO producer re-execution —
    enforced hard by ``tez.am.task.max.failed.attempts=1`` plus an exact
    attempt count off the history journal."""
    import threading

    from tez_tpu.am.history import HistoryEventType
    from tez_tpu.am.task_comm import HeartbeatRequest
    from tez_tpu.client.errors import AMCrashedError
    from tez_tpu.common.ids import DAGId, TaskAttemptId
    from tez_tpu.store import local_buffer_store, reset_store

    reset_store()
    tenants = 3
    tenant_names = [f"tenant{t}" for t in range(tenants)]

    # fault-free per-tenant baselines, each on its own throwaway AM
    baselines: List[bytes] = []
    for t in range(tenants):
        base = os.path.join(workdir, f"hakbase{seed}-t{t}")
        result_path = os.path.join(base, "result.txt")
        os.makedirs(base, exist_ok=True)
        client = TezClient.create(f"hakbase{t}", {
            "tez.staging-dir": os.path.join(base, "staging"),
            "tez.am.local.num-containers": 4}).start()
        try:
            dag = _build_tenant_dag(f"hakbase{seed}-t{t}", result_path,
                                    salt=t)
            status = client.submit_dag(dag).wait_for_completion(
                timeout=timeout)
        finally:
            client.stop()
        if status.state.name != DAGStatusState.SUCCEEDED.name or \
                not os.path.exists(result_path):
            return False, (f"tenant {t} baseline failed "
                           f"(state={status.state.name})")
        with open(result_path, "rb") as fh:
            baselines.append(fh.read())

    storm_dir = os.path.join(workdir, f"amkill{seed}")
    results_dir = os.path.join(storm_dir, "results")
    staging = os.path.join(storm_dir, "staging")
    os.makedirs(results_dir, exist_ok=True)
    session_conf = {
        "tez.staging-dir": staging,
        "tez.am.local.num-containers": 4,
        # ONE slot: A occupies it mid-run, B and C must park in the queue
        "tez.am.session.max-concurrent-dags": 1,
        "tez.am.session.queue-size": 8,
    }
    client = TezClient.create(f"amkill{seed}", session_conf,
                              session=True).start()
    crashed_errors: List[str] = []
    thread_errors: List[str] = []

    def parked_submitter(t: int) -> None:
        tenant = tenant_names[t]
        name = f"{tenant}-hak{seed}"
        result_path = os.path.join(results_dir, f"{name}.txt")
        dag = _build_tenant_dag(name, result_path, salt=t, tenant=tenant)
        try:
            client.submit_dag(dag)
        except AMCrashedError:
            crashed_errors.append(name)
        except Exception as e:  # noqa: BLE001 — wrong type is a failure
            thread_errors.append(f"{name}: {e!r}")
        else:
            thread_errors.append(f"{name}: promoted before the crash")

    ok = False
    try:
        # tenant A mid-run: one producer parks on a seeded task delay long
        # enough to hold the single slot through the kill window
        name_a = f"tenant0-hak{seed}"
        result_a = os.path.join(results_dir, f"{name_a}.txt")
        dag_a = _build_tenant_dag(
            name_a, result_a, salt=0, tenant=tenant_names[0],
            fault_spec="task.run:delay:ms=4000,n=1", fault_seed=seed)
        dc_a = client.submit_dag(dag_a)

        threads = [threading.Thread(target=parked_submitter, args=(t,),
                                    name=f"hak-submitter-{t}", daemon=True)
                   for t in (1, 2)]
        for th in threads:
            th.start()
        # wait for the parked submissions' DAG_QUEUED records to LAND (the
        # queue-depth gauge goes up before the journal append finishes —
        # crashing in that window would race the lossless ledger)
        am1 = client.framework_client.am
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(am1.logging_service.of_type(
                    HistoryEventType.DAG_QUEUED)) >= 2:
                break
            time.sleep(0.02)
        else:
            return False, "B/C never reached the admission queue"

        am1.crash()
        for th in threads:
            th.join(timeout=timeout)
        if thread_errors:
            return False, ("parked submitters did not fail typed: "
                           + "; ".join(thread_errors[:2]))
        if len(crashed_errors) != 2:
            return False, (f"expected 2 typed AMCrashedError losses, "
                           f"got {len(crashed_errors)}")

        client.reattach()
        am2 = client.framework_client.am
        requeued = am2.logging_service.of_type(
            HistoryEventType.DAG_REQUEUED_ON_RECOVERY)
        if len(requeued) != 2:
            return False, (f"{len(requeued)} DAG_REQUEUED_ON_RECOVERY "
                           f"record(s), expected 2; "
                           f"{_fsck_summary(staging, am2.app_id)}")

        # zombie fencing: a heartbeat stamped with the dead incarnation's
        # epoch must be told to die and leave a typed journal record
        zombie = TaskAttemptId(DAGId(am2.app_id, 1).vertex(0).task(0), 0)
        resp = am2.task_comm.heartbeat(HeartbeatRequest(
            attempt_id=zombie, events=[], epoch=1))
        if not resp.should_die:
            return False, "stale-epoch heartbeat was not fenced"
        if am2.task_comm.fenced_count < 1:
            return False, "fence was not counted"
        if not am2.logging_service.of_type(HistoryEventType.ATTEMPT_FENCED):
            return False, "fence left no ATTEMPT_FENCED journal record"

        # the mid-run DAG completes on its ORIGINAL handle, re-bound by
        # reattach; the queued DAGs are re-attached by name
        state = dc_a.wait_for_completion(timeout=timeout).state.name
        if state != DAGStatusState.SUCCEEDED.name:
            return False, (f"recovered mid-run DAG finished {state}; "
                           f"{_fsck_summary(staging, am2.app_id)}")
        for t in (1, 2):
            name = f"{tenant_names[t]}-hak{seed}"
            dc = client.attach_dag(name, timeout=timeout)
            state = dc.wait_for_completion(timeout=timeout).state.name
            if state != DAGStatusState.SUCCEEDED.name:
                return False, f"replayed DAG {name} finished {state}"
        for t in range(tenants):
            path = os.path.join(results_dir,
                                f"{tenant_names[t]}-hak{seed}.txt")
            got = b""
            if os.path.exists(path):
                with open(path, "rb") as fh:
                    got = fh.read()
            if got != baselines[t]:
                return False, (f"tenant {t} output diverged after replay "
                               f"({len(got)} vs {len(baselines[t])} bytes)")
        ok = True
    finally:
        client.stop()
        faults.clear_all()
        reset_store()
    if not ok:
        return False, "unreachable"

    # ---- leg 2: coded push replicas outlive a dead primary store --------
    reset_store()
    try:
        state, baseline = _run_dag(workdir, f"replbase{seed}",
                                   timeout=timeout,
                                   extra_conf={"tez.runtime.io.sort.mb": 1},
                                   producer_cls=ChaosPushEmitProcessor)
        if state != DAGStatusState.SUCCEEDED.name or not baseline:
            return False, f"replica baseline failed (state={state})"
        name = f"replkill{seed}"
        result_path = os.path.join(workdir, name, "result.txt")
        os.makedirs(os.path.dirname(result_path), exist_ok=True)
        conf = {
            "tez.staging-dir": os.path.join(workdir, name, "staging"),
            "tez.am.local.num-containers": 4,
            # ZERO retry headroom: any producer/consumer re-execution
            # fails the DAG outright, so SUCCEEDED proves the failover
            # reconstructed from the replica without re-running anything
            "tez.am.task.max.failed.attempts": 1,
            "tez.runtime.io.sort.mb": 1,
            "tez.runtime.shuffle.push.enabled": True,
            "tez.runtime.shuffle.push.replicas": 2,
            "tez.runtime.store.enabled": True,
            "tez.runtime.store.lineage.reuse": False,
        }
        rclient = TezClient.create(name, conf).start()
        try:
            dag = _build_dag(name, result_path,
                             fault_spec="store.replica.lost:fail:n=1",
                             fault_seed=seed,
                             producer_cls=ChaosPushEmitProcessor)
            dc = rclient.submit_dag(dag)
            state = dc.wait_for_completion(timeout=timeout).state.name
            attempts = len(rclient.framework_client.am.logging_service
                           .of_type(HistoryEventType.TASK_ATTEMPT_STARTED))
            store = local_buffer_store()
            sc = store.stats()["counters"] if store is not None else {}
        finally:
            rclient.stop()
            faults.clear_all()
        if state != DAGStatusState.SUCCEEDED.name:
            return False, (f"replica-failover DAG finished {state} "
                           f"(failover={sc.get('store.replica.failover', 0)})")
        got = b""
        if os.path.exists(result_path):
            with open(result_path, "rb") as fh:
                got = fh.read()
        if got != baseline:
            return False, (f"replica-failover output diverged "
                           f"({len(got)} vs {len(baseline)} bytes)")
        if attempts != NUM_PRODUCERS + 1:
            return False, (f"{attempts} task attempts ran, expected "
                           f"{NUM_PRODUCERS + 1} — a producer re-executed")
        if sc.get("store.replica.bytes", 0) < 1:
            return False, "no replica bytes were ever published"
        if sc.get("store.replica.failover", 0) < 1:
            return False, ("store.replica.lost never forced a failover — "
                           "the fault did not bite")
        return True, (f"2 requeued + mid-run replayed bit-exact, zombie "
                      f"fenced; replica leg bit-exact with "
                      f"{sc['store.replica.failover']} failover(s), "
                      f"{sc['store.replica.bytes']} replica byte(s), "
                      f"0 re-executions")
    finally:
        reset_store()


# ----------------------------------------------------------- stream kill

def _build_stream_template(name: str, parallelism: int = 2,
                           fault_spec: str = "",
                           fault_seed: int = 0) -> "DAG":
    """Window DAG template: StreamWindowSourceProcessor striping the
    sealed spool into a scatter-gather edge, StreamWindowSinkProcessor
    grouping it into a window-tagged tmp part file.  The driver clones it
    per window; a fault spec set here rides every window's dag_conf, so
    each window arms its own seeded fault scope."""
    from tez_tpu.library.streaming import (StreamWindowSinkProcessor,
                                           StreamWindowSourceProcessor)
    source = Vertex.create("source", ProcessorDescriptor.create(
        StreamWindowSourceProcessor), parallelism)
    sink = Vertex.create("sink", ProcessorDescriptor.create(
        StreamWindowSinkProcessor), 1)
    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "long"}
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=conf))
    dag = DAG.create(name).add_vertex(source).add_vertex(sink)
    dag.add_edge(Edge.create(source, sink, prop))
    if fault_spec:
        dag.set_conf("tez.test.fault.spec", fault_spec)
        dag.set_conf("tez.test.fault.seed", fault_seed)
    return dag


def _stream_records(seed: int, tenant: int, n: int) -> List[Dict[str, Any]]:
    """Deterministic per-tenant record feed: same (seed, tenant) -> same
    records, so the storm leg and the fault-free baseline ingest
    byte-identical windows."""
    rng = random.Random(seed * 1000 + tenant)
    return [{"k": f"t{tenant}key{i % 7}", "v": rng.randint(1, 100)}
            for i in range(n)]


def _stream_outputs(out_dir: str) -> Dict[str, bytes]:
    """Committed (final-named) window part files only — hidden .tmp files
    are pre-commit scratch and may legitimately differ after a crash."""
    out: Dict[str, bytes] = {}
    if not os.path.isdir(out_dir):
        return out
    for name in sorted(os.listdir(out_dir)):
        p = os.path.join(out_dir, name)
        if not name.startswith(".") and os.path.isfile(p):
            with open(p, "rb") as fh:
                out[name] = fh.read()
    return out


def run_stream_kill(seed: int, workdir: str, timeout: float = 120.0,
                    tenants: int = 3) -> Tuple[bool, str]:
    """Streaming chaos scenario (``make chaos-stream``). Returns (ok,
    detail).

    One session AM holds ``tenants`` resident streams.  Each stream's
    window template carries a seeded ``task.run`` fail fault, so task
    attempts die mid-window and are retried inside their window; after
    every stream has at least one ``WINDOW_COMMIT_FINISHED`` the AM is
    crashed mid-stream (``crash()`` — nothing graceful journaled) with
    sealed-but-uncommitted windows and a half-filled open spool on disk.
    A successor incarnation resumes every stream from the commit ledger,
    window-exact replays the uncommitted sealed windows, keeps the open
    spool's ingested records, takes the rest of the feed, and drains.

    Asserted: every committed window is bit-exact vs a fault-free
    baseline of the same feed (same cuts, same totals), the threaded
    recovery journals fsck clean with exactly ONE WINDOW_COMMIT_FINISHED
    per (stream, window) across both incarnations, and post-recovery lag
    stays inside ``tez.runtime.stream.max-lag``."""
    from tez_tpu.am.app_master import DAGAppMaster
    from tez_tpu.am.history import HistoryEventType
    from tez_tpu.am.recovery import decode_journal_line
    from tez_tpu.am.streaming import StreamSpec
    from tez_tpu.common import config as C
    from tez_tpu.common import epoch as epoch_registry
    from tez_tpu.store import reset_store
    from tez_tpu.tools import journal_fsck

    window_count = 6
    max_lag = 4
    phase1, total = 18, 27      # crash lands between w3's cut and drain
    stream_names = [f"s{t}" for t in range(tenants)]
    feeds = {t: _stream_records(seed, t, total) for t in range(tenants)}

    def session_conf(staging: str) -> "C.TezConfiguration":
        return C.TezConfiguration({
            "tez.staging-dir": staging,
            "tez.am.local.num-containers": 4,
            # one slot per stream so windows of different streams overlap
            "tez.am.session.max-concurrent-dags": tenants,
            "tez.am.session.queue-size": 32,
            "tez.runtime.stream.window.count": window_count,
            "tez.runtime.stream.max-lag": max_lag,
        })

    def make_spec(t: int, out_root: str, fault: bool) -> "StreamSpec":
        name = stream_names[t]
        dag = _build_stream_template(
            f"{name}-template",
            fault_spec="task.run:fail:n=1,exc=runtime" if fault else "",
            fault_seed=seed * 10 + t)
        return StreamSpec(name=name, plan=dag.create_dag_plan(),
                          output_dir=os.path.join(out_root, name))

    # ---- fault-free baseline: same feeds, no faults, no crash ----------
    reset_store()
    base_root = os.path.join(workdir, f"skbase{seed}")
    base_out = os.path.join(base_root, "out")
    am = DAGAppMaster(f"app_1_skb{seed}",
                      session_conf(os.path.join(base_root, "staging")),
                      attempt=1)
    am.start()
    try:
        drivers = {t: am.open_stream(make_spec(t, base_out, fault=False))
                   for t in range(tenants)}
        for t, driver in drivers.items():
            driver.ingest(feeds[t])
        for driver in drivers.values():
            driver.drain(timeout=timeout)
    finally:
        am.stop()
        faults.clear_all()
        epoch_registry.reset()
        reset_store()
    baselines = {t: _stream_outputs(os.path.join(base_out, stream_names[t]))
                 for t in range(tenants)}
    for t, files in baselines.items():
        if not files:
            return False, f"stream {stream_names[t]}: baseline " \
                          f"committed no windows"

    # ---- storm leg: seeded attempt kills + one AM crash mid-stream -----
    storm_root = os.path.join(workdir, f"skill{seed}")
    storm_out = os.path.join(storm_root, "out")
    staging = os.path.join(storm_root, "staging")
    conf = session_conf(staging)
    app_id = f"app_1_skill{seed}"
    am1 = DAGAppMaster(app_id, conf, attempt=1)
    am1.start()
    crashed = False
    try:
        drivers = {t: am1.open_stream(make_spec(t, storm_out, fault=True))
                   for t in range(tenants)}
        for t, driver in drivers.items():
            driver.ingest(feeds[t][:phase1])
        deadline = time.time() + timeout
        while time.time() < deadline:
            done = {ev.data.get("stream") for ev in
                    am1.logging_service.of_type(
                        HistoryEventType.WINDOW_COMMIT_FINISHED)}
            if done >= set(stream_names):
                break
            time.sleep(0.02)
        else:
            return False, "not every stream committed a window pre-crash"
        am1.crash()
        crashed = True
    finally:
        if not crashed:
            am1.stop()
        faults.clear_all()
        epoch_registry.reset()

    am2 = DAGAppMaster(app_id, conf, attempt=2)
    am2.start()
    ok = False
    try:
        am2.recover_and_resume()
        if set(am2.streams) != set(stream_names):
            return False, (f"successor resumed streams "
                           f"{sorted(am2.streams)}, expected "
                           f"{stream_names}; "
                           f"{_fsck_summary(staging, app_id)}")
        replayed = 0
        for t in range(tenants):
            driver = am2.streams[stream_names[t]]
            replayed += len(driver.status()["replayed"])
            driver.ingest(feeds[t][phase1:])
            lag = driver.status()["lag"]
            if lag > max_lag:
                return False, (f"stream {stream_names[t]}: post-recovery "
                               f"lag {lag} over the {max_lag} bound")
        lag_episodes = 0
        for t in range(tenants):
            final = am2.streams[stream_names[t]].drain(timeout=timeout)
            lag_episodes += final["lag_episodes"]
            if final["lag"] != 0 or not final["retired"]:
                return False, (f"stream {stream_names[t]}: drained to "
                               f"{final}")
        ok = True
    finally:
        am2.stop()
        faults.clear_all()
        epoch_registry.reset()
        reset_store()
    if not ok:
        return False, "unreachable"

    # ---- bit-exact committed windows vs the fault-free baseline --------
    windows = 0
    for t in range(tenants):
        got = _stream_outputs(os.path.join(storm_out, stream_names[t]))
        if got != baselines[t]:
            return False, (f"stream {stream_names[t]}: committed windows "
                           f"diverged from baseline ({sorted(got)} vs "
                           f"{sorted(baselines[t])}); "
                           f"{_fsck_summary(staging, app_id)}")
        windows += len(got)

    # ---- exactly-once: fsck + a direct duplicate-commit count ----------
    files = journal_fsck.discover_journals(
        os.path.join(staging, app_id, "recovery"))
    report = journal_fsck.fsck_files(files)
    if not report.ok:
        return False, f"journal fsck found errors: {report.errors[:3]}"
    commits: Dict[Tuple[str, int], int] = {}
    for path in files:
        with open(path, errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = decode_journal_line(line)
                except Exception:  # noqa: BLE001 — torn tail at the crash
                    continue
                if ev.event_type.name == "WINDOW_COMMIT_FINISHED":
                    key = (str(ev.data.get("stream")),
                           int(ev.data.get("window_id", 0)))
                    commits[key] = commits.get(key, 0) + 1
    dupes = {k: n for k, n in commits.items() if n != 1}
    if dupes:
        return False, (f"duplicate WINDOW_COMMIT_FINISHED across "
                       f"incarnations: {dupes}")
    if len(commits) != windows:
        return False, (f"{len(commits)} committed windows journaled vs "
                       f"{windows} published")
    return True, (f"{windows} window(s) bit-exact over {tenants} streams "
                  f"after mid-window attempt kills + mid-stream AM crash; "
                  f"{replayed} window-exact replay(s), 0 duplicate "
                  f"commits, {lag_episodes} lag episode(s), lag bounded "
                  f"by {max_lag}")


class RampSinkProcessor(SimpleProcessor):
    """Slow-burn sink for the SLO-burn leg: sleeps ``base + step ×
    window_id`` ms before grouping, so each successive window's
    cut→commit latency climbs a deterministic ramp — exactly the shape
    burn-rate alerting exists for (degrading, not yet breached)."""

    def run(self, inputs: Dict[str, Any], outputs: Dict[str, Any]) -> None:
        from tez_tpu.library.streaming import StreamWindowSinkProcessor
        conf = self.context.conf
        base = float(conf.get("tez.test.ramp.base-ms", 0) or 0)
        step = float(conf.get("tez.test.ramp.step-ms", 0) or 0)
        time.sleep((base + step * self.context.window_id) / 1000.0)
        StreamWindowSinkProcessor.run(self, inputs, outputs)


def _build_ramp_template(name: str, base_ms: float, step_ms: float) -> "DAG":
    """Window template whose sink latency ramps with the window id."""
    from tez_tpu.library.streaming import StreamWindowSourceProcessor
    source = Vertex.create("source", ProcessorDescriptor.create(
        StreamWindowSourceProcessor), 2)
    sink = Vertex.create("sink", ProcessorDescriptor.create(
        RampSinkProcessor), 1)
    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "long"}
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=conf))
    dag = DAG.create(name).add_vertex(source).add_vertex(sink)
    dag.add_edge(Edge.create(source, sink, prop))
    dag.set_conf("tez.test.ramp.base-ms", base_ms)
    dag.set_conf("tez.test.ramp.step-ms", step_ms)
    return dag


def run_slo_burn(seed: int, workdir: str, timeout: float = 120.0
                 ) -> Tuple[bool, str]:
    """Burn-before-breach scenario (``make chaos-slo-burn``).  Returns
    (ok, detail).

    One resident stream whose sink latency ramps ~100 ms per window
    (seed-jittered) against a 900 ms window-p95 SLO with burn alerting
    at 50% of target.  The telemetry sampler snapshots the latency
    series into windowed rings; fast-window p95 crosses ``threshold ×
    target`` several windows before the cumulative p95 crosses the
    target itself, so the journal must show the typed ``SLO_BURN_ALERT``
    strictly before the ``TENANT_SLO_BREACH`` for the same
    (kind, stream) — the alert pages while there is still error budget.

    Asserted: burn alert present and strictly earlier than the breach
    for stream "ramp"; journal fscks clean (exercising the SLO ledger's
    label checks); doctor's alert→breach join reports a positive lead
    time; the graceful stop journals a ``TELEMETRY_SNAPSHOT`` with zero
    scrape/collector errors."""
    from tez_tpu.am.app_master import DAGAppMaster
    from tez_tpu.am.history import HistoryEventType
    from tez_tpu.am.recovery import decode_journal_line
    from tez_tpu.am.streaming import StreamSpec
    from tez_tpu.common import config as C
    from tez_tpu.common import epoch as epoch_registry
    from tez_tpu.common import metrics
    from tez_tpu.obs import timeseries
    from tez_tpu.store import reset_store
    from tez_tpu.tools import doctor, journal_fsck

    rng = random.Random(seed)
    per_window = 3
    windows = 6
    base_ms = 60.0 + rng.uniform(0.0, 20.0)
    step_ms = 100.0 + rng.uniform(0.0, 10.0)
    records = [{"k": f"key{i % 5}", "v": rng.randint(1, 100)}
               for i in range(per_window * windows)]

    root = os.path.join(workdir, f"sloburn{seed}")
    staging = os.path.join(root, "staging")
    out_dir = os.path.join(root, "out")
    conf = C.TezConfiguration({
        "tez.staging-dir": staging,
        "tez.am.local.num-containers": 3,
        "tez.runtime.stream.window.count": per_window,
        # the ramp crosses 50% of target (burn) several windows before
        # the cumulative p95 crosses the target (breach)
        "tez.am.slo.window.p95-ms": 900.0,
        "tez.am.slo.min-count": 3,
        "tez.am.slo.burn.threshold": 0.5,
        "tez.am.slo.burn.fast-window-s": 30.0,
        "tez.am.slo.burn.slow-window-s": 120.0,
        "tez.am.slo.burn.min-count": 2,
        "tez.am.metrics.sample-period-ms": 25.0,
    })

    metrics.registry().reset()
    timeseries.reset()
    reset_store()
    app_id = f"app_1_sloburn{seed}"
    am = DAGAppMaster(app_id, conf, attempt=1)
    am.start()
    try:
        spec = StreamSpec(
            name="ramp",
            plan=_build_ramp_template("ramp-template", base_ms,
                                      step_ms).create_dag_plan(),
            output_dir=out_dir)
        driver = am.open_stream(spec)
        # pre-register the window-latency histograms and take one
        # baseline ring sample while both are still all-zero: the
        # windowed delta is computed against the oldest ring point, so
        # without this the first window's latency would be invisible to
        # burn evaluation and the alert could only fire after the
        # cumulative breach — the opposite of what this leg asserts
        metrics.registry().histogram("stream.window.latency")
        metrics.registry().histogram(f"stream.{spec.name}.window.latency")
        am.telemetry.tick()
        deadline = time.time() + timeout
        for w in range(windows):
            driver.ingest(records[w * per_window:(w + 1) * per_window])
            while time.time() < deadline:
                done = am.logging_service.of_type(
                    HistoryEventType.WINDOW_COMMIT_FINISHED)
                if len(done) > w:
                    break
                time.sleep(0.02)
            else:
                return False, f"window {w + 1} never committed"
            # a deterministic sampler tick between commits: the burn
            # evaluator always sees window N's latency before window
            # N+1 can push the cumulative histogram over the target
            am.telemetry.tick()
        final = driver.drain(timeout=timeout)
        if not final["retired"] or len(final["committed"]) != windows:
            return False, f"stream drained to {final}"
    finally:
        am.stop()
        epoch_registry.reset()
        reset_store()

    # ---- journal ordering: the page precedes the breach ----------------
    files = journal_fsck.discover_journals(
        os.path.join(staging, app_id, "recovery"))
    burn_t: List[float] = []
    breach_t: List[float] = []
    snapshots: List[Dict[str, Any]] = []
    for path in files:
        with open(path, errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = decode_journal_line(line)
                except Exception:  # noqa: BLE001 — torn tail
                    continue
                name = ev.event_type.name
                if name == "SLO_BURN_ALERT" \
                        and ev.data.get("stream") == "ramp":
                    burn_t.append(ev.timestamp)
                elif name == "TENANT_SLO_BREACH" \
                        and ev.data.get("stream") == "ramp":
                    breach_t.append(ev.timestamp)
                elif name == "TELEMETRY_SNAPSHOT":
                    snapshots.append(dict(ev.data))
    if not burn_t:
        return False, "no SLO_BURN_ALERT journaled for stream ramp"
    if not breach_t:
        return False, "ramp never breached (no TENANT_SLO_BREACH)"
    lead = min(breach_t) - min(burn_t)
    if lead <= 0:
        return False, (f"burn alert did NOT precede the breach "
                       f"(lead {lead:+.3f}s)")

    # ---- fsck understands the SLO records ------------------------------
    report = journal_fsck.fsck_files(files)
    if not report.ok:
        return False, f"journal fsck found errors: {report.errors[:3]}"
    key = ("*", "window_p95_ms", "ramp")
    led = report.slo.get(key)
    if not led or not led["burn_alerts"] or not led["breaches"]:
        return False, f"fsck SLO ledger missing {key}: {dict(report.slo)}"

    # ---- doctor joins the alert to its breach --------------------------
    joined = doctor.join_burn_alerts(doctor.load_slo_burn_alerts(files),
                                     doctor.load_slo_breaches(files))
    ramp = [a for a in joined if a.get("stream") == "ramp"]
    if not ramp or not any(a["breached"] and (a["lead_s"] or 0) > 0
                           for a in ramp):
        return False, f"doctor burn→breach join failed: {ramp}"

    # ---- graceful stop accounted for the plane -------------------------
    if not snapshots:
        return False, "graceful stop journaled no TELEMETRY_SNAPSHOT"
    acct = snapshots[-1]
    if acct.get("scrape_errors") or acct.get("collector_errors"):
        return False, f"telemetry plane unhealthy at stop: {acct}"

    return True, (f"{windows} windows ramped {base_ms:.0f}"
                  f"+{step_ms:.0f}ms/w; burn alert paged {lead:.3f}s "
                  f"before the breach, fsck clean, "
                  f"{acct.get('series', 0)} series at stop")


# ------------------------------------------------------------ query storm

#: Recoverable per-query faults for the query storm: every kill stays
#: inside the retry envelope (max.failed.attempts=4), so a wrong or
#: missing row is always a query-engine bug, never storm overreach.
QUERY_STORM_MENU = (
    "task.run:fail:n=1,exc=runtime",
    "task.run:fail:n=2,exc=runtime",
    "shuffle.fetch.read:fail:n=1,exc=io",
)


def run_query_storm(seed: int, workdir: str,
                    timeout: float = 120.0) -> Tuple[bool, str]:
    """Corpus queries under seeded task kills with the result cache on.
    Returns (ok, detail).

    One resident QuerySession (store enabled, so the PR-7 sealed-lineage
    store serves the PR-11 governed result cache) runs the whole
    tools/query_corpus.py suite twice — even seeds on the uniform
    corpus, odd on the Zipf-skewed one — with every DAG carrying a
    seeded recoverable task/fetch kill and an alternating tenant tag.
    Replanning is pinned off so both rounds lower to byte-identical
    vertices: round 2 must be served partly from sealed lineage.  The
    contract under all of that:

    - every query completes and its output is bit-exact vs the numpy
      oracle (the kill storm may cost retries, never rows);
    - the storm actually killed something: at least one FAILED task
      attempt in the session AM's journal;
    - round 2 hit the sealed-lineage result cache at least once —
      cached reruns must be exactly as correct as computed ones.
    """
    from tez_tpu.am.history import HistoryEventType
    from tez_tpu.query import QuerySession
    from tez_tpu.store import reset_store
    from tez_tpu.tools.query_corpus import CORPUS_QUERIES, generate

    reset_store()
    faults.clear_all()
    storm_dir = os.path.join(workdir, f"querystorm{seed}")
    skew = 0.0 if seed % 2 == 0 else 1.1
    corpus = generate(os.path.join(storm_dir, "data"), scale=0.3,
                      skew=skew, seed=seed)
    session_conf = {
        "tez.staging-dir": os.path.join(storm_dir, "staging"),
        "tez.am.local.num-containers": 4,
        "tez.am.task.max.failed.attempts": 4,
        "tez.runtime.store.enabled": True,
        # stable plans across rounds: the replan path has its own test
        # (tests/test_query.py); here round 2 must re-lower byte-
        # identically so sealed lineage can serve it
        "tez.query.replan.enabled": False,
    }
    tenants = ("tenant0", "tenant1")
    cache_hits = 0
    try:
        with QuerySession(f"querystorm{seed}", session_conf) as session:
            for rnd in (0, 1):
                for i, q in enumerate(CORPUS_QUERIES):
                    spec = QUERY_STORM_MENU[(seed + i)
                                            % len(QUERY_STORM_MENU)]
                    out = os.path.join(storm_dir,
                                       f"out_r{rnd}_{q.name}")
                    res = session.run(
                        q.build(corpus), out, query_name=q.name,
                        sink=q.sink, timeout=timeout,
                        dag_conf={"tez.test.fault.spec": spec,
                                  "tez.test.fault.seed": seed + i,
                                  "tez.dag.tenant": tenants[i % 2]})
                    if res.state != "SUCCEEDED":
                        return False, (f"round {rnd} {q.name} "
                                       f"state={res.state} under {spec}")
                    got, want = res.read_output(), q.oracle(corpus)
                    if got != want:
                        return False, (f"round {rnd} {q.name} diverged "
                                       f"under {spec}: {len(got)} rows "
                                       f"vs oracle {len(want)}")
                    if rnd == 1:
                        cache_hits += res.cache_hits
            am = session._am
            events = list(getattr(getattr(am, "logging_service", None),
                                  "events", []) or [])
    finally:
        faults.clear_all()
    killed = sum(
        1 for ev in events
        if ev.event_type is HistoryEventType.TASK_ATTEMPT_FINISHED and
        (ev.data or {}).get("state") == "FAILED")
    if killed == 0:
        return False, "storm never killed a task attempt"
    if cache_hits == 0:
        return False, ("round 2 never hit the sealed-lineage result "
                       "cache — content-addressed reuse is broken")
    queries = len(CORPUS_QUERIES)
    return True, (f"{2 * queries} query runs bit-exact on the "
                  f"{'zipf' if skew else 'uniform'} corpus; "
                  f"{killed} attempt(s) killed, round 2 served "
                  f"{cache_hits} lineage hit(s) from the result cache")


def run_device_ooo(seed: int, spans: int = 4,
                   records: int = 1500) -> Tuple[bool, str]:
    """Out-of-order device-completion scenario: the async double-buffered
    plane (ops/async_stage.py) runs under a ``device.dispatch.delay`` fault
    that holds one seeded span's completion while later spans drain past it
    on the readback workers.  Every spill must still carry its correct
    spill id and payload — bit-exact vs the fault-free SYNCHRONOUS engine —
    and the final flush-merge must be bit-exact too."""
    import numpy as np

    from tez_tpu.ops.runformat import KVBatch
    from tez_tpu.ops.sorter import DeviceSorter

    def make_batch(i: int) -> "KVBatch":
        rng = np.random.default_rng(seed * 1000 + i)
        keys = [b"k%08d" % k for k in rng.integers(0, 500, records)]
        vals = [b"v%06d" % v for v in rng.integers(0, 999999, records)]
        kb = np.frombuffer(b"".join(keys), dtype=np.uint8)
        ko = np.cumsum([0] + [len(k) for k in keys]).astype(np.int64)
        vb = np.frombuffer(b"".join(vals), dtype=np.uint8)
        vo = np.cumsum([0] + [len(v) for v in vals]).astype(np.int64)
        return KVBatch(kb, ko, vb, vo)

    def run(depth: int, spec: str):
        if spec:
            faults.install("chaos", faults.parse_spec(spec), seed=seed)
        try:
            spills: Dict[int, tuple] = {}
            s = DeviceSorter(num_partitions=4, engine="device",
                             device_min_records=0, key_width=16,
                             span_budget_bytes=20_000, pipeline_depth=depth)
            s.on_spill = lambda run_, sid: spills.update(
                {sid: (run_.batch.key_bytes.tobytes(),
                       run_.batch.val_bytes.tobytes(),
                       run_.row_index.tobytes())})
            for i in range(spans):
                s.write_batch(make_batch(i))
            s.flush_run()
        finally:
            faults.install("chaos", [])
        return spills, list(spills)

    def run_merged(depth: int, spec: str) -> tuple:
        if spec:
            faults.install("chaos", faults.parse_spec(spec), seed=seed)
        try:
            s = DeviceSorter(num_partitions=4, engine="device",
                             device_min_records=0, key_width=16,
                             span_budget_bytes=20_000, pipeline_depth=depth)
            for i in range(spans):
                s.write_batch(make_batch(i))
            r = s.flush_run()
        finally:
            faults.install("chaos", [])
        return (r.batch.key_bytes.tobytes(), r.batch.val_bytes.tobytes(),
                r.row_index.tobytes())

    delayed = random.Random(seed).randrange(spans)
    spec = f"device.dispatch.delay:delay:ms=400,n=1,match=span={delayed}"
    sync_spills, _ = run(0, "")
    async_spills, order = run(2, spec)
    if order and order[-1] != delayed:
        return False, (f"delayed span {delayed} was not last to complete "
                       f"(order {order}) — delay fault did not bite")
    if async_spills != sync_spills:
        bad = [k for k in sync_spills
               if async_spills.get(k) != sync_spills[k]]
        return False, (f"spill payloads diverge (spill ids {bad}); "
                       f"completion order {order}")
    if run_merged(2, spec) != run_merged(0, ""):
        return False, "flush-merged output diverges from sync engine"
    return True, (f"delayed span {delayed}; completion order {order}; "
                  f"{spans} spills + merged run bit-exact")


def _chaos_batch(seed: int, i: int, records: int) -> "object":
    """Deterministic ragged KVBatch shared by the device containment
    scenarios (same recipe as run_device_ooo's make_batch)."""
    import numpy as np

    from tez_tpu.ops.runformat import KVBatch
    rng = np.random.default_rng(seed * 1000 + i)
    keys = [b"k%08d" % k for k in rng.integers(0, 500, records)]
    vals = [b"v%06d" % v for v in rng.integers(0, 999999, records)]
    kb = np.frombuffer(b"".join(keys), dtype=np.uint8)
    ko = np.cumsum([0] + [len(k) for k in keys]).astype(np.int64)
    vb = np.frombuffer(b"".join(vals), dtype=np.uint8)
    vo = np.cumsum([0] + [len(v) for v in vals]).astype(np.int64)
    return KVBatch(kb, ko, vb, vo)


def run_device_hang(seed: int, spans: int = 4,
                    records: int = 1500) -> Tuple[bool, str]:
    """Hung-dispatch containment scenario: one seeded span's device
    dispatch hangs (``device.dispatch.hang`` delay fault, well past the
    watchdog deadline).  The watchdog must abandon the attempt, fail the
    span over to the host engine, and drain the remaining spans — every
    spill bit-exact vs the fault-free SYNCHRONOUS engine, flush() bounded,
    and the breaker untouched (one hang is containment's job, not the
    breaker's)."""
    from tez_tpu.ops.async_stage import CircuitBreaker
    from tez_tpu.ops.sorter import DeviceSorter

    def run(depth: int, spec: str, breaker=None):
        if spec:
            faults.install("chaos", faults.parse_spec(spec), seed=seed)
        try:
            spills: Dict[int, tuple] = {}
            s = DeviceSorter(num_partitions=4, engine="device",
                             device_min_records=0, key_width=16,
                             span_budget_bytes=20_000, pipeline_depth=depth,
                             watchdog_dispatch_ms=250,
                             watchdog_readback_ms=250,
                             breaker=breaker)
            s.on_spill = lambda run_, sid: spills.update(
                {sid: (run_.batch.key_bytes.tobytes(),
                       run_.batch.val_bytes.tobytes(),
                       run_.row_index.tobytes())})
            for i in range(spans):
                s.write_batch(_chaos_batch(seed, i, records))
            s.flush_run()
        finally:
            faults.install("chaos", [])
        return spills, s.counters

    hung = random.Random(seed).randrange(spans)
    spec = f"device.dispatch.hang:delay:ms=2000,n=1,match=span={hung}"
    sync_spills, _ = run(0, "")
    # a scenario-local breaker with a high threshold: one hang must be
    # contained WITHOUT degrading the engine (and without poisoning the
    # process singleton for later scenarios)
    br = CircuitBreaker(failures=100)
    t0 = time.time()
    hang_spills, counters = run(2, spec, breaker=br)
    wall = time.time() - t0
    fo = counters.group("DeviceFailover")
    fires = fo.find_counter("device.watchdog.fires").value
    failed_over = fo.find_counter("device.failover.spans").value
    if fires < 1:
        return False, "watchdog never fired under the hang fault"
    if failed_over < 1:
        return False, "hung span did not fail over to the host engine"
    if br.trips != 0:
        return False, f"breaker tripped ({br.trips}) on a single hang"
    if wall > 30.0:
        return False, f"flush took {wall:.1f}s — watchdog did not bound it"
    if hang_spills != sync_spills:
        bad = [k for k in sync_spills
               if hang_spills.get(k) != sync_spills[k]]
        return False, (f"spill payloads diverge after hang failover "
                       f"(spill ids {bad})")
    return True, (f"hung span {hung} abandoned after {fires} watchdog "
                  f"fire(s); {failed_over} span(s) failed over; "
                  f"{spans} spills bit-exact in {wall:.1f}s")


def run_device_oom_storm(seed: int, spans: int = 4,
                         records: int = 1500) -> Tuple[bool, str]:
    """OOM-storm containment scenario: every device dispatch raises a
    RESOURCE_EXHAUSTED-classified error (``device.dispatch.oom`` fail
    fault, budget 4).  Span 0 must first retry split on-device (the split
    halves are under the floor, so the ladder lands on host), span 1
    likewise — tripping the 2-failure breaker — and the remaining spans
    short-circuit straight to host.  A second fault-free sorter sharing the
    breaker then recovers it through a half-open probe after the cooldown.
    Both runs bit-exact vs the fault-free sync engine."""
    from tez_tpu.ops.async_stage import CircuitBreaker
    from tez_tpu.ops.sorter import DeviceSorter

    def run_merged(depth: int, spec: str, breaker=None) -> tuple:
        if spec:
            faults.install("chaos", faults.parse_spec(spec), seed=seed)
        try:
            s = DeviceSorter(num_partitions=4, engine="device",
                             device_min_records=0, key_width=16,
                             span_budget_bytes=20_000, pipeline_depth=depth,
                             pipeline_coalesce_records=0,
                             # whole span (~24KB) splits once; the ~12KB
                             # halves sit under the floor -> host ladder
                             split_min_bytes=15_000,
                             breaker_failures=2,
                             breaker=breaker)
            for i in range(spans):
                s.write_batch(_chaos_batch(seed, i, records))
            r = s.flush_run()
        finally:
            faults.install("chaos", [])
        return (r.batch.key_bytes.tobytes(), r.batch.val_bytes.tobytes(),
                r.row_index.tobytes()), s.counters

    baseline, _ = run_merged(0, "")
    br = CircuitBreaker(failures=2, cooldown_ms=300)
    spec = "device.dispatch.oom:fail:n=4,exc=runtime"
    stormed, counters = run_merged(2, spec, breaker=br)
    fo = counters.group("DeviceFailover")
    split_attempts = fo.find_counter("device.oom.split_attempts").value
    failed_over = fo.find_counter("device.failover.spans").value
    shorted = fo.find_counter("device.breaker.short_circuits").value
    if stormed != baseline:
        return False, "merged output diverges under the OOM storm"
    if split_attempts < 1:
        return False, "no on-device split retry before host failover"
    if br.trips < 1:
        return False, (f"breaker never tripped (consecutive failures "
                       f"threshold 2; {failed_over} failovers)")
    if shorted < 1:
        return False, "no span short-circuited while the breaker was open"
    # recovery leg: cooldown elapses, a fault-free sorter sharing the
    # breaker probes half-open and re-arms the device engine
    time.sleep(0.35)
    recovered, _ = run_merged(2, "", breaker=br)
    if recovered != baseline:
        return False, "merged output diverges after breaker recovery"
    if br.recoveries < 1 or br.state != "closed":
        return False, (f"breaker did not recover via half-open probe "
                       f"(state={br.state}, recoveries={br.recoveries})")
    return True, (f"{split_attempts} split retr(ies), {failed_over} span(s) "
                  f"failed over, {shorted} short-circuited; breaker tripped "
                  f"{br.trips}x and recovered via probe; both runs bit-exact")


def run_merge_storm(seed: int, batches: int = 6,
                    records: int = 900) -> Tuple[bool, str]:
    """Reduce-side merge-lane containment scenario: every async merge
    dispatch raises a RESOURCE_EXHAUSTED-classified error
    (``device.dispatch.oom`` fail fault) while fetched runs commit one per
    merge claim.  A single-run claim has no halving point, so the OOM split
    retry declines, the merge fails over to the host engine, and the
    1-failure breaker trips; later merges short-circuit straight to host
    without touching the device.  After the cooldown a fault-free manager
    sharing the breaker recovers it through a half-open probe.  Both
    drained outputs bit-exact vs the fault-free synchronous merger."""
    from tez_tpu.common.counters import TezCounters
    from tez_tpu.library.merge_manager import ShuffleMergeManager
    from tez_tpu.ops.async_stage import CircuitBreaker
    from tez_tpu.ops.runformat import KVBatch

    def make_sorted(i: int) -> "object":
        b = _chaos_batch(seed, i, records)
        return KVBatch.from_pairs(sorted(b.iter_pairs(),
                                         key=lambda kv: kv[0]))

    data = [make_sorted(i) for i in range(batches)]
    total = sum(b.nbytes for b in data)
    workdir = tempfile.mkdtemp(prefix="tez-chaos-merge-")

    def run(tag: str, depth: int, spec: str, breaker=None, paced=False):
        if spec:
            faults.install("chaos", faults.parse_spec(spec), seed=seed)
        try:
            spill = os.path.join(workdir, tag)
            os.makedirs(spill)
            counters = TezCounters()
            mm = ShuffleMergeManager(counters, total * 4, spill,
                                     engine="device", device_min_records=0,
                                     merge_threshold=0.02,
                                     max_single_fraction=2.0,
                                     block_records=256, async_depth=depth,
                                     breaker=breaker)
            for slot, b in enumerate(data):
                mm.commit(slot, b)
                if paced:
                    # one merge claim per committed run: observe the claim
                    # before the next commit so every pipeline group holds
                    # a single live run (no OOM halving point)
                    deadline = time.time() + 20.0
                    while mm._pipe_seq < slot + 1 and \
                            time.time() < deadline:
                        time.sleep(0.005)
            result = mm.finish()
            if getattr(result, "stream", None) is not None:
                out = [(k, v) for _, k, v in result.stream.iter_records()]
            else:
                out = list(result.batch.iter_pairs())
        finally:
            if spec:
                faults.install("chaos", [])
        return out, counters

    try:
        baseline, _ = run("sync", 0, "")
        br = CircuitBreaker(failures=1, cooldown_ms=400)
        spec = "device.dispatch.oom:fail:n=99,exc=runtime"
        stormed, counters = run("storm", 2, spec, breaker=br, paced=True)
        fo = counters.group("DeviceFailover")
        split_attempts = fo.find_counter("device.oom.split_attempts").value
        failed_over = fo.find_counter("device.failover.spans").value
        shorted = fo.find_counter("device.breaker.short_circuits").value
        if stormed != baseline:
            return False, "drained output diverges under the merge OOM storm"
        if split_attempts < 1:
            return False, "no OOM split attempt before host failover"
        if failed_over < 1:
            return False, "no merge failed over to the host engine"
        if br.trips < 1:
            return False, f"breaker never tripped ({failed_over} failovers)"
        if shorted < 1:
            return False, ("no merge short-circuited while the breaker "
                           "was open")
        # recovery leg: cooldown elapses, a fault-free manager sharing the
        # breaker probes half-open and re-arms the device merge engine
        time.sleep(0.45)
        recovered, _ = run("recover", 2, "", breaker=br, paced=True)
        if recovered != baseline:
            return False, "drained output diverges after breaker recovery"
        if br.recoveries < 1 or br.state != "closed":
            return False, (f"breaker did not recover via half-open probe "
                           f"(state={br.state}, recoveries={br.recoveries})")
        return True, (f"{split_attempts} split attempt(s) declined, "
                      f"{failed_over} merge(s) failed over, {shorted} "
                      f"short-circuited; breaker tripped {br.trips}x and "
                      f"recovered via probe; {batches} runs drained "
                      f"bit-exact twice")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def run_exchange_skew(seed: int, rows: int = 24_000, producers: int = 4,
                      consumers: int = 8) -> Tuple[bool, str]:
    """Skewed-key mesh exchange with one delayed chip.  A seeded corpus
    puts ~45% of all rows in one hot consumer partition (over the round
    budget, so the coordinator's splitter must engage instead of
    re-rounding), and a ``mesh.exchange.delay`` fault stalls one device's
    shard readback for longer than the whole exchange should take.  Under
    coded r2 the buddy copy must mask the straggler: output bit-exact vs
    a fault-free padded-baseline run, no multi-round storm, at least one
    split and one buddy win, and wall time well under the injected delay."""
    import numpy as np

    import jax
    from tez_tpu.common.counters import TezCounters
    from tez_tpu.ops.host_sort import fnv_rows_host
    from tez_tpu.ops.runformat import KVBatch
    from tez_tpu.parallel.coordinator import MeshExchangeCoordinator

    if len(jax.devices()) < 2:
        return True, ("SKIPPED: exchange-skew needs >= 2 devices (run via "
                      "make chaos-exchange, which forces 8 virtual CPU "
                      "devices)")

    rng = np.random.default_rng(seed)
    hot_part = seed % consumers
    pool = rng.integers(0, 256, size=(20_000, 8), dtype=np.uint8)
    part = fnv_rows_host(pool, np.full(pool.shape[0], 8,
                                       dtype=np.int64)) % consumers
    hot_pool, cold_pool = pool[part == hot_part], pool[part != hot_part]
    n_hot = int(rows * 0.45)
    keys = np.concatenate([
        hot_pool[rng.integers(0, hot_pool.shape[0], n_hot)],
        cold_pool[rng.integers(0, cold_pool.shape[0], rows - n_hot)]])
    keys = keys[rng.permutation(rows)]
    vals = rng.integers(0, 256, size=(rows, 12), dtype=np.uint8)
    spans = []
    for i in range(producers):
        k, v = keys[i::producers], vals[i::producers]
        n = k.shape[0]
        spans.append(KVBatch(
            k.reshape(-1), np.arange(n + 1, dtype=np.int64) * 8,
            v.reshape(-1), np.arange(n + 1, dtype=np.int64) * 12))

    def run(coord, edge: str, **kw):
        for i, b in enumerate(spans):
            coord.register_producer(edge, i, producers, consumers, b,
                                    16, 16, **kw)
        return [coord.wait_consumer(edge, c, producers, consumers,
                                    timeout=60.0) for c in range(consumers)]

    def sig(res):
        return [(np.asarray(b.key_bytes).tobytes(),
                 np.asarray(b.val_bytes).tobytes()) for b in res]

    golden = sig(run(MeshExchangeCoordinator(legacy_sizing=True),
                     f"chaos-exchange-{seed}-golden/a->b",
                     engine="padded"))

    per_round = 5_000        # hot partition (~10.8k rows) is over budget
    trial = MeshExchangeCoordinator(max_rows_per_round=per_round,
                                    split_after=1)
    counters = TezCounters()
    # warm exchange, fault-free: compiles the programs AND proves the
    # splitter path on the same histogram, so the timed leg below measures
    # straggler masking, not jit compilation
    warm = sig(run(trial, f"chaos-exchange-{seed}-warm/a->b", coded="r2",
                   counters=counters))
    if warm != golden:
        return False, "fault-free coded/split run diverges from baseline"
    D = trial.devices_for(consumers)
    delayed = random.Random(seed).randrange(D)
    delay_ms = 2_500
    faults.install("chaos", faults.parse_spec(
        f"mesh.exchange.delay:delay:ms={delay_ms},n=1,"
        f"match=device={delayed}"), seed=seed)
    try:
        t0 = time.perf_counter()
        out = sig(run(trial, f"chaos-exchange-{seed}-trial/a->b",
                      coded="r2", counters=counters))
        wall = time.perf_counter() - t0
    finally:
        faults.install("chaos", [])
    if out != golden:
        return False, (f"coded output diverges from the fault-free padded "
                       f"baseline (delayed device {delayed})")
    if trial.multi_round_exchanges:
        return False, (f"multi-round storm: {trial.multi_round_exchanges} "
                       f"exchange(s) re-rounded despite the splitter")
    if trial.partition_splits < 1:
        return False, "splitter never engaged on the hot partition"
    if trial.coded_buddy_wins < 1:
        return False, (f"no buddy win: the delayed chip (device {delayed}) "
                       f"was not masked by its coded copy")
    if wall >= delay_ms / 1000.0:
        return False, (f"exchange wall {wall:.2f}s >= injected "
                       f"{delay_ms}ms delay — straggler not masked")
    return True, (f"hot partition {hot_part} split "
                  f"{trial.partition_splits}x, device {delayed} delayed "
                  f"{delay_ms}ms, masked by {trial.coded_buddy_wins} buddy "
                  f"win(s); {rows} rows bit-exact in {wall:.2f}s, "
                  f"0 multi-round exchanges")


def _export_trace(path: str) -> None:
    """Write whatever the span buffer holds (it survives per-DAG disarm) as
    Perfetto trace_event JSON, then drop the buffer."""
    from tez_tpu.common import tracing
    from tez_tpu.tools import trace_export
    spans = tracing.snapshot()
    trace_export.write_trace(trace_export.spans_to_trace(spans), path)
    print(f"trace: {len(spans)} span(s) -> {path}")
    tracing.clear_all()


def _flight_dump_scenario(tag: str, seed: Any, ok: bool) -> None:
    """--dump-flight: one snapshot per scenario so tools/doctor.py always
    has flight data; a failed scenario announces its attached artifact."""
    from tez_tpu.obs import flight
    if not flight.armed():
        return
    path = flight.plane().dump(
        f"{tag}.seed{seed}.{'ok' if ok else 'FAIL'}")
    if path is not None and not ok:
        print(f"flight: snapshot attached -> {path}")


def main(argv: Optional[List[str]] = None) -> int:
    """Every chaos scenario runs under the runtime lock-order witness
    (tez.debug.lockorder plane): nested lock acquisitions recorded during
    the storm are checked for order inversions and cross-validated
    against graftlint's static lock graph, so the soak gates acquisition
    discipline alongside bit-exactness."""
    from tez_tpu.common import lockorder
    lockorder.arm("chaos")
    try:
        rc = _dispatch(argv)
    finally:
        lockorder.disarm("chaos")
    try:
        import tez_tpu
        from tez_tpu.analysis import lockorder as static_lockorder
        from tez_tpu.analysis.core import Context
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(tez_tpu.__file__)))
        edges, locks = static_lockorder.build_graph(Context(root))
        problems = lockorder.check(set(edges), locks)
    except Exception as e:  # noqa: BLE001 — static pass must not mask rc
        print(f"WARN lock-order witness: static cross-check skipped ({e})")
        problems = lockorder.check()
    if problems:
        for p in problems:
            print(f"FAIL lock-order witness: {p}")
        return rc or 1
    print(f"ok   lock-order witness: "
          f"{len(lockorder.witness().edges())} edge(s) recorded, "
          f"0 violations")
    return rc


def _dispatch(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tez_tpu.tools.chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--seed", type=int, default=0,
                    help="first storm seed (default 0)")
    ap.add_argument("--trials", type=int, default=1,
                    help="number of consecutive seeds to soak (default 1)")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="per-DAG completion timeout in seconds")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: fresh tempdir, removed)")
    ap.add_argument("--commit-storm", action="store_true",
                    help="run the mid-commit AM-kill exactly-once scenario "
                         "instead of the seeded storm soak")
    ap.add_argument("--device-ooo", action="store_true",
                    help="run the out-of-order device-completion scenario: "
                         "the async device pipeline under a seeded "
                         "device.dispatch.delay fault, spills + merged "
                         "output bit-exact vs the sync engine")
    ap.add_argument("--device-hang", action="store_true",
                    help="run the hung-dispatch containment scenario: a "
                         "seeded device.dispatch.hang fault wedges one "
                         "span's dispatch; the watchdog abandons it and "
                         "the span fails over to the host engine bit-exact")
    ap.add_argument("--device-oom-storm", action="store_true",
                    help="run the OOM-storm containment scenario: seeded "
                         "device.dispatch.oom faults drive the split-then-"
                         "fallback ladder; the breaker trips and recovers "
                         "through a half-open probe, output bit-exact")
    ap.add_argument("--merge-storm", action="store_true",
                    help="run the reduce-side merge-lane containment "
                         "scenario: seeded device.dispatch.oom faults on "
                         "every async merge dispatch drive host failover, "
                         "trip the breaker (later merges short-circuit), "
                         "then a fault-free run recovers it via half-open "
                         "probe — drained output bit-exact vs sync")
    ap.add_argument("--store-pressure", action="store_true",
                    help="run the buffer-store eviction-storm scenario: a "
                         "wide shuffle through deliberately tiny store "
                         "tiers forces watermark demotion/eviction "
                         "mid-merge; output must stay bit-exact vs a "
                         "store-disabled baseline")
    ap.add_argument("--push-storm", action="store_true",
                    help="run the push-transport kill scenario: a seeded "
                         "shuffle.push.send pfail storm kills eager pushes "
                         "mid-map-wave; the pull backstop must keep the "
                         "output bit-exact vs a fault-free pull-only "
                         "baseline, with at least one push killed and one "
                         "landed")
    ap.add_argument("--tenant-storm", action="store_true",
                    help="run the multi-tenant session soak: one resident "
                         "session AM takes barrier-synced recurring DAGs "
                         "from --tenants submitter threads under forced "
                         "am.admit.shed / am.queue.delay faults plus "
                         "seeded task faults; every accepted DAG must "
                         "complete bit-exact vs its tenant's baseline, "
                         "shed submissions are the only (typed) losses, "
                         "store bytes stay tenant-attributed, zero epoch "
                         "fences, per-tenant p95 within --p95-bound")
    ap.add_argument("--tenants", type=int, default=3,
                    help="tenant submitter threads for --tenant-storm "
                         "(default 3)")
    ap.add_argument("--rounds", type=int, default=3,
                    help="recurring DAGs per tenant for --tenant-storm "
                         "(default 3)")
    ap.add_argument("--p95-bound", type=float, default=30.0,
                    help="per-tenant p95 completion-latency bound in "
                         "seconds for --tenant-storm (default 30)")
    ap.add_argument("--query-storm", action="store_true",
                    help="run the query-engine kill scenario: the whole "
                         "tools/query_corpus.py suite twice through one "
                         "resident QuerySession (result cache on) with "
                         "every DAG carrying a seeded recoverable "
                         "task/fetch kill and a tenant tag — all outputs "
                         "bit-exact vs the numpy oracle, at least one "
                         "attempt actually killed, and round 2 served "
                         "partly from the sealed-lineage result cache")
    ap.add_argument("--am-kill", action="store_true",
                    help="run the AM crash-survival scenario: SIGKILL the "
                         "session AM with one DAG mid-run and two parked "
                         "in the admission queue, then reattach — the "
                         "successor replays the journal, requeues the "
                         "parked submissions, fences the dead "
                         "incarnation's zombies, and every DAG completes "
                         "bit-exact; plus the coded push-replica leg "
                         "(store.replica.lost forces a buddy failover "
                         "with zero producer re-execution)")
    ap.add_argument("--stream-kill", action="store_true",
                    help="run the streaming crash-survival scenario: "
                         "--tenants resident streams on one session AM "
                         "under seeded mid-window task kills, then an AM "
                         "crash mid-stream with uncommitted sealed "
                         "windows and a half-filled open spool; the "
                         "successor window-exact replays from the commit "
                         "ledger and every committed window must be "
                         "bit-exact vs a fault-free feed, with zero "
                         "duplicate commits and bounded post-recovery "
                         "lag")
    ap.add_argument("--slo-burn", action="store_true",
                    help="run the burn-before-breach SLO scenario: one "
                         "resident stream whose per-window latency ramps "
                         "toward a window-p95 SLO target; the telemetry "
                         "sampler's multi-window burn evaluation must "
                         "journal SLO_BURN_ALERT strictly before the "
                         "TENANT_SLO_BREACH lands, journal_fsck must "
                         "account both under the same (tenant, kind, "
                         "stream) key, and the doctor must join the alert "
                         "to the breach that followed it with a positive "
                         "lead time")
    ap.add_argument("--exchange-skew", action="store_true",
                    help="run the skewed-key mesh-exchange scenario: a hot "
                         "partition over the round budget plus one chip "
                         "delayed at shard readback (mesh.exchange.delay); "
                         "the splitter must avoid the multi-round storm "
                         "and coded r2 must mask the straggler, bit-exact "
                         "vs the fault-free padded baseline")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="arm the tracing plane (tez.trace.enabled) on the "
                         "storm DAGs and write a Perfetto trace_event JSON "
                         "of the recorded spans to PATH")
    ap.add_argument("--dump-flight", action="store_true",
                    help="arm the flight recorder process-wide for the run "
                         "and dump snapshots into the workdir: auto-dumps "
                         "on every shed/breaker/watchdog trigger plus one "
                         "end-of-scenario snapshot, so every failed "
                         "scenario keeps a flight artifact and "
                         "tools/doctor.py can attribute the run (the "
                         "workdir is kept, never cleaned up)")
    args = ap.parse_args(argv)
    if args.dump_flight:
        # artifacts must survive the run: pin a kept workdir before any
        # branch computes its own throwaway tempdir
        if args.workdir is None:
            args.workdir = tempfile.mkdtemp(prefix="tez-chaos-")
            print(f"flight: workdir {args.workdir} (kept)")
        from tez_tpu.obs import flight
        flight.install("chaos", dump_dir=args.workdir, max_dumps=32)

    device_scenarios = [
        (args.device_ooo, "device-ooo", run_device_ooo),
        (args.device_hang, "device-hang", run_device_hang),
        (args.device_oom_storm, "device-oom-storm", run_device_oom_storm),
        (args.merge_storm, "merge-storm", run_merge_storm),
        (args.exchange_skew, "exchange-skew", run_exchange_skew),
    ]
    if any(on for on, _, _ in device_scenarios):
        failures = 0
        for on, tag, fn in device_scenarios:
            if not on:
                continue
            for seed in range(args.seed, args.seed + args.trials):
                ok, detail = fn(seed)
                print(("ok   " if ok else "FAIL ") +
                      f"{tag} seed={seed}: {detail}")
                _flight_dump_scenario(tag, seed, ok)
                if not ok:
                    failures += 1
                    print(f"REPRO: python -m tez_tpu.tools.chaos --{tag} "
                          f"--seed {seed}")
        return 1 if failures else 0
    workdir = args.workdir or tempfile.mkdtemp(prefix="tez-chaos-")
    cleanup = args.workdir is None
    if args.store_pressure:
        failures = 0
        try:
            for seed in range(args.seed, args.seed + args.trials):
                ok, detail = run_store_pressure(seed, workdir,
                                                timeout=args.timeout)
                print(("ok   " if ok else "FAIL ") +
                      f"store-pressure seed={seed}: {detail}")
                _flight_dump_scenario("store-pressure", seed, ok)
                if not ok:
                    failures += 1
                    print(f"REPRO: python -m tez_tpu.tools.chaos "
                          f"--store-pressure --seed {seed}")
        finally:
            if cleanup:
                shutil.rmtree(workdir, ignore_errors=True)
        return 1 if failures else 0
    if args.tenant_storm:
        failures = 0
        try:
            for seed in range(args.seed, args.seed + args.trials):
                ok, detail = run_tenant_storm(seed, workdir,
                                              timeout=args.timeout,
                                              tenants=args.tenants,
                                              rounds=args.rounds,
                                              p95_bound_s=args.p95_bound)
                print(("ok   " if ok else "FAIL ") +
                      f"tenant-storm seed={seed}: {detail}")
                _flight_dump_scenario("tenant-storm", seed, ok)
                if not ok:
                    failures += 1
                    print(f"REPRO: python -m tez_tpu.tools.chaos "
                          f"--tenant-storm --seed {seed}")
        finally:
            if cleanup:
                shutil.rmtree(workdir, ignore_errors=True)
        return 1 if failures else 0
    if args.query_storm:
        failures = 0
        try:
            for seed in range(args.seed, args.seed + args.trials):
                ok, detail = run_query_storm(seed, workdir,
                                             timeout=args.timeout)
                print(("ok   " if ok else "FAIL ") +
                      f"query-storm seed={seed}: {detail}")
                _flight_dump_scenario("query-storm", seed, ok)
                if not ok:
                    failures += 1
                    print(f"REPRO: python -m tez_tpu.tools.chaos "
                          f"--query-storm --seed {seed}")
        finally:
            if cleanup:
                shutil.rmtree(workdir, ignore_errors=True)
        return 1 if failures else 0
    if args.am_kill:
        failures = 0
        try:
            for seed in range(args.seed, args.seed + args.trials):
                ok, detail = run_am_kill(seed, workdir,
                                         timeout=args.timeout)
                print(("ok   " if ok else "FAIL ") +
                      f"am-kill seed={seed}: {detail}")
                _flight_dump_scenario("am-kill", seed, ok)
                if not ok:
                    failures += 1
                    print(f"REPRO: python -m tez_tpu.tools.chaos "
                          f"--am-kill --seed {seed}")
        finally:
            if cleanup:
                shutil.rmtree(workdir, ignore_errors=True)
        return 1 if failures else 0
    if args.stream_kill:
        failures = 0
        try:
            for seed in range(args.seed, args.seed + args.trials):
                ok, detail = run_stream_kill(seed, workdir,
                                             timeout=args.timeout,
                                             tenants=args.tenants)
                print(("ok   " if ok else "FAIL ") +
                      f"stream-kill seed={seed}: {detail}")
                _flight_dump_scenario("stream-kill", seed, ok)
                if not ok:
                    failures += 1
                    print(f"REPRO: python -m tez_tpu.tools.chaos "
                          f"--stream-kill --seed {seed}")
        finally:
            if cleanup:
                shutil.rmtree(workdir, ignore_errors=True)
        return 1 if failures else 0
    if args.slo_burn:
        failures = 0
        try:
            for seed in range(args.seed, args.seed + args.trials):
                ok, detail = run_slo_burn(seed, workdir,
                                          timeout=args.timeout)
                print(("ok   " if ok else "FAIL ") +
                      f"slo-burn seed={seed}: {detail}")
                _flight_dump_scenario("slo-burn", seed, ok)
                if not ok:
                    failures += 1
                    print(f"REPRO: python -m tez_tpu.tools.chaos "
                          f"--slo-burn --seed {seed}")
        finally:
            if cleanup:
                shutil.rmtree(workdir, ignore_errors=True)
        return 1 if failures else 0
    if args.push_storm:
        failures = 0
        try:
            for seed in range(args.seed, args.seed + args.trials):
                ok, detail = run_push_storm(seed, workdir,
                                            timeout=args.timeout)
                print(("ok   " if ok else "FAIL ") +
                      f"push-storm seed={seed}: {detail}")
                _flight_dump_scenario("push-storm", seed, ok)
                if not ok:
                    failures += 1
                    print(f"REPRO: python -m tez_tpu.tools.chaos "
                          f"--push-storm --seed {seed}")
        finally:
            if cleanup:
                shutil.rmtree(workdir, ignore_errors=True)
        return 1 if failures else 0
    if args.commit_storm:
        try:
            ok, detail = run_commit_storm(workdir, timeout=args.timeout,
                                          trace=bool(args.trace_out))
        finally:
            if args.trace_out:
                _export_trace(args.trace_out)
            if cleanup:
                shutil.rmtree(workdir, ignore_errors=True)
        print(("ok   " if ok else "FAIL ") + f"commit-storm: {detail}")
        _flight_dump_scenario("commit-storm", args.seed, ok)
        if not ok:
            print("REPRO: python -m tez_tpu.tools.chaos --commit-storm")
        return 0 if ok else 1
    failures = 0
    try:
        state, baseline = _run_dag(workdir, "baseline", timeout=args.timeout)
        if state != DAGStatusState.SUCCEEDED.name or not baseline:
            print(f"FATAL: fault-free baseline failed (state={state})")
            return 2
        print(f"baseline: {len(baseline)} bytes, "
              f"{len(baseline.splitlines())} keys")
        for seed in range(args.seed, args.seed + args.trials):
            ok, spec, detail = run_trial(seed, workdir, baseline=baseline,
                                         timeout=args.timeout,
                                         trace=bool(args.trace_out))
            tag = "ok  " if ok else "FAIL"
            print(f"{tag} seed={seed} storm=[{spec}] {detail}")
            _flight_dump_scenario("storm", seed, ok)
            if not ok:
                failures += 1
                print(f"REPRO: python -m tez_tpu.tools.chaos --seed {seed}")
    finally:
        if args.trace_out:
            _export_trace(args.trace_out)
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)
    if failures:
        print(f"{failures}/{args.trials} trial(s) failed")
        return 1
    print(f"all {args.trials} trial(s) bit-exact")
    return 0


if __name__ == "__main__":
    sys.exit(main())
