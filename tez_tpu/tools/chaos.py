"""Chaos soak harness: run a canned DAG under a seeded fault storm and
check the output is bit-exact against a fault-free baseline.

Every storm is derived purely from ``--seed``, so any failure is
reproducible with the printed repro line::

    python -m tez_tpu.tools.chaos --seed 1234

Multiple trials (``--trials K``) walk seeds N, N+1, ... and share one
baseline run. The storm menu only contains *recoverable* faults — ones the
framework is expected to absorb (retries, reruns, speculation, container
respawn) — so a divergent or failed run is always a bug, never an
over-aggressive storm.
"""
from __future__ import annotations

import argparse
import os
import random
import shutil
import sys
import tempfile
from typing import List, Optional, Tuple

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common import faults
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor)
from tez_tpu.dag.dag import DAG, Edge, Vertex
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)
from tez_tpu.library.processors import SimpleProcessor

NUM_PRODUCERS = 2
KEYS_PER_TASK = 40

# Recoverable storm menu. Each entry is a single-rule spec fragment; a storm
# is a seeded sample of these joined with ';'. Budgets are deliberately
# small (n=1..2) so compound storms stay inside the framework's retry
# envelopes (task.max.failed.attempts, fetch max_attempts, ...).
STORM_MENU = (
    "shuffle.fetch.read:fail:n=1,exc=io",
    "shuffle.fetch.connect:fail:n=1,exc=conn",
    "shuffle.data:corrupt:n=1",
    "task.run:fail:n=1,exc=runtime",
    "task.run:delay:ms=400,n=1",
    "spill.write:delay:ms=150,n=2",
    "am.heartbeat:delay:ms=250,n=2",
    "am.container.launch:fail:n=1",
)


class ChaosEmitProcessor(SimpleProcessor):
    """Deterministic producer: every task emits the same (key, value) set,
    so the grouped totals downstream are a pure function of the plan."""

    def run(self, inputs, outputs):
        writer = outputs["consumer"].get_writer()
        for i in range(KEYS_PER_TASK):
            writer.write(f"key{i:03d}".encode(), i + 1)


class ChaosCountProcessor(SimpleProcessor):
    """Groups the sorted input and writes 'key total' lines (sorted, so the
    file is bit-exact regardless of fetch interleaving) to result_path."""

    def run(self, inputs, outputs):
        payload = self.context.user_payload.load() or {}
        reader = inputs["producer"].get_reader()
        totals = {k: sum(vs) for k, vs in reader}
        lines = [f"{k.decode()} {v}" for k, v in sorted(totals.items())]
        with open(payload["result_path"], "w") as fh:
            fh.write("\n".join(lines) + "\n")


def make_storm(seed: int) -> str:
    """Seeded storm spec: 2-4 distinct recoverable faults."""
    rng = random.Random(seed)
    picks = rng.sample(STORM_MENU, rng.randint(2, 4))
    return ";".join(picks)


def _build_dag(name: str, result_path: str, fault_spec: str = "",
               fault_seed: int = 0) -> DAG:
    producer = Vertex.create("producer", ProcessorDescriptor.create(
        ChaosEmitProcessor), NUM_PRODUCERS)
    consumer = Vertex.create("consumer", ProcessorDescriptor.create(
        ChaosCountProcessor, payload={"result_path": result_path}), 1)
    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "long"}
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=conf))
    dag = DAG.create(name).add_vertex(producer).add_vertex(consumer)
    dag.add_edge(Edge.create(producer, consumer, prop))
    if fault_spec:
        dag.set_conf("tez.test.fault.spec", fault_spec)
        dag.set_conf("tez.test.fault.seed", fault_seed)
    return dag


def _run_dag(workdir: str, name: str, fault_spec: str = "",
             fault_seed: int = 0, timeout: float = 120.0,
             ) -> Tuple[str, bytes]:
    """One client + one DAG in a fresh staging dir. Returns (state, result
    bytes); result is b'' if the DAG failed before writing."""
    staging = os.path.join(workdir, name, "staging")
    result_path = os.path.join(workdir, name, "result.txt")
    os.makedirs(os.path.dirname(result_path), exist_ok=True)
    client = TezClient.create(name, {
        "tez.staging-dir": staging,
        "tez.am.local.num-containers": 4,
        # leave headroom for injected task failures
        "tez.am.task.max.failed.attempts": 4,
    }).start()
    try:
        dag = _build_dag(name, result_path, fault_spec, fault_seed)
        status = client.submit_dag(dag).wait_for_completion(timeout=timeout)
        state = status.state.name
    finally:
        client.stop()
        faults.clear_all()
    data = b""
    if os.path.exists(result_path):
        with open(result_path, "rb") as fh:
            data = fh.read()
    return state, data


def run_trial(seed: int, workdir: str, baseline: Optional[bytes] = None,
              timeout: float = 120.0) -> Tuple[bool, str, str]:
    """Run one seeded storm; returns (ok, spec, detail)."""
    if baseline is None:
        state, baseline = _run_dag(workdir, "baseline", timeout=timeout)
        if state != DAGStatusState.SUCCEEDED.name or not baseline:
            return False, "", f"baseline run failed (state={state})"
    spec = make_storm(seed)
    state, got = _run_dag(workdir, f"storm{seed}", fault_spec=spec,
                          fault_seed=seed, timeout=timeout)
    if state != DAGStatusState.SUCCEEDED.name:
        return False, spec, f"storm DAG finished {state}"
    if got != baseline:
        return False, spec, (f"output diverged from baseline "
                             f"({len(got)} vs {len(baseline)} bytes)")
    return True, spec, "bit-exact vs baseline"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tez_tpu.tools.chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--seed", type=int, default=0,
                    help="first storm seed (default 0)")
    ap.add_argument("--trials", type=int, default=1,
                    help="number of consecutive seeds to soak (default 1)")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="per-DAG completion timeout in seconds")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: fresh tempdir, removed)")
    args = ap.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="tez-chaos-")
    cleanup = args.workdir is None
    failures = 0
    try:
        state, baseline = _run_dag(workdir, "baseline", timeout=args.timeout)
        if state != DAGStatusState.SUCCEEDED.name or not baseline:
            print(f"FATAL: fault-free baseline failed (state={state})")
            return 2
        print(f"baseline: {len(baseline)} bytes, "
              f"{len(baseline.splitlines())} keys")
        for seed in range(args.seed, args.seed + args.trials):
            ok, spec, detail = run_trial(seed, workdir, baseline=baseline,
                                         timeout=args.timeout)
            tag = "ok  " if ok else "FAIL"
            print(f"{tag} seed={seed} storm=[{spec}] {detail}")
            if not ok:
                failures += 1
                print(f"REPRO: python -m tez_tpu.tools.chaos --seed {seed}")
    finally:
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)
    if failures:
        print(f"{failures}/{args.trials} trial(s) failed")
        return 1
    print(f"all {args.trials} trial(s) bit-exact")
    return 0


if __name__ == "__main__":
    sys.exit(main())
