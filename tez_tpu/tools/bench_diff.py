"""Compare two bench runs metric-by-metric and fail on regressions.

Usage:
  python -m tez_tpu.tools.bench_diff OLD NEW [--threshold 0.20]

OLD/NEW are either the driver's ``BENCH_*.json`` wrappers
(``{"tail": ..., "parsed": ...}``: every JSON metric line is recovered
from the captured stdout tail) or raw ``bench.py`` stdout saved to a file.
Metrics are matched across runs by the text up to the first ``(`` —
parenthesised qualifiers (record counts, fallback labels) change between
revisions, the headline name does not.

All bench metrics are throughputs (higher is better): a metric REGRESSES
when NEW's value drops more than ``--threshold`` (default 20%) below
OLD's, and any regression makes the exit status nonzero — wire this into
CI as ``make bench-diff OLD=... NEW=...``.  A 0.0 value is the bench's
"stage unavailable" sentinel and is reported but never compared.  When
both runs carry the device pipeline's ``stage_ms`` breakdown the
per-stage deltas are printed too (informational: stage attribution shifts
between backends; the gate is the end-to-end value).

``--armed-overhead FRAC`` switches to the flight-recorder overhead gate:
OLD is a disarmed run, NEW the identical run with
``tez.obs.flight.enabled``, and any shared metric more than FRAC worse
(slower for s/ms-unit records, lower for throughputs) fails the diff —
CI uses 0.03 to hold the recorder to its 3% tier-1 budget
(docs/doctor.md).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

DEFAULT_THRESHOLD = 0.20


def normalize(metric: str) -> str:
    """Match key: the metric text up to the first parenthesis."""
    return metric.split("(", 1)[0].strip()


def _metric_lines(text: str) -> List[Dict]:
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec and "value" in rec:
            out.append(rec)
    return out


def load_metrics(path: str) -> Dict[str, Dict]:
    """{normalized_name: metric_record} from a wrapper or raw stdout file.
    Later lines win on a normalized-name collision (the bench prints the
    headline last)."""
    with open(path) as f:
        text = f.read()
    recs: List[Dict] = []
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc:
        recs = _metric_lines(doc.get("tail") or "")
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed and \
                not any(r["metric"] == parsed["metric"] for r in recs):
            recs.append(parsed)
    elif isinstance(doc, dict) and "metric" in doc:
        recs = [doc]
    elif isinstance(doc, list):
        recs = [r for r in doc
                if isinstance(r, dict) and "metric" in r and "value" in r]
    else:
        recs = _metric_lines(text)
    return {normalize(r["metric"]): r for r in recs}


def _stage_diff(old: Dict, new: Dict) -> List[str]:
    so, sn = old.get("stage_ms"), new.get("stage_ms")
    if not (isinstance(so, dict) and isinstance(sn, dict)):
        return []
    lines = []
    for stage in sorted(set(so) | set(sn)):
        a, b = float(so.get(stage, 0.0)), float(sn.get(stage, 0.0))
        lines.append(f"    stage {stage:14} {a:10.1f} {b:10.1f} "
                     f"{b - a:+10.1f} ms")
    return lines


#: units where LOWER is better (wall/latency records, e.g. a tier-1 suite
#: wall measured armed vs disarmed); everything else is a throughput
LOWER_IS_BETTER_UNITS = frozenset({"s", "sec", "seconds", "ms"})


def diff(old_path: str, new_path: str,
         threshold: float = DEFAULT_THRESHOLD,
         armed_overhead: Optional[float] = None) -> int:
    old, new = load_metrics(old_path), load_metrics(new_path)
    if not old or not new:
        print(f"no metrics parsed from "
              f"{old_path if not old else new_path}", file=sys.stderr)
        return 2
    shared = [k for k in old if k in new]
    regressions = 0
    print(f"{'metric':52} {'OLD':>10} {'NEW':>10} {'ratio':>7}")
    for key in shared:
        a, b = old[key], new[key]
        va, vb = float(a["value"]), float(b["value"])
        unit = b.get("unit", a.get("unit", ""))
        if va <= 0.0 or vb <= 0.0:
            print(f"{key:52} {va:10.2f} {vb:10.2f}    skip "
                  f"(unavailable sentinel)")
            continue
        ratio = vb / va
        flag = ""
        if armed_overhead is not None:
            # armed-vs-disarmed gate (OLD = disarmed, NEW = armed): the
            # flight recorder buys its always-on ring by promising a
            # bounded cost — flag any metric that pays more than the
            # declared overhead, in the unit's own "worse" direction
            worse = ratio > 1.0 + armed_overhead \
                if unit in LOWER_IS_BETTER_UNITS \
                else ratio < 1.0 - armed_overhead
            if worse:
                flag = (f"  << ARMED OVERHEAD "
                        f"(>{armed_overhead:.0%} vs disarmed)")
                regressions += 1
        elif ratio < 1.0 - threshold:
            flag = f"  << REGRESSION (>{threshold:.0%} drop)"
            regressions += 1
        print(f"{key:52} {va:10.2f} {vb:10.2f} {ratio:6.2f}x "
              f"{unit}{flag}")
        for line in _stage_diff(a, b):
            print(line)
    for key in sorted(set(old) - set(new)):
        print(f"{key:52} {float(old[key]['value']):10.2f} "
              f"{'-':>10}    (metric dropped)")
    for key in sorted(set(new) - set(old)):
        print(f"{key:52} {'-':>10} {float(new[key]['value']):10.2f}"
              f"    (metric added)")
    # absolute ratio floors: a metric that declares min_vs_baseline must
    # hold that vs_baseline ratio in NEW regardless of what OLD recorded
    # (so the gate bites even on the first run that ships the metric).
    # A vs_baseline <= 0 is the "stage unavailable" sentinel and is
    # reported but never gated.
    for key in sorted(new):
        rec = new[key]
        floor, vs = rec.get("min_vs_baseline"), rec.get("vs_baseline")
        if floor is None or vs is None or float(vs) <= 0.0:
            continue
        if float(vs) < float(floor):
            print(f"{key:52} vs_baseline {float(vs):.2f}x below floor "
                  f"{float(floor):.2f}x  << REGRESSION (ratio floor)")
            regressions += 1
    bound = armed_overhead if armed_overhead is not None else threshold
    what = "armed overhead" if armed_overhead is not None else "regression"
    if regressions:
        print(f"\n{regressions} metric(s) over the {bound:.0%} "
              f"{what} bound")
        return 1
    print(f"\nno {what} beyond {bound:.0%} across "
          f"{len(shared)} shared metric(s)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tez_tpu.tools.bench_diff", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("old", help="baseline run (BENCH_*.json or raw stdout)")
    ap.add_argument("new", help="candidate run")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative drop that counts as a regression "
                         "(default 0.20 = 20%%)")
    ap.add_argument("--armed-overhead", type=float, default=None,
                    metavar="FRAC",
                    help="flight-recorder gate: OLD is a disarmed run, "
                         "NEW the same run with tez.obs.flight.enabled; "
                         "fail when any shared metric is worse than FRAC "
                         "(use 0.03 for the 3%% tier-1 budget) — seconds/"
                         "ms units gate on slowdown, throughputs on drop")
    args = ap.parse_args(argv)
    return diff(args.old, args.new, threshold=args.threshold,
                armed_overhead=args.armed_overhead)


if __name__ == "__main__":
    sys.exit(main())
