"""Spill-scale OrderedWordCount bench: the 100 GB protocol's stage 1.

Reference scale story: PipelinedSorter multi-spill sort
(tez-runtime-library/.../sort/impl/PipelinedSorter.java:559), MergeManager
mem->disk cascade (.../orderedgrouped/MergeManager.java:387), io.sort.factor
batched merge (.../TezMerger.java:76).  This harness drives data >> span
budget through the FULL framework — DAG submission, producer span spills to
disk, shuffle fetch, consumer disk-cascade merge — and records the counters
that prove it (SPILLED_RECORDS, ADDITIONAL_SPILLS_BYTES_WRITTEN), with the
output verified against a streamed host golden.

High-cardinality corpus: zipfian draws over a --vocab-size vocabulary large
enough that the map-side combine cannot collapse the stream (combine is
DISABLED here anyway — the point is the raw spill path).

Usage:
    python -m tez_tpu.tools.spill_bench --mb 1024 --sort-mb 64 \
        --out SPILL_r03.json
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np


def make_corpus(path: str, target_mb: int, vocab: int, seed: int = 0
                ) -> "tuple[int, np.ndarray]":
    """Zipfian corpus over w<id> words; returns (bytes, counts[vocab])."""
    rng = np.random.default_rng(seed)
    width = len(str(vocab - 1))
    counts = np.zeros(vocab, dtype=np.int64)
    total = 0
    chunk_words = 1 << 20
    words_per_line = 8192
    with open(path, "w") as fh:
        while total < target_mb << 20:
            ids = rng.zipf(1.2, chunk_words).astype(np.int64) % vocab
            counts += np.bincount(ids, minlength=vocab)
            chunk = np.char.add("w", np.char.zfill(
                ids.astype(f"U{width}"), width))
            for s in range(0, len(chunk), words_per_line):
                text = " ".join(chunk[s:s + words_per_line])
                fh.write(text)
                fh.write("\n")
                total += len(text) + 1
    return total, counts


def verify_output(out_dir: str, golden_counts: np.ndarray) -> int:
    """Streamed verification: parse w<id> words back to ids, compare the
    whole count vector (no gigantic dicts)."""
    got = np.zeros_like(golden_counts)
    n_lines = 0
    for name in sorted(os.listdir(out_dir)):
        if name.startswith(("_", ".")):
            continue
        with open(os.path.join(out_dir, name)) as fh:
            for line in fh:
                if not line.strip():
                    continue
                w, c = line.rsplit(None, 1)
                got[int(w[1:])] += int(c)
                n_lines += 1
    assert np.array_equal(got, golden_counts), (
        f"output mismatch: {int((got != golden_counts).sum())} words differ")
    return n_lines


def run(target_mb: int, vocab: int, sort_mb: int, engine: str,
        parallelism: int, pipelined: bool = False) -> dict:
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.examples import ordered_wordcount
    td = tempfile.mkdtemp(prefix="tez_spill_")
    try:
        corpus = os.path.join(td, "corpus.txt")
        t0 = time.time()
        nbytes, golden = make_corpus(corpus, target_mb, vocab)
        gen_s = time.time() - t0
        conf = {"tez.staging-dir": os.path.join(td, "stg"),
                "tez.runtime.sorter.class": engine,
                "tez.runtime.io.sort.mb": sort_mb,
                "tez.runtime.tpu.host.spill.dir": os.path.join(td, "spill")}
        if pipelined:
            # one event per spilled span, NO producer final merge
            # (reference: tez.runtime.pipelined-shuffle.enabled)
            conf["tez.runtime.pipelined-shuffle.enabled"] = True
        out_dir = os.path.join(td, "out")
        t0 = time.time()
        with TezClient.create("spill-bench", conf) as client:
            dag = ordered_wordcount.build_dag(
                [corpus], out_dir, tokenizer_parallelism=parallelism,
                summation_parallelism=parallelism, sorter_parallelism=1,
                combine=False, tokenizer_mode="vector")
            dag_client = client.submit_dag(dag)
            status = dag_client.wait_for_completion()
            final = dag_client.get_dag_status(with_counters=True)
        wall = time.time() - t0
        assert status.state.name == "SUCCEEDED", status
        counters: dict = {}
        snap = getattr(final, "counters", None)
        if snap is not None:
            for group in snap.to_dict().values():
                for name in ("SPILLED_RECORDS", "SHUFFLE_BYTES",
                             "ADDITIONAL_SPILLS_BYTES_WRITTEN",
                             "ADDITIONAL_SPILLS_BYTES_READ",
                             "OUTPUT_RECORDS", "REDUCE_INPUT_RECORDS"):
                    if name in group:
                        counters[name] = counters.get(name, 0) + group[name]
        t0 = time.time()
        distinct = verify_output(out_dir, golden)
        verify_s = time.time() - t0

        # EXTERNAL baseline (BASELINE.md protocol): the reference-semantics
        # C++ OrderedWordCount proxy with COMBINE OFF on the identical
        # corpus — every (word,1) record through span sort + heap merges,
        # the exact machinery this bench stresses.  All-RAM and
        # single-pass (no spill I/O), which makes it a CONSERVATIVE
        # baseline: the reference would also pay disk at this scale.
        proxy_s = None
        try:
            from tez_tpu.ops.native import owc_proxy_counts
            res = owc_proxy_counts(corpus, parallelism, parallelism,
                                   combine=False)
        except (ImportError, OSError) as e:   # availability, never parse
            print(f"# owc_proxy baseline unavailable: {e}",
                  file=sys.stderr)
            res = None
        if res is not None:
            proxy_s, counts_by_word = res
            got = np.zeros_like(golden)
            for w, cnt in counts_by_word.items():
                got[int(w[1:])] += cnt
            if not np.array_equal(got, golden):
                raise RuntimeError(
                    "owc_proxy(no-combine) output mismatch vs golden")
        from tez_tpu.ops.sorter import resolve_engine
        resolved = resolve_engine(engine)
        if engine == "host":
            # --engine host exists to BYPASS the device stack; querying the
            # backend just for metadata would block on a stalled PJRT init
            backend = "(not queried)"
        else:
            import jax
            backend = jax.default_backend()
        return {
            "metric": (f"OrderedWordCount spill-scale E2E ({target_mb} MB "
                       f"input, vocab {vocab}, io.sort.mb={sort_mb}, "
                       f"combine OFF, {'pipelined, ' if pipelined else ''}"
                       f"engine={engine}->{resolved} on "
                       f"jax backend={backend}, output verified "
                       f"vs streamed host golden)"),
            "engine_requested": engine,
            "engine_resolved": resolved,
            "jax_backend": backend,
            "value": round(nbytes / 1e6 / wall, 2),
            "unit": "MB/s",
            "vs_baseline": round(proxy_s / wall, 3) if proxy_s else 0.0,
            "baseline": (f"C++ reference-semantics OrderedWordCount proxy, "
                         f"combine off, all-RAM single-pass (conservative): "
                         f"{proxy_s:.1f}s on the same corpus"
                         if proxy_s else "proxy unavailable"),
            "wall_seconds": round(wall, 1),
            "corpus_gen_seconds": round(gen_s, 1),
            "verify_seconds": round(verify_s, 1),
            "distinct_words": distinct,
            "counters": counters,
        }
    finally:
        shutil.rmtree(td, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mb", type=int, default=1024)
    ap.add_argument("--vocab-size", type=int, default=2_000_000)
    ap.add_argument("--sort-mb", type=int, default=64)
    ap.add_argument("--engine", default="auto",
                    help="auto|device|host sorter engine (auto = device "
                         "kernels when an accelerator backend answers, "
                         "host kernels on the CPU fallback)")
    ap.add_argument("--parallelism", type=int, default=4)
    ap.add_argument("--pipelined", action="store_true",
                    help="one event per spilled span; no producer final "
                         "merge (tez.runtime.pipelined-shuffle.enabled)")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    rec = run(args.mb, args.vocab_size, args.sort_mb, args.engine,
              args.parallelism, pipelined=args.pipelined)
    line = json.dumps(rec)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    spilled = rec["counters"].get("SPILLED_RECORDS", 0)
    if spilled <= 0:
        print("WARNING: no spills — raise --mb or lower --sort-mb",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
