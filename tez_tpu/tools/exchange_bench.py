"""MULTICHIP mesh-exchange bench on a skewed-key corpus.

Zipf-drawn keys with one hot partition (~30% of all rows hash to one of
the W consumer partitions) — the exact pathology ISSUE/ROADMAP item 3
names: under the legacy padded formulation CAP is set by that one hot
partition, so every (sender, dest) pair's buffer inflates to it and the
padding crosses ICI as slack.  The bench times four legs over the same
corpus and asserts they are bit-identical:

- ``padded-maxcap`` — the legacy baseline (``legacy_sizing=True``).
- ``skew-aware`` — histogram-sized rounds + balanced placement
  (engine=auto); the HEADLINE metric, floored at 1.3x the baseline via
  ``min_vs_baseline`` (tools/bench_diff.py enforces it).
- ``ragged`` — only real rows cross ICI; emitted with the 0.0
  "unavailable" sentinel where the backend lacks the thunk (XLA:CPU).
- ``coded-r2`` — the redundant exchange; informational (it SPENDS send
  flops to buy straggler masking, so no floor).

Run via ``make bench-exchange`` (TEZ_BENCH_EXCHANGE_ONLY=1 bench.py);
each leg prints one JSON metric line in the bench_diff schema.
"""
from __future__ import annotations

import time
from typing import Dict, List, Tuple

import numpy as np

from tez_tpu.ops.runformat import KVBatch

ROWS = 120_000
KEY_BYTES = 8
VAL_BYTES = 12
CONSUMERS = 8
PRODUCERS = 4
HOT_FRAC = 0.30          # fraction of rows landing in the hot partition
HOT_PART = 0
REPS = 3
MIN_VS_BASELINE = 1.3    # bench_diff floor for the skew-aware leg


def _skewed_corpus(seed: int = 11) -> List[KVBatch]:
    """PRODUCERS spans of Zipf-drawn keys with HOT_FRAC of all rows
    hashing to consumer partition HOT_PART of CONSUMERS."""
    from tez_tpu.ops.host_sort import fnv_rows_host
    rng = np.random.default_rng(seed)
    # classify a candidate key pool by the REAL partitioner so the hot
    # fraction is exact by construction, not a hash accident
    pool = rng.integers(0, 256, size=(40_000, KEY_BYTES), dtype=np.uint8)
    part = fnv_rows_host(pool, np.full(pool.shape[0], KEY_BYTES,
                                       dtype=np.int64)) % CONSUMERS
    hot_pool = pool[part == HOT_PART]
    cold_pool = pool[part != HOT_PART]
    # Zipf-ish popularity inside each pool: low ranks dominate, so the
    # corpus has genuinely repeated hot keys (grouped-reader reality),
    # not 120k distinct ones
    def _draw(p: np.ndarray, n: int) -> np.ndarray:
        ranks = np.minimum(rng.zipf(1.3, size=n) - 1, p.shape[0] - 1)
        return p[ranks]

    n_hot = int(ROWS * HOT_FRAC)
    keys = np.concatenate([_draw(hot_pool, n_hot),
                           _draw(cold_pool, ROWS - n_hot)])
    keys = keys[rng.permutation(ROWS)]
    vals = rng.integers(0, 256, size=(ROWS, VAL_BYTES), dtype=np.uint8)
    spans = []
    for i in range(PRODUCERS):
        k, v = keys[i::PRODUCERS], vals[i::PRODUCERS]
        n = k.shape[0]
        spans.append(KVBatch(
            k.reshape(-1), np.arange(n + 1, dtype=np.int64) * KEY_BYTES,
            v.reshape(-1), np.arange(n + 1, dtype=np.int64) * VAL_BYTES))
    return spans


def _run_leg(coord, spans: List[KVBatch], edge: str,
             **kw) -> List[KVBatch]:
    for i, b in enumerate(spans):
        coord.register_producer(edge, i, PRODUCERS, CONSUMERS, b,
                                KEY_BYTES, VAL_BYTES, **kw)
    return [coord.wait_consumer(edge, c, PRODUCERS, CONSUMERS, timeout=300)
            for c in range(CONSUMERS)]


def _time_leg(coord, spans: List[KVBatch], tag: str,
              **kw) -> Tuple[float, List[KVBatch]]:
    """(best wall secs, outputs): one warmup exchange (compile), then the
    best of REPS timed runs — each on a fresh edge id so the coordinator
    actually re-runs the exchange (results are cached per edge)."""
    out = _run_leg(coord, spans, f"warm-{tag}/a->b", **kw)
    best = float("inf")
    for rep in range(REPS):
        t0 = time.perf_counter()
        out = _run_leg(coord, spans, f"rep{rep}-{tag}/a->b", **kw)
        best = min(best, time.perf_counter() - t0)
    return best, out


def _sig(res: List[KVBatch]) -> List[Tuple[bytes, bytes]]:
    return [(np.asarray(b.key_bytes).tobytes(),
             np.asarray(b.val_bytes).tobytes()) for b in res]


def _mbs(wall: float) -> float:
    return ROWS * (KEY_BYTES + VAL_BYTES) / wall / 1e6


def bench_exchange(cpu_fallback: bool) -> List[Dict]:
    """Metric records for the four exchange legs (bench_diff schema)."""
    import jax
    from tez_tpu.parallel.coordinator import MeshExchangeCoordinator
    from tez_tpu.parallel.exchange import probe_ragged_support

    if len(jax.devices()) < 2:
        return [{"metric": "exchange skewed shuffle (needs >= 2 devices)",
                 "value": 0.0, "unit": "MB/s", "vs_baseline": 0.0}]
    spans = _skewed_corpus()

    base_wall, base_out = _time_leg(
        MeshExchangeCoordinator(legacy_sizing=True), spans,
        "padded", engine="padded")
    skew_wall, skew_out = _time_leg(
        MeshExchangeCoordinator(), spans, "skew", engine="auto")
    assert _sig(skew_out) == _sig(base_out), \
        "skew-aware exchange output diverged from the padded baseline"
    coded_wall, coded_out = _time_leg(
        MeshExchangeCoordinator(), spans, "coded", engine="auto",
        coded="r2")
    assert _sig(coded_out) == _sig(base_out), \
        "coded r2 exchange output diverged from the padded baseline"

    mesh = MeshExchangeCoordinator().mesh_for(
        MeshExchangeCoordinator().devices_for(CONSUMERS))
    ragged_ok, ragged_reason = probe_ragged_support(mesh)
    if ragged_ok:
        ragged_wall, ragged_out = _time_leg(
            MeshExchangeCoordinator(), spans, "ragged", engine="ragged")
        assert _sig(ragged_out) == _sig(base_out), \
            "ragged exchange output diverged from the padded baseline"
        ragged_rec = {
            "metric": f"exchange skewed shuffle ragged ({ROWS} rows)",
            "value": round(_mbs(ragged_wall), 3), "unit": "MB/s",
            "vs_baseline": round(base_wall / ragged_wall, 3)}
    else:
        ragged_rec = {
            "metric": f"exchange skewed shuffle ragged ({ragged_reason})",
            "value": 0.0, "unit": "MB/s", "vs_baseline": 0.0}

    hot_pct = int(HOT_FRAC * 100)
    return [
        {"metric": f"exchange skewed shuffle padded-maxcap ({ROWS} rows, "
                   f"{hot_pct}% hot)",
         "value": round(_mbs(base_wall), 3), "unit": "MB/s",
         "vs_baseline": 1.0},
        ragged_rec,
        {"metric": f"exchange skewed shuffle coded-r2 ({ROWS} rows, "
                   f"{hot_pct}% hot)",
         "value": round(_mbs(coded_wall), 3), "unit": "MB/s",
         "vs_baseline": round(base_wall / coded_wall, 3)},
        # headline LAST: bench_diff keeps the last record per normalized
        # name, and the skew-aware leg is the one carrying the floor
        {"metric": f"exchange skewed shuffle skew-aware ({ROWS} rows, "
                   f"{hot_pct}% hot)",
         "value": round(_mbs(skew_wall), 3), "unit": "MB/s",
         "vs_baseline": round(base_wall / skew_wall, 3),
         "min_vs_baseline": MIN_VS_BASELINE},
    ]
