"""Query-plane bench: join-strategy legs + the adaptive replan loop.

Entered via ``make bench-query`` (``TEZ_BENCH_QUERY_ONLY=1 bench.py``).
Three measurements over the deterministic TPC-H-style corpus
(tools/query_corpus.py), every output verified bit-exact against the
numpy oracle before any number is reported:

1. broadcast vs repartition on the UNIFORM corpus (info line): the
   strategy-sensitive ``nation_revenue`` join forced both ways through
   ``tez.query.join.strategy``; vs_baseline = repartition wall /
   broadcast wall (no floor — which side wins is data-dependent, the
   line exists so regressions in either lowering are visible).
2. the same pair on the ZIPF corpus (info line).
3. the ADAPTIVE REPLAN leg (the floored headline): one QuerySession,
   journaling to a real JSONL history store, runs an exchange-bound
   query twice — the build side is a selective filter the file-size
   estimator cannot see through, so run 1 lowers to a repartition
   sort-merge join; the session observes the true post-filter build
   bytes, PlanFeedback flips the node to broadcast, and run 2 re-plans.
   The leg asserts the QUERY_REPLANNED summary event hit the journal
   AND that ``graft doctor`` renders it for run 2's DAG, then reports
   ``vs_baseline = run1 wall / run2 wall`` with ``min_vs_baseline: 1.0``
   — bench_diff.py fails the bench if the replanned run ever stops
   beating the naive first run.  The result cache stays OFF for this
   leg so the speedup is purely the plan flip, never lineage reuse.
"""
from __future__ import annotations

import contextlib
import io
import os
import shutil
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

SCALE = float(os.environ.get("TEZ_BENCH_QUERY_SCALE", "2.0"))
TIMEOUT = float(os.environ.get("TEZ_BENCH_QUERY_TIMEOUT", "300"))


def _conf(workdir: str, name: str, extra: Optional[Dict] = None) -> Dict:
    conf: Dict[str, Any] = {
        "tez.staging-dir": os.path.join(workdir, name, "staging"),
        "tez.am.local.num-containers": 4,
    }
    conf.update(extra or {})
    return conf


def _run_forced(workdir: str, corpus, query, strategy: str
                ) -> Tuple[float, List[Tuple[str, str]]]:
    """One fresh session, one corpus query with the join lowering forced;
    returns (wall seconds, sorted output records)."""
    from tez_tpu.query import QuerySession
    from tez_tpu.store import reset_store
    reset_store()
    name = f"{query.name}_{strategy}"
    out = os.path.join(workdir, name, "out")
    with QuerySession(name, _conf(workdir, name, {
            "tez.query.join.strategy": strategy,
            "tez.query.replan.enabled": False})) as s:
        r = s.run(query.build(corpus), out, query_name=query.name,
                  sink=query.sink, timeout=TIMEOUT)
    assert r.state == "SUCCEEDED", f"{name} failed ({r.state})"
    got = r.read_output()
    want = query.oracle(corpus)
    assert got == want and got, (
        f"{name}: output diverges from oracle "
        f"({len(got)} vs {len(want)} records)")
    return r.wall_s, got


def _strategy_leg(workdir: str, corpus, flavor: str,
                  cpu_fallback: bool) -> dict:
    """Info line: the strategy-sensitive corpus join forced both ways."""
    from tez_tpu.tools.query_corpus import CORPUS_QUERIES
    query = next(q for q in CORPUS_QUERIES if q.strategy_sensitive)
    bc_wall, bc_out = _run_forced(workdir, corpus, query, "broadcast")
    rp_wall, rp_out = _run_forced(workdir, corpus, query, "repartition")
    assert bc_out == rp_out, \
        f"{query.name}: strategies disagree on the {flavor} corpus"
    suffix = " [CPU FALLBACK: TPU relay stalled]" if cpu_fallback else ""
    return {
        "metric": (f"query broadcast vs repartition join, {flavor} "
                   f"corpus (info line; '{query.name}', scale {SCALE}, "
                   f"both outputs bit-exact vs numpy oracle; "
                   f"repartition {rp_wall:.2f}s){suffix}"),
        "value": round(bc_wall, 3), "unit": "s",
        "vs_baseline": round(rp_wall / bc_wall, 3),
    }


def _exchange_bound_query(corpus):
    """The replan scenario: a selective numeric filter guards the build
    side, so the file-size estimator over-states it and run 1 pays a
    full repartition of the (large) lineitem side."""
    small = corpus.scan("orders").filter("o_total", "ge", "95000",
                                         numeric=True)
    return (corpus.scan("lineitem")
            .join(small, "l_orderkey", "o_orderkey")
            .aggregate(["l_flag"], [("n", "count", "l_flag"),
                                    ("rev", "sum", "l_price")]))


def _doctor_render(history_dir: str, dag_id: str) -> str:
    """Run the real doctor CLI over the bench's JSONL history store and
    return its rendered text for one DAG."""
    from tez_tpu.tools import doctor
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = doctor.main([history_dir, "--dag", dag_id])
    assert rc == 0, f"doctor exited {rc} for {dag_id}"
    return buf.getvalue()


def _replan_leg(workdir: str, corpus, cpu_fallback: bool) -> dict:
    """The floored headline: run 1 repartitions by estimate, the session
    observes, run 2 is replanned to broadcast and must win."""
    from tez_tpu.query import QuerySession
    from tez_tpu.store import reset_store
    reset_store()
    history_dir = os.path.join(workdir, "history")
    conf = _conf(workdir, "replan", {
        "tez.query.broadcast.max-mb": 0.02,
        "tez.history.logging.service.class":
            "tez_tpu.am.history:JsonlHistoryLoggingService",
        "tez.history.logging.log-dir": history_dir,
    })
    with QuerySession("replan", conf) as s:
        # warmup: a different query pays the one-time session costs
        # (library load, first-DAG scheduling) so run 1 vs run 2 compares
        # plans, not process warmth
        from tez_tpu.tools.query_corpus import CORPUS_QUERIES
        warm = next(q for q in CORPUS_QUERIES
                    if q.name == "pricing_summary")
        w = s.run(warm.build(corpus), os.path.join(workdir, "warm"),
                  query_name=warm.name, sink=warm.sink, timeout=TIMEOUT)
        assert w.state == "SUCCEEDED", f"warmup failed ({w.state})"

        r1 = s.run(_exchange_bound_query(corpus),
                   os.path.join(workdir, "replan1"),
                   query_name="exchange_bound", timeout=TIMEOUT)
        r2 = s.run(_exchange_bound_query(corpus),
                   os.path.join(workdir, "replan2"),
                   query_name="exchange_bound", timeout=TIMEOUT)
    assert r1.state == "SUCCEEDED" and r2.state == "SUCCEEDED", \
        f"replan legs failed ({r1.state}/{r2.state})"
    d1 = next(d for d in r1.decisions if d["kind"] == "join_strategy")
    d2 = next(d for d in r2.decisions if d["kind"] == "join_strategy")
    assert (d1["choice"], d1["basis"]) == ("repartition", "estimate"), d1
    assert (d2["choice"], d2["basis"]) == ("broadcast", "replan"), d2
    assert r2.replans, "run 2 replanned silently — nothing journaled"
    assert r1.read_output() == r2.read_output() != [], \
        "replanned run changed the answer"

    # the acceptance gate: the typed QUERY_REPLANNED event must be in the
    # durable journal AND visible in doctor's rendering of run 2's DAG
    report = _doctor_render(history_dir, r2.dag_id)
    assert "REPLANNED" in report and "repartition -> broadcast" in report, \
        f"doctor did not surface the replan:\n{report}"
    sys.stderr.write(report + "\n")

    suffix = " [CPU FALLBACK: TPU relay stalled]" if cpu_fallback else ""
    flip = r2.replans[0]
    return {
        "metric": (f"adaptive replan: exchange-bound join, run 1 "
                   f"{d1['choice']} by {d1['basis']} ({r1.wall_s:.2f}s) "
                   f"-> run 2 {d2['choice']} by {d2['basis']}, "
                   f"{flip['from']} -> {flip['to']} journaled as "
                   f"QUERY_REPLANNED + rendered by doctor, outputs "
                   f"bit-exact, result cache OFF (zipf corpus, scale "
                   f"{SCALE}){suffix}"),
        "value": round(r2.wall_s, 3), "unit": "s",
        "vs_baseline": round(r1.wall_s / r2.wall_s, 3),
        "min_vs_baseline": 1.0,
    }


def bench_query(cpu_fallback: bool) -> List[dict]:
    """The query-plane records for bench.py's JSON stream (headline =
    the floored replan leg, printed last)."""
    import tempfile
    from tez_tpu.tools.query_corpus import generate
    workdir = tempfile.mkdtemp(prefix="tez-querybench-")
    try:
        t0 = time.time()
        uniform = generate(os.path.join(workdir, "uniform"),
                           scale=SCALE, skew=0.0, seed=11)
        zipf = generate(os.path.join(workdir, "zipf"),
                        scale=SCALE, skew=1.1, seed=12)
        sys.stderr.write(f"corpus generated in {time.time() - t0:.1f}s "
                         f"(scale {SCALE})\n")
        # process warmup: one throwaway query pays the one-time library /
        # first-DAG costs so the FIRST timed leg isn't the slow one
        from tez_tpu.tools.query_corpus import CORPUS_QUERIES
        warm = next(q for q in CORPUS_QUERIES
                    if q.name == "pricing_summary")
        _run_forced(os.path.join(workdir, "warm"), uniform, warm, "auto")
        records = [
            _strategy_leg(os.path.join(workdir, "uni"), uniform,
                          "uniform", cpu_fallback),
            _strategy_leg(os.path.join(workdir, "zipf"), zipf,
                          "zipf", cpu_fallback),
            _replan_leg(os.path.join(workdir, "replan"), zipf,
                        cpu_fallback),
        ]
        return records
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
