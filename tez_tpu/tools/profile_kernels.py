"""On-chip kernel profiling harness: settles the XLA-vs-Pallas-vs-host
questions with measured numbers instead of defaults.

Reference role: the reference tunes its hot loops by JMH-style
micro-measurement; here the decisions are (a) whether the Pallas FNV hash
beats the XLA fori_loop version (tez.runtime.tpu.pallas.hash), (b) whether
device-side ragged->lanes encode beats the host encode + padded upload
(tez.runtime.tpu.device.encode).

Run on the target chip:  python -m tez_tpu.tools.profile_kernels [n_rows]
Prints one JSON line per measurement; exit code 0 always (advisory tool).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _make_keys(n: int, key_len: int = 12, seed: int = 0):
    rng = np.random.default_rng(seed)
    kb = rng.integers(97, 123, n * key_len, dtype=np.int64).astype(np.uint8)
    ko = (np.arange(n + 1, dtype=np.int64) * key_len)
    return kb, ko


def _time(fn, reps: int = 5) -> float:
    fn()   # warm/compile
    t0 = time.time()
    for _ in range(reps):
        fn()
    return (time.time() - t0) / reps


def main() -> int:
    import jax

    from tez_tpu.ops import device
    from tez_tpu.ops.keycodec import (encode_keys, encode_keys_device,
                                      matrix_to_lanes, pad_to_matrix)

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    width = 16
    backend = jax.default_backend()
    kb, ko = _make_keys(n)
    mat, lengths = pad_to_matrix(kb, ko, width)

    results = {}

    # -- hash: XLA fori_loop vs Pallas ------------------------------------
    def xla_hash():
        out = device.hash_partition(mat, lengths, 8, use_pallas=False)
        return out

    results["hash_xla_s"] = _time(xla_hash)
    if backend == "tpu":
        def pallas_hash():
            return device.hash_partition(mat, lengths, 8, use_pallas=True)
        try:
            a, b = xla_hash(), pallas_hash()
            assert np.array_equal(a, b), "pallas hash diverges from XLA"
            results["hash_pallas_s"] = _time(pallas_hash)
            results["pallas_speedup"] = round(
                results["hash_xla_s"] / results["hash_pallas_s"], 3)
        except Exception as e:  # noqa: BLE001 — advisory
            results["hash_pallas_error"] = f"{e!r:.200}"

    # -- encode: host pad+pack+upload vs device gather --------------------
    def host_encode():
        lanes, lens = encode_keys(kb, ko, width)
        d = jax.device_put(lanes)
        jax.block_until_ready(d)
        return d

    def device_encode():
        lanes, lens = encode_keys_device(kb, ko, width)
        jax.block_until_ready(lanes)
        return lanes

    h = np.asarray(host_encode())
    d = np.asarray(device_encode())
    assert np.array_equal(h, d), "device encode diverges from host"
    results["encode_host_s"] = _time(host_encode)
    results["encode_device_s"] = _time(device_encode)
    results["device_encode_speedup"] = round(
        results["encode_host_s"] / results["encode_device_s"], 3)

    print(json.dumps({"backend": backend, "rows": n, **results}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
