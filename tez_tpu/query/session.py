"""QuerySession: run logical plans through one resident TezClient.

The session is where the adaptive loop closes (docs/query.md):

1. plan — lower the logical plan with the session's PlanFeedback; any
   feedback decision that *changes* a physical choice is journaled as a
   typed ``QUERY_REPLANNED`` summary event BEFORE the DAG submits.
2. run — submit, wait; the session snapshots the process metrics
   registry around the run and attributes the wall to a dominant plane
   with the doctor's prefix->plane map (query/feedback.py).
3. observe — aggregate the qstats side channel (per-task exchange
   records/bytes/partition histograms) and the store's lineage
   cache-hit delta; journal one ``QUERY_SUBMITTED`` record; feed it all
   into PlanFeedback for the next plan of the same fingerprints.

Because vertex names/payloads are content-addressed from the logical
fingerprints, identical subplans across queries in one session hit the
PR-7 sealed-lineage store (and the PR-11 governed result cache riding
on it) with no query-layer bookkeeping at all.
"""
from __future__ import annotations

import dataclasses
import glob
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common import config as C
from tez_tpu.common.metrics import registry as metrics_registry
from tez_tpu.query.feedback import PlanFeedback, blame_from_histograms
from tez_tpu.query.logical import Table
from tez_tpu.query.planner import PlannedQuery, plan_query


@dataclasses.dataclass
class QueryResult:
    state: str
    dag_id: str
    query: str
    fingerprint: str
    output_path: str
    wall_s: float
    blamed: str
    decisions: List[Dict[str, Any]]
    #: QUERY_REPLANNED data dicts journaled for this run
    replans: List[Dict[str, Any]]
    cache_hits: int

    def read_output(self) -> List[Tuple[str, str]]:
        return read_query_output(self.output_path)


def read_query_output(out_dir: str) -> List[Tuple[str, str]]:
    """Sorted (key, value) records from a FileOutput directory — the
    canonical shape the numpy oracle compares against."""
    records: List[Tuple[str, str]] = []
    for part in sorted(glob.glob(os.path.join(out_dir, "part-*"))):
        with open(part, "rb") as f:
            for line in f.read().splitlines():
                if not line:
                    continue
                k, _sep, v = line.partition(b"\t")
                records.append((k.decode("utf-8"), v.decode("utf-8")))
    return sorted(records)


class QuerySession:
    """Resident query session over a (possibly shared) TezClient."""

    def __init__(self, name: str = "query", conf: Optional[Dict] = None,
                 client: Optional[TezClient] = None):
        self._owns_client = client is None
        if client is None:
            client = TezClient.create(name, dict(conf or {}),
                                      session=True).start()
        self.client = client
        self.conf = dict(client.conf)
        if conf:
            self.conf.update(conf)
        self.feedback = PlanFeedback(self.conf)
        self._runs = 0

    # -- lifecycle -----------------------------------------------------

    def close(self) -> None:
        if self._owns_client:
            self.client.stop()

    def __enter__(self) -> "QuerySession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- internals -----------------------------------------------------

    @property
    def _am(self) -> Any:
        return getattr(self.client.framework_client, "am", None)

    def _journal(self, event: HistoryEvent) -> None:
        am = self._am
        if am is not None and hasattr(am, "history"):
            am.history(event)

    def _store_lineage_hits(self) -> int:
        from tez_tpu.store import ensure_store
        store = ensure_store(self.conf)
        if store is None:
            return 0
        try:
            stats = store.stats()
            counters = stats.get("counters", stats)
            return int(counters.get("store.lineage.hits", 0))
        except Exception:
            return 0

    def _stats_dir(self) -> str:
        # one stable dir for the whole session: the stats spec rides in
        # the vertex payload, which the lineage hash covers — a per-run
        # dir would make every vertex unique and defeat the sealed-
        # lineage reuse the content-addressed names exist for.  Files
        # are atomically overwritten per (node, role, vertex, task), so
        # the dir always holds each vertex's latest observed run.
        base = str(self.conf.get(C.QUERY_STATS_DIR.name) or "")
        if not base:
            staging = str(self.conf.get("tez.staging-dir") or "") or None
            if staging is None:
                return ""
            base = os.path.join(staging, "qstats")
        return base

    @staticmethod
    def _collect_qstats(stats_dir: str
                        ) -> Dict[Tuple[str, str], Dict[str, Any]]:
        out: Dict[Tuple[str, str], Dict[str, Any]] = {}
        if not stats_dir or not os.path.isdir(stats_dir):
            return out
        for path in sorted(glob.glob(os.path.join(stats_dir, "*.json"))):
            try:
                with open(path) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            key = (rec["node"], rec["role"])
            agg = out.setdefault(key, {"bytes": 0, "records": 0,
                                       "partitions": []})
            agg["bytes"] += sum(rec.get("partitions", []))
            agg["records"] += rec.get("records", 0)
            parts = rec.get("partitions", [])
            hist = agg["partitions"]
            if len(hist) < len(parts):
                hist.extend([0] * (len(parts) - len(hist)))
            for i, b in enumerate(parts):
                hist[i] += b
        return out

    # -- the adaptive run loop -----------------------------------------

    def plan(self, table: Table, output_path: str,
             query_name: str = "", conf: Optional[Dict] = None,
             sink: Optional[Dict[str, Any]] = None,
             dag_conf: Optional[Dict] = None) -> PlannedQuery:
        merged = dict(self.conf)
        if conf:
            merged.update(conf)
        stats_dir = self._stats_dir()
        return plan_query(table, merged, output_path,
                          dag_name=f"{query_name or 'query'}_"
                                   f"r{self._runs:04d}",
                          feedback=self.feedback, stats_dir=stats_dir,
                          sink=sink, dag_conf=dag_conf)

    def run(self, table: Table, output_path: str, query_name: str = "",
            conf: Optional[Dict] = None,
            sink: Optional[Dict[str, Any]] = None,
            dag_conf: Optional[Dict] = None,
            timeout: float = 180.0) -> QueryResult:
        planned = self.plan(table, output_path, query_name=query_name,
                            conf=conf, sink=sink, dag_conf=dag_conf)
        stats_dir = self._stats_dir()
        self._runs += 1

        # journal every feedback decision that changed a physical choice
        # BEFORE the replanned DAG submits (summary event: must survive
        # an AM crash so doctor can still blame the planner)
        replans: List[Dict[str, Any]] = []
        for d in planned.decisions:
            ex = d.get("extras") or {}
            if d["basis"] == "replan" and ex.get("from") != ex.get("to"):
                data = {"query": query_name or planned.name,
                        "node": d["node"], "operator": d["operator"],
                        "kind": d["kind"], "detail": d["detail"]}
                data.update(ex)
                replans.append(data)
                self._journal(HistoryEvent(
                    HistoryEventType.QUERY_REPLANNED, data=data))

        hits_before = self._store_lineage_hits()
        hist_before = metrics_registry().histograms()
        t0 = time.monotonic()
        dag_client = self.client.submit_dag(planned.dag)
        status = dag_client.wait_for_completion(timeout=timeout)
        wall = time.monotonic() - t0
        hist_after = metrics_registry().histograms()
        blamed, _busy = blame_from_histograms(hist_before, hist_after)
        cache_hits = self._store_lineage_hits() - hits_before

        qstats = self._collect_qstats(stats_dir)
        self.feedback.record_run(planned.decisions, qstats, blamed, wall)

        self._journal(HistoryEvent(
            HistoryEventType.QUERY_SUBMITTED,
            dag_id=str(dag_client.dag_id),
            data={"query": query_name or planned.name,
                  "fingerprint": planned.fingerprint,
                  "strategies": {
                      d["node"]: d["choice"] for d in planned.decisions
                      if d["kind"] == "join_strategy"},
                  "operators": planned.operators,
                  "cache_hits": max(0, cache_hits),
                  "replans": len(replans),
                  "blamed": blamed, "wall_s": round(wall, 4)}))

        return QueryResult(
            state=status.state.name, dag_id=str(dag_client.dag_id),
            query=query_name or planned.name,
            fingerprint=planned.fingerprint, output_path=output_path,
            wall_s=wall, blamed=blamed, decisions=planned.decisions,
            replans=replans, cache_hits=max(0, cache_hits))
