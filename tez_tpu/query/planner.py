"""Lower logical plans to DAGs over the library edges.

Lowering rules (docs/query.md):

- ``scan`` + any run of ``filter``/``project``/broadcast ``hash_join``
  fuses into ONE vertex (QueryPipelineProcessor) — pipelined operators
  never pay an exchange.
- A join lowers to one of two physical strategies:

  * **broadcast**: the build (right) side terminates into a one-to-all
    ``UnorderedKVEdge``; the probe side *stays open* — the join becomes
    a fused ``hash_join`` op inside the probe stage.  Chosen when the
    build side's estimated (or observed, after a replan) size fits
    ``tez.query.broadcast.max-mb``.
  * **repartition**: both sides terminate into key-partitioned
    ``OrderedPartitionedKVEdge``s feeding a QuerySortMergeJoinProcessor
    at ``tez.query.reducers`` parallelism.

- ``aggregate`` terminates its child with map-side partial aggregation
  (the combiner analog) into an ordered edge grouped on the keys;
  ``window`` and ``limit`` terminate into ordered edges keyed by the
  partition / order columns (limit funnels to 1 partition).

Every vertex is named ``q_<kind>_<fp12>`` from the logical fingerprint
of the operator chain it executes and tagged with ``tez.query.operator``
— so history/flight events attribute back to plan operators, and
identical subplans lower to byte-identical vertices that the PR-7
sealed-lineage store serves as cache hits across queries.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tez_tpu.common import config as C
from tez_tpu.common.payload import (InputDescriptor,
                                    InputInitializerDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, DataSourceDescriptor,
                             Edge, Vertex)
from tez_tpu.library.conf import (OrderedPartitionedKVEdgeConfig,
                                  UnorderedKVEdgeConfig)
from tez_tpu.query.logical import Node, Table

_PROCESSORS = {
    "scan": "tez_tpu.query.processors:QueryPipelineProcessor",
    "smj": "tez_tpu.query.processors:QuerySortMergeJoinProcessor",
    "agg": "tez_tpu.query.processors:QueryAggregateProcessor",
    "win": "tez_tpu.query.processors:QueryWindowProcessor",
    "limit": "tez_tpu.query.processors:QueryLimitProcessor",
}


def _get(conf: Any, key) -> Any:
    v = conf.get(key.name) if conf is not None else None
    return key.default if v is None else v


@dataclasses.dataclass
class PlannedQuery:
    """A lowered query: the DAG plus the attribution/decision record
    the session journals (QUERY_SUBMITTED) and feeds PlanFeedback."""
    dag: DAG
    name: str
    fingerprint: str
    sink_vertex: str
    output_path: str
    #: vertex name -> operator tag ("scan(x)+filter(...)@fp")
    operators: Dict[str, str]
    #: per-choice records: {"node", "operator", "kind", "choice", "basis",
    #: "detail"} — kind is join_strategy or parallelism
    decisions: List[Dict[str, Any]]


class _Stage:
    """An open (not yet terminated) physical stage being fused."""

    def __init__(self, kind: str, node: Node, parallelism: int,
                 payload: Dict[str, Any], label_parts: List[str]):
        self.kind = kind
        self.node = node          # deepest logical node fused so far
        self.parallelism = parallelism
        self.payload = payload    # stage-specific fields (no ops/emit yet)
        self.ops: List[Dict[str, Any]] = []
        self.labels = list(label_parts)
        #: (source Vertex, "broadcast" | "ordered") resolved at terminate
        self.in_edges: List[Tuple[Vertex, str]] = []
        self.scan_source: Optional[Dict[str, Any]] = None


class _Planner:
    def __init__(self, conf: Any, feedback: Any, stats_dir: str):
        self.conf = conf
        self.feedback = feedback
        self.stats_dir = stats_dir
        self.vertices: Dict[str, Vertex] = {}
        self.edges: List[Edge] = []
        self.operators: Dict[str, str] = {}
        self.decisions: List[Dict[str, Any]] = []

    # -- knobs ---------------------------------------------------------

    def _reducers(self, node: Node, operator: str) -> int:
        base = int(_get(self.conf, C.QUERY_REDUCERS))
        if self.feedback is not None:
            advised = self.feedback.advise_reducers(node.fingerprint, base)
            if advised is not None:
                self.decisions.append({
                    "node": node.fingerprint, "operator": operator,
                    "kind": "parallelism", "choice": advised[0],
                    "basis": "replan", "detail": advised[1],
                    "extras": advised[2]})
                return advised[0]
        self.decisions.append({
            "node": node.fingerprint, "operator": operator,
            "kind": "parallelism", "choice": base, "basis": "default",
            "detail": f"tez.query.reducers={base}"})
        return base

    def _join_strategy(self, node: Node
                       ) -> Tuple[str, str, str, Dict[str, Any]]:
        """-> (strategy, basis, detail, journal-extras)."""
        forced = str(_get(self.conf, C.QUERY_JOIN_STRATEGY))
        how = node.spec["how"]
        if how == "semi_distinct":
            # distinct-on-key needs the key-partitioned exchange
            return "repartition", "required", "semi_distinct join", {}
        if forced != "auto":
            return forced, "forced", f"tez.query.join.strategy={forced}", {}
        pinned = node.spec["strategy"]
        if pinned != "auto":
            return pinned, "pinned", f"builder pinned {pinned}", {}
        max_mb = float(_get(self.conf, C.QUERY_BROADCAST_MAX_MB))
        if self.feedback is not None:
            advised = self.feedback.advise_strategy(
                node.fingerprint, max_mb)
            if advised is not None:
                return advised[0], "replan", advised[1], advised[2]
        est_mb = node.children[1].estimated_bytes() / (1024.0 * 1024.0)
        if est_mb <= max_mb:
            return ("broadcast", "estimate",
                    f"build est {est_mb:.2f}MB <= {max_mb}MB", {})
        return ("repartition", "estimate",
                f"build est {est_mb:.2f}MB > {max_mb}MB", {})

    # -- vertex assembly -----------------------------------------------

    def _vertex_name(self, kind: str, node: Node) -> str:
        name = f"q_{kind}_{node.fingerprint[:12]}"
        while name in self.vertices:   # self-join duplicate subplan
            name += "b"
        return name

    def _stats_spec(self, node: Node, role: str) -> Optional[Dict[str, Any]]:
        if not self.stats_dir:
            return None
        return {"dir": self.stats_dir, "node": node.fingerprint,
                "role": role}

    def terminate(self, stage: _Stage, emit: Dict[str, Any]) -> Vertex:
        """Close a stage: build its vertex, payload, and in-edges.  A
        stage created for an exchange keeps the name its upstreams'
        emit specs were built against (``_forced_name``) even after
        further ops fused into it."""
        vname = getattr(stage, "_forced_name", None) or \
            self._vertex_name(stage.kind, stage.node)
        payload = dict(stage.payload)
        payload["stage"] = stage.kind
        payload["ops"] = stage.ops
        payload["emit"] = emit
        vertex = Vertex.create(vname, ProcessorDescriptor.create(
            _PROCESSORS[stage.kind], payload=payload), stage.parallelism)
        tag = f"{'+'.join(stage.labels)}@{stage.node.fingerprint}"
        vertex.set_conf(C.QUERY_OPERATOR_TAG.name, tag)
        if stage.scan_source is not None:
            src = stage.scan_source
            vertex.add_data_source("input", DataSourceDescriptor.create(
                InputDescriptor.create("tez_tpu.io.text:TextInput"),
                InputInitializerDescriptor.create(
                    "tez_tpu.io.text:TextSplitGenerator",
                    payload={"paths": src["paths"],
                             "desired_splits": stage.parallelism})))
        for src_vertex, edge_kind in stage.in_edges:
            if edge_kind == "broadcast":
                cfg = UnorderedKVEdgeConfig.new_builder("bytes", "bytes") \
                    .set_from_configuration(self.conf).build()
                prop = cfg.create_default_broadcast_edge_property()
            else:
                cfg = OrderedPartitionedKVEdgeConfig.new_builder(
                    "bytes", "bytes") \
                    .set_from_configuration(self.conf).build()
                prop = cfg.create_default_edge_property()
            self.edges.append(Edge.create(src_vertex, vertex, prop))
        self.vertices[vname] = vertex
        self.operators[vname] = tag
        return vertex

    # -- lowering ------------------------------------------------------

    def compile(self, node: Node) -> _Stage:
        """-> an open stage whose row schema is ``node.schema``."""
        op = node.op
        if op == "scan":
            splits = int(_get(self.conf, C.QUERY_SCAN_SPLITS))
            stage = _Stage("scan", node, splits,
                           {"source": {"mode": node.spec["mode"],
                                       "delimiter": node.spec["delimiter"],
                                       "input": "input"}},
                           [node.describe()])
            stage.scan_source = {"paths": list(node.spec["paths"])}
            return stage

        if op == "filter":
            stage = self.compile(node.children[0])
            child_schema = node.children[0].schema
            stage.ops.append({
                "op": "filter",
                "idx": child_schema.index(node.spec["col"]),
                "cmp": node.spec["cmp"], "value": node.spec["value"],
                "numeric": node.spec["numeric"]})
            stage.node = node
            stage.labels.append(node.describe())
            return stage

        if op == "project":
            stage = self.compile(node.children[0])
            child_schema = node.children[0].schema
            stage.ops.append({
                "op": "project",
                "idxs": [child_schema.index(c)
                         for c in node.spec["columns"]]})
            stage.node = node
            stage.labels.append(node.describe())
            return stage

        if op == "join":
            return self._compile_join(node)

        if op == "aggregate":
            return self._compile_aggregate(node)

        if op == "window":
            return self._compile_window(node)

        if op == "limit":
            return self._compile_limit(node)

        raise ValueError(f"unknown logical op {op!r}")

    def _compile_join(self, node: Node) -> _Stage:
        left_node, right_node = node.children
        lkey = left_node.schema.index(node.spec["left_key"])
        rkey = right_node.schema.index(node.spec["right_key"])
        how = node.spec["how"]
        strategy, basis, detail, extras = self._join_strategy(node)
        self.decisions.append({
            "node": node.fingerprint, "operator": node.describe(),
            "kind": "join_strategy", "choice": strategy, "basis": basis,
            "detail": detail, "extras": extras})
        keep = [i for i, c in enumerate(right_node.schema)
                if c != node.spec["right_key"]] if how == "inner" else []

        if strategy == "broadcast":
            probe = self.compile(left_node)
            build = self.compile(right_node)
            # the probe stage stays OPEN (more ops may fuse into it), so
            # its final vertex name is unknown here; the build side's
            # emit names no output ("") and the runtime resolves the
            # single output a build vertex has (processors._EdgeEmit)
            build_vertex = self.terminate(
                build, {"kind": "edge", "output": "", "key_idx": rkey,
                        "partitions": 1,
                        "stats": self._stats_spec(node, "build")})
            probe.in_edges.append((build_vertex, "broadcast"))
            probe.ops.append({"op": "hash_join",
                              "build": build_vertex.name,
                              "key_idx": lkey, "how": how, "keep": keep})
            probe.node = node
            probe.labels.append(node.describe())
            return probe

        reducers = self._reducers(node, node.describe())
        smj_name = self._vertex_name("smj", node)
        left = self.compile(left_node)
        right = self.compile(right_node)
        left_vertex = self.terminate(
            left, {"kind": "edge", "output": smj_name, "key_idx": lkey,
                   "partitions": reducers,
                   "stats": self._stats_spec(node, "left")})
        right_vertex = self.terminate(
            right, {"kind": "edge", "output": smj_name, "key_idx": rkey,
                    "partitions": reducers,
                    "stats": self._stats_spec(node, "build")})
        stage = _Stage("smj", node, reducers,
                       {"left_input": left_vertex.name,
                        "right_input": right_vertex.name,
                        "how": how, "right_keep": keep},
                       [node.describe()])
        stage.in_edges.append((left_vertex, "ordered"))
        stage.in_edges.append((right_vertex, "ordered"))
        stage._forced_name = smj_name
        return stage

    def _compile_aggregate(self, node: Node) -> _Stage:
        child = node.children[0]
        child_schema = child.schema
        key_idxs = [child_schema.index(k) for k in node.spec["keys"]]
        aggs = [[fn, child_schema.index(col) if fn != "count" else 0]
                for _out, fn, col in node.spec["aggs"]]
        reducers = self._reducers(node, node.describe())
        agg_name = self._vertex_name("agg", node)
        upstream = self.compile(child)
        up_vertex = self.terminate(
            upstream, {"kind": "agg_edge", "output": agg_name,
                       "key_idxs": key_idxs, "aggs": aggs,
                       "partitions": reducers,
                       "stats": self._stats_spec(node, "group")})
        stage = _Stage("agg", node, reducers,
                       {"agg_input": up_vertex.name,
                        "key_width": len(key_idxs),
                        "aggs": [fn for fn, _idx in aggs]},
                       [node.describe()])
        stage.in_edges.append((up_vertex, "ordered"))
        stage._forced_name = agg_name
        return stage

    def _compile_window(self, node: Node) -> _Stage:
        child = node.children[0]
        child_schema = child.schema
        part_idx = child_schema.index(node.spec["partition"])
        reducers = self._reducers(node, node.describe())
        win_name = self._vertex_name("win", node)
        upstream = self.compile(child)
        up_vertex = self.terminate(
            upstream, {"kind": "edge", "output": win_name,
                       "key_idx": part_idx, "partitions": reducers,
                       "stats": self._stats_spec(node, "group")})
        stage = _Stage("win", node, reducers,
                       {"win_input": up_vertex.name,
                        "order_idx": child_schema.index(node.spec["order"]),
                        "func": node.spec["func"]},
                       [node.describe()])
        stage.in_edges.append((up_vertex, "ordered"))
        stage._forced_name = win_name
        return stage

    def _compile_limit(self, node: Node) -> _Stage:
        child = node.children[0]
        child_schema = child.schema
        order = node.spec["order"]
        key_idx = child_schema.index(order[0]) if order else 0
        limit_name = self._vertex_name("limit", node)
        upstream = self.compile(child)
        up_vertex = self.terminate(
            upstream, {"kind": "edge", "output": limit_name,
                       "key_idx": key_idx, "partitions": 1,
                       "stats": self._stats_spec(node, "order")})
        stage = _Stage("limit", node, 1,
                       {"limit_input": up_vertex.name, "n": node.spec["n"]},
                       [node.describe()])
        stage.in_edges.append((up_vertex, "ordered"))
        stage._forced_name = limit_name
        return stage


def plan_query(table: "Table | Node", conf: Any, output_path: str,
               dag_name: str = "query", feedback: Any = None,
               stats_dir: str = "",
               sink: Optional[Dict[str, Any]] = None,
               dag_conf: Optional[Dict[str, Any]] = None) -> PlannedQuery:
    """Lower ``table`` (or a raw plan Node) to a ready-to-submit DAG
    writing (key, value) text records under ``output_path``.

    ``sink`` overrides the output record shape: ``{"key_col": name,
    "value_cols": [names], "literal": str}`` — default key = first
    column, value = '|'-joined remaining columns.  ``dag_conf`` entries
    land on the DAG itself (tenant tags, fault specs, tracing).
    """
    root = table.plan if isinstance(table, Table) else table
    if stats_dir == "":
        stats_dir = str(_get(conf, C.QUERY_STATS_DIR) or "")
    planner = _Planner(conf, feedback, stats_dir)
    stage = planner.compile(root)

    schema = list(root.schema)
    sink = sink or {}
    key_col = sink.get("key_col", schema[0])
    value_cols = sink.get("value_cols")
    if value_cols is None:
        value_cols = [c for c in schema if c != key_col] or []
    emit = {"kind": "sink", "output": "output",
            "key_idx": schema.index(key_col),
            "value_idxs": [schema.index(c) for c in value_cols],
            "literal": sink.get("literal")}
    sink_vertex = planner.terminate(stage, emit)
    sink_vertex.add_data_sink("output", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": output_path,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": output_path})))
    dag = DAG.create(dag_name)
    for k, v in (dag_conf or {}).items():
        dag.set_conf(k, v)
    for v in planner.vertices.values():
        dag.add_vertex(v)
    for e in planner.edges:
        dag.add_edge(e)
    return PlannedQuery(dag=dag, name=dag_name,
                        fingerprint=root.fingerprint,
                        sink_vertex=sink_vertex.name,
                        output_path=output_path,
                        operators=dict(planner.operators),
                        decisions=list(planner.decisions))
