"""Relational query layer: logical plans compiled onto the DAG substrate.

Tez exists to be compiled onto by higher engines (Hive/Pig — SURVEY
§"What Tez is"); this package is that engine in miniature.  A
dataframe-ish builder (:mod:`tez_tpu.query.logical`) produces logical
plans; the planner (:mod:`tez_tpu.query.planner`) lowers them to DAGs
over the existing library edges, choosing the physical join strategy
from partition stats; :mod:`tez_tpu.query.session` runs them through a
resident TezClient session with lineage/result-cache reuse and feeds
observed run telemetry back into :mod:`tez_tpu.query.feedback` for
adaptive re-optimization (docs/query.md).
"""
from tez_tpu.query.logical import Table  # noqa: F401
from tez_tpu.query.planner import plan_query  # noqa: F401
from tez_tpu.query.feedback import PlanFeedback  # noqa: F401
from tez_tpu.query.session import QuerySession  # noqa: F401
