"""Runtime operators for lowered query stages.

One vertex = one *stage*: a fused chain of row ops fed by either a text
source (pipeline), a pair of key-sorted grouped edges (sort-merge join),
or one grouped edge (aggregate / window / limit).  The stage payload is
plain JSON assembled by the planner — column references are resolved to
indexes at plan time, so the runtime never sees a schema.

Rows travel edges as ``key = key-column bytes`` / ``value = '|'-joined
row`` ("bytes" serdes); byte order of keys is exactly the lexicographic
order the logical layer promises, so grouped ordered edges give the
sort-merge/window/limit operators their ordering for free.

Every edge emit can drop a per-task qstats JSON (records + per-partition
bytes, partitioned with the same FNV-1a hash the runtime partitioner
uses) into ``tez.query.stats.dir`` — the observed-size side channel
PlanFeedback replans from (docs/query.md).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from tez_tpu.api.runtime import LogicalInput, LogicalOutput
from tez_tpu.library.partitioners import _stable_hash
from tez_tpu.library.processors import SimpleProcessor

Row = Tuple[str, ...]


def decode_row(value: bytes) -> Row:
    return tuple(value.decode("utf-8").split("|"))


def encode_row(row: Row) -> bytes:
    return "|".join(row).encode("utf-8")


# -- row ops ----------------------------------------------------------------

def _cmp(cmp: str, lhs: str, rhs: str, numeric: bool) -> bool:
    if cmp == "contains":
        return rhs in lhs
    a: Any
    b: Any
    if numeric:
        a, b = int(lhs), int(rhs)
    else:
        a, b = lhs, rhs
    return {"eq": a == b, "ne": a != b, "lt": a < b,
            "le": a <= b, "gt": a > b, "ge": a >= b}[cmp]


def apply_ops(row: Row, ops: List[Dict[str, Any]],
              builds: Dict[str, Dict[str, Any]]) -> List[Row]:
    """Run the fused op chain over one row; hash-join ops fan out."""
    rows = [row]
    for op in ops:
        kind = op["op"]
        if kind == "filter":
            rows = [r for r in rows
                    if _cmp(op["cmp"], r[op["idx"]], op["value"],
                            op["numeric"])]
        elif kind == "project":
            idxs = op["idxs"]
            rows = [tuple(r[i] for i in idxs) for r in rows]
        elif kind == "hash_join":
            table = builds[op["build"]]
            key_idx, how, keep = op["key_idx"], op["how"], op["keep"]
            out: List[Row] = []
            for r in rows:
                matches = table.get(r[key_idx])
                if not matches:
                    continue
                if how == "semi":
                    out.append(r)
                else:  # inner
                    for br in matches:
                        out.append(tuple(r) + tuple(br[i] for i in keep))
            rows = out
        else:
            raise ValueError(f"unknown op {kind!r}")
        if not rows:
            break
    return rows


def load_build_side(reader: Any) -> Dict[str, List[Row]]:
    """Materialize a broadcast build input: key -> rows."""
    table: Dict[str, List[Row]] = {}
    for k, v in reader:
        key = k.decode("utf-8") if isinstance(k, (bytes, bytearray)) else str(k)
        table.setdefault(key, []).append(decode_row(bytes(v)))
    return table


# -- emitters ---------------------------------------------------------------

class _Stats:
    """Per-task qstats accumulator for one outgoing exchange."""

    def __init__(self, spec: Dict[str, Any], vertex: str, task: int):
        self.spec, self.vertex, self.task = spec, vertex, task
        self.partitions = [0] * max(1, int(spec.get("partitions", 1)))
        self.records = 0

    def record(self, key: bytes, nbytes: int) -> None:
        part = _stable_hash(key) % len(self.partitions)
        self.partitions[part] += nbytes
        self.records += 1

    def flush(self) -> None:
        d = self.spec["dir"]
        os.makedirs(d, exist_ok=True)
        name = (f"{self.spec['node']}_{self.spec['role']}_"
                f"{self.vertex}_{self.task:05d}.json")
        tmp = os.path.join(d, "." + name + ".tmp")
        with open(tmp, "w") as f:
            json.dump({"node": self.spec["node"], "role": self.spec["role"],
                       "vertex": self.vertex, "task": self.task,
                       "records": self.records,
                       "partitions": self.partitions}, f)
        os.replace(tmp, os.path.join(d, name))


class _EdgeEmit:
    def __init__(self, spec: Dict[str, Any], outputs: Dict[str, LogicalOutput],
                 vertex: str, task: int):
        out = spec["output"]
        if not out:
            # broadcast build side: its consumer's vertex name wasn't
            # known at plan time, but a build stage has exactly one output
            (out,) = outputs.keys()
        self.writer = outputs[out].get_writer()
        self.key_idx = spec["key_idx"]
        self.stats: Optional[_Stats] = None
        if spec.get("stats"):
            st = dict(spec["stats"])
            st["partitions"] = spec.get("partitions", 1)
            self.stats = _Stats(st, vertex, task)

    def write(self, row: Row) -> None:
        key = row[self.key_idx].encode("utf-8")
        value = encode_row(row)
        self.writer.write(key, value)
        if self.stats is not None:
            self.stats.record(key, len(key) + len(value))

    def finish(self) -> None:
        if self.stats is not None:
            self.stats.flush()


class _AggEdgeEmit:
    """Map-side partial aggregation (the combiner analog): accumulate
    per group key, emit one partial row per key at finish."""

    def __init__(self, spec: Dict[str, Any], outputs: Dict[str, LogicalOutput],
                 vertex: str, task: int):
        self.writer = outputs[spec["output"]].get_writer()
        self.key_idxs = spec["key_idxs"]
        self.aggs = spec["aggs"]  # [[fn, idx], ...]
        self.acc: Dict[Tuple[str, ...], List[int]] = {}
        self.stats: Optional[_Stats] = None
        if spec.get("stats"):
            st = dict(spec["stats"])
            st["partitions"] = spec.get("partitions", 1)
            self.stats = _Stats(st, vertex, task)

    def write(self, row: Row) -> None:
        key = tuple(row[i] for i in self.key_idxs)
        acc = self.acc.get(key)
        if acc is None:
            self.acc[key] = [
                1 if fn == "count" else int(row[idx])
                for fn, idx in self.aggs]
            return
        for slot, (fn, idx) in enumerate(self.aggs):
            if fn == "count":
                acc[slot] += 1
            elif fn == "sum":
                acc[slot] += int(row[idx])
            elif fn == "min":
                acc[slot] = min(acc[slot], int(row[idx]))
            else:
                acc[slot] = max(acc[slot], int(row[idx]))

    def finish(self) -> None:
        for key in sorted(self.acc):
            row = key + tuple(str(v) for v in self.acc[key])
            kb = "|".join(key).encode("utf-8")
            vb = encode_row(row)
            self.writer.write(kb, vb)
            if self.stats is not None:
                self.stats.record(kb, len(kb) + len(vb))
        if self.stats is not None:
            self.stats.flush()


class _SinkEmit:
    def __init__(self, spec: Dict[str, Any],
                 outputs: Dict[str, LogicalOutput]):
        self.writer = outputs[spec["output"]].get_writer()
        self.key_idx = spec["key_idx"]
        self.value_idxs = spec["value_idxs"]
        self.literal = spec.get("literal")

    def write(self, row: Row) -> None:
        if self.literal is not None:
            value = self.literal
        else:
            value = "|".join(row[i] for i in self.value_idxs)
        self.writer.write(row[self.key_idx], value)

    def finish(self) -> None:
        pass


def _make_emit(payload: Dict[str, Any], outputs: Dict[str, LogicalOutput],
               vertex: str, task: int):
    spec = payload["emit"]
    kind = spec["kind"]
    if kind == "edge":
        return _EdgeEmit(spec, outputs, vertex, task)
    if kind == "agg_edge":
        return _AggEdgeEmit(spec, outputs, vertex, task)
    if kind == "sink":
        return _SinkEmit(spec, outputs)
    raise ValueError(f"unknown emit kind {kind!r}")


# -- stage processors -------------------------------------------------------

class _QueryProcessor(SimpleProcessor):
    """Shared scaffolding: payload, broadcast build sides, emitter."""

    def _setup(self, inputs: Dict[str, LogicalInput],
               outputs: Dict[str, LogicalOutput]):
        payload = self.context.user_payload.load() or {}
        builds = {
            op["build"]: load_build_side(inputs[op["build"]].get_reader())
            for op in payload.get("ops", []) if op["op"] == "hash_join"}
        emit = _make_emit(payload, outputs, self.context.vertex_name,
                          self.context.task_index)
        return payload, builds, emit


class QueryPipelineProcessor(_QueryProcessor):
    """Text source -> fused ops (filter/project/broadcast hash join) ->
    emit.  The scan stage of every plan."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        payload, builds, emit = self._setup(inputs, outputs)
        src = payload["source"]
        mode, delim = src["mode"], src.get("delimiter", "|")
        ops = payload.get("ops", [])
        reader = inputs[src.get("input", "input")].get_reader()
        for _offset, line in reader:
            text = line.decode("utf-8")
            if mode == "table":
                text = text.rstrip("\r\n")
                if not text:
                    continue
                rows = [tuple(text.split(delim))]
            elif mode == "lines":
                text = text.strip()
                if not text:
                    continue
                rows = [(text,)]
            else:  # words
                rows = [(w,) for w in text.split()]
            for row in rows:
                for out in apply_ops(row, ops, builds):
                    emit.write(out)
        emit.finish()


class QuerySortMergeJoinProcessor(_QueryProcessor):
    """Lockstep merge of two key-sorted grouped inputs (the repartition
    strategy).  ``how``: inner = per-pair fan-out, semi = every left row
    of a matching key, semi_distinct = the key once."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        payload, builds, emit = self._setup(inputs, outputs)
        how = payload["how"]
        keep = payload.get("right_keep", [])
        ops = payload.get("ops", [])
        left = iter(inputs[payload["left_input"]].get_reader())
        right = iter(inputs[payload["right_input"]].get_reader())

        def nxt(it):
            try:
                k, vs = next(it)
                return bytes(k), vs
            except StopIteration:
                return None, None

        lk, lvs = nxt(left)
        rk, rvs = nxt(right)
        while lk is not None and rk is not None:
            if lk == rk:
                lrows = sorted(decode_row(bytes(v)) for v in lvs)
                if how == "semi_distinct":
                    outs: List[Row] = [(lk.decode("utf-8"),)]
                elif how == "semi":
                    outs = lrows
                else:
                    rrows = sorted(decode_row(bytes(v)) for v in rvs)
                    outs = [lr + tuple(rr[i] for i in keep)
                            for lr in lrows for rr in rrows]
                for row in outs:
                    for out in apply_ops(row, ops, builds):
                        emit.write(out)
                lk, lvs = nxt(left)
                rk, rvs = nxt(right)
            elif lk < rk:
                lk, lvs = nxt(left)
            else:
                rk, rvs = nxt(right)
        emit.finish()


class QueryAggregateProcessor(_QueryProcessor):
    """Final aggregation over grouped partial rows (value layout:
    key columns + one partial per agg)."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        payload, builds, emit = self._setup(inputs, outputs)
        width = payload["key_width"]
        aggs = payload["aggs"]  # [fn, ...] merge functions by slot
        ops = payload.get("ops", [])
        for k, vs in inputs[payload["agg_input"]].get_reader():
            finals: Optional[List[int]] = None
            key_cols: Row = ()
            for v in vs:
                row = decode_row(bytes(v))
                key_cols = row[:width]
                partials = [int(x) for x in row[width:]]
                if finals is None:
                    finals = partials
                    continue
                for slot, fn in enumerate(aggs):
                    if fn in ("count", "sum"):
                        finals[slot] += partials[slot]
                    elif fn == "min":
                        finals[slot] = min(finals[slot], partials[slot])
                    else:
                        finals[slot] = max(finals[slot], partials[slot])
            row = key_cols + tuple(str(v) for v in (finals or []))
            for out in apply_ops(row, ops, builds):
                emit.write(out)
        emit.finish()


class QueryWindowProcessor(_QueryProcessor):
    """Per-partition window: rows of each key group sorted by the order
    column (ties by full row), then row_number / cume_sum appended."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        payload, builds, emit = self._setup(inputs, outputs)
        order_idx = payload["order_idx"]
        func = payload["func"]
        ops = payload.get("ops", [])
        for _k, vs in inputs[payload["win_input"]].get_reader():
            rows = sorted((decode_row(bytes(v)) for v in vs),
                          key=lambda r: (r[order_idx], r))
            running = 0
            for i, row in enumerate(rows):
                if func == "row_number":
                    tagged = row + (str(i + 1),)
                else:  # cume_sum
                    running += int(row[order_idx])
                    tagged = row + (str(running),)
                for out in apply_ops(tagged, ops, builds):
                    emit.write(out)
        emit.finish()


class QueryLimitProcessor(_QueryProcessor):
    """Global top-n funnel: single consumer of a 1-partition ordered
    edge keyed by the order columns; stops after n rows."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        payload, builds, emit = self._setup(inputs, outputs)
        n = payload["n"]
        ops = payload.get("ops", [])
        taken = 0
        for _k, vs in inputs[payload["limit_input"]].get_reader():
            if taken >= n:
                break
            for row in sorted(decode_row(bytes(v)) for v in vs):
                if taken >= n:
                    break
                taken += 1
                for out in apply_ops(row, ops, builds):
                    emit.write(out)
        emit.finish()
