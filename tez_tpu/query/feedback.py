"""Adaptive re-optimization: observed runs feed the next plan.

After every query run the session records, per logical node fingerprint:

- the physical choices the planner made (strategy, parallelism),
- the observed exchange sizes and per-partition byte histograms from the
  qstats side channel (tez.query.stats.dir, query/processors.py),
- the dominant blamed plane of the run — the doctor's plane attribution
  primitive (obs.timeseries.plane_for_name over the process histogram
  deltas, the same prefix->plane map tools/doctor.py sweeps with),
- wall-clock.

On the next plan of the same node, :meth:`PlanFeedback.advise_strategy`
flips an exchange-bound repartition join to broadcast once the observed
build side is known to fit ``tez.query.broadcast.max-mb`` (the static
estimator cannot see through a selective filter; the observation can),
flips a broadcast join whose build side outgrew the threshold back to
repartition, and :meth:`advise_reducers` doubles a skewed exchange's
parallelism (largest partition > skew-factor x the mean of the rest)
up to ``tez.query.replan.max-reducers``.  Every decision taken is journaled by
the session as a typed ``QUERY_REPLANNED`` summary event so the doctor
can blame the planner itself (docs/query.md, docs/doctor.md).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from tez_tpu.common import config as C
from tez_tpu.obs.timeseries import plane_for_name


def _get(conf: Any, key) -> Any:
    v = conf.get(key.name) if conf is not None else None
    return key.default if v is None else v


@dataclasses.dataclass
class ObservedNode:
    """What past runs taught us about one logical plan node."""
    strategy: str = ""           # physical strategy last used
    reducers: int = 0            # exchange parallelism last used
    #: role -> total observed bytes through that exchange
    bytes_by_role: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: role -> per-partition byte histogram (summed over tasks)
    partitions_by_role: Dict[str, List[int]] = \
        dataclasses.field(default_factory=dict)
    blamed: str = ""             # dominant plane of the last run
    wall_s: float = 0.0
    runs: int = 0


class PlanFeedback:
    """Per-session replan state; one instance lives on a QuerySession."""

    def __init__(self, conf: Any = None):
        self.enabled = bool(_get(conf, C.QUERY_REPLAN_ENABLED))
        self.skew_factor = float(_get(conf, C.QUERY_REPLAN_SKEW_FACTOR))
        self.max_reducers = int(_get(conf, C.QUERY_REPLAN_MAX_REDUCERS))
        self.nodes: Dict[str, ObservedNode] = {}

    # -- planner-facing advice -----------------------------------------

    def advise_strategy(self, fp: str, max_mb: float
                        ) -> Optional[Tuple[str, str, Dict[str, Any]]]:
        """-> (strategy, detail, journal-extras) or None (no opinion)."""
        obs = self.nodes.get(fp)
        if not self.enabled or obs is None or obs.runs == 0:
            return None
        build = obs.bytes_by_role.get("build")
        if build is None:
            return None
        build_mb = build / (1024.0 * 1024.0)
        extras = {"from": obs.strategy, "blamed": obs.blamed,
                  "observed_build_mb": round(build_mb, 3)}
        if obs.strategy == "repartition" and build_mb <= max_mb:
            # the static estimator mis-sized the build side (it cannot
            # see through a selective filter); the observation can.  An
            # exchange/transport-bound run 1 makes the case stronger,
            # but the observed fit alone already justifies the flip.
            bound = (f"run {obs.runs} was {obs.blamed}-bound and "
                     if obs.blamed in ("exchange", "transport") else
                     f"estimate miss (run {obs.runs} blamed "
                     f"{obs.blamed or 'n/a'}): ")
            extras["to"] = "broadcast"
            return ("broadcast",
                    f"{bound}the observed build side ({build_mb:.2f}MB) "
                    f"fits {max_mb}MB — flipping to broadcast", extras)
        if obs.strategy == "broadcast" and build_mb > max_mb:
            extras["to"] = "repartition"
            return ("repartition",
                    f"observed build side {build_mb:.2f}MB outgrew "
                    f"{max_mb}MB — flipping to repartition", extras)
        # stick with what worked; pin it so an estimate never flip-flops
        # a strategy observation already validated
        extras["to"] = obs.strategy
        return (obs.strategy,
                f"keeping observed-good {obs.strategy} "
                f"(build {build_mb:.2f}MB, blamed {obs.blamed or 'n/a'})",
                extras)

    def advise_reducers(self, fp: str, base: int
                        ) -> Optional[Tuple[int, str, Dict[str, Any]]]:
        obs = self.nodes.get(fp)
        if not self.enabled or obs is None or obs.runs == 0:
            return None
        current = obs.reducers or base
        for role, hist in sorted(obs.partitions_by_role.items()):
            if len(hist) < 2 or max(hist) <= 0:
                continue
            peak = max(hist)
            # skew = peak vs the mean of the OTHER partitions.  (peak vs
            # the overall mean is bounded by len(hist), so a factor >= 2
            # could never fire at 2 reducers no matter how skewed.)
            rest = (sum(hist) - peak) / float(len(hist) - 1)
            skewed = peak > self.skew_factor * rest if rest > 0 else True
            if skewed and current < self.max_reducers:
                bumped = min(current * 2, self.max_reducers)
                extras = {"from": current, "to": bumped, "role": role,
                          "peak_bytes": peak, "rest_bytes": round(rest, 1)}
                return (bumped,
                        f"{role} exchange skewed (peak {peak}B > "
                        f"{self.skew_factor}x rest-mean {rest:.0f}B) — "
                        f"reducers {current} -> {bumped}", extras)
        if current != base:
            # keep an earlier bump sticky across runs
            return (current, f"keeping replanned parallelism {current}",
                    {"from": current, "to": current})
        return None

    # -- session-facing recording --------------------------------------

    def record_run(self, decisions: List[Dict[str, Any]],
                   stats: Dict[Tuple[str, str], Dict[str, Any]],
                   blamed: str, wall_s: float) -> None:
        """``stats``: (node_fp, role) -> {"bytes": n, "partitions": [..]}
        aggregated from the qstats side channel by the session."""
        touched: Dict[str, ObservedNode] = {}
        for d in decisions:
            obs = self.nodes.setdefault(d["node"], ObservedNode())
            touched[d["node"]] = obs
            if d["kind"] == "join_strategy":
                obs.strategy = d["choice"]
            elif d["kind"] == "parallelism":
                obs.reducers = int(d["choice"])
        for (fp, role), s in stats.items():
            obs = self.nodes.setdefault(fp, ObservedNode())
            touched[fp] = obs
            obs.bytes_by_role[role] = int(s.get("bytes", 0))
            obs.partitions_by_role[role] = list(s.get("partitions", []))
        for obs in touched.values():
            obs.blamed = blamed
            obs.wall_s = wall_s
            obs.runs += 1


def blame_from_histograms(before: Dict[str, Any],
                          after: Dict[str, Any]) -> Tuple[str, float]:
    """Dominant plane of a run from process-histogram deltas: the
    doctor's prefix->plane attribution applied to the busy-ms each plane
    accumulated between two registry snapshots.  -> (plane, busy_ms);
    ('', 0.0) when nothing moved."""
    busy: Dict[str, float] = {}
    for name, h in after.items():
        plane = plane_for_name(name)
        if plane is None:
            continue
        prev = before.get(name)
        delta = h.sum_ms - (prev.sum_ms if prev is not None else 0.0)
        if delta > 0:
            busy[plane] = busy.get(plane, 0.0) + delta
    if not busy:
        return "", 0.0
    plane = max(sorted(busy), key=lambda p: busy[p])
    return plane, busy[plane]
