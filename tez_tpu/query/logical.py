"""Logical plan nodes + the dataframe-ish builder.

A plan is an immutable tree of :class:`Node` records.  Every node knows
its output ``schema`` (a tuple of column names) and a content-addressed
``fingerprint`` — sha256 over the node's own spec plus its children's
fingerprints.  The fingerprint is the *logical* identity: PlanFeedback
keys observed stats and replan decisions on it, and the planner derives
deterministic vertex names from it so identical subplans lower to
identical vertices and hit the PR-7 sealed-lineage store across queries
(docs/query.md, docs/store.md).

Semantics are deliberately small and exact: rows are tuples of strings,
comparisons are lexicographic unless ``numeric`` asks for int parsing,
aggregates are integer count/sum/min/max.  That keeps every operator
bit-exact against the numpy oracle in tools/query_corpus.py under any
physical strategy.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: filter comparators (runtime evaluation in query/processors.py)
CMP_OPS = ("eq", "ne", "lt", "le", "gt", "ge", "contains")
AGG_FNS = ("count", "sum", "min", "max")
JOIN_HOW = ("inner", "semi", "semi_distinct")
WINDOW_FNS = ("row_number", "cume_sum")


@dataclasses.dataclass(frozen=True)
class Node:
    """One logical operator.  ``spec`` holds the op-specific parameters
    (JSON-serializable), ``children`` the input plans in order."""
    op: str
    spec: Dict[str, Any]
    children: Tuple["Node", ...]
    schema: Tuple[str, ...]

    @property
    def fingerprint(self) -> str:
        body = json.dumps(
            {"op": self.op, "spec": self.spec,
             "children": [c.fingerprint for c in self.children]},
            sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(body.encode()).hexdigest()[:16]

    def describe(self) -> str:
        """Short operator label for vertex tags and journal events."""
        s = self.spec
        if self.op == "scan":
            return f"scan({s['table']})"
        if self.op == "filter":
            return f"filter({s['col']}{s['cmp']}{s['value']})"
        if self.op == "project":
            return f"project({','.join(s['columns'])})"
        if self.op == "join":
            return f"{s['how']}_join({s['left_key']}={s['right_key']})"
        if self.op == "aggregate":
            return f"aggregate({','.join(s['keys'])})"
        if self.op == "window":
            return f"window({s['func']}/{s['partition']})"
        if self.op == "limit":
            return f"limit({s['n']})"
        return self.op

    def walk(self) -> List["Node"]:
        out: List[Node] = []
        for c in self.children:
            out.extend(c.walk())
        out.append(self)
        return out

    def estimated_bytes(self) -> int:
        """Static size estimate (docs/query.md "strategy selection"):
        scans stat their files; everything narrower passes its input
        through unchanged — the planner deliberately cannot see through
        a selective filter, which is exactly what the observed-stats
        replan path exists to correct."""
        if self.op == "scan":
            total = 0
            for p in self.spec["paths"]:
                try:
                    total += os.path.getsize(p)
                except OSError:
                    pass
            return total
        if self.op == "join":
            return sum(c.estimated_bytes() for c in self.children)
        if self.op == "limit":
            return min(self.children[0].estimated_bytes(), 1 << 16)
        return self.children[0].estimated_bytes()


def _col_index(schema: Sequence[str], col: str) -> int:
    try:
        return list(schema).index(col)
    except ValueError:
        raise KeyError(f"column {col!r} not in schema {tuple(schema)}")


class Table:
    """Dataframe-ish builder over :class:`Node` trees.

    ::

        orders = Table.scan("orders", paths, ["o_orderkey", "o_custkey",
                                              "o_total"])
        q = (orders.filter("o_total", "ge", "00000500", numeric=False)
                   .join(customer, "o_custkey", "c_custkey")
                   .aggregate(["c_nation"], [("revenue", "sum", "o_total")])
                   .limit(10, ["c_nation"]))

    Each method returns a new Table; the underlying plan is ``.plan``.
    """

    def __init__(self, plan: Node):
        self.plan = plan

    @property
    def schema(self) -> Tuple[str, ...]:
        return self.plan.schema

    # -- leaves --------------------------------------------------------

    @staticmethod
    def scan(table: str, paths: Sequence[str], columns: Sequence[str],
             mode: str = "table", delimiter: str = "|") -> "Table":
        """``mode``: 'table' = one row per line, columns split on
        ``delimiter``; 'lines' = one single-column row per non-empty
        stripped line; 'words' = one single-column row per whitespace
        token (the wordcount-ish corpora the examples use)."""
        if mode not in ("table", "lines", "words"):
            raise ValueError(f"bad scan mode {mode!r}")
        if mode in ("lines", "words") and len(columns) != 1:
            raise ValueError(f"scan mode {mode!r} is single-column")
        node = Node("scan", {"table": table, "paths": list(paths),
                             "columns": list(columns), "mode": mode,
                             "delimiter": delimiter},
                    (), tuple(columns))
        return Table(node)

    # -- row ops -------------------------------------------------------

    def filter(self, col: str, cmp: str, value: str,
               numeric: bool = False) -> "Table":
        if cmp not in CMP_OPS:
            raise ValueError(f"bad cmp {cmp!r} (want one of {CMP_OPS})")
        _col_index(self.schema, col)
        node = Node("filter", {"col": col, "cmp": cmp, "value": str(value),
                               "numeric": bool(numeric)},
                    (self.plan,), self.schema)
        return Table(node)

    def project(self, columns: Sequence[str]) -> "Table":
        for c in columns:
            _col_index(self.schema, c)
        node = Node("project", {"columns": list(columns)},
                    (self.plan,), tuple(columns))
        return Table(node)

    # -- joins ---------------------------------------------------------

    def _join(self, other: "Table", left_key: str, right_key: str,
              how: str, strategy: str) -> "Table":
        if how not in JOIN_HOW:
            raise ValueError(f"bad join how {how!r}")
        _col_index(self.schema, left_key)
        _col_index(other.schema, right_key)
        if how == "inner":
            schema = tuple(self.schema) + tuple(
                c for c in other.schema if c != right_key)
        elif how == "semi":
            schema = tuple(self.schema)
        else:  # semi_distinct: just the join key, one row per match
            schema = (left_key,)
        node = Node("join", {"left_key": left_key, "right_key": right_key,
                             "how": how, "strategy": strategy},
                    (self.plan, other.plan), schema)
        return Table(node)

    def join(self, other: "Table", left_key: str,
             right_key: Optional[str] = None, how: str = "inner") -> "Table":
        """Strategy chosen by the planner (stats vs
        tez.query.broadcast.max-mb, then PlanFeedback)."""
        return self._join(other, left_key, right_key or left_key,
                          how, "auto")

    def hash_join(self, other: "Table", left_key: str,
                  right_key: Optional[str] = None,
                  how: str = "inner") -> "Table":
        """Pin the broadcast hash strategy (build side = ``other``)."""
        return self._join(other, left_key, right_key or left_key,
                          how, "broadcast")

    def sort_merge_join(self, other: "Table", left_key: str,
                        right_key: Optional[str] = None,
                        how: str = "inner") -> "Table":
        """Pin the repartition sort-merge strategy."""
        return self._join(other, left_key, right_key or left_key,
                          how, "repartition")

    # -- shuffles ------------------------------------------------------

    def aggregate(self, keys: Sequence[str],
                  aggs: Sequence[Tuple[str, str, str]]) -> "Table":
        """``aggs`` = [(out_col, fn, in_col)] with fn in count/sum/min/
        max over integer-parsed columns; empty aggs = DISTINCT keys."""
        for k in keys:
            _col_index(self.schema, k)
        for out, fn, col in aggs:
            if fn not in AGG_FNS:
                raise ValueError(f"bad agg fn {fn!r}")
            if fn != "count":
                _col_index(self.schema, col)
        node = Node("aggregate",
                    {"keys": list(keys),
                     "aggs": [[o, f, c] for o, f, c in aggs]},
                    (self.plan,),
                    tuple(keys) + tuple(o for o, _f, _c in aggs))
        return Table(node)

    def window(self, partition: str, order: str, func: str = "row_number",
               out_col: str = "w_rank") -> "Table":
        """Per-partition window over rows ordered lexicographically by
        ``order`` (ties broken by the full row)."""
        if func not in WINDOW_FNS:
            raise ValueError(f"bad window fn {func!r}")
        _col_index(self.schema, partition)
        _col_index(self.schema, order)
        node = Node("window", {"partition": partition, "order": order,
                               "func": func, "out_col": out_col},
                    (self.plan,), tuple(self.schema) + (out_col,))
        return Table(node)

    def limit(self, n: int, order: Sequence[str]) -> "Table":
        """Global top-``n`` by lexicographic ``order`` columns (ties by
        full row) — a single-reducer funnel, deterministic by design."""
        for c in order:
            _col_index(self.schema, c)
        node = Node("limit", {"n": int(n), "order": list(order)},
                    (self.plan,), self.schema)
        return Table(node)
