"""TezClient: session & non-session DAG submission.

Reference parity: tez-api/.../client/TezClient.java:228 (builder, start:384,
submitDAG:613, stop:727, preWarm:897) + FrameworkClient SPI (YARN vs
LocalClient).  Here the stock framework client is local/in-process (the
reference's LocalClient path); a cluster deployment would swap a gRPC
FrameworkClient behind the same surface.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

from tez_tpu.am.app_master import DAGAppMaster
from tez_tpu.client.dag_client import DAGClient
from tez_tpu.client.errors import DAGRejectedError
from tez_tpu.common import config as C
from tez_tpu.common.ids import new_app_id
from tez_tpu.dag.dag import DAG
from tez_tpu.utils.backoff import ExponentialBackoff, retry_call

log = logging.getLogger(__name__)

__all__ = ["TezClient", "FrameworkClient", "LocalFrameworkClient",
           "DAGRejectedError"]


class _RetryAfterBackoff:
    """Backoff policy flooring each delay at the AM's RETRY-AFTER hint.

    The server's hint is a floor, not the whole story: sleeping exactly
    retry-after re-synchronizes every shed client into the same resubmit
    instant, so full-jittered exponential delay rides on top (the same
    decorrelation argument as utils/backoff.py)."""

    def __init__(self, inner: ExponentialBackoff):
        self.inner = inner
        self.hint = 0.0

    def delay(self, attempt: int) -> float:
        return self.hint + self.inner.delay(attempt)

    def sleep(self, attempt: int) -> None:
        time.sleep(self.delay(attempt))


class FrameworkClient:
    """SPI: how to reach/launch an AM (reference: FrameworkClient.java:58)."""

    def start(self) -> None: ...

    def stop(self) -> None: ...

    def submit_dag(self, plan: Any) -> Any:
        raise NotImplementedError


class LocalFrameworkClient(FrameworkClient):
    """In-process AM (reference: LocalClient.java:80)."""

    def __init__(self, conf: C.TezConfiguration):
        self.conf = conf
        self.app_id = new_app_id()
        self.am: Optional[DAGAppMaster] = None
        self._attempt = 0

    def start(self) -> None:
        self._attempt = 1
        self.am = DAGAppMaster(self.app_id, self.conf,
                               attempt=self._attempt)
        self.am.start()

    def stop(self) -> None:
        if self.am is not None:
            self.am.stop()
            self.am = None

    def submit_dag(self, plan: Any) -> Any:
        return self.am.submit_dag(plan)

    def reattach(self) -> Any:
        """Successor incarnation of a crashed in-process AM: attempt+1
        (which zombie-fences the dead incarnation's attempts via the epoch
        registry), journal replay, admission-queue rebuild — the local
        analog of reconnecting to a supervisor-restarted AM."""
        self._attempt += 1
        self.am = DAGAppMaster(self.app_id, self.conf,
                               attempt=self._attempt)
        self.am.start()
        self.am.recover_and_resume()
        return self.am


class TezClient:
    def __init__(self, name: str, conf: Optional[Dict[str, Any]] = None,
                 session: bool = False):
        self.name = name
        self.conf = C.TezConfiguration(conf or {})
        self.session_mode = session or self.conf.get(C.SESSION_MODE)
        self.framework_client: Optional[FrameworkClient] = None
        self._started = False
        #: weakrefs to every DAGClient this client issued — reattach()
        #: re-binds the live ones against the recovered AM registry
        self._handles: list = []

    @staticmethod
    def create(name: str, conf: Optional[Dict[str, Any]] = None,
               session: bool = False) -> "TezClient":
        return TezClient(name, conf, session)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "TezClient":
        assert not self._started
        if self.conf.get("tez.framework.mode") == "remote":
            from tez_tpu.client.remote import RemoteFrameworkClient
            self.framework_client = RemoteFrameworkClient(self.conf)
        else:
            self.framework_client = LocalFrameworkClient(self.conf)
        self.framework_client.start()
        self._started = True
        return self

    #: client-side-only keys never shipped into DAG plans (the job token
    #: must not leak into the plan -> history journal on disk)
    _CLIENT_ONLY_KEYS = ("tez.job.token", "tez.am.address",
                         "tez.framework.mode")

    def submit_dag(self, dag: DAG) -> DAGClient:
        assert self._started, "client not started"
        conf = {k: v for k, v in self.conf.items()
                if k not in self._CLIENT_ONLY_KEYS}
        plan = dag.create_dag_plan(conf)
        dag_id = self.framework_client.submit_dag(plan)
        return self._track(DAGClient(self.framework_client.am, dag_id))

    def _track(self, handle: DAGClient) -> DAGClient:
        import weakref
        self._handles = [r for r in self._handles if r() is not None]
        self._handles.append(weakref.ref(handle))
        return handle

    def submit_dag_with_retry(self, dag: DAG, retries: int = 5,
                              backoff: Optional[ExponentialBackoff] = None,
                              rng: Any = None) -> DAGClient:
        """submit_dag that honors load shedding: a typed
        :class:`DAGRejectedError` (the AM's SHED verdict) is resubmitted
        after sleeping at least its RETRY-AFTER hint plus full-jitter
        exponential backoff.  Any other failure — and the final rejection
        after ``retries`` attempts — propagates unchanged."""
        policy = _RetryAfterBackoff(
            backoff or ExponentialBackoff(base=0.2, cap=10.0, jitter=True,
                                          rng=rng))

        def once() -> DAGClient:
            try:
                return self.submit_dag(dag)
            except DAGRejectedError as e:
                policy.hint = max(0.0, float(e.retry_after_s))
                log.info("dag %s shed by AM (%s); retry after >= %.3fs",
                         dag.name, e.reason, policy.hint)
                raise

        return retry_call(once, retries, retryable=(DAGRejectedError,),
                          backoff=policy)

    def queue_status(self) -> Dict[str, Any]:
        """The AM's admission/queue snapshot (works for local and remote
        framework clients — the remote proxy has the same method)."""
        return self.framework_client.am.queue_status()

    # -- AM crash survival (docs/recovery.md) --------------------------------
    def reattach(self) -> "TezClient":
        """Recover from an AM crash: rediscover/restart the AM and re-bind
        every live DAGClient handle against the recovered registry.

        Local framework client: constructs the successor incarnation
        (attempt+1) and runs journal replay inline.  Remote: bounded
        full-jitter reconnect to the captured AM address — the supervisor
        restarts the process, the successor replays before serving.
        Handles whose dag_id the recovered registry cannot resolve raise a
        typed :class:`DAGLostError` — by then the journal has been replayed,
        so an unknown dag_id is proof the DAG never reached a replayable
        state."""
        assert self._started, "client not started"
        am = self.framework_client.reattach()
        from tez_tpu.client.errors import DAGLostError
        lost = []
        for ref in list(self._handles):
            handle = ref()
            if handle is None:
                continue
            handle._am = am
            # registry validation is local-AM only: a remote proxy answers
            # per-call (an unknown dag_id reports state UNKNOWN instead)
            find = getattr(am, "find_dag", None)
            if find is None:
                continue
            dag_id = str(handle.dag_id)
            if find(handle.dag_id, include_retired=True) is None and \
                    dag_id not in am.completed_dags:
                lost.append(dag_id)
        if lost:
            raise DAGLostError(
                ", ".join(lost),
                reason="no journal record reached a replayable state "
                       "(not recovered, not requeued, not completed)")
        return self

    def attach_dag(self, name: str, timeout: float = 60.0,
                   poll: float = 0.05) -> DAGClient:
        """Re-bind to a DAG by NAME after reattach() — the handle for a
        submission whose original submitter observed AMCrashedError.

        dag ids are AM-assigned, so a submission that died parked in the
        admission queue never had one; its journaled DAG_QUEUED record
        replays under the successor AM and eventually promotes to a real
        dag_id, which this polls for.  Raises :class:`DAGLostError` once
        the name is provably absent everywhere — not running, not retired,
        not parked in the recovered queue."""
        assert self._started, "client not started"
        from tez_tpu.client.errors import DAGLostError
        am = self.framework_client.am
        deadline = time.time() + timeout
        missing_since: Optional[float] = None
        while True:
            dag_id = am.find_dag_id_by_name(name)
            if dag_id is not None:
                return self._track(DAGClient(am, dag_id))
            if name in (am.queued_dag_names() or []):
                missing_since = None   # parked: promotion is coming
            elif missing_since is None:
                missing_since = time.time()
            elif time.time() - missing_since > 0.5:
                # absent from registry AND queue across multiple probes —
                # the replayed journal holds no trace of this name
                raise DAGLostError(
                    name, reason="recovered AM has no queued or submitted "
                                 "record under this name")
            if time.time() > deadline:
                raise TimeoutError(
                    f"DAG {name} not re-attachable within {timeout}s")
            time.sleep(poll)

    def pre_warm(self) -> None:
        """Spin runners up before the first DAG (reference: preWarm:897).
        Works for both local and remote framework clients."""
        self.framework_client.am.prewarm()

    def stop(self) -> None:
        if self._started:
            self.framework_client.stop()
            self._started = False

    def __enter__(self) -> "TezClient":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
