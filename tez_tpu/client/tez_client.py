"""TezClient: session & non-session DAG submission.

Reference parity: tez-api/.../client/TezClient.java:228 (builder, start:384,
submitDAG:613, stop:727, preWarm:897) + FrameworkClient SPI (YARN vs
LocalClient).  Here the stock framework client is local/in-process (the
reference's LocalClient path); a cluster deployment would swap a gRPC
FrameworkClient behind the same surface.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

from tez_tpu.am.app_master import DAGAppMaster
from tez_tpu.client.dag_client import DAGClient
from tez_tpu.client.errors import DAGRejectedError
from tez_tpu.common import config as C
from tez_tpu.common.ids import new_app_id
from tez_tpu.dag.dag import DAG
from tez_tpu.utils.backoff import ExponentialBackoff, retry_call

log = logging.getLogger(__name__)

__all__ = ["TezClient", "FrameworkClient", "LocalFrameworkClient",
           "DAGRejectedError"]


class _RetryAfterBackoff:
    """Backoff policy flooring each delay at the AM's RETRY-AFTER hint.

    The server's hint is a floor, not the whole story: sleeping exactly
    retry-after re-synchronizes every shed client into the same resubmit
    instant, so full-jittered exponential delay rides on top (the same
    decorrelation argument as utils/backoff.py)."""

    def __init__(self, inner: ExponentialBackoff):
        self.inner = inner
        self.hint = 0.0

    def delay(self, attempt: int) -> float:
        return self.hint + self.inner.delay(attempt)

    def sleep(self, attempt: int) -> None:
        time.sleep(self.delay(attempt))


class FrameworkClient:
    """SPI: how to reach/launch an AM (reference: FrameworkClient.java:58)."""

    def start(self) -> None: ...

    def stop(self) -> None: ...

    def submit_dag(self, plan: Any) -> Any:
        raise NotImplementedError


class LocalFrameworkClient(FrameworkClient):
    """In-process AM (reference: LocalClient.java:80)."""

    def __init__(self, conf: C.TezConfiguration):
        self.conf = conf
        self.app_id = new_app_id()
        self.am: Optional[DAGAppMaster] = None

    def start(self) -> None:
        self.am = DAGAppMaster(self.app_id, self.conf)
        self.am.start()

    def stop(self) -> None:
        if self.am is not None:
            self.am.stop()
            self.am = None

    def submit_dag(self, plan: Any) -> Any:
        return self.am.submit_dag(plan)


class TezClient:
    def __init__(self, name: str, conf: Optional[Dict[str, Any]] = None,
                 session: bool = False):
        self.name = name
        self.conf = C.TezConfiguration(conf or {})
        self.session_mode = session or self.conf.get(C.SESSION_MODE)
        self.framework_client: Optional[FrameworkClient] = None
        self._started = False

    @staticmethod
    def create(name: str, conf: Optional[Dict[str, Any]] = None,
               session: bool = False) -> "TezClient":
        return TezClient(name, conf, session)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "TezClient":
        assert not self._started
        if self.conf.get("tez.framework.mode") == "remote":
            from tez_tpu.client.remote import RemoteFrameworkClient
            self.framework_client = RemoteFrameworkClient(self.conf)
        else:
            self.framework_client = LocalFrameworkClient(self.conf)
        self.framework_client.start()
        self._started = True
        return self

    #: client-side-only keys never shipped into DAG plans (the job token
    #: must not leak into the plan -> history journal on disk)
    _CLIENT_ONLY_KEYS = ("tez.job.token", "tez.am.address",
                         "tez.framework.mode")

    def submit_dag(self, dag: DAG) -> DAGClient:
        assert self._started, "client not started"
        conf = {k: v for k, v in self.conf.items()
                if k not in self._CLIENT_ONLY_KEYS}
        plan = dag.create_dag_plan(conf)
        dag_id = self.framework_client.submit_dag(plan)
        return DAGClient(self.framework_client.am, dag_id)

    def submit_dag_with_retry(self, dag: DAG, retries: int = 5,
                              backoff: Optional[ExponentialBackoff] = None,
                              rng: Any = None) -> DAGClient:
        """submit_dag that honors load shedding: a typed
        :class:`DAGRejectedError` (the AM's SHED verdict) is resubmitted
        after sleeping at least its RETRY-AFTER hint plus full-jitter
        exponential backoff.  Any other failure — and the final rejection
        after ``retries`` attempts — propagates unchanged."""
        policy = _RetryAfterBackoff(
            backoff or ExponentialBackoff(base=0.2, cap=10.0, jitter=True,
                                          rng=rng))

        def once() -> DAGClient:
            try:
                return self.submit_dag(dag)
            except DAGRejectedError as e:
                policy.hint = max(0.0, float(e.retry_after_s))
                log.info("dag %s shed by AM (%s); retry after >= %.3fs",
                         dag.name, e.reason, policy.hint)
                raise

        return retry_call(once, retries, retryable=(DAGRejectedError,),
                          backoff=policy)

    def queue_status(self) -> Dict[str, Any]:
        """The AM's admission/queue snapshot (works for local and remote
        framework clients — the remote proxy has the same method)."""
        return self.framework_client.am.queue_status()

    def pre_warm(self) -> None:
        """Spin runners up before the first DAG (reference: preWarm:897).
        Works for both local and remote framework clients."""
        self.framework_client.am.prewarm()

    def stop(self) -> None:
        if self._started:
            self.framework_client.stop()
            self._started = False

    def __enter__(self) -> "TezClient":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
