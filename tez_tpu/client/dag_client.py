"""Client handle to a submitted DAG.

Reference parity: tez-api/.../dag/api/client/{DAGClient,DAGClientImpl,
DAGStatus,VertexStatus,Progress}.java and DAGClientAMProtocol.proto:100-108
(getDAGStatus, tryKillDAG, getVertexStatus).
"""
from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Dict, List, Optional

from tez_tpu.common.counters import TezCounters
from tez_tpu.common.ids import DAGId


class DAGStatusState(enum.Enum):
    SUBMITTED = "SUBMITTED"
    INITING = "INITING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    KILLED = "KILLED"
    FAILED = "FAILED"
    ERROR = "ERROR"


_STATE_MAP = {
    "NEW": DAGStatusState.SUBMITTED,
    "INITED": DAGStatusState.INITING,
    "RUNNING": DAGStatusState.RUNNING,
    "COMMITTING": DAGStatusState.RUNNING,
    "SUCCEEDED": DAGStatusState.SUCCEEDED,
    "FAILED": DAGStatusState.FAILED,
    "KILLED": DAGStatusState.KILLED,
    "ERROR": DAGStatusState.ERROR,
}

TERMINAL_STATES = frozenset({DAGStatusState.SUCCEEDED, DAGStatusState.FAILED,
                             DAGStatusState.KILLED, DAGStatusState.ERROR})


@dataclasses.dataclass
class Progress:
    total_task_count: int = 0
    succeeded_task_count: int = 0
    running_task_count: int = 0
    failed_task_count: int = 0
    killed_task_count: int = 0


@dataclasses.dataclass
class VertexStatus:
    name: str
    state: str
    progress: Progress
    diagnostics: List[str]


@dataclasses.dataclass
class DAGStatus:
    name: str
    state: DAGStatusState
    progress: float
    vertex_status: Dict[str, VertexStatus]
    diagnostics: List[str]
    counters: Optional[TezCounters] = None

    @property
    def is_completed(self) -> bool:
        return self.state in TERMINAL_STATES


class DAGClient:
    def __init__(self, am: Any, dag_id: DAGId):
        self._am = am
        self.dag_id = dag_id

    def get_dag_status(self, with_counters: bool = False) -> DAGStatus:
        raw = self._am.dag_status(self.dag_id)
        vs = {}
        for name, d in raw.get("vertices", {}).items():
            vs[name] = VertexStatus(
                name=name, state=d["state"],
                progress=Progress(
                    total_task_count=d["total_tasks"],
                    succeeded_task_count=d["succeeded"],
                    running_task_count=d["running"],
                    failed_task_count=d["failed"],
                    killed_task_count=d["killed"]),
                diagnostics=d.get("diagnostics", []))
        counters = None
        if with_counters:
            find = getattr(self._am, "find_dag", None)  # local AM only;
            # remote proxies report counters via history instead
            dag = find(self.dag_id, include_retired=True) \
                if find is not None else None
            if dag is not None and dag.dag_id == self.dag_id:
                counters = dag.counters
        return DAGStatus(
            name=raw["name"], state=_STATE_MAP.get(raw["state"],
                                                   DAGStatusState.SUBMITTED),
            progress=raw.get("progress", 0.0),
            vertex_status=vs, diagnostics=raw.get("diagnostics", []),
            counters=counters)

    def wait_for_completion(self, timeout: Optional[float] = None,
                            poll: float = 0.05) -> DAGStatus:
        deadline = None if timeout is None else time.time() + timeout
        # Prefer the AM's completion condition over polling when available.
        try:
            self._am.wait_for_dag(self.dag_id, timeout)
        except TimeoutError:
            pass
        while True:
            status = self.get_dag_status()
            if status.is_completed:
                # aggregate counters on the final read
                return self.get_dag_status(with_counters=True)
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(f"DAG {self.dag_id} not done")
            time.sleep(poll)

    def try_kill_dag(self, reason: str = "killed by client") -> None:
        self._am.kill_dag(self.dag_id, reason)
