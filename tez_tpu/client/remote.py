"""Remote framework client: reach a standalone AM over the wire.

Reference parity: FrameworkClient SPI (tez-api FrameworkClient.java:58) —
the standalone/ZK mode where the AM runs independently and clients connect
by address (ZkStandaloneClientFrameworkService analog with a well-known
address instead of a ZK registry).
"""
from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Any, Optional, Tuple

from tez_tpu.am.umbilical_server import FramedClient
from tez_tpu.common.security import JobTokenSecretManager

log = logging.getLogger(__name__)

#: Server-side wait slices stay well under the socket timeout so the
#: request/reply framing never desyncs on long DAGs.
_WAIT_SLICE = 20.0


class RemoteAMProxy(FramedClient):
    """DAGClient-compatible surface (dag_status/kill_dag/wait_for_dag) plus
    submit_dag, over the DAGClientServer socket protocol."""

    _purpose = b"client-hello"

    def submit_dag(self, plan: Any) -> Any:
        return self._call("submit_dag", plan)

    def dag_status(self, dag_id: Any) -> Any:
        return self._call("dag_status", dag_id)

    def kill_dag(self, dag_id: Any, reason: str = "killed by client") -> None:
        self._call("kill_dag", dag_id, reason)

    def wait_for_dag(self, dag_id: Any, timeout: Optional[float] = None):
        """Client-side polling in slices: each server call blocks at most
        _WAIT_SLICE seconds, far below the socket timeout, so a stalled DAG
        can never desynchronize the connection."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            remaining = _WAIT_SLICE if deadline is None else \
                min(_WAIT_SLICE, max(0.05, deadline - time.time()))
            try:
                return self._call("wait_for_dag", dag_id, remaining)
            except TimeoutError:
                if deadline is not None and time.time() >= deadline:
                    raise

    def prewarm(self) -> None:
        self._call("prewarm")

    def queue_status(self) -> Any:
        """Admission/queue snapshot (same shape as GET /queue on the AM
        web UI): per-tenant in-flight/queued/shed counts + queue depth."""
        return self._call("queue_status")

    def find_dag_id_by_name(self, name: str) -> Optional[str]:
        return self._call("find_dag_id_by_name", name)

    def queued_dag_names(self) -> Any:
        return self._call("queued_dag_names")

    def web_ui_address(self) -> Optional[str]:
        return self._call("web_ui_address")

    def shutdown_session(self) -> None:
        self._call("shutdown_session")


class RemoteFrameworkClient:
    """FrameworkClient connecting to an already-running standalone AM."""

    def __init__(self, conf: Any):
        self.conf = conf
        self.am: Optional[RemoteAMProxy] = None
        self._hb_stop = threading.Event()
        self._hb_proxy: Optional[RemoteAMProxy] = None
        #: (host, port) captured at start(): synchronous stop() must not
        #: depend on tez.am.address still being present/parseable then
        self._am_addr: Optional[Tuple[str, int]] = None

    def start(self) -> None:
        addr = self.conf.get("tez.am.address")
        token = self.conf.get("tez.job.token", "")
        if not addr or not token:
            raise ValueError("remote mode needs tez.am.address and "
                             "tez.job.token")
        host, _, port = addr.partition(":")
        self._am_addr = (host, int(port))
        secrets = JobTokenSecretManager(bytes.fromhex(token))
        from tez_tpu.common.tls import client_context
        ssl_ctx = client_context(self.conf)
        # per-call RPC timeout (tez.client.timeout-ms) + a connect retry
        # window for a session AM that is still coming up
        # (tez.session.client.timeout.secs; reference: TezClient.start
        # waiting for the session AM to accept connections)
        rpc_timeout = max(
            float(self.conf.get("tez.client.timeout-ms", 60_000)) / 1000.0,
            1.0)
        # captured for reattach(): rediscovering a restarted AM must not
        # depend on the conf still carrying the address/token verbatim
        self._secrets = secrets
        self._ssl_ctx = ssl_ctx
        self._rpc_timeout = rpc_timeout
        start_wait = float(self.conf.get(
            "tez.session.client.timeout.secs", 120))
        deadline = time.time() + max(start_wait, 0)
        while True:
            try:
                self.am = RemoteAMProxy(host, int(port), secrets,
                                        timeout=rpc_timeout,
                                        ssl_context=ssl_ctx)
                break
            except OSError:
                if time.time() >= deadline:
                    raise
                time.sleep(0.5)
        # Keepalive on its OWN connection (the main proxy is not safe for
        # interleaved calls): an idle-but-alive client must not trip the
        # AM's session expiry (reference: TezClient.sendAMHeartbeat:568).
        interval = float(self.conf.get(
            "tez.client.am.heartbeat.interval.secs", 5))
        if interval > 0:
            self._hb_proxy = RemoteAMProxy(host, int(port), secrets,
                                           timeout=rpc_timeout,
                                           ssl_context=ssl_ctx)

            def _beat() -> None:
                while not self._hb_stop.wait(interval):
                    try:
                        self._hb_proxy.web_ui_address()
                    except Exception:  # noqa: BLE001 — AM gone; the main
                        return         # proxy's next call surfaces the error

            threading.Thread(target=_beat, daemon=True,
                             name="client-am-heartbeat").start()

    def stop(self) -> None:
        self._hb_stop.set()
        if self._hb_proxy is not None:
            self._hb_proxy.close()
            self._hb_proxy = None
        if self.am is None:
            return
        # session mode: stopping the client ends the session AM (reference:
        # TezClient.stop -> shutdownSession).  asynchronous-stop (the
        # reference default) fires the RPC and returns; synchronous stop
        # polls until the AM port actually closes so callers can rely on
        # the session being gone.
        if bool(self.conf.get("tez.session.mode", False)):
            try:
                self.am.shutdown_session()
            except Exception:  # noqa: BLE001 — AM already gone
                pass
            if not bool(self.conf.get("tez.client.asynchronous-stop", True)):
                # prefer the host/port captured at start(); fall back to a
                # GUARDED re-parse — a missing/cleared :port must degrade
                # to skipping the poll, never raise before self.am.close()
                target = self._am_addr
                if target is None:
                    addr = str(self.conf.get("tez.am.address", ""))
                    host, _, port = addr.partition(":")
                    try:
                        target = (host, int(port))
                    except (TypeError, ValueError):
                        target = None
                wait_ms = float(self.conf.get(
                    "tez.client.diagnostics.wait.timeout-ms", 15_000))
                deadline = time.time() + wait_ms / 1000.0
                while target is not None and time.time() < deadline:
                    try:
                        with socket.create_connection(target, timeout=1.0):
                            pass
                        time.sleep(0.2)   # still listening: AM lingering
                    except OSError:
                        break             # port closed: session is down
        self.am.close()
        self.am = None

    def submit_dag(self, plan: Any) -> Any:
        return self.am.submit_dag(plan)

    def reattach(self) -> Any:
        """Rediscover a restarted AM at the address captured at start().

        Bounded full-jitter retry (tez.am.recovery.reattach.{retries,
        backoff-ms}) covers the supervisor's restart window; the successor
        incarnation replays the journal before accepting clients, so a
        successful reconnect already sees the recovered registry
        (docs/recovery.md)."""
        from tez_tpu.common import config as C
        from tez_tpu.utils.backoff import ExponentialBackoff, retry_call
        if self._am_addr is None:
            raise RuntimeError("reattach before start(): no captured AM "
                               "address")
        if self.am is not None:
            try:
                self.am.close()
            except Exception:  # noqa: BLE001 — the old AM is dead anyway
                pass
            self.am = None
        host, port = self._am_addr
        retries = max(1, int(self.conf.get(
            C.AM_RECOVERY_REATTACH_RETRIES) or 5))
        base_s = max(0.01, float(self.conf.get(
            C.AM_RECOVERY_REATTACH_BACKOFF_MS) or 200.0) / 1000.0)

        def connect() -> RemoteAMProxy:
            return RemoteAMProxy(host, port, self._secrets,
                                 timeout=self._rpc_timeout,
                                 ssl_context=self._ssl_ctx)

        self.am = retry_call(
            connect, retries, retryable=(OSError,),
            backoff=ExponentialBackoff(base=base_s, cap=10.0, jitter=True))
        log.info("re-attached to AM at %s:%d", host, port)
        return self.am
