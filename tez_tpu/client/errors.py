"""Typed client-facing errors for the multi-tenant session AM.

Kept in a leaf module (no tez_tpu.am imports) so both sides of the
umbilical can import it: the AM's AdmissionController raises
DAGRejectedError, DAGClientServer pickles it verbatim onto the wire
(exceptions round-trip as ``(False, exc)`` frames), and the remote
client re-raises the same type with the retry hint intact.
"""
from __future__ import annotations


class DAGRejectedError(RuntimeError):
    """A submit was shed by admission control — a verdict, not a failure.

    Carries the shed contract (docs/multitenancy.md): the AM promises it
    kept no state for this submission, and the client should wait at
    least ``retry_after_s`` (plus its own full-jitter backoff — see
    TezClient.submit_dag_with_retry) before resubmitting.
    """

    def __init__(self, reason: str, retry_after_s: float = 0.5,
                 tenant: str = "", queue_depth: int = 0,
                 tenant_inflight: int = 0):
        super().__init__(reason)
        self.reason = reason
        #: minimum client-side wait before resubmitting, in seconds
        self.retry_after_s = float(retry_after_s)
        #: tenant the verdict was issued against ("" = anonymous)
        self.tenant = tenant
        #: admission queue depth observed at the verdict (the "queue
        #: position" a resubmit would land behind)
        self.queue_depth = int(queue_depth)
        #: DAGs this tenant already had running + queued at the verdict
        self.tenant_inflight = int(tenant_inflight)

    # RuntimeError.__reduce__ only replays ``args``; spell out the full
    # constructor so the pickled copy that crosses the umbilical keeps
    # the retry hint and queue position.
    def __reduce__(self):
        return (DAGRejectedError,
                (self.reason, self.retry_after_s, self.tenant,
                 self.queue_depth, self.tenant_inflight))

    def __str__(self) -> str:
        who = self.tenant or "<anon>"
        return (f"DAG rejected ({self.reason}); tenant={who} "
                f"inflight={self.tenant_inflight} "
                f"queue_depth={self.queue_depth} "
                f"RETRY-AFTER {self.retry_after_s:.3f}s")


class AMCrashedError(RuntimeError):
    """The AM died with this submission accepted but not yet started.

    NOT a loss: the submission's ``DAG_QUEUED`` record survives in the
    recovery journal and a successor AM incarnation replays it
    (docs/recovery.md).  The client should ``reattach()`` and re-bind by
    dag name instead of resubmitting — a resubmit would run the DAG
    twice."""

    def __init__(self, sub_id: str, dag_name: str = ""):
        super().__init__(
            f"AM crashed with submission {sub_id} "
            f"({dag_name or '<unnamed>'}) journaled but not started; "
            f"reattach and wait — do not resubmit")
        self.sub_id = sub_id
        self.dag_name = dag_name

    def __reduce__(self):
        return (AMCrashedError, (self.sub_id, self.dag_name))


class DAGLostError(RuntimeError):
    """Re-attach failed for a DAG the recovered journal cannot replay.

    Raised ONLY when the journal proves the DAG never reached a
    replayable state (no unresolved ``DAG_QUEUED`` record and no
    ``DAG_SUBMITTED`` record under the recovered registry) — every other
    case re-binds or replays (docs/recovery.md)."""

    def __init__(self, dag_ref: str, reason: str = ""):
        super().__init__(
            f"DAG {dag_ref} lost across AM restart"
            + (f": {reason}" if reason else ""))
        self.dag_ref = dag_ref
        self.reason = reason

    def __reduce__(self):
        return (DAGLostError, (self.dag_ref, self.reason))
