"""Blockwise vectorized k-way merge over block-sorted KVBatch streams.

The spill-scale analog of TezMerger's record-streaming MergeQueue
(tez-runtime-library/.../common/sort/impl/TezMerger.java:76), re-thought for
this framework's batch-first data plane: instead of a per-record Python heap
(one compare + one yield per record — the round-3 45x spill cliff), sources
advance one *block prefix* at a time and every prefix set merges with the
vectorized run merge (`ops.sorter.merge_sorted_runs` — numpy lexsort on the
host, or the device merge-path kernel: the slices handed over are already
sorted, so the device ranks rows by partitioned binary search instead of
re-sorting them), so Python cost is O(blocks), not O(records).

Algorithm (classic tournament over block boundaries):
  each source = iterator of KVBatch blocks, each internally sorted and
  globally ordered across blocks within the source.  Per round:
    boundary  = min over sources of (last sort key of current block)
    cut_s     = upper_bound(boundary) within source s's current block
    emit      = vectorized merge of the `[pos, cut)` slices
  The source owning the boundary drains its whole block each round, so the
  total vectorized-merge work is one merge per record and the per-round
  Python cost is k bisects of O(log block) byte compares.

Equal keys across sources emerge in source-list order (pass sources in run
age order for the reference's MergeQueue arrival-order semantics); within a
source, producer order is preserved exactly (stable merges).
"""
from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tez_tpu.ops.runformat import KVBatch, Run

__all__ = ["iter_merged_blocks"]


class _Source:
    """One block-sorted input stream with its normalized sort-key view."""

    def __init__(self, blocks: Iterator[KVBatch],
                 normalizer: Optional[Callable[[bytes], bytes]]):
        self.blocks = blocks
        self.normalizer = normalizer
        self.batch: Optional[KVBatch] = None
        self.sort_bytes: Optional[np.ndarray] = None
        self.sort_offsets: Optional[np.ndarray] = None
        self.pos = 0

    def advance(self) -> bool:
        """Load the next non-empty block; False when exhausted."""
        from tez_tpu.ops.sorter import normalize_batch_keys
        for batch in self.blocks:
            if batch.num_records == 0:
                continue
            self.batch = batch
            if self.normalizer is not None:
                self.sort_bytes, self.sort_offsets = \
                    normalize_batch_keys(batch, self.normalizer)
            else:
                self.sort_bytes = batch.key_bytes
                self.sort_offsets = batch.key_offsets
            self.pos = 0
            return True
        self.batch = None
        return False

    def sort_key(self, i: int) -> bytes:
        o = self.sort_offsets
        return self.sort_bytes[int(o[i]):int(o[i + 1])].tobytes()

    def last_key(self) -> bytes:
        return self.sort_key(self.batch.num_records - 1)

    def lower_bound(self, key: bytes) -> int:
        """First row index in [pos, n) whose sort key is >= `key`."""
        lo, hi = self.pos, self.batch.num_records
        while lo < hi:
            mid = (lo + hi) // 2
            if self.sort_key(mid) < key:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def upper_bound(self, key: bytes) -> int:
        """First row index in [pos, n) whose sort key exceeds `key`."""
        lo, hi = self.pos, self.batch.num_records
        while lo < hi:
            mid = (lo + hi) // 2
            if self.sort_key(mid) <= key:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def take_to(self, cut: int) -> Optional[KVBatch]:
        """Consume rows [pos, cut); None when empty."""
        if cut <= self.pos:
            return None
        piece = self.batch.slice_rows(self.pos, cut)
        self.pos = cut
        return piece

    def drain_equal(self, key: bytes) -> Iterator[KVBatch]:
        """Stream this source's entire run of rows == `key`, crossing block
        boundaries (a giant equal-key run spanning blocks must emit
        contiguously to preserve the reference MergeQueue's source-order
        semantics for ties).  Yields piece-at-a-time so a hot key never
        materializes whole — resident memory stays one block."""
        while self.batch is not None:
            if self.pos < self.batch.num_records and \
                    self.sort_key(self.pos) != key:
                return
            piece = self.take_to(self.upper_bound(key))
            if piece is not None:
                yield piece
            if self.pos < self.batch.num_records:
                return
            if not self.advance():
                return


def iter_merged_blocks(
        sources: Sequence[Iterator[KVBatch]],
        key_width: int,
        engine: str = "host",
        key_normalizer: Optional[Callable[[bytes], bytes]] = None,
        merge_factor: int = 64,
        device_min_records: Optional[int] = None,
        counters=None) -> Iterator[KVBatch]:
    """Yield globally-sorted KVBatch blocks merged from k block-sorted
    sources.  Resident memory is one block per source plus one merge round's
    output."""
    from tez_tpu.ops.sorter import DEVICE_SORT_MIN_RECORDS, merge_sorted_runs
    if device_min_records is None:
        device_min_records = DEVICE_SORT_MIN_RECORDS
    active: List[_Source] = []
    for it in sources:
        s = _Source(iter(it), key_normalizer)
        if s.advance():
            active.append(s)
    while active:
        if len(active) == 1:
            # single remaining source: its blocks are already sorted
            s = active[0]
            if s.pos == 0:
                yield s.batch
            elif s.pos < s.batch.num_records:
                yield s.batch.slice_rows(s.pos, s.batch.num_records)
            while s.advance():
                yield s.batch
            return
        boundary = min(s.last_key() for s in active)
        # phase 1: rows strictly below the boundary key — safe to merge
        # (no source can still hold an unseen row < boundary)
        slices: List[Run] = []
        for s in active:
            piece = s.take_to(s.lower_bound(boundary))
            if piece is not None:
                slices.append(Run(piece, np.array([0, piece.num_records],
                                                  dtype=np.int64)))
        if len(slices) == 1:
            yield slices[0].batch
        elif slices:
            merged = merge_sorted_runs(
                slices, 1, key_width, counters=counters, engine=engine,
                merge_factor=merge_factor, key_normalizer=key_normalizer,
                device_min_records=device_min_records)
            yield merged.batch
        # phase 2: rows == boundary, streamed per source IN SOURCE ORDER and
        # contiguously across each source's block boundaries — exactly the
        # heap-merge tie order (equal keys: all of the earlier run's rows,
        # then the next run's).  Pieces yield as they drain so a hot key
        # never materializes whole.
        for s in active:
            yield from s.drain_equal(boundary)
        active = [s for s in active if s.batch is not None]
