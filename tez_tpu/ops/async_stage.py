"""Asynchronous double-buffered device staging pipeline.

The device data plane used to pay every span serially: host ragged->lane
encode, H2D staging, sort dispatch, partition-index readback — one span at a
time, the chip idle during host work and the host idle during device work.
This module is the overlap engine (Exoshuffle / pipelined-TF lesson: at data-
plane scale *staging overlap*, not kernel speed, is the dominant lever):

  submit(span k+2) ... -> [encode+stage span k+1]   (staging thread)
                          [dispatch span k]         (device in flight)
                          [readback span k-1]       (readback workers)

Design points:

* **Bounded dispatch-ahead.**  At most ``depth`` spans are past the staging
  gate at once (encoded/staged/dispatched but not yet fully read back).
  ``depth=2`` is classic double buffering: one span on the device, one
  staged and ready to go the moment the device frees.  The submit side is
  *not* blocked by the gate — spans queue host-side as raw payloads (cheap:
  the collector's own buffers) and the staging thread pulls them through.
* **Out-of-order completion.**  Readback runs on a small worker pool, so a
  span stalled in D2H (or delayed by the ``device.dispatch.delay`` fault
  point) does not block the span behind it.  Completion callbacks therefore
  fire in *completion* order; callers that need submission order key their
  results by span id (DeviceSorter keys runs by spill id).
* **Span batching.**  Spans submitted with ``coalesce=True`` are merged by
  the staging thread into one bucketed dispatch while their combined record
  count fits ``coalesce_records`` — many small spans amortize one
  dispatch's trace/compile-cache/launch overhead (the chatter killer for
  small-span workloads).
* **Deterministic instrumentation.**  The clock is injectable and every
  stage transition lands in ``events`` when ``instrument=True`` — the
  scheduler's overlap contract (span k+1's encode starts before span k's
  dispatch completes; in-flight depth never exceeds the bound) is asserted
  by unit tests against a fake clock, not by eyeballing wall time.

Failure containment (active only when a ``failover_fn`` is wired):

* **Dispatch watchdog.**  A monitor thread enforces per-stage deadlines
  (``watchdog_dispatch_ms`` / ``watchdog_readback_ms``, measured on the
  injectable clock) on every in-progress device attempt.  A hung dispatch
  or readback is *abandoned* — the group is claimed away from its worker,
  journaled as a span event, and re-sorted through ``failover_fn`` — so a
  wedged chip can never wedge ``drain()``/``flush()``.
* **Host-engine failover + circuit breaker.**  Any device-attempt failure
  (watchdog fire, device exception, worker death) re-routes the group
  through ``failover_fn`` (DeviceSorter wires the host engine, which is
  golden-tested bit-exact against the device kernels).  Consecutive
  failures trip a sticky per-process :class:`CircuitBreaker`; while open,
  new groups short-circuit straight to host, and after ``cooldown_ms`` one
  half-open probe group is allowed back on the device — success re-arms
  the engine.
* **OOM ladder.**  Failures classified RESOURCE_EXHAUSTED first retry via
  ``oom_retry_fn`` (DeviceSorter: re-sort on device with the span split in
  half, recursively, down to a byte floor) before host failover — one
  oversized span doesn't count against the breaker or leave the device.
* **Crash containment.**  Readback runs on *daemon* worker threads with a
  bounded-join shutdown (a hung worker can neither wedge ``drain()`` nor
  interpreter exit — the stdlib pool's atexit join would), and a staging
  thread wedged inside a hung dispatch hands its queue to the monitor
  thread, which drains the remaining spans through failover.

Every stage emits ``common/tracing.py`` spans (``device.encode`` /
``device.h2d`` / ``device.dispatch`` / ``device.d2h``) and the matching
``common/metrics.py`` histograms (``device.encode``, ``device.h2d``,
``device.dispatch_wait``, ``device.d2h``; failover re-sorts land in
``device.failover.host_sort``), so the overlap is visible in a Perfetto
export and regressions show up in ``tools/counter_diff.py``.  Containment
decisions emit ``DeviceFailover`` counters (``device.failover.spans``,
``device.watchdog.fires``, ``device.breaker.trips`` ...) plus the
``device.breaker.state`` gauge on /metrics.
"""
from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tez_tpu.common import faults, metrics, tracing
from tez_tpu.obs import flight as _flight

log = logging.getLogger(__name__)

#: Stage names, in pipeline order (also the tracing span names).
STAGE_ENCODE = "device.encode"
STAGE_H2D = "device.h2d"
STAGE_DISPATCH = "device.dispatch"
STAGE_D2H = "device.d2h"
#: Pseudo-stage for a host-engine failover re-sort (tracing + events name).
STAGE_FAILOVER = "device.failover"

#: Histogram fed by the dispatch->readback-complete interval: how long a
#: dispatched program was in flight before its results were host-visible.
DISPATCH_WAIT_HIST = "device.dispatch_wait"
#: Histogram fed by failover re-sorts (host engine wall per group).
FAILOVER_HIST = "device.failover.host_sort"

#: Counter group carrying the containment plane's decisions; dotted counter
#: names so history dumps read as device.failover.spans etc.
COUNTER_GROUP = "DeviceFailover"

#: Real-time poll period bounds of the watchdog monitor thread.  Deadlines
#: are compared on the pipeline's injectable clock; only the poll cadence
#: is wall time, so fake-clock tests fire within one poll of advancing it.
#: The cadence scales with the tightest configured budget (budget/8,
#: clamped to these bounds): production deadlines are tens of seconds, and
#: a 20 ms poll would burn GIL slices against the staging thread's encode
#: work for nothing, while fake-clock tests (budgets ~1 s) still get a
#: sub-200 ms reaction.
WATCHDOG_POLL_MIN_S = 0.02
WATCHDOG_POLL_MAX_S = 0.5

_BREAKER_GAUGE = "device.breaker.state"
_BREAKER_STATE_VALUES = {"closed": 0.0, "half-open": 1.0, "open": 2.0}


def _count(counters: Any, name: str, n: int = 1) -> None:
    if counters is not None:
        counters.group(COUNTER_GROUP).find_counter(name).increment(n)


class CircuitBreaker:
    """Sticky consecutive-failure breaker over the device engine.

    closed -> (``failures`` consecutive device-attempt failures) -> open
    open -> (``cooldown_ms`` elapsed on the injectable clock) -> half-open
    half-open: exactly one caller gets ``allow_device() == True`` (the
    probe); its success closes the breaker, its failure re-opens it for
    another cooldown.  While open/probing every other caller is told to
    route straight to the host engine.

    One breaker is shared per process by default (:func:`process_breaker`):
    a sick chip is a *process* property, so every sorter in the task
    benefits from the first one's diagnosis.
    """

    def __init__(self, failures: int = 3, cooldown_ms: float = 5_000.0,
                 clock: Callable[[], float] = time.perf_counter) -> None:
        self._lock = threading.Lock()
        self.failures = max(1, int(failures))
        self.cooldown_ms = float(cooldown_ms)
        self._clock = clock
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False
        self.trips = 0
        self.probes = 0
        self.recoveries = 0

    def configure(self, failures: Optional[int] = None,
                  cooldown_ms: Optional[float] = None,
                  clock: Optional[Callable[[], float]] = None) -> None:
        """Idempotent re-parameterization (the process singleton is built
        before any sorter can pass its knobs down)."""
        with self._lock:
            if failures is not None:
                self.failures = max(1, int(failures))
            if cooldown_ms is not None:
                self.cooldown_ms = float(cooldown_ms)
            if clock is not None:
                self._clock = clock

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _set_state(self, state: str) -> None:
        self._state = state
        metrics.set_gauge(_BREAKER_GAUGE, _BREAKER_STATE_VALUES[state])

    def allow_device(self) -> bool:
        """True when the caller may attempt the device: breaker closed, or
        the caller just became the half-open probe."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open" and \
                    (self._clock() - self._opened_at) * 1000.0 >= \
                    self.cooldown_ms:
                self._set_state("half-open")
                self._probing = False
            if self._state == "half-open" and not self._probing:
                self._probing = True
                self.probes += 1
                tracing.event("device.breaker.probe")
                _flight.record(_flight.BREAKER, "half-open")
                return True
            return False

    def record_success(self, counters: Any = None) -> None:
        with self._lock:
            self._consecutive = 0
            recovered = self._state != "closed"
            if recovered:
                self._set_state("closed")
                self._probing = False
                self.recoveries += 1
        if recovered:
            tracing.event("device.breaker.closed")
            _flight.record(_flight.BREAKER, "closed")
            _count(counters, "device.breaker.recoveries")

    def record_failure(self, counters: Any = None) -> None:
        with self._lock:
            self._consecutive += 1
            tripped = False
            # a half-open probe failure re-opens immediately; closed trips
            # only at the consecutive threshold
            if self._state == "half-open" or (
                    self._state == "closed" and
                    self._consecutive >= self.failures):
                self._set_state("open")
                self._probing = False
                self._opened_at = self._clock()
                self.trips += 1
                tripped = True
            elif self._state == "open":
                # stragglers already past the breaker check: keep it open
                self._opened_at = self._clock()
        if tripped:
            tracing.event("device.breaker.open",
                          consecutive=self._consecutive)
            _flight.record(_flight.BREAKER, "open", a=self._consecutive)
            _flight.auto_dump("device.breaker.open")
            _count(counters, "device.breaker.trips")


_PROC_BREAKER: Optional[CircuitBreaker] = None
_PROC_BREAKER_LOCK = threading.Lock()


def process_breaker() -> CircuitBreaker:
    """The sticky per-process breaker shared by every pipeline that doesn't
    inject its own."""
    global _PROC_BREAKER
    with _PROC_BREAKER_LOCK:
        if _PROC_BREAKER is None:
            _PROC_BREAKER = CircuitBreaker()
        return _PROC_BREAKER


def reset_process_breaker() -> None:
    """Forget the process breaker (tests/chaos isolate scenarios with it)."""
    global _PROC_BREAKER
    with _PROC_BREAKER_LOCK:
        _PROC_BREAKER = None
    metrics.set_gauge(_BREAKER_GAUGE, 0.0)


# -- device memory pressure hooks (evict-then-split) -------------------------
# The tiered buffer store (tez_tpu.store) registers its
# relieve_device_pressure here; the RESOURCE_EXHAUSTED ladder calls
# relieve_pressure() BEFORE halving a span, so HBM held by evictable
# store entries (cold resident key lanes) is reclaimed first and the
# span often retries whole instead of paying the split merge.

_PRESSURE_HOOKS: List[Callable[[int], int]] = []
_PRESSURE_LOCK = threading.Lock()


def register_pressure_hook(fn: Callable[[int], int]) -> None:
    """Register a callback (nbytes_wanted -> nbytes_freed)."""
    with _PRESSURE_LOCK:
        if fn not in _PRESSURE_HOOKS:
            _PRESSURE_HOOKS.append(fn)


def clear_pressure_hooks() -> None:
    with _PRESSURE_LOCK:
        _PRESSURE_HOOKS.clear()


def relieve_pressure(nbytes: int, counters: Any = None) -> int:
    """Ask every registered hook to free device memory; returns the total
    bytes reclaimed (0 when no hook is registered or nothing is
    evictable)."""
    with _PRESSURE_LOCK:
        hooks = list(_PRESSURE_HOOKS)
    freed = 0
    for fn in hooks:
        try:
            freed += int(fn(int(nbytes)))
        except Exception:  # noqa: BLE001 — relief is best-effort
            log.exception("pressure hook failed")
    if freed > 0:
        _count(counters, "device.oom.evicted_bytes", freed)
        _count(counters, "device.oom.evict_relief")
    return freed


class _DaemonPool:
    """Readback worker pool on *daemon* threads with a bounded-join
    shutdown.  The stdlib ThreadPoolExecutor's workers are non-daemon and
    joined unconditionally at interpreter exit — one watchdog-abandoned
    (permanently hung) readback would wedge both ``drain()`` and process
    shutdown.  Here a hung worker just never picks up its sentinel and the
    daemon flag lets the interpreter leave without it."""

    def __init__(self, workers: int, name: str) -> None:
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads: List[threading.Thread] = []
        for i in range(max(1, workers)):
            t = threading.Thread(target=self._loop,
                                 name=f"{name}_{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, args = item
            # task fns own their error handling (_readback_one never lets
            # an exception escape); a raise here would only kill the worker
            try:
                fn(*args)
            except BaseException:  # noqa: BLE001
                pass

    def submit(self, fn: Callable, *args: Any) -> None:
        self._q.put((fn, args))

    def shutdown(self, timeout: float = 10.0) -> None:
        for _ in self._threads:
            self._q.put(None)
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))


class PipelineStats:
    """Counters the scheduler maintains under its lock; snapshot freely."""

    __slots__ = ("submitted", "dispatched", "completed", "coalesced_groups",
                 "max_in_flight", "failovers", "watchdog_fires",
                 "oom_splits")

    def __init__(self) -> None:
        self.submitted = 0
        self.dispatched = 0
        self.completed = 0
        self.coalesced_groups = 0
        self.max_in_flight = 0
        self.failovers = 0
        self.watchdog_fires = 0
        self.oom_splits = 0

    def to_dict(self) -> Dict[str, int]:
        return {s: getattr(self, s) for s in self.__slots__}


class _Group:
    """One dispatch unit: one span, or several coalesced small spans."""

    __slots__ = ("ids", "payloads", "staged", "inflight", "t_dispatch",
                 "claimed", "gate_held")

    def __init__(self, ids: List[Any], payloads: List[Any]) -> None:
        self.ids = ids
        self.payloads = payloads
        self.staged: Any = None
        self.inflight: Any = None
        self.t_dispatch = 0.0
        #: exactly-once completion token: set by whichever of {worker
        #: thread, watchdog} gets to finish the group first; the loser
        #: discards its (late) result silently
        self.claimed = False
        #: True while this group holds one dispatch-ahead gate slot
        self.gate_held = False


class AsyncSpanPipeline:
    """Bounded dispatch-ahead scheduler over caller-provided stage functions.

    Parameters
    ----------
    encode_fn(payload) -> staged
        Host-side work (ragged->lane encode, precombine).  Runs on the
        staging thread; overlaps in-flight device work.
    stage_fn(staged) -> staged'
        H2D staging: uploads host arrays, returns device handles.  Runs on
        the staging thread right after encode (its cost is histogrammed
        separately).  May be None (encode_fn already staged).
    dispatch_fn(staged) -> inflight
        Launches the device program.  Must be *asynchronous* (JAX dispatch
        semantics: returns futures-backed arrays immediately).
    readback_fn(inflight, ids) -> result
        Blocks until device results are host-visible and builds the final
        result.  Runs on readback workers; may complete out of order.
    coalesce_fn(list_of_staged) -> staged
        Merges several staged spans into one dispatch unit.  Required only
        when callers submit with ``coalesce=True``.
    records_fn(payload) -> int
        Span size in records, used by the coalescing budget.
    on_complete(ids, result)
        Completion callback; ids is the tuple of span ids the dispatch
        covered (len 1 unless coalesced).  May fire out of submission
        order; the pipeline serializes calls (one at a time) but makes no
        ordering promise.
    depth
        Max groups past the staging gate (staged or in flight).  2 =
        double buffering.
    failover_fn(ids, payloads) -> result
        Host-engine re-sort of a group from its RAW payloads; must be
        bit-exact with the device path.  Wiring this turns the containment
        plane on; without it any stage error poisons the pipeline exactly
        as before.
    oom_retry_fn(ids, payloads) -> result
        RESOURCE_EXHAUSTED ladder: retry the group on-device split (raise
        to decline, e.g. at the split byte floor — the group then takes
        ``failover_fn``).
    breaker
        Shared :class:`CircuitBreaker`; defaults to the process singleton
        when the containment plane is on.
    watchdog_dispatch_ms / watchdog_readback_ms
        Per-stage deadlines on the injectable clock; 0 leaves that stage
        unwatched.  The monitor thread starts only when a deadline is set
        AND ``failover_fn`` is wired.
    """

    def __init__(self,
                 dispatch_fn: Callable[[Any], Any],
                 readback_fn: Callable[[Any, Tuple[Any, ...]], Any],
                 encode_fn: Optional[Callable[[Any], Any]] = None,
                 stage_fn: Optional[Callable[[Any], Any]] = None,
                 coalesce_fn: Optional[Callable[[List[Any]], Any]] = None,
                 records_fn: Optional[Callable[[Any], int]] = None,
                 on_complete: Optional[Callable[[Tuple[Any, ...], Any],
                                                None]] = None,
                 depth: int = 2,
                 coalesce_records: int = 0,
                 readback_workers: int = 2,
                 counters: Any = None,
                 clock: Callable[[], float] = time.perf_counter,
                 instrument: bool = False,
                 paused: bool = False,
                 name: str = "device-pipeline",
                 failover_fn: Optional[Callable[[Tuple[Any, ...],
                                                 List[Any]], Any]] = None,
                 oom_retry_fn: Optional[Callable[[Tuple[Any, ...],
                                                  List[Any]], Any]] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 watchdog_dispatch_ms: float = 0.0,
                 watchdog_readback_ms: float = 0.0,
                 dispatch_wait_hist: str = DISPATCH_WAIT_HIST) -> None:
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self._encode_fn = encode_fn or (lambda p: p)
        self._stage_fn = stage_fn
        self._dispatch_fn = dispatch_fn
        self._readback_fn = readback_fn
        self._coalesce_fn = coalesce_fn
        self._records_fn = records_fn or (lambda p: 1)
        self._on_complete = on_complete
        self.depth = depth
        self.coalesce_records = coalesce_records
        self._counters = counters
        self._clock = clock
        self._name = name
        self.stats = PipelineStats()
        #: (span_id_or_ids, stage, edge, t) when instrument=True
        self.events: List[Tuple[Any, str, str, float]] = []
        self._instrument = instrument

        self._failover_fn = failover_fn
        self._oom_retry_fn = oom_retry_fn
        self._breaker: Optional[CircuitBreaker] = None
        if failover_fn is not None:
            self._breaker = breaker if breaker is not None \
                else process_breaker()
        self._watchdog_dispatch_ms = float(watchdog_dispatch_ms)
        self._watchdog_readback_ms = float(watchdog_readback_ms)
        #: which histogram records dispatch->host-visible latency: the sort
        #: plane keeps DISPATCH_WAIT_HIST; the reduce-side merge lane
        #: (library/merge_manager.py) points this at "device.merge" so its
        #: waits don't pollute the producer pipeline's stage breakdown
        self._dispatch_wait_hist = dispatch_wait_hist

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: "collections.deque[Tuple[Any, Any, bool]]" = \
            collections.deque()
        self._in_flight = 0          # groups past the staging gate
        self._open_spans = 0         # submitted, not yet completed
        self._results: Dict[Any, Any] = {}
        self._completion_order: List[Any] = []
        self._error: Optional[BaseException] = None
        self._closed = False
        #: paused=True holds the staging thread until resume(): callers that
        #: want DETERMINISTIC coalescing submit every span first, then
        #: resume — otherwise the staging thread races the submit loop and
        #: group boundaries depend on scheduling
        self._paused = paused
        self._complete_lock = threading.Lock()
        #: in-progress device attempts under a deadline:
        #: id(group) -> (group, ids, stage, deadline-on-injectable-clock)
        self._watch: Dict[int, Tuple[_Group, Tuple[Any, ...], str, float]] \
            = {}
        #: True once the watchdog abandoned a dispatch: the staging thread
        #: is stuck inside dispatch_fn and can never pull the queue again —
        #: the monitor thread owns _pending from then on
        self._wedged = False
        #: True once ANY attempt was watchdog-abandoned: some worker may be
        #: permanently stuck, so drain() joins with a short bound instead
        #: of the cooperative-shutdown one
        self._abandoned = False

        self._staging = threading.Thread(
            target=self._staging_loop, name=f"{name}-staging", daemon=True)
        self._staging.start()
        self._readback = _DaemonPool(readback_workers, f"{name}-readback")
        self._monitor: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()
        if failover_fn is not None and (self._watchdog_dispatch_ms > 0 or
                                        self._watchdog_readback_ms > 0):
            budgets = [b for b in (self._watchdog_dispatch_ms,
                                   self._watchdog_readback_ms) if b > 0]
            self._poll_s = min(WATCHDOG_POLL_MAX_S,
                               max(WATCHDOG_POLL_MIN_S,
                                   min(budgets) / 1000.0 / 8.0))
            self._monitor = threading.Thread(
                target=self._watchdog_loop, name=f"{name}-watchdog",
                daemon=True)
            self._monitor.start()

    # -- instrumentation -----------------------------------------------------
    def _mark(self, ids: Any, stage: str, edge: str) -> float:
        t = self._clock()
        if self._instrument:
            with self._lock:
                self.events.append((ids, stage, edge, t))
        return t

    def _observe(self, hist: str, t0: float, t1: float) -> None:
        metrics.observe(hist, max(0.0, (t1 - t0) * 1000.0),
                        counters=self._counters)

    # -- submit side ---------------------------------------------------------
    def submit(self, span_id: Any, payload: Any,
               coalesce: bool = False) -> None:
        """Queue a span.  Never blocks on the dispatch-ahead gate (raw
        payloads are the collector's own buffers); raises the pipeline's
        first stage error if one already occurred."""
        with self._cv:
            if self._error is not None:
                raise RuntimeError(
                    f"{self._name}: pipeline failed") from self._error
            if self._closed:
                raise RuntimeError(f"{self._name}: submit after drain")
            self._pending.append((span_id, payload, coalesce))
            self._open_spans += 1
            self.stats.submitted += 1
            self._cv.notify_all()

    def resume(self) -> None:
        """Release a pipeline constructed with paused=True."""
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def drain(self) -> Dict[Any, Any]:
        """Block until every submitted span completed; stop the staging
        thread; re-raise the first stage error.  Returns {span_id: result}
        (completion order preserved in ``completion_order``).

        Bounded even under a wedged device: a watchdog-abandoned worker is
        a daemon thread the shutdown joins with a timeout, never waits on
        forever."""
        with self._cv:
            self._paused = False
            self._closed = True
            self._cv.notify_all()
            while self._open_spans > 0 and self._error is None:
                self._cv.wait(timeout=0.5)
            error = self._error
        if self._monitor is not None:
            self._monitor_stop.set()
            self._monitor.join(timeout=5.0)
        # an abandoned attempt means its thread may never exit its stage
        # fn: join with a short bound instead of waiting out a hung chip
        short = self._wedged or self._abandoned
        self._staging.join(timeout=1.0 if short else 30.0)
        self._readback.shutdown(timeout=1.0 if short else 30.0)
        if error is not None:
            raise error
        return dict(self._results)

    @property
    def completion_order(self) -> List[Any]:
        with self._lock:
            return list(self._completion_order)

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    # -- staging thread ------------------------------------------------------
    def _next_group(self) -> Optional[_Group]:
        """Pop the next dispatch unit, coalescing greedily while allowed.
        Returns None when closed and empty."""
        with self._cv:
            while True:
                if self._error is not None or self._wedged:
                    return None
                if self._pending and not self._paused:
                    break
                if self._closed:
                    return None
                self._cv.wait(timeout=0.5)
            span_id, payload, coalesce = self._pending.popleft()
            ids, payloads = [span_id], [payload]
            if coalesce and self._coalesce_fn is not None and \
                    self.coalesce_records > 0:
                total = self._records_fn(payload)
                while self._pending:
                    nid, npay, nco = self._pending[0]
                    if not nco:
                        break
                    nrec = self._records_fn(npay)
                    if total + nrec > self.coalesce_records:
                        break
                    self._pending.popleft()
                    ids.append(nid)
                    payloads.append(npay)
                    total += nrec
                if len(ids) > 1:
                    self.stats.coalesced_groups += 1
            return _Group(ids, payloads)

    def _gate_acquire(self, group: _Group) -> None:
        """The dispatch-ahead bound: wait until fewer than ``depth`` groups
        are past the staging gate."""
        with self._cv:
            while self._in_flight >= self.depth and self._error is None:
                self._cv.wait(timeout=0.5)
            self._in_flight += 1
            group.gate_held = True
            self.stats.max_in_flight = max(self.stats.max_in_flight,
                                           self._in_flight)

    def _gate_release(self, group: _Group) -> None:
        """Release the group's gate slot exactly once (the watchdog and a
        late-returning worker may both reach a release path)."""
        with self._cv:
            if group.gate_held:
                group.gate_held = False
                self._in_flight -= 1
                self._cv.notify_all()

    def _claim(self, group: _Group) -> bool:
        """Win the right to finish this group.  Exactly one of {worker
        thread, watchdog monitor} completes/fails a group; the other side's
        late outcome is discarded."""
        with self._lock:
            if group.claimed:
                return False
            group.claimed = True
            return True

    def _fail(self, exc: BaseException) -> None:
        with self._cv:
            if self._error is None:
                self._error = exc
            self._cv.notify_all()

    def _complete(self, group: _Group, ids: Tuple[Any, ...],
                  result: Any) -> None:
        with self._complete_lock:
            if self._on_complete is not None:
                self._on_complete(ids, result)
            with self._cv:
                for sid in ids:
                    self._results[sid] = result
                    self._completion_order.append(sid)
                self.stats.completed += len(ids)
                self._open_spans -= len(ids)
                self._cv.notify_all()

    def _staging_loop(self) -> None:
        while True:
            group = self._next_group()
            if group is None:
                return
            ids = tuple(group.ids)
            try:
                # The gate is taken BEFORE encode: depth bounds everything
                # past raw payloads, so host staging memory (padded
                # matrices + lane arrays) is bounded by depth spans too.
                self._gate_acquire(group)
                if self._error is not None:
                    self._gate_release(group)
                    return
                if self._breaker is not None and \
                        not self._breaker.allow_device():
                    # breaker open: the device engine is sick — route the
                    # group straight to the host engine, never touch the
                    # chip
                    _count(self._counters, "device.breaker.short_circuits",
                           len(ids))
                    self._claim(group)
                    self._failover_group(group, ids, reason="breaker-open")
                    continue
                t0 = self._mark(ids, STAGE_ENCODE, "start")
                with tracing.span(STAGE_ENCODE, cat="device",
                                  spans=repr(list(ids))):
                    staged = [self._encode_fn(p) for p in group.payloads]
                t1 = self._mark(ids, STAGE_ENCODE, "end")
                self._observe(STAGE_ENCODE, t0, t1)
                one = staged[0] if len(staged) == 1 else \
                    self._coalesce_fn(staged)
                t0 = self._mark(ids, STAGE_H2D, "start")
                with tracing.span(STAGE_H2D, cat="device",
                                  spans=repr(list(ids))):
                    if self._stage_fn is not None:
                        one = self._stage_fn(one)
                t1 = self._mark(ids, STAGE_H2D, "end")
                self._observe(STAGE_H2D, t0, t1)
                t_d = self._mark(ids, STAGE_DISPATCH, "start")
                self._watch_begin(group, ids, STAGE_DISPATCH,
                                  self._watchdog_dispatch_ms)
                try:
                    # chaos seams: an injected hang (delay mode) sits
                    # inside the watch window like a stuck XLA dispatch;
                    # an injected OOM drives the split/fallback ladder
                    if faults.armed():
                        for sid in ids:
                            faults.fire("device.dispatch.oom",
                                        f"span={sid}")
                            faults.fire("device.dispatch.hang",
                                        f"span={sid}")
                    with tracing.span(STAGE_DISPATCH, cat="device",
                                      spans=repr(list(ids))):
                        inflight = self._dispatch_fn(one)
                finally:
                    self._watch_end(group)
                self._mark(ids, STAGE_DISPATCH, "end")
                if group.claimed:
                    # the watchdog abandoned this dispatch while we were
                    # stuck in it and already failed the group over; our
                    # late result is dead and so is this thread's queue
                    # (the monitor owns _pending once _wedged is set)
                    return
                group.staged = None
                group.inflight = inflight
                group.t_dispatch = t_d
                with self._lock:
                    self.stats.dispatched += 1
                self._readback.submit(self._readback_one, group, ids)
            except BaseException as e:  # noqa: BLE001 — surfaces via drain
                self._contain_failure(group, ids, e)
                if self._error is not None:
                    return

    # -- readback workers ----------------------------------------------------
    def _readback_one(self, group: _Group, ids: Tuple[Any, ...]) -> None:
        try:
            if faults.armed():
                for sid in ids:
                    faults.fire("device.readback.fail", f"span={sid}")
            t0 = self._mark(ids, STAGE_D2H, "start")
            self._watch_begin(group, ids, STAGE_D2H,
                              self._watchdog_readback_ms)
            try:
                with tracing.span(STAGE_D2H, cat="device",
                                  spans=repr(list(ids))):
                    result = self._readback_fn(group.inflight, ids)
            finally:
                self._watch_end(group)
            t1 = self._mark(ids, STAGE_D2H, "end")
            self._observe(STAGE_D2H, t0, t1)
            self._observe(self._dispatch_wait_hist, group.t_dispatch, t1)
            # deterministic completion-reorder hook (chaos/test plane):
            # a delay rule here holds THIS span's completion while later
            # spans drain through the other workers
            if faults.armed():
                for sid in ids:
                    faults.fire("device.dispatch.delay", f"span={sid}")
        except BaseException as e:  # noqa: BLE001 — surfaces via drain
            self._contain_failure(group, ids, e)
            return
        if not self._claim(group):
            return  # watchdog abandoned this attempt mid-readback
        if self._breaker is not None:
            self._breaker.record_success(self._counters)
        self._gate_release(group)
        try:
            self._complete(group, ids, result)
        except BaseException as e:  # noqa: BLE001 — completion errors are
            self._fail(e)           # final: the group is already claimed

    # -- failure containment -------------------------------------------------
    def _contain_failure(self, group: _Group, ids: Tuple[Any, ...],
                         exc: BaseException) -> None:
        """The containment ladder for a device-attempt failure: OOM ->
        split retry on device -> host failover; anything else -> host
        failover; no failover hook -> poison the pipeline (the original
        contract)."""
        if self._failover_fn is None or \
                isinstance(exc, (KeyboardInterrupt, SystemExit)):
            self._gate_release(group)
            self._fail(exc)
            return
        if not self._claim(group):
            return  # the watchdog already owns this group's outcome
        if self._breaker is not None:
            self._breaker.record_failure(self._counters)
        if self._oom_retry_fn is not None and _is_oom(exc):
            with self._lock:
                self.stats.oom_splits += 1
            _count(self._counters, "device.oom.split_attempts")
            tracing.event("device.oom.split", spans=repr(list(ids)),
                          error=str(exc)[:200])
            try:
                with tracing.span("device.oom_retry", cat="device",
                                  spans=repr(list(ids))):
                    result = self._oom_retry_fn(ids, group.payloads)
            except BaseException as e2:  # noqa: BLE001 — ladder continues
                exc = e2  # floor reached / split retry failed: host takes it
            else:
                # the split retry finished ON DEVICE: the engine is alive
                _count(self._counters, "device.oom.split_success")
                if self._breaker is not None:
                    self._breaker.record_success(self._counters)
                self._gate_release(group)
                try:
                    self._complete(group, ids, result)
                except BaseException as e3:  # noqa: BLE001
                    self._fail(e3)
                return
        self._failover_group(group, ids, reason=type(exc).__name__,
                             cause=exc)

    def _failover_group(self, group: _Group, ids: Tuple[Any, ...],
                        reason: str,
                        cause: Optional[BaseException] = None) -> None:
        """Re-sort a claimed group through the host engine and complete it;
        a failover failure is final (poisons the pipeline)."""
        try:
            t0 = self._mark(ids, STAGE_FAILOVER, "start")
            tracing.event("device.failover", spans=repr(list(ids)),
                          reason=reason)
            with tracing.span(STAGE_FAILOVER, cat="device",
                              spans=repr(list(ids)), reason=reason):
                result = self._failover_fn(ids, group.payloads)
            t1 = self._mark(ids, STAGE_FAILOVER, "end")
            self._observe(FAILOVER_HIST, t0, t1)
            with self._lock:
                self.stats.failovers += 1
            _count(self._counters, "device.failover.spans", len(ids))
            _count(self._counters, "device.failover.groups")
            self._gate_release(group)
            self._complete(group, ids, result)
        except BaseException as e:  # noqa: BLE001 — surfaces via drain
            if cause is not None and e is not cause:
                e.__cause__ = cause
            self._gate_release(group)
            self._fail(e)

    # -- watchdog monitor ----------------------------------------------------
    def _watch_begin(self, group: _Group, ids: Tuple[Any, ...], stage: str,
                     budget_ms: float) -> None:
        if self._monitor is None or budget_ms <= 0:
            return
        with self._lock:
            self._watch[id(group)] = (
                group, ids, stage, self._clock() + budget_ms / 1000.0)

    def _watch_end(self, group: _Group) -> None:
        if self._monitor is None:
            return
        with self._lock:
            self._watch.pop(id(group), None)

    def _watchdog_loop(self) -> None:
        while not self._monitor_stop.wait(self._poll_s):
            now = self._clock()
            expired: List[Tuple[_Group, Tuple[Any, ...], str]] = []
            with self._lock:
                for key, (group, ids, stage, deadline) in \
                        list(self._watch.items()):
                    if now >= deadline:
                        del self._watch[key]
                        expired.append((group, ids, stage))
            for group, ids, stage in expired:
                self._watchdog_fire(group, ids, stage)
            if self._wedged:
                self._drain_pending_failover()

    def _watchdog_fire(self, group: _Group, ids: Tuple[Any, ...],
                       stage: str) -> None:
        if not self._claim(group):
            return  # the attempt finished between expiry check and here
        self._mark(ids, "device.watchdog", "fire")
        self._abandoned = True
        with self._lock:
            self.stats.watchdog_fires += 1
        _count(self._counters, "device.watchdog.fires")
        _count(self._counters,
               "device.watchdog.dispatch_fires"
               if stage == STAGE_DISPATCH else
               "device.watchdog.readback_fires")
        tracing.event("device.watchdog.fired", stage=stage,
                      spans=repr(list(ids)))
        _flight.record(_flight.WATCHDOG, stage, a=len(ids))
        _flight.auto_dump(f"device.watchdog.{stage}")
        if stage == STAGE_DISPATCH:
            # the staging thread is stuck inside dispatch_fn: no further
            # group will ever be pulled — hand the queue to this monitor
            with self._cv:
                self._wedged = True
                self._cv.notify_all()
        if self._breaker is not None:
            self._breaker.record_failure(self._counters)
        self._failover_group(group, ids, reason=f"watchdog:{stage}")

    def _drain_pending_failover(self) -> None:
        """Monitor-thread path: with the staging thread wedged, pull the
        remaining queued spans and complete them through failover (these
        never passed the gate — no slot to release)."""
        while True:
            with self._cv:
                if not self._pending:
                    return
                span_id, payload, _co = self._pending.popleft()
            group = _Group([span_id], [payload])
            group.claimed = True
            _count(self._counters, "device.failover.drained")
            self._failover_group(group, (span_id,), reason="staging-wedged")


def _is_oom(exc: BaseException) -> bool:
    from tez_tpu.ops.device import is_resource_exhausted
    return is_resource_exhausted(exc)


def overlap_pairs(events: Sequence[Tuple[Any, str, str, float]]
                  ) -> List[Tuple[Any, Any]]:
    """Instrumentation helper: pairs (a, b) where span-group b's encode
    started strictly before span-group a's dispatch COMPLETED (its readback
    finished — the dispatch call itself returns immediately under JAX's
    async dispatch, so D2H end is the completion edge).  This is the
    pipeline's overlap witness; with the injectable clock it is
    deterministic under a fake clock."""
    complete: Dict[Any, float] = {}
    encode_start: Dict[Any, float] = {}
    order: List[Any] = []
    for ids, stage, edge, t in events:
        if stage == STAGE_D2H and edge == "end":
            complete[ids] = t
        elif stage == STAGE_ENCODE and edge == "start":
            encode_start[ids] = t
            order.append(ids)
    out = []
    for i, a in enumerate(order):
        for b in order[i + 1:]:
            if a in complete and b in encode_start and \
                    encode_start[b] < complete[a]:
                out.append((a, b))
    return out
