"""Asynchronous double-buffered device staging pipeline.

The device data plane used to pay every span serially: host ragged->lane
encode, H2D staging, sort dispatch, partition-index readback — one span at a
time, the chip idle during host work and the host idle during device work.
This module is the overlap engine (Exoshuffle / pipelined-TF lesson: at data-
plane scale *staging overlap*, not kernel speed, is the dominant lever):

  submit(span k+2) ... -> [encode+stage span k+1]   (staging thread)
                          [dispatch span k]         (device in flight)
                          [readback span k-1]       (readback workers)

Design points:

* **Bounded dispatch-ahead.**  At most ``depth`` spans are past the staging
  gate at once (encoded/staged/dispatched but not yet fully read back).
  ``depth=2`` is classic double buffering: one span on the device, one
  staged and ready to go the moment the device frees.  The submit side is
  *not* blocked by the gate — spans queue host-side as raw payloads (cheap:
  the collector's own buffers) and the staging thread pulls them through.
* **Out-of-order completion.**  Readback runs on a small worker pool, so a
  span stalled in D2H (or delayed by the ``device.dispatch.delay`` fault
  point) does not block the span behind it.  Completion callbacks therefore
  fire in *completion* order; callers that need submission order key their
  results by span id (DeviceSorter keys runs by spill id).
* **Span batching.**  Spans submitted with ``coalesce=True`` are merged by
  the staging thread into one bucketed dispatch while their combined record
  count fits ``coalesce_records`` — many small spans amortize one
  dispatch's trace/compile-cache/launch overhead (the chatter killer for
  small-span workloads).
* **Deterministic instrumentation.**  The clock is injectable and every
  stage transition lands in ``events`` when ``instrument=True`` — the
  scheduler's overlap contract (span k+1's encode starts before span k's
  dispatch completes; in-flight depth never exceeds the bound) is asserted
  by unit tests against a fake clock, not by eyeballing wall time.

Every stage emits ``common/tracing.py`` spans (``device.encode`` /
``device.h2d`` / ``device.dispatch`` / ``device.d2h``) and the matching
``common/metrics.py`` histograms (``device.encode``, ``device.h2d``,
``device.dispatch_wait``, ``device.d2h``), so the overlap is visible in a
Perfetto export and regressions show up in ``tools/counter_diff.py``.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tez_tpu.common import faults, metrics, tracing

#: Stage names, in pipeline order (also the tracing span names).
STAGE_ENCODE = "device.encode"
STAGE_H2D = "device.h2d"
STAGE_DISPATCH = "device.dispatch"
STAGE_D2H = "device.d2h"

#: Histogram fed by the dispatch->readback-complete interval: how long a
#: dispatched program was in flight before its results were host-visible.
DISPATCH_WAIT_HIST = "device.dispatch_wait"


class PipelineStats:
    """Counters the scheduler maintains under its lock; snapshot freely."""

    __slots__ = ("submitted", "dispatched", "completed", "coalesced_groups",
                 "max_in_flight")

    def __init__(self) -> None:
        self.submitted = 0
        self.dispatched = 0
        self.completed = 0
        self.coalesced_groups = 0
        self.max_in_flight = 0

    def to_dict(self) -> Dict[str, int]:
        return {s: getattr(self, s) for s in self.__slots__}


class _Group:
    """One dispatch unit: one span, or several coalesced small spans."""

    __slots__ = ("ids", "payloads", "staged", "inflight", "t_dispatch")

    def __init__(self, ids: List[Any], payloads: List[Any]) -> None:
        self.ids = ids
        self.payloads = payloads
        self.staged: Any = None
        self.inflight: Any = None
        self.t_dispatch = 0.0


class AsyncSpanPipeline:
    """Bounded dispatch-ahead scheduler over caller-provided stage functions.

    Parameters
    ----------
    encode_fn(payload) -> staged
        Host-side work (ragged->lane encode, precombine).  Runs on the
        staging thread; overlaps in-flight device work.
    stage_fn(staged) -> staged'
        H2D staging: uploads host arrays, returns device handles.  Runs on
        the staging thread right after encode (its cost is histogrammed
        separately).  May be None (encode_fn already staged).
    dispatch_fn(staged) -> inflight
        Launches the device program.  Must be *asynchronous* (JAX dispatch
        semantics: returns futures-backed arrays immediately).
    readback_fn(inflight, ids) -> result
        Blocks until device results are host-visible and builds the final
        result.  Runs on readback workers; may complete out of order.
    coalesce_fn(list_of_staged) -> staged
        Merges several staged spans into one dispatch unit.  Required only
        when callers submit with ``coalesce=True``.
    records_fn(payload) -> int
        Span size in records, used by the coalescing budget.
    on_complete(ids, result)
        Completion callback; ids is the tuple of span ids the dispatch
        covered (len 1 unless coalesced).  May fire out of submission
        order; the pipeline serializes calls (one at a time) but makes no
        ordering promise.
    depth
        Max groups past the staging gate (staged or in flight).  2 =
        double buffering.
    """

    def __init__(self,
                 dispatch_fn: Callable[[Any], Any],
                 readback_fn: Callable[[Any, Tuple[Any, ...]], Any],
                 encode_fn: Optional[Callable[[Any], Any]] = None,
                 stage_fn: Optional[Callable[[Any], Any]] = None,
                 coalesce_fn: Optional[Callable[[List[Any]], Any]] = None,
                 records_fn: Optional[Callable[[Any], int]] = None,
                 on_complete: Optional[Callable[[Tuple[Any, ...], Any],
                                                None]] = None,
                 depth: int = 2,
                 coalesce_records: int = 0,
                 readback_workers: int = 2,
                 counters: Any = None,
                 clock: Callable[[], float] = time.perf_counter,
                 instrument: bool = False,
                 paused: bool = False,
                 name: str = "device-pipeline") -> None:
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self._encode_fn = encode_fn or (lambda p: p)
        self._stage_fn = stage_fn
        self._dispatch_fn = dispatch_fn
        self._readback_fn = readback_fn
        self._coalesce_fn = coalesce_fn
        self._records_fn = records_fn or (lambda p: 1)
        self._on_complete = on_complete
        self.depth = depth
        self.coalesce_records = coalesce_records
        self._counters = counters
        self._clock = clock
        self._name = name
        self.stats = PipelineStats()
        #: (span_id_or_ids, stage, edge, t) when instrument=True
        self.events: List[Tuple[Any, str, str, float]] = []
        self._instrument = instrument

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: "collections.deque[Tuple[Any, Any, bool]]" = \
            collections.deque()
        self._in_flight = 0          # groups past the staging gate
        self._open_spans = 0         # submitted, not yet completed
        self._results: Dict[Any, Any] = {}
        self._completion_order: List[Any] = []
        self._error: Optional[BaseException] = None
        self._closed = False
        #: paused=True holds the staging thread until resume(): callers that
        #: want DETERMINISTIC coalescing submit every span first, then
        #: resume — otherwise the staging thread races the submit loop and
        #: group boundaries depend on scheduling
        self._paused = paused
        self._complete_lock = threading.Lock()

        self._staging = threading.Thread(
            target=self._staging_loop, name=f"{name}-staging", daemon=True)
        self._staging.start()
        import concurrent.futures
        self._readback = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, readback_workers),
            thread_name_prefix=f"{name}-readback")

    # -- instrumentation -----------------------------------------------------
    def _mark(self, ids: Any, stage: str, edge: str) -> float:
        t = self._clock()
        if self._instrument:
            with self._lock:
                self.events.append((ids, stage, edge, t))
        return t

    def _observe(self, hist: str, t0: float, t1: float) -> None:
        metrics.observe(hist, max(0.0, (t1 - t0) * 1000.0),
                        counters=self._counters)

    # -- submit side ---------------------------------------------------------
    def submit(self, span_id: Any, payload: Any,
               coalesce: bool = False) -> None:
        """Queue a span.  Never blocks on the dispatch-ahead gate (raw
        payloads are the collector's own buffers); raises the pipeline's
        first stage error if one already occurred."""
        with self._cv:
            if self._error is not None:
                raise RuntimeError(
                    f"{self._name}: pipeline failed") from self._error
            if self._closed:
                raise RuntimeError(f"{self._name}: submit after drain")
            self._pending.append((span_id, payload, coalesce))
            self._open_spans += 1
            self.stats.submitted += 1
            self._cv.notify_all()

    def resume(self) -> None:
        """Release a pipeline constructed with paused=True."""
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def drain(self) -> Dict[Any, Any]:
        """Block until every submitted span completed; stop the staging
        thread; re-raise the first stage error.  Returns {span_id: result}
        (completion order preserved in ``completion_order``)."""
        with self._cv:
            self._paused = False
            self._closed = True
            self._cv.notify_all()
            while self._open_spans > 0 and self._error is None:
                self._cv.wait(timeout=0.5)
            error = self._error
        self._staging.join(timeout=30.0)
        self._readback.shutdown(wait=True)
        if error is not None:
            raise error
        return dict(self._results)

    @property
    def completion_order(self) -> List[Any]:
        with self._lock:
            return list(self._completion_order)

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    # -- staging thread ------------------------------------------------------
    def _next_group(self) -> Optional[_Group]:
        """Pop the next dispatch unit, coalescing greedily while allowed.
        Returns None when closed and empty."""
        with self._cv:
            while True:
                if self._error is not None:
                    return None
                if self._pending and not self._paused:
                    break
                if self._closed:
                    return None
                self._cv.wait(timeout=0.5)
            span_id, payload, coalesce = self._pending.popleft()
            ids, payloads = [span_id], [payload]
            if coalesce and self._coalesce_fn is not None and \
                    self.coalesce_records > 0:
                total = self._records_fn(payload)
                while self._pending:
                    nid, npay, nco = self._pending[0]
                    if not nco:
                        break
                    nrec = self._records_fn(npay)
                    if total + nrec > self.coalesce_records:
                        break
                    self._pending.popleft()
                    ids.append(nid)
                    payloads.append(npay)
                    total += nrec
                if len(ids) > 1:
                    self.stats.coalesced_groups += 1
            return _Group(ids, payloads)

    def _gate_acquire(self) -> None:
        """The dispatch-ahead bound: wait until fewer than ``depth`` groups
        are past the staging gate."""
        with self._cv:
            while self._in_flight >= self.depth and self._error is None:
                self._cv.wait(timeout=0.5)
            self._in_flight += 1
            self.stats.max_in_flight = max(self.stats.max_in_flight,
                                           self._in_flight)

    def _gate_release(self) -> None:
        with self._cv:
            self._in_flight -= 1
            self._cv.notify_all()

    def _fail(self, exc: BaseException) -> None:
        with self._cv:
            if self._error is None:
                self._error = exc
            self._cv.notify_all()

    def _staging_loop(self) -> None:
        while True:
            group = self._next_group()
            if group is None:
                return
            ids = tuple(group.ids)
            try:
                # The gate is taken BEFORE encode: depth bounds everything
                # past raw payloads, so host staging memory (padded
                # matrices + lane arrays) is bounded by depth spans too.
                self._gate_acquire()
                if self._error is not None:
                    self._gate_release()
                    return
                t0 = self._mark(ids, STAGE_ENCODE, "start")
                with tracing.span(STAGE_ENCODE, cat="device",
                                  spans=repr(list(ids))):
                    staged = [self._encode_fn(p) for p in group.payloads]
                t1 = self._mark(ids, STAGE_ENCODE, "end")
                self._observe(STAGE_ENCODE, t0, t1)
                one = staged[0] if len(staged) == 1 else \
                    self._coalesce_fn(staged)
                t0 = self._mark(ids, STAGE_H2D, "start")
                with tracing.span(STAGE_H2D, cat="device",
                                  spans=repr(list(ids))):
                    if self._stage_fn is not None:
                        one = self._stage_fn(one)
                t1 = self._mark(ids, STAGE_H2D, "end")
                self._observe(STAGE_H2D, t0, t1)
                t_d = self._mark(ids, STAGE_DISPATCH, "start")
                with tracing.span(STAGE_DISPATCH, cat="device",
                                  spans=repr(list(ids))):
                    inflight = self._dispatch_fn(one)
                self._mark(ids, STAGE_DISPATCH, "end")
                group.staged = None
                group.inflight = inflight
                group.t_dispatch = t_d
                with self._lock:
                    self.stats.dispatched += 1
                self._readback.submit(self._readback_one, group, ids)
            except BaseException as e:  # noqa: BLE001 — surfaces via drain
                self._gate_release()
                self._fail(e)
                return

    # -- readback workers ----------------------------------------------------
    def _readback_one(self, group: _Group, ids: Tuple[Any, ...]) -> None:
        try:
            t0 = self._mark(ids, STAGE_D2H, "start")
            with tracing.span(STAGE_D2H, cat="device",
                              spans=repr(list(ids))):
                result = self._readback_fn(group.inflight, ids)
            t1 = self._mark(ids, STAGE_D2H, "end")
            self._observe(STAGE_D2H, t0, t1)
            self._observe(DISPATCH_WAIT_HIST, group.t_dispatch, t1)
            # deterministic completion-reorder hook (chaos/test plane):
            # a delay rule here holds THIS span's completion while later
            # spans drain through the other workers
            if faults.armed():
                for sid in ids:
                    faults.fire("device.dispatch.delay", f"span={sid}")
            self._gate_release()
            with self._complete_lock:
                if self._on_complete is not None:
                    self._on_complete(ids, result)
                with self._cv:
                    for sid in ids:
                        self._results[sid] = result
                        self._completion_order.append(sid)
                    self.stats.completed += len(ids)
                    self._open_spans -= len(ids)
                    self._cv.notify_all()
        except BaseException as e:  # noqa: BLE001 — surfaces via drain
            self._gate_release()
            self._fail(e)


def overlap_pairs(events: Sequence[Tuple[Any, str, str, float]]
                  ) -> List[Tuple[Any, Any]]:
    """Instrumentation helper: pairs (a, b) where span-group b's encode
    started strictly before span-group a's dispatch COMPLETED (its readback
    finished — the dispatch call itself returns immediately under JAX's
    async dispatch, so D2H end is the completion edge).  This is the
    pipeline's overlap witness; with the injectable clock it is
    deterministic under a fake clock."""
    complete: Dict[Any, float] = {}
    encode_start: Dict[Any, float] = {}
    order: List[Any] = []
    for ids, stage, edge, t in events:
        if stage == STAGE_D2H and edge == "end":
            complete[ids] = t
        elif stage == STAGE_ENCODE and edge == "start":
            encode_start[ids] = t
            order.append(ids)
    out = []
    for i, a in enumerate(order):
        for b in order[i + 1:]:
            if a in complete and b in encode_start and \
                    encode_start[b] < complete[a]:
                out.append((a, b))
    return out
