"""Device sorter: PipelinedSorter semantics on TPU kernels.

Reference parity: tez-runtime-library/.../common/sort/impl/PipelinedSorter.java:75
— records collect into spans; full spans sort independently (there: background
threads, here: device kernels while the host keeps collecting); flush merges
spans (or, pipelined, emits each span as its own spill).  Spill-to-host-disk
replaces spill-to-local-FS.

Exactness: the device sorts by (partition, fixed-width key prefix) stably;
rows whose keys exceed the prefix width get a host tie-break pass so final
order equals full raw-byte order for ANY key length (SURVEY.md §7
"byte-identical ordered output").
"""
from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from tez_tpu.common.counters import TaskCounter, TezCounters
from tez_tpu.ops import device
from tez_tpu.ops.keycodec import encode_keys, pad_to_matrix, matrix_to_lanes
from tez_tpu.ops.runformat import (FileRun, KVBatch, PartitionedRunWriter,
                                   Run, adjacent_equal_rows, gather_ragged,
                                   save_run_partitioned)

log = logging.getLogger(__name__)


def _exact_tiebreak(lengths: np.ndarray, partitions: np.ndarray,
                    lanes: np.ndarray, width: int,
                    keyfn: Callable[[int], bytes]) -> Optional[np.ndarray]:
    """Return a refinement permutation for rows whose sorted (partition,
    prefix) group contains a SORT key longer than `width`, or None if exact
    already.  `lengths`/`keyfn` describe the sort keys in sorted order (the
    normalized keys when a comparator is configured).  Host cost is
    proportional to colliding rows only."""
    if len(lengths) == 0 or lengths.max(initial=0) <= width:
        return None
    clamped = np.minimum(lengths, width + 1)
    same_as_prev = np.zeros(len(lengths), dtype=bool)
    if len(lengths) > 1:
        same_as_prev[1:] = (partitions[1:] == partitions[:-1]) & \
            (clamped[1:] == clamped[:-1]) & \
            np.all(lanes[1:] == lanes[:-1], axis=1)
    # group starts
    starts = np.flatnonzero(~same_as_prev)
    ends = np.append(starts[1:], len(lengths))
    perm = np.arange(len(lengths), dtype=np.int64)
    changed = False
    for s, e in zip(starts, ends):
        if e - s <= 1:
            continue
        if int(lengths[s:e].max()) <= width:
            continue  # prefix fully determined the order
        keys = [keyfn(i) for i in range(s, e)]
        order = sorted(range(e - s), key=lambda j: keys[j])
        if order != list(range(e - s)):
            perm[s:e] = s + np.asarray(order, dtype=np.int64)
            changed = True
    return perm if changed else None


def _sorted_key_view(sort_bytes: np.ndarray, sort_offsets: np.ndarray,
                     perm: np.ndarray
                     ) -> Tuple[np.ndarray, Callable[[int], bytes]]:
    """(lengths, keyfn) over the sort keys in sorted order, slicing the
    already-materialized ragged arrays (no re-normalization)."""
    starts = sort_offsets[:-1][perm]
    lengths = (sort_offsets[1:] - sort_offsets[:-1])[perm]

    def keyfn(i: int) -> bytes:
        s = int(starts[i])
        return sort_bytes[s:s + int(lengths[i])].tobytes()

    return lengths, keyfn


def normalize_batch_keys(batch: KVBatch,
                         normalizer: Callable[[bytes], bytes]
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Materialize normalized sort keys as ragged (bytes, offsets) arrays.
    Per-record host cost — paid only when a custom comparator is configured
    (the reference's RawComparator pays per-COMPARISON, which is worse)."""
    n = batch.num_records
    keys = [normalizer(batch.key(i)) for i in range(n)]
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    data = np.frombuffer(b"".join(keys), dtype=np.uint8)
    return data, offsets


class SpanBuffer:
    """Collect-side buffer: raw bytes accumulated until the span budget."""

    def __init__(self) -> None:
        self.keys: List[bytes] = []
        self.vals: List[bytes] = []
        self.parts: List[int] = []     # only when a custom partitioner runs
        self.nbytes = 0
        self.batches: List[KVBatch] = []
        self._partitioned: Optional[bool] = None   # set by the first add
        self.all_pre_combined = True   # every added batch promised unique keys

    def _set_mode(self, partitioned: bool) -> None:
        if self._partitioned is None:
            self._partitioned = partitioned
        elif self._partitioned != partitioned:
            raise ValueError(
                "cannot mix partitioned and unpartitioned writes in one "
                "span (custom Partitioner output must cover every record)")

    def add(self, key: bytes, value: bytes,
            partition: Optional[int] = None) -> None:
        self._set_mode(partition is not None)
        self.all_pre_combined = False
        self.keys.append(key)
        self.vals.append(value)
        if partition is not None:
            self.parts.append(partition)
        self.nbytes += len(key) + len(value) + 16

    def add_batch(self, batch: KVBatch) -> None:
        self._set_mode(False)
        if not batch.pre_combined:
            self.all_pre_combined = False
        self.batches.append(batch)
        self.nbytes += batch.nbytes

    @property
    def num_records(self) -> int:
        return len(self.keys) + sum(b.num_records for b in self.batches)

    def to_batch(self) -> KVBatch:
        parts = list(self.batches)
        if self.keys:
            parts.append(KVBatch.from_pairs(list(zip(self.keys, self.vals))))
        if not parts:
            return KVBatch.empty()
        return parts[0] if len(parts) == 1 else KVBatch.concat(parts)


Combiner = Callable[[Run], Run]

#: Below this many records a device dispatch (trace/compile-cache lookup +
#: H2D/D2H) costs more than the host sort itself; the device engine routes
#: smaller spans to the host sorter.  The TPU-native framework pattern:
#: accelerate the big batches, keep the chatter off the chip.
DEVICE_SORT_MIN_RECORDS = 1 << 16

#: Auto-engine floor on a span's total SORT-KEY bytes for the device path
#: (tez.runtime.sort.engine.min-bytes).  The device sorts key lanes only —
#: wide-VALUE spans clear the record-count bar while carrying few key bytes,
#: so the dispatch+transfer overhead buys almost no device work and the
#: host gather of the wide values dominates either way.  Only consulted
#: when the engine was requested as `auto`; an explicit engine=device is
#: never silently rerouted by width.
ENGINE_MIN_KEY_BYTES = 1 << 20

#: Failure-containment defaults for the async device plane (overridden by
#: the tez.runtime.device.* knobs via library/outputs.py).
DEVICE_WATCHDOG_DISPATCH_MS = 60_000.0
DEVICE_WATCHDOG_READBACK_MS = 60_000.0
DEVICE_BREAKER_FAILURES = 3
DEVICE_BREAKER_COOLDOWN_MS = 5_000.0
DEVICE_SPLIT_MIN_BYTES = 1 << 20


def resolve_engine(engine: str) -> str:
    """Resolve the `auto` engine: device kernels when an accelerator
    backend answers, host kernels on the CPU fallback (where an XLA:CPU
    sort + dispatch round-trip loses to numpy/native outright).  Per-span
    width/count routing happens later (DeviceSorter._span_engine)."""
    if engine == "auto":
        return "device" if device.accelerator_present() else "host"
    return engine


def _route_engine(engine: str, n: int, min_records: int,
                  key_nbytes: int = -1, min_key_bytes: int = 0) -> str:
    """Per-span engine routing: host below the record-count floor and —
    when the caller opts in by passing key_nbytes >= 0 (auto engines) —
    host below the key-byte floor too."""
    if engine != "device":
        return engine
    if n < min_records:
        return "host"
    if min_key_bytes > 0 and 0 <= key_nbytes < min_key_bytes:
        return "host"
    return engine


class DeviceSorter:
    """The OrderedPartitionedKVOutput engine."""

    def __init__(self, num_partitions: int, key_width: int = 16,
                 span_budget_bytes: int = 256 << 20,
                 spill_dir: Optional[str] = None,
                 counters: Optional[TezCounters] = None,
                 combiner: Optional[Combiner] = None,
                 partitioner: str = "hash",
                 mem_budget_bytes: Optional[int] = None,
                 engine: str = "device",
                 sort_threads: int = 0,
                 merge_factor: int = 64,
                 key_normalizer: Optional[Callable[[bytes], bytes]] = None,
                 spill_codec: Optional[str] = None,
                 resident_keys: bool = True,
                 device_min_records: int = DEVICE_SORT_MIN_RECORDS,
                 engine_min_bytes: int = ENGINE_MIN_KEY_BYTES,
                 pipeline_depth: int = 0,
                 pipeline_coalesce_records: int = -1,
                 watchdog_dispatch_ms: float = DEVICE_WATCHDOG_DISPATCH_MS,
                 watchdog_readback_ms: float = DEVICE_WATCHDOG_READBACK_MS,
                 breaker_failures: int = DEVICE_BREAKER_FAILURES,
                 breaker_cooldown_ms: float = DEVICE_BREAKER_COOLDOWN_MS,
                 split_min_bytes: int = DEVICE_SPLIT_MIN_BYTES,
                 breaker=None):
        self.num_partitions = num_partitions
        self.key_width = max(4, key_width)
        # 'device' (TPU kernels) | 'host' (np.lexsort/native) | 'auto'
        self.engine = resolve_engine(engine)
        #: width-aware auto routing: a span only takes the device path when
        #: its total key bytes clear this floor TOO (never applied to an
        #: explicitly requested device engine)
        self._auto_engine = engine == "auto"
        self.engine_min_bytes = engine_min_bytes
        self.device_min_records = device_min_records
        #: async double-buffered device plane (ops/async_stage.py): spans
        #: submit to a bounded dispatch-ahead pipeline — span k+1's host
        #: encode/H2D overlaps span k's in-flight sort while span k-1's
        #: readback drains; completed runs collect out-of-order and are
        #: reassembled in spill-id order at flush (bit-exact vs sync).
        #: 0 = synchronous spans (host engines: the pipeline only helps
        #: when a dispatch actually leaves the host, so it stays off).
        self.pipeline_depth = pipeline_depth if self.engine == "device" else 0
        #: span-batching budget (records): small adjacent spans coalesce
        #: into ONE bucketed dispatch while their sum fits.  -1 = auto
        #: (device_min_records: exactly the spans too small to be worth a
        #: dispatch each), 0 = off.
        self.pipeline_coalesce_records = (
            device_min_records if pipeline_coalesce_records < 0
            else pipeline_coalesce_records)
        self._pipeline = None
        self._async_store_ids: List[int] = []
        #: failure containment for the async plane (ops/async_stage.py):
        #: watchdog deadlines, host-engine failover via the circuit
        #: breaker, and the OOM split floor.  breaker=None = the sticky
        #: per-process breaker (a sick chip is a process property).
        self.watchdog_dispatch_ms = watchdog_dispatch_ms
        self.watchdog_readback_ms = watchdog_readback_ms
        self.breaker_failures = breaker_failures
        self.breaker_cooldown_ms = breaker_cooldown_ms
        self.split_min_bytes = split_min_bytes
        self._breaker = breaker
        #: keep sorted key lanes in HBM for downstream device merges.  The
        #: pinned HBM (~(key width + 4) B/row per registered output, freed
        #: at DAG deletion) is OUTSIDE the host memory budgets — operators
        #: of long many-output DAGs can turn it off
        #: (tez.runtime.tpu.resident.keys).
        self.resident_keys = resident_keys
        #: custom comparator as key normalization (library/comparators.py);
        #: None = sort by raw key bytes (zero-cost default)
        self.key_normalizer = key_normalizer
        #: host-spill compression (reference: tez.runtime.compress on IFile)
        self.spill_codec = spill_codec
        self.span_budget = span_budget_bytes
        self.spill_dir = spill_dir
        self.counters = counters or TezCounters()
        # per-record hot path: resolve the counter ONCE (find_counter takes
        # a registry lock per call)
        self._out_records_ctr = self.counters.find_counter(
            TaskCounter.OUTPUT_RECORDS)
        self.combiner = combiner
        self.partitioner = partitioner
        self.mem_budget = mem_budget_bytes or (span_budget_bytes * 2)
        #: bounded k-way merge width (reference: io.sort.factor)
        self.merge_factor = merge_factor
        #: background span sorting ("sortmaster" analog: collection
        #: continues while a full span sorts; PipelinedSorter.java:326).
        #: Capped at ONE worker: counters follow a single-writer-per-counter
        #: rule (the collector thread owns OUTPUT_*, the sortmaster owns the
        #: sort/merge/spill counters) and on_spill consumers are not
        #: required to be re-entrant.
        self._executor = None
        if sort_threads > 0:
            import concurrent.futures
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="sortmaster")
        self._pending = []
        import threading as _threading
        self._store_lock = _threading.Lock()
        self._span = SpanBuffer()
        self._runs: List[Run | str] = []   # Run (in RAM) or path (spilled)
        self._runs_nbytes = 0
        self._closed = False
        self.num_spills = 0
        self.on_spill: Optional[Callable[[Run, int], None]] = None  # pipelined

    # -- write side ----------------------------------------------------------
    def write(self, key: bytes, value: bytes,
              partition: Optional[int] = None) -> None:
        """partition: pre-computed by a custom Partitioner over the LOGICAL
        key/value (the serde runs before this layer); None = device hash."""
        if partition is not None and not 0 <= partition < self.num_partitions:
            raise ValueError(
                f"partitioner returned {partition}, valid range is "
                f"[0, {self.num_partitions})")
        self._span.add(key, value, partition)
        self._out_records_ctr.increment()
        if self._span.nbytes >= self.span_budget:
            self._sort_span()

    def write_batch(self, batch: KVBatch) -> None:
        self._span.add_batch(batch)
        self._out_records_ctr.increment(batch.num_records)
        if self._span.nbytes >= self.span_budget:
            self._sort_span()

    # -- span sort (device) --------------------------------------------------
    def _precombine(self, batch: KVBatch,
                    custom_parts: Optional[np.ndarray],
                    skip: bool = False) -> KVBatch:
        """Hash-combine BEFORE the sort when the combiner allows it.

        The reference combines after each spill sort
        (PipelinedSorter.java:559 -> combiner on the sorted stream); on TPU
        the sort is the expensive device step, so collapsing duplicate keys
        first shrinks pad/lanes/sort/gather by the duplication factor.  The
        post-sort combiner still runs (idempotent for sum) and covers the
        paths this fast path declines."""
        if skip or self.combiner is not sum_long_combiner or \
                custom_parts is not None:
            return batch
        n = batch.num_records
        if n < 2:
            return batch
        if not bool(np.all(np.diff(batch.val_offsets) == 8)):
            return batch   # long-serde fixed-8 values only
        from tez_tpu.ops.native import hash_sum_native
        from tez_tpu.ops.serde import decode_longs_be, encode_longs_be
        decoded = decode_longs_be(batch.val_bytes, n)
        res = hash_sum_native(batch.key_bytes, batch.key_offsets, decoded)
        if res is None:
            return batch   # native lib unavailable
        first_idx, sums = res
        kb2, ko2 = gather_ragged(batch.key_bytes, batch.key_offsets,
                                 first_idx)
        vb = encode_longs_be(sums)
        vo = np.arange(len(sums) + 1, dtype=np.int64) * 8
        self.counters.increment(TaskCounter.COMBINE_INPUT_RECORDS, n)
        self.counters.increment(TaskCounter.COMBINE_OUTPUT_RECORDS,
                                len(sums))
        return KVBatch(kb2, ko2, vb, vo)

    def _finalize_span(self) -> Run:
        """Sort + combine the current span (shared by spill and flush)."""
        batch = self._span.to_batch()
        custom_parts = np.asarray(self._span.parts, dtype=np.int32) \
            if self._span.parts else None
        # a span made entirely of pre-combined batches (e.g. ONE fused
        # tokenizer emission) has nothing for the hash pass to collapse
        skip_pre = self._span.all_pre_combined and \
            len(self._span.batches) == 1
        self._span = SpanBuffer()
        batch = self._precombine(batch, custom_parts, skip=skip_pre)
        run = self.sort_batch(batch, custom_partitions=custom_parts)
        if self.combiner is not None:
            run = self.combiner(run)
        self.num_spills += 1
        return run

    # -- async double-buffered span plane ------------------------------------
    def _ensure_pipeline(self):
        if self._pipeline is None:
            from tez_tpu.ops.async_stage import (AsyncSpanPipeline,
                                                 process_breaker)
            breaker = self._breaker
            if breaker is None:
                breaker = process_breaker()
                breaker.configure(failures=self.breaker_failures,
                                  cooldown_ms=self.breaker_cooldown_ms)
            self._pipeline = AsyncSpanPipeline(
                encode_fn=self._async_encode,
                stage_fn=self._async_h2d,
                dispatch_fn=self._async_dispatch,
                readback_fn=self._async_readback,
                coalesce_fn=self._async_coalesce,
                records_fn=lambda p: p["batch"].num_records,
                on_complete=self._async_complete,
                depth=self.pipeline_depth,
                coalesce_records=self.pipeline_coalesce_records,
                counters=self.counters,
                name="sorter-pipeline",
                failover_fn=self._async_failover,
                oom_retry_fn=self._async_oom_retry,
                breaker=breaker,
                watchdog_dispatch_ms=self.watchdog_dispatch_ms,
                watchdog_readback_ms=self.watchdog_readback_ms)
        return self._pipeline

    def _group_batch(self, ids, payloads) -> Tuple[KVBatch,
                                                   Optional[np.ndarray]]:
        """Rebuild one dispatch group's span from its RAW payloads (the
        failover/retry paths re-run precombine — the device attempt's
        encode results died with the attempt)."""
        batches = [self._precombine(p["batch"], p["custom_parts"],
                                    skip=p["skip_pre"]) for p in payloads]
        batch = batches[0] if len(batches) == 1 else KVBatch.concat(batches)
        # coalesced groups never carry custom partitions (_submit_span_async
        # excludes them from coalescing)
        custom_parts = payloads[0]["custom_parts"] if len(payloads) == 1 \
            else None
        return batch, custom_parts

    def _async_failover(self, ids, payloads) -> Run:
        """Host-engine failover for a failed device attempt (watchdog fire,
        device exception, breaker short-circuit): bit-exact with the device
        path by the host/device golden contract (tests/test_device_parity)."""
        batch, custom_parts = self._group_batch(ids, payloads)
        run = self.sort_batch(batch, custom_partitions=custom_parts,
                              engine="host")
        if self.combiner is not None:
            run = self.combiner(run)
        return run

    def _async_oom_retry(self, ids, payloads) -> Run:
        """RESOURCE_EXHAUSTED ladder: EVICT then split.  First ask the
        buffer store's pressure hooks to reclaim HBM (cold resident key
        lanes demote to the host tier) and retry the WHOLE span on
        device; only when nothing was evictable — or the whole-span
        retry OOMs again — fall to the halving split (recursively, down
        to split_min_bytes) before the host engine takes over.  Merging
        the stably-sorted halves with run-age tie order equals the
        stable sort of the whole span — bit-exact."""
        from tez_tpu.ops import async_stage
        from tez_tpu.ops.device import is_resource_exhausted
        batch, custom_parts = self._group_batch(ids, payloads)
        freed = async_stage.relieve_pressure(batch.nbytes, self.counters)
        if freed > 0:
            try:
                run = self.sort_batch(batch,
                                      custom_partitions=custom_parts,
                                      engine="device")
                if self.combiner is not None:
                    run = self.combiner(run)
                return run
            except BaseException as e:  # noqa: BLE001 — ladder continues
                if not is_resource_exhausted(e):
                    raise
        run = self._split_device_sort(batch, custom_parts,
                                      detail=f"span={min(ids)}")
        if self.combiner is not None:
            run = self.combiner(run)
        return run

    def _split_device_sort(self, batch: KVBatch,
                           custom_parts: Optional[np.ndarray],
                           detail: str) -> Run:
        from tez_tpu.common import faults
        from tez_tpu.ops.device import is_resource_exhausted
        n = batch.num_records
        nbytes = int(batch.key_offsets[-1]) + int(batch.val_offsets[-1])
        if n < 2 or nbytes <= self.split_min_bytes:
            # at the floor: decline the retry — the caller's ladder sends
            # the span to the host engine
            raise MemoryError(
                f"span at OOM-split floor ({nbytes}B <= "
                f"{self.split_min_bytes}B, n={n})")
        h = n // 2
        runs: List[Run] = []
        for lo, hi in ((0, h), (h, n)):
            half = batch.take(np.arange(lo, hi, dtype=np.int64))
            parts_half = custom_parts[lo:hi] if custom_parts is not None \
                else None
            try:
                if faults.armed():
                    faults.fire("device.dispatch.oom",
                                f"{detail}:split[{lo}:{hi})")
                runs.append(self.sort_batch(half,
                                            custom_partitions=parts_half,
                                            engine="device"))
            except BaseException as e:  # noqa: BLE001 — recurse on OOM only
                if not is_resource_exhausted(e):
                    raise
                runs.append(self._split_device_sort(half, parts_half,
                                                    detail))
        # run-age tie order makes the merge of the stably-sorted halves
        # identical to the stable sort of the concatenated span
        return merge_sorted_runs(runs, self.num_partitions, self.key_width,
                                 counters=self.counters, engine="device",
                                 key_normalizer=self.key_normalizer,
                                 device_min_records=self.device_min_records)

    def _submit_span_async(self) -> None:
        batch = self._span.to_batch()
        custom_parts = np.asarray(self._span.parts, dtype=np.int32) \
            if self._span.parts else None
        skip_pre = self._span.all_pre_combined and \
            len(self._span.batches) == 1
        self._span = SpanBuffer()
        spill_id = self.num_spills
        self.num_spills += 1
        # pipelined mode keeps one span per spill_id (consumers track spill
        # ids); store mode may coalesce — the joint stable sort of adjacent
        # spans equals the merge of their individual sorts (ties keep
        # arrival order), so the flush-time merge output is unchanged
        coalesce = self.on_spill is None and custom_parts is None
        self._ensure_pipeline().submit(
            spill_id,
            {"batch": batch, "custom_parts": custom_parts,
             "skip_pre": skip_pre},
            coalesce=coalesce)

    def _async_encode(self, payload: dict) -> dict:
        """Staging thread: precombine + host ragged->lane encode (the
        resident fast path's host work), overlapped with in-flight sorts."""
        batch = self._precombine(payload["batch"], payload["custom_parts"],
                                 skip=payload["skip_pre"])
        custom_parts = payload["custom_parts"]
        engine = self._span_engine(batch)
        if custom_parts is None and self.partitioner == "hash" and \
                engine != "host" and self.key_normalizer is None and \
                self.resident_keys and batch.num_records > 0:
            klens = batch.key_offsets[1:] - batch.key_offsets[:-1]
            wmax = int(klens.max(initial=1))
            if wmax <= self.key_width:
                eff = ((max(wmax, 1) + 3) // 4) * 4
                mat, lengths = pad_to_matrix(batch.key_bytes,
                                             batch.key_offsets, eff)
                return {"kind": "resident", "batch": batch,
                        "lanes": matrix_to_lanes(mat), "lengths": lengths}
        return {"kind": "generic", "batch": batch,
                "custom_parts": custom_parts}

    def _async_coalesce(self, staged_list: List[dict]) -> dict:
        batch = KVBatch.concat([s["batch"] for s in staged_list])
        if all(s["kind"] == "resident" for s in staged_list):
            width = max(s["lanes"].shape[1] for s in staged_list)
            # widening narrower views with ZERO lanes preserves order:
            # bytes beyond a key's length are zero in the lane encoding
            lanes = np.concatenate([
                s["lanes"] if s["lanes"].shape[1] == width else
                np.pad(s["lanes"], ((0, 0), (0, width - s["lanes"].shape[1])))
                for s in staged_list])
            lengths = np.concatenate([s["lengths"] for s in staged_list])
            return {"kind": "resident", "batch": batch,
                    "lanes": lanes, "lengths": lengths}
        return {"kind": "generic", "batch": batch, "custom_parts": None}

    def _async_h2d(self, staged: dict) -> dict:
        if staged["kind"] == "resident":
            staged["staged_dev"] = device.stage_resident_span(
                staged["lanes"], staged["lengths"])
        return staged

    def _async_dispatch(self, staged: dict) -> dict:
        t0 = time.time()
        if staged["kind"] == "resident":
            inflight = device.dispatch_resident_span(staged["staged_dev"],
                                                     self.num_partitions)
            return {"kind": "resident", "batch": staged["batch"],
                    "inflight": inflight, "t0": t0}
        # generic spans (normalizer / custom partitioner / host-routed /
        # over-width keys): the full sync span sort runs here on the staging
        # thread — still overlapped against other spans' readback
        run = self.sort_batch(staged["batch"],
                              custom_partitions=staged["custom_parts"])
        return {"kind": "generic", "run": run, "t0": t0}

    def _async_readback(self, inflight: dict, ids) -> Run:
        if inflight["kind"] == "resident":
            sp, perm, dev = device.readback_resident_span(
                inflight["inflight"])
            sorted_batch = inflight["batch"].take(perm)
            sorted_batch.dev_keys = dev
            self._record_sort_ms(inflight["t0"])
            run = Run.from_sorted_batch(sorted_batch, sp,
                                        self.num_partitions)
        else:
            run = inflight["run"]
        if self.combiner is not None:
            run = self.combiner(run)
        return run

    def _async_complete(self, ids, run: Run) -> None:
        """Completion callback — fires in COMPLETION order (out-of-order
        under delays); coalesced groups complete under their first spill
        id."""
        sid = min(ids)
        if self.on_spill is not None:
            self.on_spill(run, sid)
        else:
            with self._store_lock:
                self._store_run(run)
                self._async_store_ids.append(sid)

    def _drain_async(self) -> None:
        """Block until every submitted span completed, then restore spill-id
        order over the stored runs so the flush merge sees the same run
        sequence as the synchronous engine (stable ties = run order)."""
        pipe, self._pipeline = self._pipeline, None
        if pipe is not None:
            pipe.drain()
        if self._async_store_ids:
            order = sorted(range(len(self._async_store_ids)),
                           key=lambda i: self._async_store_ids[i])
            self._runs = [self._runs[i] for i in order]
            self._async_store_ids = []

    def _sort_span(self) -> None:
        if self._span.num_records == 0:
            return
        if self.pipeline_depth > 0:
            self._submit_span_async()
            return
        if self._executor is not None:
            # hand the full span to the sortmaster; keep collecting
            batch = self._span.to_batch()
            custom_parts = np.asarray(self._span.parts, dtype=np.int32) \
                if self._span.parts else None
            skip_pre = self._span.all_pre_combined and \
                len(self._span.batches) == 1
            self._span = SpanBuffer()
            spill_id = self.num_spills
            self.num_spills += 1

            def _bg() -> None:
                pre = self._precombine(batch, custom_parts, skip=skip_pre)
                run = self.sort_batch(pre, custom_partitions=custom_parts)
                if self.combiner is not None:
                    run = self.combiner(run)
                if self.on_spill is not None:
                    self.on_spill(run, spill_id)
                else:
                    # store (and possibly disk-spill) AS spans finish so RAM
                    # stays bounded by mem_budget, same as the sync path
                    with self._store_lock:
                        self._store_run(run)

            self._pending.append(self._executor.submit(_bg))
            return
        run = self._finalize_span()
        if self.on_spill is not None:
            # pipelined shuffle: each span ships immediately
            self.on_spill(run, self.num_spills - 1)
        else:
            self._store_run(run)

    def _span_engine(self, batch: KVBatch) -> str:
        """Per-span routing: record-count floor always; key-byte floor only
        for auto-resolved device engines (wide-value small-key spans carry
        too little device work to pay a dispatch)."""
        key_nbytes = int(batch.key_offsets[-1]) if self._auto_engine else -1
        return _route_engine(self.engine, batch.num_records,
                             self.device_min_records,
                             key_nbytes=key_nbytes,
                             min_key_bytes=self.engine_min_bytes)

    def _record_sort_ms(self, t0: float) -> None:
        ms = (time.time() - t0) * 1000.0
        self.counters.find_counter(TaskCounter.DEVICE_SORT_MILLIS)\
            .increment(int(ms))
        from tez_tpu.common import metrics
        metrics.observe("device.sort", ms, counters=self.counters)

    def sort_batch(self, batch: KVBatch,
                   custom_partitions: Optional[np.ndarray] = None,
                   engine: Optional[str] = None) -> Run:
        """engine overrides the per-span routing: the containment plane
        forces 'host' (failover re-sort) or 'device' (OOM split retry);
        None = normal routing."""
        t0 = time.time()
        if custom_partitions is not None:
            # validate ONCE for every engine path: a short array would read
            # past the buffer inside the native comparator and an
            # out-of-range id would index past num_partitions-sized native
            # buffers (heap corruption, not a python error)
            if len(custom_partitions) != batch.num_records:
                raise ValueError(
                    "custom partitions must cover every record in the span")
            if batch.num_records and (
                    int(custom_partitions.min()) < 0 or
                    int(custom_partitions.max()) >= self.num_partitions):
                raise ValueError(
                    f"partitioner returned ids outside "
                    f"[0, {self.num_partitions})")
        # hybrid routing: tiny spans sort faster on host than a device
        # round-trip, even under the device engine
        if engine is None:
            engine = self._span_engine(batch)
        if custom_partitions is None and self.partitioner == "hash" and \
                engine != "host" and self.key_normalizer is None and \
                self.resident_keys:
            klens = batch.key_offsets[1:] - batch.key_offsets[:-1]
            wmax = int(klens.max(initial=1))
            if wmax <= self.key_width:
                # device-resident fast path: lanes sized to the ACTUAL max
                # key length (fewer upload bytes), full keys fit them, so
                # the FNV hash derives from lanes ON DEVICE (no hash-matrix
                # upload), prefix order IS exact byte order (no tie-break),
                # and the sorted key columns stay in HBM for the consumer
                # merge (VERDICT r1 item 4)
                eff = ((max(wmax, 1) + 3) // 4) * 4
                mat, lengths = pad_to_matrix(batch.key_bytes,
                                             batch.key_offsets, eff)
                lanes = matrix_to_lanes(mat)
                sorted_partitions, perm, dev = \
                    device.hash_sort_span_resident(lanes, lengths,
                                                   self.num_partitions)
                sorted_batch = batch.take(perm)
                sorted_batch.dev_keys = dev
                self._record_sort_ms(t0)
                return Run.from_sorted_batch(sorted_batch, sorted_partitions,
                                             self.num_partitions)
        if self.key_normalizer is not None:
            sort_bytes, sort_offsets = normalize_batch_keys(
                batch, self.key_normalizer)
        else:
            sort_bytes, sort_offsets = batch.key_bytes, batch.key_offsets
        if engine == "host":
            run = self._native_host_sort(batch, sort_bytes, sort_offsets,
                                         custom_partitions, t0)
            if run is not None:
                return run
        mat, lengths = pad_to_matrix(sort_bytes, sort_offsets, self.key_width)
        lanes = matrix_to_lanes(mat)
        if custom_partitions is not None:
            partitions = custom_partitions
            if engine == "host":
                from tez_tpu.ops.host_sort import host_sort_run
                sorted_partitions, perm = host_sort_run(partitions, lanes,
                                                        lengths)
            else:
                sorted_partitions, perm = device.sort_run(partitions, lanes,
                                                          lengths)
        elif self.partitioner == "hash":
            # fused single-dispatch kernel: full-key FNV hash (matrix padded
            # to the longest key so every byte is hashed — host-partitioner
            # parity) + (partition, key) LSD sort
            klens = batch.key_offsets[1:] - batch.key_offsets[:-1]
            wmax = int(klens.max(initial=1))
            hash_w = 1 << max(2, (wmax - 1).bit_length())
            hmat, hlens = pad_to_matrix(batch.key_bytes, batch.key_offsets,
                                        hash_w)
            if engine == "host":
                from tez_tpu.ops.host_sort import (host_hash_partition,
                                                   host_sort_run)
                partitions = host_hash_partition(hmat, hlens,
                                                 self.num_partitions)
                sorted_partitions, perm = host_sort_run(partitions, lanes,
                                                        lengths)
            else:
                sorted_partitions, perm = device.hash_sort_span(
                    hmat, hlens, lanes, lengths, self.num_partitions)
        else:
            partitions = np.zeros(batch.num_records, dtype=np.int32)
            if engine == "host":
                from tez_tpu.ops.host_sort import host_sort_run
                sorted_partitions, perm = host_sort_run(partitions, lanes,
                                                        lengths)
            else:
                sorted_partitions, perm = device.sort_run(partitions, lanes,
                                                          lengths)
        sorted_batch = batch.take(perm)
        sort_lengths, keyfn = _sorted_key_view(sort_bytes, sort_offsets, perm)
        refinement = _exact_tiebreak(
            sort_lengths, sorted_partitions, lanes[perm], self.key_width,
            keyfn)
        if refinement is not None:
            sorted_batch = sorted_batch.take(refinement)
        self._record_sort_ms(t0)
        return Run.from_sorted_batch(sorted_batch, sorted_partitions,
                                     self.num_partitions)

    def _native_host_sort(self, batch: KVBatch, sort_bytes: np.ndarray,
                          sort_offsets: np.ndarray,
                          custom_parts: Optional[np.ndarray],
                          t0: float) -> Optional[Run]:
        """C-speed host span sort: threaded FNV partition + stable parallel
        index sort over the ragged sort keys (full-key compares — no padded
        matrix, no tie-break pass), GIL released so concurrent tasks
        overlap.  None when the native lib is unavailable (numpy lexsort
        path takes over)."""
        from tez_tpu.ops.native import (fnv32_partition_native,
                                        sort_partition_keys_native,
                                        span_sort_emit_native)
        if self.key_normalizer is None:
            # fused fast path: partition + stable sort + materialization in
            # ONE native call — sorted key bytes emit sequentially (dedup
            # path repeats each unique key in place), values follow the
            # stable permutation; no Python-side take().  custom_parts
            # length/range were validated at the sort_batch boundary.
            fused = span_sort_emit_native(
                batch.key_bytes, batch.key_offsets,
                batch.val_bytes, batch.val_offsets,
                self.num_partitions, custom_parts,
                compute_hash=(custom_parts is None and
                              self.partitioner == "hash"))
            if fused is not None:
                out_kb, out_ko, out_vb, out_vo, row_index = fused
                self._record_sort_ms(t0)
                return Run(KVBatch(out_kb, out_ko, out_vb, out_vo),
                           row_index)
        parts: Optional[np.ndarray]
        if custom_parts is not None:
            parts = custom_parts
        elif self.partitioner == "hash" and self.num_partitions > 1:
            parts = fnv32_partition_native(batch.key_bytes,
                                           batch.key_offsets,
                                           self.num_partitions)
            if parts is None:
                return None
        else:
            parts = None    # everything lands in partition 0
        perm = sort_partition_keys_native(sort_bytes, sort_offsets, parts)
        if perm is None:
            return None
        sorted_batch = batch.take(perm)
        if parts is None:
            sorted_partitions = np.zeros(batch.num_records, dtype=np.int32)
        else:
            sorted_partitions = parts[perm]
        self._record_sort_ms(t0)
        return Run.from_sorted_batch(sorted_batch, sorted_partitions,
                                     self.num_partitions)

    def _store_run(self, run: Run) -> None:
        self.counters.increment(TaskCounter.SPILLED_RECORDS,
                                run.batch.num_records)
        if self.spill_dir is not None and \
                self._runs_nbytes + run.nbytes > self.mem_budget:
            path = os.path.join(self.spill_dir,
                                f"spill_{uuid.uuid4().hex}.prun")
            save_run_partitioned(run, path, codec=self.spill_codec)
            # count bytes actually written: with compression on, disk I/O
            # is what these counters exist to report
            written = os.path.getsize(path)
            self.counters.increment(TaskCounter.ADDITIONAL_SPILLS_BYTES_WRITTEN,
                                    written)
            self.counters.increment(TaskCounter.ADDITIONAL_SPILL_COUNT)
            self.counters.increment(TaskCounter.HOST_SPILL_BYTES, written)
            self._runs.append(path)
        else:
            self._runs.append(run)
            self._runs_nbytes += run.nbytes

    def _drain_pending(self, store: bool) -> None:
        """Join the sortmaster (workers stored/shipped their runs already).
        Exception-safe: the executor always shuts down, then the first
        worker error re-raises."""
        error: Optional[BaseException] = None
        try:
            for fut in self._pending:
                try:
                    fut.result()
                except BaseException as e:  # noqa: BLE001
                    if error is None:
                        error = e
        finally:
            self._pending = []
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None
        if error is not None:
            raise error

    # -- flush ---------------------------------------------------------------
    def flush(self) -> Optional[Run]:
        """Final merge of all spans, fully materialized (compat surface for
        in-RAM callers/tests).  Returns None in pipelined mode.  Spill-scale
        callers want flush_run(), which keeps disk-resident data on disk."""
        result = self.flush_run()
        if isinstance(result, FileRun):
            run = result.to_run()
            result.delete()
            return run
        return result

    def flush_run(self):
        """Final merge of all spans.  Returns None in pipelined mode (spans
        already shipped via on_spill; a trailing partial span ships here).

        In-RAM cases return a `Run` exactly as before (single-span fast
        path; all-RAM multi-span device merge with HBM-resident keys).  When
        any span spilled to disk, the merge instead STREAMS: a partition-
        major blockwise k-way merge (ops/block_merge.py) over the
        partition-indexed span files, written incrementally to one final
        partition-indexed file — no second full sort, no full
        materialization; resident memory is one block per span.  Returns a
        disk-backed `FileRun` (reference: the final IFile + TezSpillRecord
        a PipelinedSorter task publishes, PipelinedSorter.java:559 final
        merge -> TezMerger.java:76)."""
        assert not self._closed
        self._closed = True
        if self.pipeline_depth > 0:
            # async plane: the trailing span submits like any other, then
            # the drain barrier collects out-of-order completions and
            # restores spill-id order
            self._sort_span()
            self._drain_async()
            self._drain_pending(store=True)   # no-op unless sortmaster ran
            if self.on_spill is not None:
                return None
        elif self.on_spill is not None:
            if self._span.num_records > 0:
                self._sort_span()
            self._drain_pending(store=False)
            return None
        else:
            if self._span.num_records > 0 and not self._runs and \
                    not self._pending:
                # common fast path: everything fit one span
                return self._finalize_span()
            self._sort_span()
            self._drain_pending(store=True)
        runs = list(self._runs)
        self._runs = []
        if not runs:
            return Run(KVBatch.empty(),
                       np.zeros(self.num_partitions + 1, dtype=np.int64))
        if not any(isinstance(r, str) for r in runs):
            if len(runs) == 1:
                return runs[0]
            merged = merge_sorted_runs(
                runs, self.num_partitions, self.key_width,
                counters=self.counters, engine=self.engine,
                merge_factor=self.merge_factor,
                key_normalizer=self.key_normalizer,
                device_min_records=self.device_min_records)
            if self.combiner is not None:
                merged = self.combiner(merged)
            return merged
        return self._stream_final_merge(runs)

    def _stream_final_merge(self, runs: List["Run | str"]) -> "FileRun":
        """Blockwise partition-major merge of spilled + resident spans into
        one partition-indexed file."""
        from tez_tpu.ops.block_merge import iter_merged_blocks
        sources: List["Run | FileRun"] = []
        for r in runs:
            if isinstance(r, str):
                self.counters.increment(
                    TaskCounter.ADDITIONAL_SPILLS_BYTES_READ,
                    os.path.getsize(r))
                sources.append(FileRun(r))
            else:
                sources.append(r)
        path = os.path.join(self.spill_dir,
                            f"final_{uuid.uuid4().hex}.prun")
        writer = PartitionedRunWriter(path, self.num_partitions,
                                      codec=self.spill_codec)
        self.counters.increment(TaskCounter.MERGED_MAP_OUTPUTS, len(sources))
        try:
            for p in range(self.num_partitions):
                srcs = []
                for s in sources:
                    if s.partition_row_count(p) == 0:
                        continue
                    srcs.append(s.iter_partition_blocks(p)
                                if isinstance(s, FileRun)
                                else iter([s.partition(p)]))
                for block in iter_merged_blocks(
                        srcs, self.key_width, engine=self.engine,
                        key_normalizer=self.key_normalizer,
                        merge_factor=self.merge_factor,
                        device_min_records=self.device_min_records):
                    if self.combiner is not None:
                        # block-local combine: legal for the (associative)
                        # combiner contract; a key split across block edges
                        # keeps at most one extra record per edge, and the
                        # consumer's grouped reader re-unifies it
                        combined = self.combiner(Run(
                            block, np.array([0, block.num_records],
                                            dtype=np.int64)))
                        block = combined.batch
                    writer.append(block, p)
            writer.close()
        except BaseException:
            writer.abort()
            raise
        self.counters.increment(TaskCounter.ADDITIONAL_SPILLS_BYTES_WRITTEN,
                                writer.bytes_written)
        # span spill files are dead now
        for r in runs:
            if isinstance(r, str):
                try:
                    os.remove(r)
                except OSError:
                    pass
        return FileRun(path)


def _record_merge_ms(counters: Optional[TezCounters], t0: float) -> None:
    """device.merge latency histogram: wall of one device merge dispatch
    (merge-path ladder or resident merge), the reduce-side twin of
    device.sort."""
    from tez_tpu.common import metrics
    metrics.observe("device.merge", (time.time() - t0) * 1000.0,
                    counters=counters)


def _merge_resident_partitioned(live: Sequence[Run], num_partitions: int
                                ) -> Tuple[np.ndarray, np.ndarray]:
    """Multi-partition device-resident merge: each run's HBM key columns are
    (partition, key)-sorted, so partition p occupies the contiguous rows
    [row_index[p], row_index[p+1]) of its device view — merge those slices
    per partition and emit partitions in order.  Within a partition, slices
    merge in run order (stable ties = MergeQueue age semantics), so the
    result is bit-identical to the generic concat+sort merge.  Returns
    (permutation into the concat of live runs' batches, row_index)."""
    offs = np.zeros(len(live), dtype=np.int64)
    if len(live) > 1:
        np.cumsum([r.batch.num_records for r in live[:-1]], out=offs[1:])
    pieces: List[np.ndarray] = []
    counts = np.zeros(num_partitions, dtype=np.int64)
    for p in range(num_partitions):
        slices, bases = [], []
        for r, off in zip(live, offs):
            lo, hi = int(r.row_index[p]), int(r.row_index[p + 1])
            if hi > lo:
                lanes_dev, lens_dev, _lo0, _n = r.batch.dev_keys
                slices.append((lanes_dev, lens_dev, lo, hi))
                bases.append(off + lo)
        if not slices:
            continue
        perm = device.merge_resident_slices(slices)
        cnts = np.asarray([hi - lo for (_l, _n, lo, hi) in slices],
                          dtype=np.int64)
        bounds = np.zeros(len(cnts) + 1, dtype=np.int64)
        np.cumsum(cnts, out=bounds[1:])
        sl = np.searchsorted(bounds[1:], perm, side="right")
        pieces.append(np.asarray(bases, dtype=np.int64)[sl] +
                      (perm - bounds[sl]))
        counts[p] = len(perm)
    row_index = np.zeros(num_partitions + 1, dtype=np.int64)
    np.cumsum(counts, out=row_index[1:])
    total = np.concatenate(pieces) if pieces else np.zeros(0, np.int64)
    return total, row_index


def merge_sorted_runs(runs: Sequence[Run], num_partitions: int,
                      key_width: int,
                      counters: Optional[TezCounters] = None,
                      engine: str = "device",
                      merge_factor: int = 0,
                      key_normalizer: Optional[Callable[[bytes], bytes]]
                      = None,
                      device_min_records: int = DEVICE_SORT_MIN_RECORDS
                      ) -> Run:
    """k-way merge of partition-sorted runs (TezMerger analog): concatenate,
    stable device sort by (partition, key prefix), host tie-break.

    merge_factor > 0 bounds how many runs merge per pass (io.sort.factor):
    each device sort then works on at most factor runs' worth of rows, which
    bounds the PER-MERGE device working set (HBM buffers + sort scratch);
    host-side runs still coexist — the host-spill path in DeviceSorter is
    what bounds host RAM (SURVEY.md §5.7 multi-pass external merge)."""
    if merge_factor > 1 and len(runs) > merge_factor:
        level = list(runs)
        while len(level) > merge_factor:
            nxt = []
            for i in range(0, len(level), merge_factor):
                chunk = level[i:i + merge_factor]
                # inner passes skip counters: only the final pass reports
                # (avoids double-counting MERGED_MAP_OUTPUTS / merge millis)
                nxt.append(chunk[0] if len(chunk) == 1 else
                           merge_sorted_runs(
                               chunk, num_partitions, key_width, None,
                               engine, key_normalizer=key_normalizer,
                               device_min_records=device_min_records))
            level = nxt
        runs = level
    t0 = time.time()
    if engine != "host" and key_normalizer is None:
        live = [r for r in runs if r.batch.num_records > 0]
        views = [r.batch.dev_keys for r in live]
        if live and all(v is not None for v in views):
            # mixed lane widths are fine: narrower views widen with zero
            # lanes on device (zero = absent bytes in the lane encoding)
            # device-resident merge: key columns are already in HBM from
            # the producers' span sorts — only the permutation comes back
            # (VERDICT r1 item 4; TezMerger semantics preserved)
            if num_partitions == 1:
                perm = device.merge_resident_slices(views)
                row_index = None
            else:
                perm, row_index = _merge_resident_partitioned(
                    live, num_partitions)
            _record_merge_ms(counters, t0)
            batch = KVBatch.concat([r.batch for r in live])
            sorted_batch = batch.take(perm)
            if counters is not None:
                counters.find_counter(TaskCounter.DEVICE_MERGE_MILLIS)\
                    .increment(int((time.time() - t0) * 1000))
                counters.increment(TaskCounter.MERGED_MAP_OUTPUTS, len(runs))
            if row_index is None:
                row_index = np.array([0, sorted_batch.num_records], np.int64)
            return Run(sorted_batch, row_index)
    # hybrid routing for the generic path only — when producer key lanes
    # are already device-resident the resident merge above is cheaper than
    # any host sort regardless of size
    engine = _route_engine(engine, sum(r.batch.num_records for r in runs),
                           device_min_records)
    if engine == "host" and key_normalizer is None:
        # fused fast path: group-scan each sorted run, k-way merge group
        # heads, emit contiguous segment copies — no concatenation and no
        # per-row gather.  Equal (partition, key) groups emit in `runs`
        # order (MergeQueue age semantics).
        live = [r for r in runs if r.batch.num_records > 0]
        if live and all(r.num_partitions == num_partitions for r in live):
            from tez_tpu.ops.native import merge_emit_native
            fused = merge_emit_native(
                [(r.batch.key_bytes, r.batch.key_offsets,
                  r.batch.val_bytes, r.batch.val_offsets, r.row_index)
                 for r in live], num_partitions)
            if fused is not None:
                out_kb, out_ko, out_vb, out_vo, row_index = fused
                if counters is not None:
                    counters.find_counter(TaskCounter.DEVICE_MERGE_MILLIS)\
                        .increment(int((time.time() - t0) * 1000))
                    counters.increment(TaskCounter.MERGED_MAP_OUTPUTS,
                                       len(runs))
                return Run(KVBatch(out_kb, out_ko, out_vb, out_vo),
                           row_index)
    batch = KVBatch.concat([r.batch for r in runs])
    partitions = np.concatenate([
        np.repeat(np.arange(r.num_partitions, dtype=np.int32),
                  np.diff(r.row_index)) for r in runs]) \
        if runs else np.zeros(0, np.int32)
    if key_normalizer is not None:
        sort_bytes, sort_offsets = normalize_batch_keys(batch, key_normalizer)
    else:
        sort_bytes, sort_offsets = batch.key_bytes, batch.key_offsets
    if engine == "host":
        # native merge: the runs are ALREADY (partition, key)-sorted, so a
        # ladder of in-place merges (O(n log k)) replaces a full re-sort;
        # full-key compares, run-order ties (= MergeQueue age order via the
        # concat index), GIL released
        from tez_tpu.ops.native import merge_runs_native
        run_bounds = np.zeros(len(runs) + 1, dtype=np.int64)
        np.cumsum([r.batch.num_records for r in runs], out=run_bounds[1:])
        perm_n = merge_runs_native(
            sort_bytes, sort_offsets,
            partitions if num_partitions > 1 else None, run_bounds)
        if perm_n is not None:
            sorted_batch = batch.take(perm_n)
            sorted_partitions = partitions[perm_n]
            if counters is not None:
                counters.find_counter(TaskCounter.DEVICE_MERGE_MILLIS)\
                    .increment(int((time.time() - t0) * 1000))
                counters.increment(TaskCounter.MERGED_MAP_OUTPUTS, len(runs))
            return Run.from_sorted_batch(sorted_batch, sorted_partitions,
                                         num_partitions)
    mat, lengths = pad_to_matrix(sort_bytes, sort_offsets, key_width)
    lanes = matrix_to_lanes(mat)
    if engine == "host":
        from tez_tpu.ops.host_sort import host_sort_run
        sorted_partitions, perm = host_sort_run(partitions, lanes, lengths)
    else:
        # the inputs are PRE-SORTED runs: the O(N) merge-path ladder
        # (cross-rank scatter per level) replaces the O(N log N)
        # concatenate+re-sort dispatch.  Same composite comparator as
        # sort_run, equal keys keep run-arrival order, and prefix-equal
        # beyond-cap keys still fall to the host tie-break below.
        run_bounds = np.zeros(len(runs) + 1, dtype=np.int64)
        np.cumsum([r.batch.num_records for r in runs], out=run_bounds[1:])
        t_dev = time.time()
        perm = device.merge_path_runs(
            [partitions[run_bounds[i]:run_bounds[i + 1]]
             for i in range(len(runs))],
            [lanes[run_bounds[i]:run_bounds[i + 1]]
             for i in range(len(runs))],
            [lengths[run_bounds[i]:run_bounds[i + 1]]
             for i in range(len(runs))])
        _record_merge_ms(counters, t_dev)
        sorted_partitions = partitions[perm]
    sorted_batch = batch.take(perm)
    sort_lengths, keyfn = _sorted_key_view(sort_bytes, sort_offsets, perm)
    refinement = _exact_tiebreak(sort_lengths, sorted_partitions,
                                 lanes[perm], key_width, keyfn)
    if refinement is not None:
        sorted_batch = sorted_batch.take(refinement)
    if counters is not None:
        counters.find_counter(TaskCounter.DEVICE_MERGE_MILLIS)\
            .increment(int((time.time() - t0) * 1000))
        counters.increment(TaskCounter.MERGED_MAP_OUTPUTS, len(runs))
    return Run.from_sorted_batch(sorted_batch, sorted_partitions,
                                 num_partitions)


# ---------------------------------------------------------------------------
# combiners
# ---------------------------------------------------------------------------
def sum_long_combiner(run: Run) -> Run:
    """Vectorized combine for 8-byte big-endian-long values: sums values of
    equal (partition, key) groups (the WordCount/OrderedWordCount combiner)."""
    from tez_tpu.ops.serde import VarLongSerde
    batch = run.batch
    n = batch.num_records
    if n == 0:
        return run
    ko, kb = batch.key_offsets, batch.key_bytes
    lengths = ko[1:] - ko[:-1]
    partitions = np.repeat(np.arange(run.num_partitions, dtype=np.int32),
                           np.diff(run.row_index))
    # adjacent-equal detection (sorted within partition): same partition,
    # same length, same bytes
    same = np.zeros(n, dtype=bool)
    if n > 1:
        cand = (partitions[1:] == partitions[:-1]) & \
            (lengths[1:] == lengths[:-1])
        idx = np.flatnonzero(cand)
        same[idx + 1] = adjacent_equal_rows(kb, ko, idx)
    group_starts = np.flatnonzero(~same)
    # decode values (8-byte BE unsigned with sign-flip encoding); the fast
    # path requires every value to be exactly 8 bytes (long serde), not just
    # the right total
    uniform_long = bool(np.all(np.diff(batch.val_offsets) == 8))
    vals = batch.val_bytes.reshape(n, 8) if uniform_long else None
    serde = VarLongSerde()
    if vals is not None:
        nums = vals.astype(np.uint64)
        weights = (256 ** np.arange(7, -1, -1)).astype(np.uint64)
        unsigned = (nums * weights).sum(axis=1, dtype=np.uint64)
        # encoding is val + 2^63 (mod 2^64) == top-bit flip of two's complement
        decoded = (unsigned ^ np.uint64(1 << 63)).view(np.int64)
        sums = np.add.reduceat(decoded, group_starts)
        out_vals = b"".join(serde.to_bytes(int(s)) for s in sums)
        vb = np.frombuffer(out_vals, dtype=np.uint8).copy()
        vo = np.arange(len(group_starts) + 1, dtype=np.int64) * 8
    else:
        # ragged fallback
        sums = []
        bounds = np.append(group_starts, n)
        for s, e in zip(bounds[:-1], bounds[1:]):
            sums.append(sum(serde.from_bytes(batch.value(i))
                            for i in range(s, e)))
        out_vals = b"".join(serde.to_bytes(s) for s in sums)
        vb = np.frombuffer(out_vals, dtype=np.uint8).copy()
        vo = np.arange(len(group_starts) + 1, dtype=np.int64) * 8
    kb2, ko2 = gather_ragged(kb, ko, group_starts)
    new_counts = np.bincount(partitions[group_starts],
                             minlength=run.num_partitions).astype(np.int64)
    row_index = np.zeros(run.num_partitions + 1, dtype=np.int64)
    np.cumsum(new_counts, out=row_index[1:])
    return Run(KVBatch(kb2, ko2, vb, vo), row_index)
