"""Host sorting engine: numpy lexsort fallback for device-less environments.

Reference parity: the reference ships two sorters (PipelinedSorter /
DefaultSorter) selected by config; here 'device' (ops.device kernels) vs
'host' (this module) selected by tez.runtime.sorter.class.  Byte-identical
output contract with the device engine (same golden tests).
"""
from __future__ import annotations

import numpy as np


def fnv_rows_host(key_mat: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Vectorized FNV-1a over each row's first lengths[i] bytes — identical
    to the device kernel and the scalar HashPartitioner."""
    h = np.full(key_mat.shape[0], 2166136261, dtype=np.uint64)
    for j in range(key_mat.shape[1]):
        nh = ((h ^ key_mat[:, j].astype(np.uint64)) * np.uint64(16777619)) \
            & np.uint64(0xFFFFFFFF)
        h = np.where(j < lengths, nh, h)
    return h.astype(np.uint32)


def host_hash_partition(key_mat: np.ndarray, lengths: np.ndarray,
                        num_partitions: int) -> np.ndarray:
    return (fnv_rows_host(key_mat, lengths) %
            np.uint32(num_partitions)).astype(np.int32)


def host_sort_run(partitions: np.ndarray, lanes: np.ndarray,
                  lengths: np.ndarray) -> tuple:
    """np.lexsort by (partition, lanes..., clamped length) — the host twin
    of device.sort_run (stable, same key order)."""
    n = partitions.shape[0]
    if n == 0:
        return partitions, np.zeros(0, dtype=np.int32)
    width_cap = lanes.shape[1] * 4 + 1
    clamped = np.minimum(lengths.astype(np.int64), width_cap)
    # lexsort: LAST key is most significant
    cols = [clamped] + [lanes[:, i] for i in range(lanes.shape[1] - 1, -1, -1)]
    cols.append(partitions)
    perm = np.lexsort(cols).astype(np.int32)
    return partitions[perm], perm
