"""Run format: the IFile analog for HBM/host-RAM resident sorted runs.

Reference parity: tez-runtime-library/.../common/sort/impl/IFile.java:67 (KV
run format with per-partition index) + TezSpillRecord.java (partition index).
Differences by design (SURVEY.md §2.5): instead of a varint byte stream, a
run is a *columnar quad* — key bytes + offsets, value bytes + offsets — plus
a partition row index.  That layout is what the device kernels consume
directly (offsets+bytes dual tensors), needs no per-record decode loop, and
serializes to disk with a checksummed header for the host-spill path
(IFileOutputStream CRC analog).
"""
from __future__ import annotations

import dataclasses
import io
import os
import struct
import zlib
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tez_tpu.common import faults

MAGIC = b"TPRUN1"
#: MAGIC + pack("<BIQ", flag, crc32(payload), len(payload)).  The CRC covers
#: the payload only, so corrupt-injection below the header is guaranteed to
#: surface as the checksum IOError (not a codec decode error).
RUN_HEADER_NBYTES = len(MAGIC) + 13


def _zstd_codec():
    import zstandard   # baked into the image; gate loudly if ever absent
    comp = zstandard.ZstdCompressor(level=1)
    dec = zstandard.ZstdDecompressor()
    return comp.compress, dec.decompress


def _lz4_codec():
    try:
        import lz4.frame
    except ImportError:
        raise ValueError(
            "run codec 'lz4' requires the lz4 module, which is not "
            "available in this environment (supported here: zlib, zstd)"
        ) from None
    return lz4.frame.compress, lz4.frame.decompress


#: codec name -> (wire flag, lazy (compress, decompress) factory).  The flag
#: is stored in the run header, so blobs stay self-describing across codec
#: config changes (reference: per-stream codec in IFile.java:67).
_CODECS = {
    None: (0, lambda: (lambda b: b, lambda b: b)),
    "zlib": (1, lambda: (lambda b: zlib.compress(b, 1), zlib.decompress)),
    "zstd": (2, _zstd_codec),
    "lz4": (3, _lz4_codec),
}
_FLAG_TO_NAME = {flag: name for name, (flag, _) in _CODECS.items()}


def resolve_codec(codec: Optional[str]):
    """-> (wire flag, compress, decompress); loud error on unknown names —
    an unknown codec silently writing uncompressed is worse."""
    entry = _CODECS.get(codec)
    if entry is None:
        raise ValueError(f"unsupported run codec {codec!r} "
                         f"(supported: zlib, zstd, lz4)")
    flag, factory = entry
    compress, decompress = factory()
    return flag, compress, decompress


def resolve_codec_flag(flag: int):
    if flag not in _FLAG_TO_NAME:
        raise ValueError(f"unknown run codec flag {flag}")
    name = _FLAG_TO_NAME[flag]
    return (name,) + resolve_codec(name)[1:]


def _ranges(lengths: np.ndarray) -> np.ndarray:
    """[3,1,2] -> [0,1,2, 0, 0,1] (per-segment aranges)."""
    total = int(lengths.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    starts = np.zeros(len(lengths), dtype=np.int64)
    np.cumsum(lengths[:-1], out=starts[1:])
    return np.arange(total, dtype=np.int64) - np.repeat(starts, lengths)


def gather_ragged(data: np.ndarray, offsets: np.ndarray,
                  perm: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Permute a ragged array: returns (new_data, new_offsets).

    Large batches go through the native multithreaded per-row memcpy
    (native/ragged.cpp); numpy fancy indexing otherwise."""
    from tez_tpu.ops.native import MIN_NATIVE_BYTES
    if data.nbytes >= MIN_NATIVE_BYTES:
        n_src = len(offsets) - 1
        if n_src > 0:
            w = int(offsets[1]) - int(offsets[0])
            if 0 < w <= 64 and int(offsets[-1]) == n_src * w and \
                    not bool((offsets[1:] != offsets[:-1] + w).any()):
                from tez_tpu.ops.native import gather_fixed_native
                fixed = gather_fixed_native(data, w, perm)
                if fixed is not None:
                    return fixed, np.arange(len(perm) + 1,
                                            dtype=np.int64) * w
        from tez_tpu.ops.native import gather_ragged_native
        native = gather_ragged_native(data, offsets, perm)
        if native is not None:
            return native
    lengths = offsets[1:] - offsets[:-1]
    new_lengths = lengths[perm]
    new_offsets = np.zeros(len(perm) + 1, dtype=np.int64)
    np.cumsum(new_lengths, out=new_offsets[1:])
    idx = np.repeat(offsets[:-1][perm], new_lengths) + _ranges(new_lengths)
    return data[idx], new_offsets


def adjacent_equal_rows(data: np.ndarray, offsets: np.ndarray,
                        cand: np.ndarray) -> np.ndarray:
    """For each candidate row index i (caller guarantees rows i and i+1
    have equal byte length), return True where row i's bytes equal row
    i+1's — one flat gather per side + a per-pair reduction instead of a
    Python loop over pairs (the grouping/combine hot path: adjacent-equal
    detection over sorted runs, ValuesIterator.java:45 semantics)."""
    m = len(cand)
    if m == 0:
        return np.zeros(0, dtype=bool)
    lengths = (offsets[1:] - offsets[:-1])[cand]
    from tez_tpu.ops.native import MIN_NATIVE_BYTES
    if int(lengths.sum()) >= MIN_NATIVE_BYTES:
        # the numpy path materializes one int64 index per BYTE (8x memory
        # expansion); the native threaded memcmp avoids it on large runs
        from tez_tpu.ops.native import adjacent_equal_native
        native = adjacent_equal_native(data, offsets, cand)
        if native is not None:
            return native
    out = np.ones(m, dtype=bool)          # zero-length pairs are equal
    nz = np.flatnonzero(lengths)
    if len(nz) == 0:
        return out
    nz_cand = cand[nz]
    nz_len = lengths[nz]
    within = _ranges(nz_len)
    idx_a = np.repeat(offsets[nz_cand], nz_len) + within
    idx_b = np.repeat(offsets[nz_cand + 1], nz_len) + within
    neq = data[idx_a] != data[idx_b]
    pair_starts = np.zeros(len(nz), dtype=np.int64)
    np.cumsum(nz_len[:-1], out=pair_starts[1:])
    mismatches = np.add.reduceat(neq.astype(np.int64), pair_starts)
    out[nz] = mismatches == 0
    return out


def concat_ragged(parts: Sequence[Tuple[np.ndarray, np.ndarray]]
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate (data, offsets) raggeds."""
    if not parts:
        return np.zeros(0, np.uint8), np.zeros(1, np.int64)
    datas = [p[0] for p in parts]
    data = np.concatenate(datas) if datas else np.zeros(0, np.uint8)
    sizes = [len(p[1]) - 1 for p in parts]
    offsets = np.zeros(sum(sizes) + 1, dtype=np.int64)
    pos, base = 1, 0
    for (d, o), sz in zip(parts, sizes):
        offsets[pos:pos + sz] = o[1:] + base
        base += len(d)
        pos += sz
    return data, offsets


@dataclasses.dataclass
class KVBatch:
    """Columnar record batch: ragged keys + ragged values.

    dev_keys optionally carries a DEVICE-resident view of the sort keys —
    (lanes u32[NB, L], lengths i32[NB], lo, hi) where rows [lo, hi) of the
    bucketed arrays align with this batch's rows and tail rows are
    sentinels.  It lets a same-process consumer merge fetched partitions
    without re-uploading key bytes (SURVEY.md §2.5 "spans = device
    buffers"); it is dropped by serialization, pickling, take() and
    concat() (order changes invalidate the row alignment)."""
    key_bytes: np.ndarray     # uint8[..]
    key_offsets: np.ndarray   # int64[N+1]
    val_bytes: np.ndarray
    val_offsets: np.ndarray
    dev_keys: Optional[tuple] = dataclasses.field(
        default=None, compare=False, repr=False)
    #: producer promise: keys in this batch are already unique (e.g. the
    #: fused tokenize+count aggregator) — the sorter skips its pre-sort
    #: hash combine for spans made only of such batches.  Dropped (False)
    #: by take()/concat()/serialization like dev_keys.
    pre_combined: bool = dataclasses.field(
        default=False, compare=False, repr=False)

    def __getstate__(self) -> dict:
        state = self.__dict__.copy()
        state["dev_keys"] = None   # device handles never cross processes
        return state

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)

    @property
    def num_records(self) -> int:
        return len(self.key_offsets) - 1

    @property
    def nbytes(self) -> int:
        return (self.key_bytes.nbytes + self.val_bytes.nbytes +
                self.key_offsets.nbytes + self.val_offsets.nbytes)

    def key(self, i: int) -> bytes:
        return self.key_bytes[self.key_offsets[i]:self.key_offsets[i + 1]]\
            .tobytes()

    def value(self, i: int) -> bytes:
        return self.val_bytes[self.val_offsets[i]:self.val_offsets[i + 1]]\
            .tobytes()

    def take(self, perm: np.ndarray) -> "KVBatch":
        kb, ko = gather_ragged(self.key_bytes, self.key_offsets, perm)
        vb, vo = gather_ragged(self.val_bytes, self.val_offsets, perm)
        return KVBatch(kb, ko, vb, vo)

    def slice_rows(self, start: int, stop: int) -> "KVBatch":
        ko = self.key_offsets[start:stop + 1]
        vo = self.val_offsets[start:stop + 1]
        dev = None
        if self.dev_keys is not None:
            lanes, lens, lo, _hi = self.dev_keys
            dev = (lanes, lens, lo + start, lo + stop)   # view, no copy
        # the subtraction already yields fresh int64 arrays — an astype
        # here would be a second full copy on the per-block hot path
        return KVBatch(
            self.key_bytes[ko[0]:ko[-1]], ko - ko[0],
            self.val_bytes[vo[0]:vo[-1]], vo - vo[0],
            dev_keys=dev)

    @staticmethod
    def empty() -> "KVBatch":
        z = np.zeros(0, np.uint8)
        o = np.zeros(1, np.int64)
        return KVBatch(z, o, z.copy(), o.copy())

    @staticmethod
    def concat(batches: Sequence["KVBatch"]) -> "KVBatch":
        kb, ko = concat_ragged([(b.key_bytes, b.key_offsets) for b in batches])
        vb, vo = concat_ragged([(b.val_bytes, b.val_offsets) for b in batches])
        return KVBatch(kb, ko, vb, vo)

    @staticmethod
    def from_pairs(pairs: Sequence[Tuple[bytes, bytes]]) -> "KVBatch":
        ko = np.zeros(len(pairs) + 1, dtype=np.int64)
        vo = np.zeros(len(pairs) + 1, dtype=np.int64)
        for i, (k, v) in enumerate(pairs):
            ko[i + 1] = ko[i] + len(k)
            vo[i + 1] = vo[i] + len(v)
        kb = np.frombuffer(b"".join(k for k, _ in pairs), dtype=np.uint8).copy()
        vb = np.frombuffer(b"".join(v for _, v in pairs), dtype=np.uint8).copy()
        return KVBatch(kb, ko, vb, vo)

    def iter_pairs(self) -> Iterator[Tuple[bytes, bytes]]:
        for i in range(self.num_records):
            yield self.key(i), self.value(i)


@dataclasses.dataclass
class Run:
    """A partition-sorted KV run + partition row index.

    Rows [row_index[p], row_index[p+1]) belong to partition p and are
    key-sorted within.  The TezSpillRecord analog is `row_index` (+ byte
    sizes derivable from offsets).
    """
    batch: KVBatch
    row_index: np.ndarray     # int64[P+1]

    @property
    def num_partitions(self) -> int:
        return len(self.row_index) - 1

    def partition(self, p: int) -> KVBatch:
        return self.batch.slice_rows(int(self.row_index[p]),
                                     int(self.row_index[p + 1]))

    def partition_row_count(self, p: int) -> int:
        return int(self.row_index[p + 1] - self.row_index[p])

    def partition_nbytes(self, p: int) -> int:
        s, e = int(self.row_index[p]), int(self.row_index[p + 1])
        return int((self.batch.key_offsets[e] - self.batch.key_offsets[s]) +
                   (self.batch.val_offsets[e] - self.batch.val_offsets[s]))

    def empty_partition_flags(self) -> List[bool]:
        return [self.partition_row_count(p) == 0
                for p in range(self.num_partitions)]

    @property
    def nbytes(self) -> int:
        return self.batch.nbytes

    # -- host-spill serialization (checksummed; IFileOutputStream analog) ----
    # Offset arrays (key_offsets / val_offsets) are DELTA-CODED on the
    # wire: per-record LENGTHS in the narrowest unsigned dtype that fits
    # (u8/u16/u32; i64 raw offsets beyond that).  For small-record spills
    # this is the difference between 16 B and 2 B of index per record —
    # on-disk size was otherwise ~2x the KV payload.  Wire dtype chars
    # '1'/'2'/'4' mark delta-u8/u16/u32; everything stays self-describing.
    _DELTA_CHARS = {b"1": np.uint8, b"2": np.uint16, b"4": np.uint32}

    @staticmethod
    def _encode_offsets(offsets: np.ndarray) -> Tuple[bytes, np.ndarray]:
        if len(offsets) and int(offsets[0]) != 0:
            # delta coding reconstructs from base 0: a rebased view must
            # ship raw (lossless) rather than silently rebase
            return offsets.dtype.char.encode(), offsets
        lens = np.diff(offsets)
        m = int(lens.max(initial=0))
        if m < (1 << 8):
            return b"1", lens.astype(np.uint8)
        if m < (1 << 16):
            return b"2", lens.astype(np.uint16)
        if m < (1 << 32):
            return b"4", lens.astype(np.uint32)
        return offsets.dtype.char.encode(), offsets

    @staticmethod
    def _decode_offsets(char: bytes, raw: np.ndarray) -> np.ndarray:
        offsets = np.zeros(len(raw) + 1, dtype=np.int64)
        np.cumsum(raw, out=offsets[1:])
        return offsets

    def _wire_arrays(self) -> List[Tuple[bytes, np.ndarray]]:
        kc, ko = self._encode_offsets(self.batch.key_offsets)
        vc, vo = self._encode_offsets(self.batch.val_offsets)
        return [(self.batch.key_bytes.dtype.char.encode(),
                 self.batch.key_bytes),
                (kc, ko),
                (self.batch.val_bytes.dtype.char.encode(),
                 self.batch.val_bytes),
                (vc, vo),
                (self.row_index.dtype.char.encode(), self.row_index)]

    def to_bytes(self, codec: Optional[str] = None) -> bytes:
        flag, compress, _ = resolve_codec(codec)
        buf = io.BytesIO()
        for char, a in self._wire_arrays():
            raw = compress(np.ascontiguousarray(a).tobytes())
            buf.write(struct.pack("<cQ", char, len(raw)))
            buf.write(raw)
        payload = buf.getvalue()
        header = MAGIC + struct.pack(
            "<BIQ", flag, zlib.crc32(payload), len(payload))
        return header + payload

    @staticmethod
    def from_bytes(data: bytes, where: str = "<bytes>") -> "Run":
        if data[:len(MAGIC)] != MAGIC:
            raise IOError(f"bad run magic in {where}")
        off = len(MAGIC)
        flag, crc, size = struct.unpack_from("<BIQ", data, off)
        off += 1 + 4 + 8
        payload = data[off:off + size]
        if zlib.crc32(payload) != crc:
            raise IOError(f"checksum mismatch in {where}")
        try:
            _, _, decompress = resolve_codec_flag(flag)
        except ValueError as e:
            raise IOError(f"{e} in {where}") from None
        buf = io.BytesIO(payload)
        arrays = []
        for _ in range(5):
            dtype_c, length = struct.unpack("<cQ", buf.read(9))
            raw = decompress(buf.read(length))
            dt = Run._DELTA_CHARS.get(dtype_c)
            if dt is not None:
                arrays.append(Run._decode_offsets(
                    dtype_c, np.frombuffer(raw, dtype=dt)))
            else:
                arrays.append(np.frombuffer(raw, dtype=np.dtype(
                    dtype_c.decode())).copy())
        kb, ko, vb, vo, ri = arrays
        return Run(KVBatch(kb, ko, vb, vo), ri)

    def write_to(self, fh, codec: Optional[str] = None) -> int:
        """Stream this run into an open file.  The uncompressed hot path
        writes each wire array buffer directly (one checksum pass + one
        write pass — no BytesIO assembly, no tobytes copies); codecs fall
        back to the blob builder.  Returns bytes written."""
        flag, _compress, _ = resolve_codec(codec)
        if flag != 0:
            blob = self.to_bytes(codec)
            fh.write(blob)
            return len(blob)
        pairs = [(c, np.ascontiguousarray(a)) for c, a in
                 self._wire_arrays()]
        headers = [struct.pack("<cQ", c, a.nbytes) for c, a in pairs]
        crc = 0
        for h, (_c, a) in zip(headers, pairs):
            crc = zlib.crc32(h, crc)
            crc = zlib.crc32(memoryview(a).cast("B"), crc)
        size = sum(len(h) + a.nbytes for h, (_c, a) in zip(headers, pairs))
        fh.write(MAGIC + struct.pack("<BIQ", 0, crc, size))
        for h, (_c, a) in zip(headers, pairs):
            fh.write(h)
            fh.write(memoryview(a).cast("B"))
        return len(MAGIC) + 13 + size

    def save(self, path: str, codec: Optional[str] = None) -> None:
        from tez_tpu.common import metrics
        faults.fire("spill.write", detail=path)
        with metrics.timer("spill.write"):
            tmp = path + ".tmp"
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "wb") as fh:
                self.write_to(fh, codec)
            os.replace(tmp, path)

    @staticmethod
    def load(path: str) -> "Run":
        faults.fire("spill.read", detail=path)
        with open(path, "rb") as fh:
            data = fh.read()
        data = faults.corrupt_bytes("spill.read", path, data,
                                    lo=RUN_HEADER_NBYTES)
        return Run.from_bytes(data, where=path)

    @staticmethod
    def from_sorted_batch(batch: KVBatch, sorted_partitions: np.ndarray,
                          num_partitions: int) -> "Run":
        """Build the row index from the (sorted) per-row partition ids."""
        counts = np.bincount(sorted_partitions, minlength=num_partitions)\
            .astype(np.int64)
        row_index = np.zeros(num_partitions + 1, dtype=np.int64)
        np.cumsum(counts, out=row_index[1:])
        return Run(batch, row_index)


def _write_block(fh, piece: KVBatch, codec: Optional[str]) -> int:
    """Write one length-prefixed single-partition Run blob (the shared
    block format of ChunkedRunWriter and PartitionedRunWriter).  Returns
    the blob size (excluding the 8-byte prefix)."""
    run = Run(piece, np.array([0, piece.num_records], dtype=np.int64))
    if codec is None:
        # streamed write: length backfilled after the streaming pass (the
        # writers' targets are regular seekable files)
        at = fh.tell()
        fh.write(struct.pack("<Q", 0))
        size = run.write_to(fh)
        end = fh.tell()
        fh.seek(at)
        fh.write(struct.pack("<Q", size))
        fh.seek(end)
    else:
        blob = run.to_bytes(codec)
        size = len(blob)
        fh.write(struct.pack("<Q", size))
        fh.write(blob)
    return size


class ChunkedRunWriter:
    """Append-only on-disk run of globally-sorted record blocks.

    The consumer-side spill format (MergeManager mem->disk merge target,
    reference MergeManager.java:387 InMemoryMerger writing an IFile): a
    sequence of length-prefixed single-partition Run blobs, each internally
    sorted and globally ordered across blocks, so a reader can stream the
    run block-at-a-time with bounded memory.
    """

    def __init__(self, path: str, codec: Optional[str] = None,
                 block_records: int = 65536):
        self.path = path
        self.codec = codec
        self.block_records = block_records
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path + ".tmp", "wb")
        self.blocks = 0
        self.records = 0
        self.bytes_written = 0

    def append(self, batch: KVBatch) -> None:
        """Append a sorted batch, splitting into bounded blocks."""
        for s in range(0, batch.num_records, self.block_records):
            piece = batch.slice_rows(s, min(s + self.block_records,
                                            batch.num_records))
            size = _write_block(self._fh, piece, self.codec)
            self.blocks += 1
            self.records += piece.num_records
            self.bytes_written += size + 8

    def close(self) -> str:
        self._fh.close()
        os.replace(self.path + ".tmp", self.path)
        return self.path


def iter_chunked_run(path: str):
    """Stream the sorted blocks of a ChunkedRunWriter file (bounded memory:
    one block resident at a time)."""
    with open(path, "rb") as fh:
        while True:
            raw = fh.read(8)
            if len(raw) < 8:
                return
            (n,) = struct.unpack("<Q", raw)
            yield Run.from_bytes(fh.read(n), where=path).batch


PR_MAGIC = b"TZPRUN1\n"
PR_FOOTER_MAGIC = b"TZPRIDX1"


class PartitionedRunWriter:
    """On-disk partition-indexed run: the spill-scale twin of `Run`.

    The true IFile + TezSpillRecord analog for data that must not live in
    RAM (reference: IFile.java:67 written per spill by PipelinedSorter.java:559,
    indexed by TezSpillRecord.java): a sequence of length-prefixed sorted
    single-partition Run blobs appended PARTITION-MAJOR (partition ids must
    be non-decreasing, matching a partition-sorted producer run), followed by
    a footer index of per-partition byte ranges / row counts / KV byte sizes.
    Each partition is therefore one contiguous byte range of whole blocks —
    a fetch can slice it without touching other partitions, and a merge can
    stream it block-at-a-time with bounded memory.
    """

    def __init__(self, path: str, num_partitions: int,
                 codec: Optional[str] = None, block_records: int = 65536):
        self.path = path
        self.num_partitions = num_partitions
        self.codec = codec
        self.block_records = block_records
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path + ".tmp", "wb")
        self._fh.write(PR_MAGIC)
        self._pos = len(PR_MAGIC)
        self._byte_off = np.full(num_partitions + 1, -1, dtype=np.int64)
        self._byte_off[0] = self._pos
        self._rows = np.zeros(num_partitions, dtype=np.int64)
        self._kv_bytes = np.zeros(num_partitions, dtype=np.int64)
        self._cur = 0
        self.bytes_written = 0

    def _advance_to(self, partition: int) -> None:
        if partition < self._cur:
            raise ValueError(
                f"partition-major order violated: {partition} after "
                f"{self._cur}")
        while self._cur < partition:
            self._cur += 1
            self._byte_off[self._cur] = self._pos

    def append(self, batch: KVBatch, partition: int) -> None:
        """Append a sorted batch belonging to `partition`, splitting into
        bounded blocks."""
        self._advance_to(partition)
        for s in range(0, batch.num_records, self.block_records):
            piece = batch.slice_rows(
                s, min(s + self.block_records, batch.num_records))
            size = _write_block(self._fh, piece, self.codec)
            self._pos += 8 + size
            self.bytes_written += 8 + size
        self._rows[partition] += batch.num_records
        self._kv_bytes[partition] += int(
            batch.key_offsets[-1] + batch.val_offsets[-1])

    def append_run(self, run: "Run") -> None:
        """Append a whole partition-sorted run (span-spill path)."""
        for p in range(run.num_partitions):
            if run.partition_row_count(p):
                self.append(run.partition(p), p)

    def abort(self) -> None:
        """Failure cleanup: close the handle and remove the temp file."""
        try:
            self._fh.close()
        except OSError:
            pass
        try:
            os.remove(self.path + ".tmp")
        except OSError:
            pass

    def close(self) -> str:
        if self.num_partitions > 0:
            self._advance_to(self.num_partitions - 1)
        self._byte_off[self.num_partitions] = self._pos
        footer = io.BytesIO()
        footer.write(struct.pack("<I", self.num_partitions))
        footer.write(self._byte_off.tobytes())
        footer.write(self._rows.tobytes())
        footer.write(self._kv_bytes.tobytes())
        payload = footer.getvalue()
        self._fh.write(payload)
        self._fh.write(struct.pack("<IQ", zlib.crc32(payload), len(payload)))
        self._fh.write(PR_FOOTER_MAGIC)
        self._fh.close()
        os.replace(self.path + ".tmp", self.path)
        return self.path


class FileRun:
    """Run-shaped view over a PartitionedRunWriter file.

    Satisfies the shuffle-service contract (`num_partitions`, `partition()`,
    `partition_nbytes()`, `partition_row_count()`, `empty_partition_flags()`,
    `nbytes`) while the record data stays on disk; `partition()` materializes
    one partition (bounded by that partition's size), and
    `iter_partition_blocks()` streams it block-at-a-time for merges."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            end = fh.tell()
            fh.seek(end - len(PR_FOOTER_MAGIC) - 12)
            crc, size = struct.unpack("<IQ", fh.read(12))
            if fh.read(len(PR_FOOTER_MAGIC)) != PR_FOOTER_MAGIC:
                raise IOError(f"bad partitioned-run footer in {path}")
            fh.seek(end - len(PR_FOOTER_MAGIC) - 12 - size)
            payload = fh.read(size)
            if zlib.crc32(payload) != crc:
                raise IOError(f"partitioned-run index checksum in {path}")
            (p,) = struct.unpack_from("<I", payload)
            off = 4
            self.num_partitions = p
            self._byte_off = np.frombuffer(payload, np.int64, p + 1, off)
            off += (p + 1) * 8
            self._rows = np.frombuffer(payload, np.int64, p, off)
            off += p * 8
            self._kv_bytes = np.frombuffer(payload, np.int64, p, off)

    @property
    def nbytes(self) -> int:
        return int(self._kv_bytes.sum())

    def partition_row_count(self, p: int) -> int:
        return int(self._rows[p])

    def partition_nbytes(self, p: int) -> int:
        return int(self._kv_bytes[p])

    def empty_partition_flags(self) -> List[bool]:
        return [int(r) == 0 for r in self._rows]

    def iter_partition_blocks(self, p: int) -> Iterator[KVBatch]:
        """Stream partition p's sorted blocks (bounded memory)."""
        lo, hi = int(self._byte_off[p]), int(self._byte_off[p + 1])
        if lo >= hi:
            return
        faults.fire("spill.read", detail=self.path)
        with open(self.path, "rb") as fh:
            fh.seek(lo)
            pos = lo
            while pos < hi:
                (n,) = struct.unpack("<Q", fh.read(8))
                blob = faults.corrupt_bytes("spill.read", self.path,
                                            fh.read(n), lo=RUN_HEADER_NBYTES)
                yield Run.from_bytes(blob, where=self.path).batch
                pos += 8 + n

    def partition(self, p: int) -> KVBatch:
        blocks = list(self.iter_partition_blocks(p))
        if not blocks:
            return KVBatch.empty()
        return blocks[0] if len(blocks) == 1 else KVBatch.concat(blocks)

    def to_run(self) -> Run:
        """Materialize fully (compat shim for small data / legacy callers)."""
        parts = [self.partition(p) for p in range(self.num_partitions)]
        row_index = np.zeros(self.num_partitions + 1, dtype=np.int64)
        np.cumsum(self._rows, out=row_index[1:])
        return Run(KVBatch.concat(parts) if parts else KVBatch.empty(),
                   row_index)

    def delete(self) -> None:
        try:
            os.remove(self.path)
        except OSError:
            pass


def save_run_partitioned(run: Run, path: str, codec: Optional[str] = None,
                        block_records: int = 65536) -> str:
    """Write a partition-sorted in-RAM Run as a partition-indexed file."""
    from tez_tpu.common import metrics
    faults.fire("spill.write", detail=path)
    with metrics.timer("spill.write"):
        w = PartitionedRunWriter(path, run.num_partitions, codec=codec,
                                 block_records=block_records)
        w.append_run(run)
        return w.close()
