"""Normalized fixed-width key encoding for device sort.

The TPU sorter needs static shapes (SURVEY.md §7 "Variable-length KV on
TPU"): variable-length keys are carried as (bytes, offsets) pairs and, for
sorting, normalized into a fixed number of big-endian uint32 lanes so that
lane-lexicographic order == raw-byte lexicographic order (the reference's
raw-comparator semantics, ExternalSorter/IFile byte ordering).

Keys longer than the configured width sort by their prefix; equal-prefix
groups are then ordered by a host tie-break pass (sorter.py) so the final
order is exact for any key length.
"""
from __future__ import annotations

import numpy as np


def pad_to_matrix(key_bytes: np.ndarray, offsets: np.ndarray,
                  width: int) -> tuple[np.ndarray, np.ndarray]:
    """Ragged bytes -> (padded uint8[N, width], lengths int32[N]).

    Vectorized gather; pad value 0 sorts below every real byte, matching
    shorter-key-first byte order ("a" < "ab")."""
    n = len(offsets) - 1
    lengths = (offsets[1:] - offsets[:-1]).astype(np.int64)
    mat = np.zeros((n, width), dtype=np.uint8)
    if n == 0 or key_bytes.size == 0:
        # no rows, or every key empty — nothing to gather
        return mat, lengths.astype(np.int32)
    take = np.minimum(lengths, width)
    # index matrix: offsets[i] + j  (clamped), masked by j < take[i]
    j = np.arange(width)[None, :]
    idx = offsets[:-1, None] + j
    valid = j < take[:, None]
    idx = np.where(valid, idx, 0)
    vals = key_bytes[idx]
    mat = np.where(valid, vals, 0).astype(np.uint8)
    return mat, lengths.astype(np.int32)


def matrix_to_lanes(mat: np.ndarray) -> np.ndarray:
    """uint8[N, W] -> big-endian uint32[N, W/4] lanes; W padded to mult of 4.

    Lexicographic comparison of lanes == lexicographic comparison of bytes.
    """
    n, w = mat.shape
    pad = (-w) % 4
    if pad:
        mat = np.pad(mat, ((0, 0), (0, pad)))
        w += pad
    lanes = mat.reshape(n, w // 4, 4).astype(np.uint32)
    return (lanes[..., 0] << 24) | (lanes[..., 1] << 16) | \
        (lanes[..., 2] << 8) | lanes[..., 3]


def encode_keys(key_bytes: np.ndarray, offsets: np.ndarray,
                width: int) -> tuple[np.ndarray, np.ndarray]:
    """Ragged keys -> (uint32 lanes [N, ceil(width/4)], lengths[N])."""
    mat, lengths = pad_to_matrix(key_bytes, offsets, width)
    return matrix_to_lanes(mat), lengths


def lanes_to_matrix(lanes: np.ndarray) -> np.ndarray:
    """Inverse of matrix_to_lanes: big-endian uint32[N, L] -> uint8[N, L*4]."""
    n, num_lanes = lanes.shape
    mat = np.zeros((n, num_lanes * 4), dtype=np.uint8)
    for i in range(4):
        mat[:, i::4] = ((lanes >> (24 - 8 * i)) & 0xFF).astype(np.uint8)
    return mat
