"""Normalized fixed-width key encoding for device sort.

The TPU sorter needs static shapes (SURVEY.md §7 "Variable-length KV on
TPU"): variable-length keys are carried as (bytes, offsets) pairs and, for
sorting, normalized into a fixed number of big-endian uint32 lanes so that
lane-lexicographic order == raw-byte lexicographic order (the reference's
raw-comparator semantics, ExternalSorter/IFile byte ordering).

Keys longer than the configured width sort by their prefix; equal-prefix
groups are then ordered by a host tie-break pass (sorter.py) so the final
order is exact for any key length.
"""
from __future__ import annotations

import numpy as np


def pad_to_matrix(key_bytes: np.ndarray, offsets: np.ndarray,
                  width: int) -> tuple[np.ndarray, np.ndarray]:
    """Ragged bytes -> (padded uint8[N, width], lengths int32[N]).

    Vectorized gather; pad value 0 sorts below every real byte, matching
    shorter-key-first byte order ("a" < "ab")."""
    n = len(offsets) - 1
    lengths = (offsets[1:] - offsets[:-1]).astype(np.int64)
    mat = np.zeros((n, width), dtype=np.uint8)
    if n == 0 or key_bytes.size == 0:
        # no rows, or every key empty — nothing to gather
        return mat, lengths.astype(np.int32)
    step = int(lengths[0])
    if 0 < step <= width and \
            int(offsets[-1]) - int(offsets[0]) == step * n and \
            (lengths == step).all():
        # uniform fixed-width fast path: a reshape replaces the (n, width)
        # fancy gather — fixed-length keys are the common data-plane case
        # and the gather dominates host encode time at span scale
        fixed = key_bytes[int(offsets[0]):int(offsets[-1])].reshape(n, step)
        if step == width:
            mat = np.ascontiguousarray(fixed)
        else:
            mat[:, :step] = fixed
        return mat, lengths.astype(np.int32)
    take = np.minimum(lengths, width)
    # index matrix: offsets[i] + j  (clamped), masked by j < take[i]
    j = np.arange(width)[None, :]
    idx = offsets[:-1, None] + j
    valid = j < take[:, None]
    idx = np.where(valid, idx, 0)
    vals = key_bytes[idx]
    mat = np.where(valid, vals, 0).astype(np.uint8)
    return mat, lengths.astype(np.int32)


def matrix_to_lanes(mat: np.ndarray) -> np.ndarray:
    """uint8[N, W] -> big-endian uint32[N, W/4] lanes; W padded to mult of 4.

    Lexicographic comparison of lanes == lexicographic comparison of bytes.
    """
    n, w = mat.shape
    pad = (-w) % 4
    if pad:
        mat = np.pad(mat, ((0, 0), (0, pad)))
        w += pad
    if mat.flags.c_contiguous:
        # reinterpret rows as big-endian u32 and convert to native in one
        # pass — same packing as the shift/or chain below without the 4x
        # widening intermediate
        return mat.view(">u4").astype(np.uint32)
    lanes = mat.reshape(n, w // 4, 4).astype(np.uint32)
    return (lanes[..., 0] << 24) | (lanes[..., 1] << 16) | \
        (lanes[..., 2] << 8) | lanes[..., 3]


def encode_keys(key_bytes: np.ndarray, offsets: np.ndarray,
                width: int) -> tuple[np.ndarray, np.ndarray]:
    """Ragged keys -> (uint32 lanes [N, ceil(width/4)], lengths[N])."""
    mat, lengths = pad_to_matrix(key_bytes, offsets, width)
    return matrix_to_lanes(mat), lengths


def encode_keys_device(key_bytes: np.ndarray, offsets: np.ndarray,
                       width: int):
    """Device-resident ragged->lanes encode: upload the RAW ragged bytes +
    offsets and run the padded gather + big-endian lane packing as one XLA
    program on the chip (gather is hardware-optimized there; a hand-rolled
    per-row DMA kernel would be strictly worse).  Returns device arrays
    (lanes u32[N, ceil(width/4)], lengths i32[N]).

    This is the device twin of encode_keys — the answer to SURVEY.md §7's
    "variable-length KV on TPU" risk: ragged keys cross the PCIe/ICI
    boundary raw, and every derived fixed-width view lives in HBM.
    """
    import jax.numpy as jnp

    n = len(offsets) - 1
    if n == 0 or key_bytes.size == 0:
        return (jnp.zeros((n, max(1, (width + 3) // 4)), dtype=jnp.uint32),
                jnp.zeros((n,), dtype=jnp.int32))
    return _encode_keys_jit(jnp.asarray(key_bytes),
                            jnp.asarray(offsets.astype(np.int32)), width)


def _encode_keys_jit(key_bytes, offsets, width: int):
    import functools

    import jax

    @functools.partial(jax.jit, static_argnames=("width",))
    def go(data, offs, width: int):
        import jax.numpy as jnp
        starts = offs[:-1]
        lengths = (offs[1:] - starts).astype(jnp.int32)
        w4 = width + ((-width) % 4)
        j = jnp.arange(w4, dtype=jnp.int32)[None, :]
        idx = jnp.clip(starts[:, None] + j, 0, data.shape[0] - 1)
        # mask at WIDTH (not the lane-rounded w4): bytes past the configured
        # width must zero-pad exactly like host pad_to_matrix
        valid = j < jnp.minimum(lengths, width)[:, None]
        mat = jnp.where(valid, jnp.take(data, idx), 0).astype(jnp.uint32)
        m = mat.reshape(mat.shape[0], w4 // 4, 4)
        lanes = (m[..., 0] << 24) | (m[..., 1] << 16) | \
            (m[..., 2] << 8) | m[..., 3]
        return lanes, lengths

    return go(key_bytes, offsets, width)


def lanes_to_matrix(lanes: np.ndarray) -> np.ndarray:
    """Inverse of matrix_to_lanes: big-endian uint32[N, L] -> uint8[N, L*4]."""
    n, num_lanes = lanes.shape
    mat = np.zeros((n, num_lanes * 4), dtype=np.uint8)
    for i in range(4):
        mat[:, i::4] = ((lanes >> (24 - 8 * i)) & 0xFF).astype(np.uint8)
    return mat
