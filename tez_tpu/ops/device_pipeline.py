"""Device-resident shuffle+sort pipeline for fixed-width records.

This is the HBM-resident heart of the data plane (SURVEY.md §2.5: "spans =
device buffers", spill = device->host DMA only on overflow): records whose
keys are normalized to u32 lanes and whose values are fixed-width words flow
hash->sort->merge entirely on device — the host only sees control metadata
(partition boundaries) and whatever a leaf output finally materializes.

The variable-length KVBatch path (ops.sorter) wraps this with host ragged
gathers; benchmarks and device-to-device edges use it directly.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tez_tpu.ops.device import (_bucket, _hash_to_partitions,
                                _lsd_passes,
                                uniform_clamped_lengths)


@functools.partial(jax.jit,
                   static_argnames=("num_partitions", "skip_length_pass"))
def _fused_pipeline(key_mat: jnp.ndarray, hash_lengths: jnp.ndarray,
                    lanes: jnp.ndarray, sort_lengths: jnp.ndarray,
                    vals: jnp.ndarray, num_partitions: int,
                    skip_length_pass: bool = False
                    ) -> Tuple[jnp.ndarray, ...]:
    """hash-partition + LSD (partition, lanes, length) sort + payload gather,
    one dispatch, everything stays in HBM.  Hash and sort bodies are the
    shared device.py helpers — one implementation for every kernel."""
    partitions = _hash_to_partitions(key_mat, hash_lengths, num_partitions)
    sorted_parts, perm = _lsd_passes(partitions, lanes, sort_lengths,
                                     skip_length_pass)
    out_lanes = lanes[perm]
    out_vals = vals[perm]
    # per-partition row counts (for the partition index) on device
    counts = jnp.bincount(
        jnp.clip(sorted_parts.astype(jnp.int32), 0, num_partitions),
        length=num_partitions + 1)[:num_partitions]
    return sorted_parts.astype(jnp.int32), out_lanes, out_vals, perm, counts


def device_shuffle_sort(lanes, lengths, vals, key_mat, hash_lengths,
                        num_partitions: int, uniform_length=None):
    """Device-resident pipeline over already-device (or host) arrays.
    Returns device arrays (sorted_partitions, lanes, vals, perm, counts).

    uniform_length: pass True/False when the caller already knows (keeps the
    lengths array device-resident); None = detect from a host array."""
    n = int(lanes.shape[0])
    nb = _bucket(n)
    width_cap = lanes.shape[1] * 4 + 1
    if uniform_length is None:
        uniform = isinstance(lengths, np.ndarray) and \
            uniform_clamped_lengths(lengths, width_cap)[0]
    else:
        uniform = bool(uniform_length)
    if nb != n:
        pad = nb - n
        key_mat = jnp.pad(key_mat, ((0, pad), (0, 0)), constant_values=255)
        hash_lengths = jnp.pad(hash_lengths, (0, pad), constant_values=-1)
        lanes = jnp.pad(lanes, ((0, pad), (0, 0)),
                        constant_values=np.uint32(0xFFFFFFFF))
        lengths = jnp.pad(lengths, (0, pad), constant_values=width_cap)
        vals = jnp.pad(vals, ((0, pad),) + ((0, 0),) * (vals.ndim - 1))
    slen = jnp.minimum(lengths, width_cap).astype(jnp.uint32)
    return _fused_pipeline(jnp.asarray(key_mat),
                           jnp.asarray(hash_lengths, dtype=jnp.int32),
                           jnp.asarray(lanes), slen, jnp.asarray(vals),
                           num_partitions, skip_length_pass=uniform)
