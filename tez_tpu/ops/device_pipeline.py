"""Device-resident shuffle+sort pipeline for fixed-width records.

This is the HBM-resident heart of the data plane (SURVEY.md §2.5: "spans =
device buffers", spill = device->host DMA only on overflow): records whose
keys are normalized to u32 lanes and whose values are fixed-width words flow
hash->sort->merge entirely on device — the host only sees control metadata
(partition boundaries) and whatever a leaf output finally materializes.

Two entry points:

* :func:`device_shuffle_sort` — one synchronous span (the original path).
* :class:`DeviceSpanScheduler` — the asynchronous double-buffered plane
  (ops/async_stage.py): spans submit as raw host arrays; a staging thread
  encodes/bucket-pads/uploads span k+1 while span k's `_fused_pipeline` is
  in flight and span k-1's readback drains on worker threads.  Small spans
  coalesce into one bucketed dispatch.  The variable-length KVBatch path
  (ops.sorter) builds the same AsyncSpanPipeline around its own
  Run-producing stages; this class serves raw-array producers (benchmarks,
  device-to-device edges).

The reduce side runs a third AsyncSpanPipeline instance: the merge lane in
library/merge_manager.py, whose dispatch stage is the merge-path kernel
(ops/device.py merge_path_runs — O(N) partitioned binary-merge of
pre-sorted runs, no re-sort) and whose readback stage is the chunked-run
disk write, so fetch/commit, device merge, and spill IO overlap.
"""
from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tez_tpu.ops.device import (_bucket, _hash_to_partitions,
                                _lsd_passes, accelerator_present,
                                uniform_clamped_lengths)


def _fused_pipeline_impl(key_mat: jnp.ndarray, hash_lengths: jnp.ndarray,
                         lanes: jnp.ndarray, sort_lengths: jnp.ndarray,
                         vals: jnp.ndarray, num_partitions: int,
                         skip_length_pass: bool = False
                         ) -> Tuple[jnp.ndarray, ...]:
    """hash-partition + LSD (partition, lanes, length) sort + payload gather,
    one dispatch, everything stays in HBM.  Hash and sort bodies are the
    shared device.py helpers — one implementation for every kernel."""
    partitions = _hash_to_partitions(key_mat, hash_lengths, num_partitions)
    sorted_parts, perm = _lsd_passes(partitions, lanes, sort_lengths,
                                     skip_length_pass)
    out_lanes = lanes[perm]
    out_vals = vals[perm]
    # per-partition row counts (for the partition index) on device:
    # sorted_parts is already sorted, so P+1 binary searches beat a
    # full bincount scan (padding sentinels carry partition INT32_MAX
    # and fall past the last boundary)
    sp32 = sorted_parts.astype(jnp.int32)
    bounds = jnp.searchsorted(
        sp32, jnp.arange(num_partitions + 1, dtype=jnp.int32))
    counts = bounds[1:] - bounds[:-1]
    return sp32, out_lanes, out_vals, perm, counts


_fused_pipeline = jax.jit(
    _fused_pipeline_impl,
    static_argnames=("num_partitions", "skip_length_pass"))


@functools.lru_cache(maxsize=1)
def _fused_pipeline_donated():
    """Donating flavor for the async plane: the staged lane/value buffers
    alias the sorted outputs, so the sort+gather runs in-place in HBM —
    double-buffered staging slots don't triple the resident footprint.
    Accelerator backends only (XLA:CPU ignores donation, warning per call).
    """
    if not accelerator_present():
        return _fused_pipeline
    return jax.jit(_fused_pipeline_impl,
                   static_argnames=("num_partitions", "skip_length_pass"),
                   donate_argnums=(2, 4))


def device_shuffle_sort(lanes, lengths, vals, key_mat, hash_lengths,
                        num_partitions: int, uniform_length=None):
    """Device-resident pipeline over already-device (or host) arrays.
    Returns device arrays (sorted_partitions, lanes, vals, perm, counts).

    uniform_length: pass True/False when the caller already knows (keeps the
    lengths array device-resident); None = detect from a host array."""
    n = int(lanes.shape[0])
    nb = _bucket(n)
    width_cap = lanes.shape[1] * 4 + 1
    if uniform_length is None:
        uniform = isinstance(lengths, np.ndarray) and \
            uniform_clamped_lengths(lengths, width_cap)[0]
    else:
        uniform = bool(uniform_length)
    if nb != n:
        pad = nb - n
        key_mat = jnp.pad(key_mat, ((0, pad), (0, 0)), constant_values=255)
        hash_lengths = jnp.pad(hash_lengths, (0, pad), constant_values=-1)
        lanes = jnp.pad(lanes, ((0, pad), (0, 0)),
                        constant_values=np.uint32(0xFFFFFFFF))
        lengths = jnp.pad(lengths, (0, pad), constant_values=width_cap)
        vals = jnp.pad(vals, ((0, pad),) + ((0, 0),) * (vals.ndim - 1))
    slen = jnp.minimum(lengths, width_cap).astype(jnp.uint32)
    return _fused_pipeline(jnp.asarray(key_mat),
                           jnp.asarray(hash_lengths, dtype=jnp.int32),
                           jnp.asarray(lanes), slen, jnp.asarray(vals),
                           num_partitions, skip_length_pass=uniform)


class DeviceSpanScheduler:
    """Async double-buffered plane over fixed-width spans.

    submit() takes host arrays (lanes, lengths, vals, key_mat, hash_lengths)
    for one span; results() blocks until everything drained and returns
    {span_id: (sorted_partitions, out_lanes, out_vals, perm, counts, n)} as
    HOST arrays (n = real rows; bucketed rows beyond n are tail sentinels).
    Coalesced spans share one result tuple whose rows are the stable sort of
    the concatenated spans — identical to merging the individually sorted
    spans, since stable ties preserve arrival order.
    """

    def __init__(self, num_partitions: int, depth: int = 2,
                 coalesce_records: int = 0, readback_workers: int = 2,
                 key_width: int = 0, counters: Any = None,
                 clock: Callable[[], float] = time.perf_counter,
                 instrument: bool = False, paused: bool = False,
                 contain_failures: bool = False,
                 watchdog_dispatch_ms: float = 0.0,
                 watchdog_readback_ms: float = 0.0,
                 breaker: Any = None) -> None:
        from tez_tpu.ops.async_stage import AsyncSpanPipeline
        self.num_partitions = num_partitions
        # key_width only matters for submit_ragged(); every ragged key must
        # fit in it (the hash matrix is built at the next power-of-two width,
        # so a longer key would hash truncated and land in the wrong
        # partition)
        self.key_width = key_width
        self.pipeline = AsyncSpanPipeline(
            encode_fn=self._encode,
            stage_fn=self._h2d,
            dispatch_fn=self._dispatch,
            readback_fn=self._readback,
            coalesce_fn=self._coalesce,
            records_fn=self._records,
            depth=depth,
            coalesce_records=coalesce_records,
            readback_workers=readback_workers,
            counters=counters, clock=clock, instrument=instrument,
            paused=paused, name="device-span",
            # failure containment: a failed/hung device attempt re-sorts
            # through the numpy twin of _fused_pipeline (bit-exact)
            failover_fn=self._host_failover if contain_failures else None,
            breaker=breaker,
            watchdog_dispatch_ms=watchdog_dispatch_ms,
            watchdog_readback_ms=watchdog_readback_ms)

    def submit(self, span_id, lanes, lengths, vals, key_mat, hash_lengths,
               coalesce: bool = True) -> None:
        self.pipeline.submit(span_id, {
            "lanes": lanes, "lengths": lengths, "vals": vals,
            "key_mat": key_mat, "hash_lengths": hash_lengths,
        }, coalesce=coalesce)

    def submit_ragged(self, span_id, key_bytes, key_offsets, val_bytes,
                      val_width: int, coalesce: bool = True) -> None:
        """Submit one span of ragged key bytes + fixed-width values.  The
        lane/hash-matrix encode runs on the staging thread (this is the
        overlapped host-encode stage); requires key_width > 0 at
        construction and every key to fit in it."""
        if self.key_width <= 0:
            raise ValueError("submit_ragged requires key_width > 0")
        self.pipeline.submit(span_id, {
            "key_bytes": key_bytes, "key_offsets": key_offsets,
            "val_bytes": val_bytes, "val_width": val_width,
        }, coalesce=coalesce)

    def resume(self) -> None:
        self.pipeline.resume()

    def results(self) -> Dict[Any, Tuple]:
        return self.pipeline.drain()

    # -- stages (staging thread / readback workers) -------------------------
    @staticmethod
    def _records(p: Dict) -> int:
        if "lanes" in p:
            return int(p["lanes"].shape[0])
        return len(p["key_offsets"]) - 1

    def _encode(self, p: Dict) -> Dict:
        if "key_bytes" in p:
            return self._encode_ragged(p)
        # raw-array producers arrive lane-encoded already; the encode stage
        # normalizes dtypes so coalesce/pad are pure concatenation
        return {
            "lanes": np.ascontiguousarray(p["lanes"], dtype=np.uint32),
            "lengths": np.asarray(p["lengths"], dtype=np.int64),
            "vals": np.ascontiguousarray(p["vals"]),
            "key_mat": np.ascontiguousarray(p["key_mat"], dtype=np.uint8),
            "hash_lengths": np.asarray(p["hash_lengths"], dtype=np.int32),
        }

    def _encode_ragged(self, p: Dict) -> Dict:
        from tez_tpu.ops.keycodec import matrix_to_lanes, pad_to_matrix
        kb, ko = p["key_bytes"], p["key_offsets"]
        n = len(ko) - 1
        mat, lengths = pad_to_matrix(kb, ko, self.key_width)
        lanes = matrix_to_lanes(mat)
        hash_w = 1 << max(2, (self.key_width - 1).bit_length())
        hmat, hlens = pad_to_matrix(kb, ko, hash_w)
        vals = np.ascontiguousarray(
            p["val_bytes"].reshape(n, p["val_width"])).view(np.uint32)
        return {
            "lanes": lanes, "lengths": lengths.astype(np.int64),
            "vals": vals, "key_mat": hmat,
            "hash_lengths": hlens.astype(np.int32),
        }

    def _coalesce(self, staged: List[Dict]) -> Dict:
        # defer the merge: _h2d writes every span straight into the
        # bucketed staging buffers — one copy instead of concat-then-pad.
        # Coalesced spans must share lane/hash/value widths (the ragged
        # path guarantees it; mismatched pre-encoded spans fail loudly on
        # assignment).
        return {"_spans": staged}

    def _bucketize(self, s: Dict) -> Dict:
        """Host-side half of H2D staging: merge the (possibly coalesced)
        spans into bucket-padded numpy buffers with the device kernels' tail
        sentinels.  Shared by the device upload (_h2d) and the host failover
        twin (_host_failover) so padding semantics can never diverge."""
        spans = s["_spans"] if "_spans" in s else [s]
        first = spans[0]
        nlanes = first["lanes"].shape[1]
        width_cap = nlanes * 4 + 1
        n = sum(int(sp["lanes"].shape[0]) for sp in spans)
        nb = _bucket(n)
        # bucketed staging buffers pre-filled with the tail sentinels
        lanes = np.full((nb, nlanes), np.uint32(0xFFFFFFFF), dtype=np.uint32)
        key_mat = np.full((nb, first["key_mat"].shape[1]), 255,
                          dtype=np.uint8)
        hash_lengths = np.full(nb, -1, dtype=np.int32)
        lengths = np.full(nb, width_cap, dtype=np.int64)
        vals = np.zeros((nb,) + first["vals"].shape[1:],
                        dtype=first["vals"].dtype)
        off = 0
        for sp in spans:
            m = int(sp["lanes"].shape[0])
            lanes[off:off + m] = sp["lanes"]
            key_mat[off:off + m] = sp["key_mat"]
            hash_lengths[off:off + m] = sp["hash_lengths"]
            lengths[off:off + m] = sp["lengths"]
            vals[off:off + m] = sp["vals"]
            off += m
        uniform = n == 0 or \
            uniform_clamped_lengths(lengths[:n], width_cap)[0]
        slen = np.minimum(lengths, width_cap).astype(np.uint32)
        return {
            "key_mat": key_mat, "hash_lengths": hash_lengths,
            "lanes": lanes, "sort_lengths": slen, "vals": vals,
            "uniform": uniform, "n": n,
        }

    def _h2d(self, s: Dict) -> Dict:
        h = self._bucketize(s)
        return {
            "key_mat": jnp.asarray(h["key_mat"]),
            "hash_lengths": jnp.asarray(h["hash_lengths"], dtype=jnp.int32),
            "lanes": jnp.asarray(h["lanes"]),
            "sort_lengths": jnp.asarray(h["sort_lengths"]),
            "vals": jnp.asarray(h["vals"]),
            "uniform": h["uniform"], "n": h["n"],
        }

    def _dispatch(self, s: Dict):
        out = _fused_pipeline_donated()(
            s["key_mat"], s["hash_lengths"], s["lanes"], s["sort_lengths"],
            s["vals"], self.num_partitions, skip_length_pass=s["uniform"])
        return out + (s["n"],)

    def _readback(self, inflight, ids):
        sp, out_lanes, out_vals, perm, counts, n = inflight
        return (np.asarray(sp), np.asarray(out_lanes), np.asarray(out_vals),
                np.asarray(perm), np.asarray(counts), n)

    # -- failure containment -------------------------------------------------
    def _host_failover(self, ids, payloads) -> Tuple:
        """Numpy twin of _fused_pipeline over the RAW payloads: the same
        bucketed staging buffers, FNV hash-partition (padding rows carry
        partition INT32_MAX like _hash_to_partitions), stable
        (partition, lanes, length) sort, gather, and searchsorted counts —
        bit-exact with the device result, never touches the device."""
        from tez_tpu.ops.host_sort import host_hash_partition, host_sort_run
        staged = [self._encode(p) for p in payloads]
        one = staged[0] if len(staged) == 1 else self._coalesce(staged)
        s = self._bucketize(one)
        n = s["n"]
        parts = np.full(s["key_mat"].shape[0],
                        np.iinfo(np.int32).max, dtype=np.int32)
        if n > 0:
            parts[:n] = host_hash_partition(
                s["key_mat"][:n], s["hash_lengths"][:n], self.num_partitions)
        sp, perm = host_sort_run(parts, s["lanes"], s["sort_lengths"])
        sp32 = sp.astype(np.int32)
        bounds = np.searchsorted(
            sp32, np.arange(self.num_partitions + 1, dtype=np.int32))
        counts = (bounds[1:] - bounds[:-1]).astype(np.int32)
        return (sp32, s["lanes"][perm], s["vals"][perm],
                perm.astype(np.int32), counts, n)
