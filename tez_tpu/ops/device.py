"""jit'd device kernels for the data plane: hash-partition, segmented sort.

These are the TPU replacements for the reference's byte-crunching loops
(PipelinedSorter.collect/sort spans, HashPartitioner, TezMerger) —
SURVEY.md §2.5 "TPU-native equivalent" column.  All kernels are shape-
bucketed (power-of-two padding) so XLA compiles a bounded set of programs;
compiled functions are cached per-process (jit cache) and survive across
tasks via runner reuse.

Sorting model: keys are fixed-width uint32 lanes (ops/keycodec); the sort is
a single variadic stable `lax.sort` over (partition, lane_0..lane_{L-1})
carrying the record permutation — XLA lowers this to its optimized on-device
sort; the merge of k sorted runs reuses the same kernel on the concatenation
(sort networks beat heap-merge on TPU's vector units; runs' stable order
preserves within-key arrival order like the reference's MergeQueue).
"""
from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Persistent compilation cache: sort-kernel compiles are seconds-to-minutes
# on TPU; cache them across processes (runner reuse only caches in-process).
try:
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("TEZ_TPU_JAX_CACHE",
                                     "/tmp/tez_tpu_jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:  # noqa: BLE001 — older jax without the knob
    pass

FNV_OFFSET = np.uint32(2166136261)
FNV_PRIME = np.uint32(16777619)


@functools.lru_cache(maxsize=1)
def single_pass_variadic() -> bool:
    """True when the sort body should use ONE variadic multi-key `lax.sort`
    instead of chained single-key LSD passes.

    XLA:CPU compiles the N-operand variadic sort instantly and runs it ~2x
    faster than the chained ladder (one comparator walk instead of L+2
    full passes over the permutation).  On TPU the variadic sort costs
    minutes of XLA compile time at large N, so accelerator backends keep
    the chained passes.  Evaluated at trace time (Python-level branch in
    the jitted bodies); cached — one backend query per process."""
    if os.environ.get("TEZ_TPU_FORCE_LSD_PASSES"):
        return False
    try:
        return jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001
        return False


@functools.lru_cache(maxsize=1)
def accelerator_present() -> bool:
    """True when the default JAX backend is an accelerator (TPU/GPU).

    The `auto` engine routes through this: device kernels are a *loss* on
    the CPU backend (XLA CPU sort + dispatch overhead vs numpy/native), so
    auto picks the host engine there and the device engine whenever a real
    chip answers.  Cached — one backend query per process."""
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:  # noqa: BLE001 — backend init failure = no accelerator
        return False


#: Substrings marking a device failure as an out-of-memory class.  XLA
#: surfaces HBM exhaustion as a RuntimeError/XlaRuntimeError whose message
#: carries the gRPC-style status name, not a dedicated exception type, so
#: classification is message-based; the fault plane's injected
#: `device.dispatch.oom` errors match the same way.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "out of memory", "OOM", "device.dispatch.oom")


def is_resource_exhausted(exc: BaseException) -> bool:
    """True when a device-attempt failure should take the OOM ladder
    (retry on-device with the span split) rather than plain host failover."""
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def uniform_clamped_lengths(lengths: np.ndarray, width_cap: int):
    """(is_uniform, pad_value) over CLAMPED lengths — the shared uniformity
    test for the skip-length-pass optimization (clamp first: all-long keys
    compare equal at the cap)."""
    if len(lengths) == 0:
        return False, width_cap
    clamped = np.minimum(lengths.astype(np.int64), width_cap)
    lo, hi = int(clamped.min()), int(clamped.max())
    return lo == hi, (lo if lo == hi else width_cap)


def _bucket(n: int, floor: int = 256) -> int:
    """Round up to the shape bucket (power of two) to bound recompiles."""
    b = floor
    while b < n:
        b <<= 1
    return b


# ---------------------------------------------------------------------------
# hash partition
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("num_partitions",))
def _fnv_partition(key_mat: jnp.ndarray, lengths: jnp.ndarray,
                   num_partitions: int) -> jnp.ndarray:
    """FNV-1a over each row's first `lengths[i]` bytes of key_mat[i, :].

    Byte-identical to library.partitioners.HashPartitioner._stable_hash for
    keys that fit the padded width.  key_mat: uint8[N, W]; returns int32[N].
    """
    h = _fnv_rows(key_mat, lengths)
    return (h % jnp.uint32(num_partitions)).astype(jnp.int32)


def hash_partition(key_mat: np.ndarray, lengths: np.ndarray,
                   num_partitions: int, use_pallas: bool = False) -> np.ndarray:
    """Host wrapper with shape bucketing.

    use_pallas routes to the Pallas FNV kernel (same hash body) on TPU
    backends; elsewhere it falls back to the XLA path so the flag is safe to
    set fleet-wide."""
    n = key_mat.shape[0]
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    if use_pallas and jax.default_backend() == "tpu":
        from tez_tpu.ops.pallas_kernels import hash_partition_pallas
        return hash_partition_pallas(key_mat, lengths, num_partitions)
    nb = _bucket(n)
    if nb != n:
        key_mat = np.pad(key_mat, ((0, nb - n), (0, 0)))
        lengths = np.pad(lengths, (0, nb - n))
    out = _fnv_partition(key_mat, jnp.asarray(lengths), num_partitions)
    return np.asarray(out)[:n]


# ---------------------------------------------------------------------------
# partitioned stable sort
# ---------------------------------------------------------------------------
def _fnv_rows(key_mat: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """Traced FNV-1a over each row's first `lengths[i]` bytes — the ONE hash
    body shared by every kernel (host-partitioner parity)."""
    def body(j, h):
        byte = key_mat[:, j].astype(jnp.uint32)
        nh = ((h ^ byte) * FNV_PRIME).astype(jnp.uint32)
        return jnp.where(j < lengths, nh, h)

    h = jnp.full((key_mat.shape[0],), FNV_OFFSET, dtype=jnp.uint32)
    return jax.lax.fori_loop(0, key_mat.shape[1], body, h)


def _hash_to_partitions(key_mat: jnp.ndarray, hash_lengths: jnp.ndarray,
                        num_partitions: int) -> jnp.ndarray:
    """Hash + padding sentinel: rows with hash_lengths < 0 get partition MAX
    so they sort to the tail."""
    h = _fnv_rows(key_mat, hash_lengths)
    return jnp.where(
        hash_lengths < 0, jnp.int32(np.iinfo(np.int32).max),
        (h % jnp.uint32(num_partitions)).astype(jnp.int32))


def _lsd_passes(partitions: jnp.ndarray, lanes: jnp.ndarray,
                lengths: jnp.ndarray,
                skip_length_pass: bool = False
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Traced body shared by the fused kernels: stable LSD passes by
    (partition, lanes..., clamped length).

    skip_length_pass: set when every key in the span has the same length —
    the pass would be an identity reorder (fixed-width key workloads save a
    full sort pass).  The partition pass always runs: it doubles as the
    padding separator (pad rows carry partition MAX)."""
    n = partitions.shape[0]
    perm = jnp.arange(n, dtype=jnp.int32)
    if single_pass_variadic():
        # one variadic sort == the full LSD ladder: lexicographic
        # (partition, lane_0..lane_{L-1}[, length]) with perm as the FINAL
        # key.  perm is unique, so the composite order is total: an
        # UNSTABLE sort is deterministic and equal-key rows land in
        # ascending-perm (= arrival) order — bit-identical to the stable
        # ladder, and XLA:CPU's unstable sort is ~25% faster.
        keys = (partitions.astype(jnp.uint32),)
        keys += tuple(lanes[:, i] for i in range(lanes.shape[1]))
        if not skip_length_pass:
            keys += (lengths.astype(jnp.uint32),)
        res = jax.lax.sort(keys + (perm,), dimension=0, is_stable=False,
                           num_keys=len(keys) + 1)
        return res[0].astype(jnp.int32), res[-1]
    if not skip_length_pass:
        _, perm = jax.lax.sort((lengths.astype(jnp.uint32), perm),
                               dimension=0, is_stable=True, num_keys=1)
    for i in range(lanes.shape[1] - 1, -1, -1):
        _, perm = jax.lax.sort((lanes[:, i][perm], perm),
                               dimension=0, is_stable=True, num_keys=1)
    sorted_parts, perm = jax.lax.sort(
        (partitions.astype(jnp.uint32)[perm], perm),
        dimension=0, is_stable=True, num_keys=1)
    return sorted_parts.astype(jnp.int32), perm


# ---------------------------------------------------------------------------
# device-resident span sort + merge (VERDICT r1 item 4: the framework's hot
# path keeps key material in HBM across sort -> shuffle -> merge; the host
# only sees permutations and does the leaf ragged gathers).  Only valid when
# every key fits the lane width — then lanes+lengths ARE the full key, the
# FNV hash can be derived on device (no separate hash-matrix upload) and
# prefix order IS exact byte order (no tie-break pass).
# ---------------------------------------------------------------------------
def _fnv_rows_from_lanes(lanes: jnp.ndarray,
                         lengths: jnp.ndarray) -> jnp.ndarray:
    """FNV-1a over each row's first `lengths[i]` bytes, reconstructed from
    the big-endian u32 lanes (keycodec.matrix_to_lanes packing).  Exact
    parity with _fnv_rows/HashPartitioner when true length <= lane bytes."""
    h = jnp.full((lanes.shape[0],), FNV_OFFSET, dtype=jnp.uint32)
    for j in range(lanes.shape[1] * 4):     # static unroll, W is small
        byte = (lanes[:, j // 4] >> (24 - 8 * (j % 4))) & jnp.uint32(0xFF)
        nh = ((h ^ byte) * FNV_PRIME).astype(jnp.uint32)
        h = jnp.where(j < lengths, nh, h)
    return h


def _fused_resident_hash_sort_impl(lanes: jnp.ndarray, lengths: jnp.ndarray,
                                   num_partitions: int,
                                   skip_length_pass: bool = False
                                   ) -> Tuple[jnp.ndarray, ...]:
    """hash-from-lanes + LSD sort; ALSO returns the sorted key columns as
    device arrays so downstream merges never re-upload them.  Sentinel rows
    (length < 0) take partition MAX and sort to the tail."""
    h = _fnv_rows_from_lanes(lanes, lengths)
    partitions = jnp.where(
        lengths < 0, jnp.int32(np.iinfo(np.int32).max),
        (h % jnp.uint32(num_partitions)).astype(jnp.int32))
    sort_lens = jnp.where(lengths < 0, jnp.uint32(0xFFFFFFFF),
                          lengths.astype(jnp.uint32))
    sp, perm = _lsd_passes(partitions, lanes, sort_lens, skip_length_pass)
    return sp, perm, lanes[perm], lengths[perm]


_fused_resident_hash_sort = jax.jit(
    _fused_resident_hash_sort_impl,
    static_argnames=("num_partitions", "skip_length_pass"))


@functools.lru_cache(maxsize=1)
def _resident_sort_donated():
    """Donating flavor for the async pipeline: the staged (bucketed) input
    lanes buffer aliases the sorted-lanes output, so the sort runs in-place
    in HBM instead of holding both copies live.  Accelerator backends only —
    XLA:CPU ignores donation (with a warning per call), so the plain jit is
    returned there."""
    if not accelerator_present():
        return _fused_resident_hash_sort
    return jax.jit(_fused_resident_hash_sort_impl,
                   static_argnames=("num_partitions", "skip_length_pass"),
                   donate_argnums=(0,))


# -- decomposed resident-span stages (ops/async_stage.py pipeline) ----------
# hash_sort_span_resident = stage + dispatch + readback run back-to-back;
# the async pipeline runs them on different threads so span k+1's staging
# overlaps span k's in-flight sort.

def stage_resident_span(lanes: np.ndarray, lengths: np.ndarray):
    """Host bucket-pad + H2D upload.  Returns (lanes_dev, lens_dev, n,
    skip_length_pass)."""
    n = lanes.shape[0]
    uniform, _pad = uniform_clamped_lengths(lengths, lanes.shape[1] * 4 + 1)
    nb = _bucket(n)
    lengths = lengths.astype(np.int32)
    if nb != n:
        lanes = np.pad(lanes, ((0, nb - n), (0, 0)),
                       constant_values=np.uint32(0xFFFFFFFF))
        lengths = np.pad(lengths, (0, nb - n), constant_values=-1)
    return (jax.device_put(jnp.asarray(lanes)),
            jax.device_put(jnp.asarray(lengths)), n, uniform)


def dispatch_resident_span(staged, num_partitions: int):
    """Launch the fused kernel; returns in-flight device arrays immediately
    (JAX async dispatch) — block via readback_resident_span."""
    lanes_dev, lens_dev, n, uniform = staged
    sp, perm, out_lanes, out_lens = _resident_sort_donated()(
        lanes_dev, lens_dev, num_partitions, skip_length_pass=uniform)
    return sp, perm, out_lanes, out_lens, n


def readback_resident_span(inflight):
    """Block until host-visible; same return shape as
    hash_sort_span_resident."""
    sp, perm, out_lanes, out_lens, n = inflight
    return (np.asarray(sp)[:n], np.asarray(perm)[:n],
            (out_lanes, out_lens, 0, n))


def hash_sort_span_resident(lanes: np.ndarray, lengths: np.ndarray,
                            num_partitions: int):
    """Fused span kernel, resident flavor: upload = lanes + lengths ONLY
    (~20B/row vs ~36B for the matrix path); returns host (sorted partitions,
    permutation) plus device (sorted lanes, sorted lengths, bucketed) whose
    rows >= n are tail sentinels.  Caller guarantees max true length <=
    lane bytes."""
    n = lanes.shape[0]
    if n == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32), None)
    uniform, _pad = uniform_clamped_lengths(lengths, lanes.shape[1] * 4 + 1)
    nb = _bucket(n)
    lengths = lengths.astype(np.int32)
    if nb != n:
        lanes = np.pad(lanes, ((0, nb - n), (0, 0)),
                       constant_values=np.uint32(0xFFFFFFFF))
        lengths = np.pad(lengths, (0, nb - n), constant_values=-1)
    # uniform real lengths make the length pass an identity reorder even
    # with tail sentinels present: sentinel order is fully decided by the
    # final partition pass (partition MAX)
    sp, perm, out_lanes, out_lens = _fused_resident_hash_sort(
        jnp.asarray(lanes), jnp.asarray(lengths), num_partitions,
        skip_length_pass=uniform)
    sp = np.asarray(sp)[:n]
    perm = np.asarray(perm)[:n]
    return sp, perm, (out_lanes, out_lens, 0, n)


@functools.partial(jax.jit, static_argnames=("out_rows", "out_lanes"))
def _slice_to_bucket(lanes: jnp.ndarray, lengths: jnp.ndarray,
                     lo, count, out_rows: int, out_lanes: int):
    """Dynamic [lo, lo+count) slice padded to a STATIC out_rows bucket with
    tail sentinels — dynamic offsets keep the compile count bounded by
    (input bucket, output bucket) pairs, not by data-dependent slice sizes.
    Narrower views widen to out_lanes with ZERO lanes: bytes beyond a key's
    length are zero in the lane encoding, so widening preserves order."""
    idx = lo + jnp.arange(out_rows)
    safe = jnp.minimum(idx, lanes.shape[0] - 1)
    sl = jnp.take(lanes, safe, axis=0)
    ln = jnp.take(lengths, safe, axis=0)
    if lanes.shape[1] < out_lanes:
        sl = jnp.pad(sl, ((0, 0), (0, out_lanes - lanes.shape[1])))
    mask = jnp.arange(out_rows) < count
    sl = jnp.where(mask[:, None], sl, jnp.uint32(0xFFFFFFFF))
    ln = jnp.where(mask, ln, -1)
    return sl, ln


@jax.jit
def _fused_resident_merge(lanes_list, lens_list):
    """Single-partition k-way merge of device-resident sorted key columns:
    stable sort of the concatenation (TezMerger semantics — equal keys keep
    run order).  Sentinel rows (length < 0) sort to the tail."""
    lanes = jnp.concatenate(lanes_list, axis=0)
    lens = jnp.concatenate(lens_list, axis=0)
    parts = jnp.where(lens < 0, jnp.int32(np.iinfo(np.int32).max),
                      jnp.int32(0))
    sort_lens = jnp.where(lens < 0, jnp.uint32(0xFFFFFFFF),
                          lens.astype(jnp.uint32))
    _, perm = _lsd_passes(parts, lanes, sort_lens)
    return perm


def _map_bucketed_perm(perm: np.ndarray, counts, common: int) -> np.ndarray:
    """Map a permutation over the BUCKETED concatenation (k runs, each
    padded to `common` rows) back to host rows of the real concatenation,
    dropping sentinel positions."""
    bounds = np.zeros(len(counts) + 1, dtype=np.int64)
    np.cumsum([common] * len(counts), out=bounds[1:])
    host_offsets = np.zeros(len(counts), dtype=np.int64)
    np.cumsum(counts[:-1], out=host_offsets[1:])
    run_id = np.searchsorted(bounds[1:], perm, side="right")
    within = perm - bounds[run_id]
    real = within < np.asarray(counts)[run_id]
    return (host_offsets[run_id] + within)[real].astype(np.int64)


def merge_resident_slices(slices, kernel: str = "merge_path") -> np.ndarray:
    """k-way merge over device-resident key views.

    slices: list of (lanes_dev, lens_dev, lo, hi) with identical lane
    counts.  Returns the merge permutation into the HOST concatenation of
    the real rows (run order preserved for equal keys).  No key bytes move
    host->device; only the permutation comes back.

    kernel="merge_path" (default) runs the O(N) partitioned binary-merge
    ladder — each level ranks every row of one run in its sibling, so a
    k-way merge is log2(k) linear passes instead of one O(N log N) re-sort
    of the concatenation.  kernel="sort" keeps the concatenate+re-sort
    program callable (bench comparison, escape hatch)."""
    counts = [hi - lo for (_l, _n, lo, hi) in slices]
    # ONE common bucket for every slice: the merge program's compile key is
    # then (k, B, L) instead of the full ordered tuple of per-run sizes —
    # bounded compile variety at the cost of sorting k*B instead of
    # sum(bucket_i) rows (sentinels are cheap; compiles are not)
    common = _bucket(max(counts))
    width = max(l.shape[1] for (l, _n, _lo, _hi) in slices)
    lanes_list, lens_list = [], []
    for (lanes, lens, lo, hi) in slices:
        sl, ln = _slice_to_bucket(lanes, lens, lo, hi - lo, common, width)
        lanes_list.append(sl)
        lens_list.append(ln)
    if kernel == "merge_path":
        perm = np.asarray(_merge_path_resident(lanes_list, lens_list, common))
    else:
        perm = np.asarray(_fused_resident_merge(lanes_list, lens_list))
    return _map_bucketed_perm(perm, counts, common)


# ---------------------------------------------------------------------------
# merge-path kernel: O(N) two-way merge of pre-sorted runs via cross-ranks.
# out_pos(a_i) = i + |{b : b < a_i}| and out_pos(b_j) = j + |{a : a <= b_j}|
# tile [0, na+nb) exactly (the asymmetric <=/< pair is what makes equal keys
# emit in run-arrival order — the earlier run wins, matching the stable
# concatenate+sort kernel and TezMerger's MergeQueue).  A k-way merge is a
# log2(k) ladder of pair merges; runs stay HBM-resident between levels, so
# encode/H2D is paid once per cascade instead of once per level.
# ---------------------------------------------------------------------------
def _lex_lt(al: jnp.ndarray, alen: jnp.ndarray,
            bl: jnp.ndarray, blen: jnp.ndarray) -> jnp.ndarray:
    """Row-wise (lanes..., length) lexicographic less-than over equal-shape
    batches — the SAME composite comparator the LSD sort kernels order by
    (lane 0 most significant, clamped length last).  Sentinel rows carry
    length 0xFFFFFFFF, above any real clamped length, so an all-FF real key
    still sorts before the pad tail."""
    res = alen < blen
    for i in range(al.shape[1] - 1, -1, -1):
        res = jnp.where(al[:, i] == bl[:, i], res, al[:, i] < bl[:, i])
    return res


def _rank_search(run_lanes: jnp.ndarray, run_lens: jnp.ndarray,
                 q_lanes: jnp.ndarray, q_lens: jnp.ndarray,
                 count_equal: bool) -> jnp.ndarray:
    """Vectorized binary search: rank of every query row in the sorted run.
    count_equal=False counts strictly-less rows, True counts less-or-equal
    (resolved at trace time — two compiled flavors).  O(m log n) total work
    versus the O((m+n) log(m+n)) comparator sort it replaces."""
    n = run_lanes.shape[0]
    m = q_lanes.shape[0]
    lo = jnp.zeros((m,), jnp.int32)
    hi = jnp.full((m,), n, jnp.int32)

    def body(_, carry):
        lo, hi = carry
        mid = (lo + hi) >> 1
        mid_l = jnp.take(run_lanes, mid, axis=0)
        mid_n = jnp.take(run_lens, mid, axis=0)
        if count_equal:   # run[mid] <= q  <=>  not (q < run[mid])
            before = ~_lex_lt(q_lanes, q_lens, mid_l, mid_n)
        else:             # run[mid] < q
            before = _lex_lt(mid_l, mid_n, q_lanes, q_lens)
        active = lo < hi
        lo = jnp.where(active & before, mid + 1, lo)
        hi = jnp.where(active & ~before, mid, hi)
        return lo, hi

    lo, _ = jax.lax.fori_loop(0, n.bit_length() + 1, body, (lo, hi))
    return lo


@functools.lru_cache(maxsize=1)
def _pallas_merge_ranks() -> bool:
    """Route rank computation through the Pallas flavor on TPU backends
    (same search body — pallas_kernels delegates to _rank_search)."""
    if os.environ.get("TEZ_TPU_DISABLE_PALLAS_MERGE"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001
        return False


def _rank_rows(run_lanes: jnp.ndarray, run_lens: jnp.ndarray,
               q_lanes: jnp.ndarray, q_lens: jnp.ndarray,
               count_equal: bool) -> jnp.ndarray:
    if _pallas_merge_ranks():
        from tez_tpu.ops.pallas_kernels import merge_rank_pallas
        return merge_rank_pallas(run_lanes, run_lens, q_lanes, q_lens,
                                 count_equal)
    return _rank_search(run_lanes, run_lens, q_lanes, q_lens, count_equal)


@jax.jit
def _merge_path_pair(a_lanes, a_lens, a_idx, b_lanes, b_lens, b_idx):
    """One O(na+nb) merge level: scatter both runs straight to their output
    positions.  Sentinel rows participate too — A-sentinel i lands at
    i + realB and B-sentinel j at j + na, so the scatter is a collision-free
    permutation with every real row in the prefix and the output again a
    sorted run (ladder levels compose without re-compacting)."""
    na, nb = a_lanes.shape[0], b_lanes.shape[0]
    ra = _rank_rows(b_lanes, b_lens, a_lanes, a_lens, count_equal=False)
    rb = _rank_rows(a_lanes, a_lens, b_lanes, b_lens, count_equal=True)
    pos_a = jnp.arange(na, dtype=jnp.int32) + ra
    pos_b = jnp.arange(nb, dtype=jnp.int32) + rb
    out_lanes = jnp.empty((na + nb, a_lanes.shape[1]), a_lanes.dtype)
    out_lanes = out_lanes.at[pos_a].set(a_lanes).at[pos_b].set(b_lanes)
    out_lens = jnp.empty((na + nb,), a_lens.dtype)
    out_lens = out_lens.at[pos_a].set(a_lens).at[pos_b].set(b_lens)
    out_idx = jnp.empty((na + nb,), a_idx.dtype)
    out_idx = out_idx.at[pos_a].set(a_idx).at[pos_b].set(b_idx)
    return out_lanes, out_lens, out_idx


@jax.jit
def _merge_path_prep(lanes, lens, base):
    """Per-run ladder prep: int32 lengths (-1 pad sentinel) -> u32 sort
    lengths (0xFFFFFFFF sentinel) + global bucket indices.  `base` is a
    dynamic argument so per-run offsets don't multiply compile keys."""
    sort_lens = jnp.where(lens < 0, jnp.uint32(0xFFFFFFFF),
                          lens.astype(jnp.uint32))
    idx = base + jnp.arange(lanes.shape[0], dtype=jnp.int32)
    return sort_lens, idx


def _merge_path_ladder(runs):
    """log2(k) ladder over (lanes, sort_lens, idx) triples: pair adjacent
    runs left-to-right (odd last carries up) so equal keys meet in run
    order at every level.  Returns the final idx column (the merge
    permutation over the bucketed concatenation); everything stays on
    device until the caller reads it back."""
    while len(runs) > 1:
        nxt = [_merge_path_pair(*runs[i], *runs[i + 1])
               for i in range(0, len(runs) - 1, 2)]
        if len(runs) % 2:
            nxt.append(runs[-1])
        runs = nxt
    return runs[0][2]


def _merge_path_resident(lanes_list, lens_list, common: int):
    runs = []
    for i, (sl, ln) in enumerate(zip(lanes_list, lens_list)):
        sort_lens, idx = _merge_path_prep(sl, ln, i * common)
        runs.append((sl, sort_lens, idx))
    return _merge_path_ladder(runs)


def merge_path_runs(parts_list: list[np.ndarray],
                    lanes_list: list[np.ndarray],
                    lengths_list: list[np.ndarray]) -> np.ndarray:
    """Generic (non-resident) k-way merge-path merge of pre-sorted runs.

    Each run is sorted by (partition, key lanes, clamped length); the
    partition id is prepended as the most-significant u32 lane so the
    composite comparator reproduces partition-major order.  Returns the
    merge permutation into the host concatenation of the runs (equal keys
    in run-arrival order).  Like sort_run, prefix-equal beyond-cap keys
    compare equal here and are resolved by the host tie-break pass."""
    counts = [l.shape[0] for l in lanes_list]
    live = [i for i, c in enumerate(counts) if c > 0]
    if not live:
        return np.zeros(0, dtype=np.int64)
    width = max(lanes_list[i].shape[1] for i in live)
    width_cap = width * 4 + 1
    common = _bucket(max(counts[i] for i in live))
    runs = []
    for j, i in enumerate(live):
        n = counts[i]
        comp = np.empty((common, width + 1), dtype=np.uint32)
        comp[:n, 0] = parts_list[i].astype(np.uint32)
        comp[:n, 1:1 + lanes_list[i].shape[1]] = lanes_list[i]
        comp[:n, 1 + lanes_list[i].shape[1]:] = 0
        comp[n:] = np.uint32(0xFFFFFFFF)
        lens = np.full(common, -1, dtype=np.int32)
        lens[:n] = np.minimum(lengths_list[i].astype(np.int64), width_cap)
        sort_lens, idx = _merge_path_prep(jnp.asarray(comp),
                                          jnp.asarray(lens), j * common)
        runs.append((jnp.asarray(comp), sort_lens, idx))
    perm = np.asarray(_merge_path_ladder(runs))
    mapped = _map_bucketed_perm(perm, [counts[i] for i in live], common)
    if len(live) != len(counts):   # re-offset into the FULL concatenation
        all_offsets = np.zeros(len(counts), dtype=np.int64)
        np.cumsum(counts[:-1], out=all_offsets[1:])
        live_offsets = np.zeros(len(live), dtype=np.int64)
        np.cumsum([counts[i] for i in live[:-1]], out=live_offsets[1:])
        run_id = np.searchsorted(live_offsets[1:], mapped, side="right")
        mapped = mapped - live_offsets[run_id] + all_offsets[np.asarray(live)[run_id]]
    return mapped


@functools.partial(jax.jit,
                   static_argnames=("num_partitions", "skip_length_pass"))
def _fused_hash_sort(key_mat: jnp.ndarray, hash_lengths: jnp.ndarray,
                     lanes: jnp.ndarray, sort_lengths: jnp.ndarray,
                     num_partitions: int,
                     skip_length_pass: bool = False
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One dispatch: full-key FNV hash-partition + LSD sort.  Fusing all
    passes into a single XLA program matters on TPU: per-dispatch latency
    (host<->device round trips) would otherwise dominate small spans."""
    partitions = _hash_to_partitions(key_mat, hash_lengths, num_partitions)
    return _lsd_passes(partitions, lanes, sort_lengths, skip_length_pass)


@functools.partial(jax.jit, static_argnames=("skip_length_pass",))
def _fused_sort(partitions: jnp.ndarray, lanes: jnp.ndarray,
                lengths: jnp.ndarray, skip_length_pass: bool = False
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    return _lsd_passes(partitions, lanes, lengths, skip_length_pass)


def hash_sort_span(key_mat: np.ndarray, hash_lengths: np.ndarray,
                   lanes: np.ndarray, lengths: np.ndarray,
                   num_partitions: int) -> tuple[np.ndarray, np.ndarray]:
    """Fused span kernel: hash-partition + stable (partition, key) sort in a
    single device dispatch.  Returns (sorted partitions, permutation)."""
    n = key_mat.shape[0]
    if n == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    width_cap = lanes.shape[1] * 4 + 1
    slen = np.minimum(lengths.astype(np.int64), width_cap)
    # uniform clamped lengths over REAL rows: the length pass would be an
    # identity reorder — skip a full sort pass.  Pad rows are irrelevant to
    # every pass but the final partition one (which sweeps them to the tail
    # as a block), so they are padded with the same uniform value.
    uniform, pad_len = uniform_clamped_lengths(slen, width_cap)
    nb = _bucket(n)
    hash_lengths = hash_lengths.astype(np.int32)
    if nb != n:
        pad = nb - n
        key_mat = np.pad(key_mat, ((0, pad), (0, 0)), constant_values=255)
        hash_lengths = np.pad(hash_lengths, (0, pad), constant_values=-1)
        lanes = np.pad(lanes, ((0, pad), (0, 0)),
                       constant_values=np.uint32(0xFFFFFFFF))
        slen = np.pad(slen, (0, pad), constant_values=pad_len)
    sp, perm = _fused_hash_sort(jnp.asarray(key_mat),
                                jnp.asarray(hash_lengths),
                                jnp.asarray(lanes),
                                jnp.asarray(slen.astype(np.uint32)),
                                num_partitions,
                                skip_length_pass=uniform)
    sp = np.asarray(sp)
    perm = np.asarray(perm)
    if nb != n:
        keep = perm < n
        sp, perm = sp[keep], perm[keep]
    return sp, perm


def sort_run(partitions: np.ndarray, lanes: np.ndarray,
             lengths: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """LSD radix sort by (partition, key lanes, clamped length): stable
    single-key u32 passes from least- to most-significant key, fused into
    the one compiled `_fused_sort` program (variadic N-operand `lax.sort`
    costs minutes of XLA compile time at large N on TPU; chained single-key
    sorts compile in seconds).

    The clamped length disambiguates keys whose zero padding collides (if
    padded prefixes are equal, the longer key == shorter key + trailing
    zeros, so byte order == length order); beyond-prefix lengths compare
    equal and are resolved by the host tie-break pass.

    Returns (sorted partition ids, permutation); padding rows (partition
    = MAX) sort to the tail and are stripped by the caller.
    """
    n = partitions.shape[0]
    if n == 0:
        return partitions, np.zeros(0, dtype=np.int32)
    width_cap = lanes.shape[1] * 4 + 1
    lengths = np.minimum(lengths.astype(np.int64), width_cap)
    nb = _bucket(n)
    if nb != n:
        partitions = np.pad(partitions, (0, nb - n),
                            constant_values=np.iinfo(np.int32).max)
        lanes = np.pad(lanes, ((0, nb - n), (0, 0)))
        lengths = np.pad(lengths, (0, nb - n))
    sorted_parts, perm = _fused_sort(jnp.asarray(partitions),
                                     jnp.asarray(lanes),
                                     jnp.asarray(lengths.astype(np.uint32)))
    return (np.asarray(sorted_parts)[:n], np.asarray(perm)[:n])


# ---------------------------------------------------------------------------
# merge of sorted runs = sort of concatenation (stable; run order preserved)
# ---------------------------------------------------------------------------
def merge_runs(lanes_list: list[np.ndarray],
               lengths_list: list[np.ndarray]) -> np.ndarray:
    """k-way merge of sorted key-lane arrays -> global permutation into the
    concatenation.  Stability keeps equal keys in run order (TezMerger
    segment-queue semantics)."""
    if not lanes_list:
        return np.zeros(0, dtype=np.int32)
    lanes = np.concatenate(lanes_list, axis=0)
    lengths = np.concatenate(lengths_list, axis=0)
    zeros = np.zeros(lanes.shape[0], dtype=np.int32)
    _, perm = sort_run(zeros, lanes, lengths)
    return perm


# ---------------------------------------------------------------------------
# segmented (per-partition) counts
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("num_partitions",))
def _partition_histogram(partitions: jnp.ndarray,
                         num_partitions: int) -> jnp.ndarray:
    one_hot = jax.nn.one_hot(partitions, num_partitions, dtype=jnp.int32)
    return one_hot.sum(axis=0)


def partition_counts(partitions: np.ndarray, num_partitions: int) -> np.ndarray:
    n = partitions.shape[0]
    if n == 0:
        return np.zeros(num_partitions, dtype=np.int64)
    nb = _bucket(n)
    if nb != n:
        partitions = np.pad(partitions, (0, nb - n), constant_values=-1)
    out = _partition_histogram(jnp.asarray(partitions), num_partitions)
    return np.asarray(out).astype(np.int64)
