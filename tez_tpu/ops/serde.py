"""Key/value serialization.

Reference parity: tez-runtime-library/.../common/serializer/ (pluggable Hadoop
serialization) — here a small registry of codecs turning Python objects into
bytes for the device data plane.  The data plane itself only ever sees bytes;
serdes sit at the Writer/Reader surface.
"""
from __future__ import annotations

import pickle
import struct
from typing import Any


class Serde:
    name = "abstract"

    def to_bytes(self, obj: Any) -> bytes:
        raise NotImplementedError

    def from_bytes(self, data: bytes) -> Any:
        raise NotImplementedError


class BytesSerde(Serde):
    name = "bytes"

    def to_bytes(self, obj: Any) -> bytes:
        if isinstance(obj, bytes):
            return obj
        if isinstance(obj, bytearray):
            return bytes(obj)
        if isinstance(obj, str):
            return obj.encode()
        raise TypeError(f"BytesSerde cannot encode {type(obj)}")

    def from_bytes(self, data: bytes) -> bytes:
        return data


class TextSerde(Serde):
    name = "text"

    def to_bytes(self, obj: Any) -> bytes:
        return obj.encode() if isinstance(obj, str) else bytes(obj)

    def from_bytes(self, data: bytes) -> str:
        return data.decode()


class VarLongSerde(Serde):
    """8-byte big-endian signed (big-endian so byte order == numeric order,
    which lets longs be used as sort keys directly)."""
    name = "long"

    def to_bytes(self, obj: Any) -> bytes:
        # flip sign bit so negative numbers sort below positive byte-wise
        return struct.pack(">Q", (int(obj) + (1 << 63)) & ((1 << 64) - 1))

    def from_bytes(self, data: bytes) -> int:
        return struct.unpack(">Q", data)[0] - (1 << 63)


def encode_longs_be(values: "np.ndarray") -> "np.ndarray":
    """Vectorized VarLongSerde.to_bytes: int64 array -> uint8 array of
    8-byte big-endian sign-flipped encodings (byte order == numeric order)."""
    import numpy as np
    enc = (values.astype(np.int64).view(np.uint64)
           ^ np.uint64(1 << 63)).astype(">u8")
    return np.frombuffer(enc.tobytes(), dtype=np.uint8).copy()


def decode_longs_be(val_bytes: "np.ndarray", n: int) -> "np.ndarray":
    """Vectorized VarLongSerde.from_bytes over n fixed-8-byte values."""
    import numpy as np
    u = np.ascontiguousarray(val_bytes).reshape(n, 8)
    return (u.view(">u8").astype(np.uint64).ravel()
            ^ np.uint64(1 << 63)).view(np.int64)


class PickleSerde(Serde):
    name = "pickle"

    def to_bytes(self, obj: Any) -> bytes:
        return pickle.dumps(obj, protocol=4)

    def from_bytes(self, data: bytes) -> Any:
        return pickle.loads(data)


_SERDES = {s.name: s for s in
           (BytesSerde(), TextSerde(), VarLongSerde(), PickleSerde())}


def get_serde(name: str) -> Serde:
    try:
        return _SERDES[name]
    except KeyError:
        raise ValueError(f"unknown serde {name!r}; have {sorted(_SERDES)}")
