"""Pallas TPU kernels for the data plane's hot byte-level ops.

The LSD sort rides XLA's native `lax.sort` (already optimal); the remaining
hot op with awkward XLA lowering is the per-row FNV-1a hash over key bytes —
a `fori_loop` of masked u32 multiplies that XLA materializes as W sequential
HLO ops over the full column.  The Pallas version tiles rows into VMEM and
keeps the hash accumulator in registers across the byte loop (unrolled at
trace time, W is static).

Enabled via tez.runtime.tpu.pallas.hash (default off until profiled on the
target chip); CPU tests run the same kernel in interpret mode.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

ROW_BLOCK = 1024


def _fnv_kernel(key_ref, len_ref, out_ref):
    """One grid step: hash ROW_BLOCK rows of a u32-cast byte matrix.

    Delegates to device._fnv_rows — the ONE hash body shared by every kernel
    — so the Pallas partitioner can never diverge from the host partitioner."""
    from tez_tpu.ops.device import _fnv_rows
    out_ref[:] = _fnv_rows(key_ref[:], len_ref[:])


@functools.partial(jax.jit, static_argnames=("interpret",))
def fnv_hash_pallas(key_mat_u32: jnp.ndarray, lengths: jnp.ndarray,
                    interpret: bool = False) -> jnp.ndarray:
    """Row-wise FNV-1a over key bytes.

    key_mat_u32: uint32[N, W] (bytes pre-cast to u32; N multiple of
    ROW_BLOCK — callers pad), lengths: int32[N].  Returns uint32[N].
    """
    from jax.experimental import pallas as pl

    n, w = key_mat_u32.shape
    grid = (n // ROW_BLOCK,)
    return pl.pallas_call(
        _fnv_kernel,
        out_shape=jax.ShapeDtypeStruct((n,), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_BLOCK, w), lambda i: (i, 0)),
            pl.BlockSpec((ROW_BLOCK,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((ROW_BLOCK,), lambda i: (i,)),
        interpret=interpret,
    )(key_mat_u32, lengths)


MERGE_ROW_BLOCK = 256


def _merge_rank_kernel(run_lanes_ref, run_lens_ref, q_lanes_ref, q_lens_ref,
                       out_ref, *, count_equal):
    """One grid step: rank MERGE_ROW_BLOCK query rows in the full sorted
    run (the run is replicated to every step — it is the binary-search
    haystack, not tileable without a second-level search).

    Delegates to device._rank_search — the ONE comparator+search body shared
    with the XLA merge-path kernel — so the Pallas flavor can never diverge
    from the fallback ordering."""
    from tez_tpu.ops.device import _rank_search
    out_ref[:] = _rank_search(run_lanes_ref[:], run_lens_ref[:],
                              q_lanes_ref[:], q_lens_ref[:], count_equal)


@functools.partial(jax.jit, static_argnames=("count_equal", "interpret"))
def merge_rank_pallas(run_lanes: jnp.ndarray, run_lens: jnp.ndarray,
                      q_lanes: jnp.ndarray, q_lens: jnp.ndarray,
                      count_equal: bool = False,
                      interpret: bool = False) -> jnp.ndarray:
    """Rank of every query row in a sorted run (merge-path cross-rank).

    run_lanes: uint32[N, W] sorted with run_lens: uint32[N]; q_lanes:
    uint32[M, W] with q_lens: uint32[M].  M and N are power-of-two bucket
    sizes (device._bucket), so MERGE_ROW_BLOCK | M when M >= 256; smaller
    query blocks fall through to the XLA search directly.  Returns int32[M].
    """
    from jax.experimental import pallas as pl
    from tez_tpu.ops.device import _rank_search

    m, w = q_lanes.shape
    if m < MERGE_ROW_BLOCK or m % MERGE_ROW_BLOCK:
        return _rank_search(run_lanes, run_lens, q_lanes, q_lens, count_equal)
    n = run_lanes.shape[0]
    grid = (m // MERGE_ROW_BLOCK,)
    kernel = functools.partial(_merge_rank_kernel, count_equal=count_equal)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m,), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((n, w), lambda i: (0, 0)),
            pl.BlockSpec((n,), lambda i: (0,)),
            pl.BlockSpec((MERGE_ROW_BLOCK, w), lambda i: (i, 0)),
            pl.BlockSpec((MERGE_ROW_BLOCK,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((MERGE_ROW_BLOCK,), lambda i: (i,)),
        interpret=interpret,
    )(run_lanes, run_lens, q_lanes, q_lens)


def hash_partition_pallas(key_mat: np.ndarray, lengths: np.ndarray,
                          num_partitions: int,
                          interpret: bool = False) -> np.ndarray:
    """Drop-in twin of device.hash_partition backed by the Pallas kernel."""
    n = key_mat.shape[0]
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    pad = (-n) % ROW_BLOCK
    mat = np.pad(key_mat, ((0, pad), (0, 0))) if pad else key_mat
    lens = np.pad(lengths, (0, pad)) if pad else lengths
    h = fnv_hash_pallas(jnp.asarray(mat, dtype=jnp.uint32),
                        jnp.asarray(lens, dtype=jnp.int32),
                        interpret=interpret)
    return (np.asarray(h)[:n] % num_partitions).astype(np.int32)
