"""Pallas TPU kernels for the data plane's hot byte-level ops.

The LSD sort rides XLA's native `lax.sort` (already optimal); the remaining
hot op with awkward XLA lowering is the per-row FNV-1a hash over key bytes —
a `fori_loop` of masked u32 multiplies that XLA materializes as W sequential
HLO ops over the full column.  The Pallas version tiles rows into VMEM and
keeps the hash accumulator in registers across the byte loop (unrolled at
trace time, W is static).

Enabled via tez.runtime.tpu.pallas.hash (default off until profiled on the
target chip); CPU tests run the same kernel in interpret mode.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

ROW_BLOCK = 1024


def _fnv_kernel(key_ref, len_ref, out_ref):
    """One grid step: hash ROW_BLOCK rows of a u32-cast byte matrix.

    Delegates to device._fnv_rows — the ONE hash body shared by every kernel
    — so the Pallas partitioner can never diverge from the host partitioner."""
    from tez_tpu.ops.device import _fnv_rows
    out_ref[:] = _fnv_rows(key_ref[:], len_ref[:])


@functools.partial(jax.jit, static_argnames=("interpret",))
def fnv_hash_pallas(key_mat_u32: jnp.ndarray, lengths: jnp.ndarray,
                    interpret: bool = False) -> jnp.ndarray:
    """Row-wise FNV-1a over key bytes.

    key_mat_u32: uint32[N, W] (bytes pre-cast to u32; N multiple of
    ROW_BLOCK — callers pad), lengths: int32[N].  Returns uint32[N].
    """
    from jax.experimental import pallas as pl

    n, w = key_mat_u32.shape
    grid = (n // ROW_BLOCK,)
    return pl.pallas_call(
        _fnv_kernel,
        out_shape=jax.ShapeDtypeStruct((n,), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_BLOCK, w), lambda i: (i, 0)),
            pl.BlockSpec((ROW_BLOCK,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((ROW_BLOCK,), lambda i: (i,)),
        interpret=interpret,
    )(key_mat_u32, lengths)


def hash_partition_pallas(key_mat: np.ndarray, lengths: np.ndarray,
                          num_partitions: int,
                          interpret: bool = False) -> np.ndarray:
    """Drop-in twin of device.hash_partition backed by the Pallas kernel."""
    n = key_mat.shape[0]
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    pad = (-n) % ROW_BLOCK
    mat = np.pad(key_mat, ((0, pad), (0, 0))) if pad else key_mat
    lens = np.pad(lengths, (0, pad)) if pad else lengths
    h = fnv_hash_pallas(jnp.asarray(mat, dtype=jnp.uint32),
                        jnp.asarray(lens, dtype=jnp.int32),
                        interpret=interpret)
    return (np.asarray(h)[:n] % num_partitions).astype(np.int32)
