"""ctypes bindings for the native host ops (native/ragged.cpp).

Auto-builds `native/libtezhost.so` with g++ on first use (cached); every
caller has a numpy fallback, so a missing toolchain degrades gracefully.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libtezhost.so")

_lib: "ctypes.CDLL | None | bool" = None   # None=untried, False=unavailable
_lock = threading.Lock()

#: Below this many bytes the thread spawn outweighs the copy.
MIN_NATIVE_BYTES = 1 << 20


def _load() -> "ctypes.CDLL | None":
    global _lib
    if _lib is False:
        return None
    if _lib is not None:
        return _lib
    with _lock:
        if _lib not in (None,):
            return _lib if _lib is not False else None
        try:
            # make is a no-op when current and rebuilds a stale .so after a
            # source change (the .so is newer-than-sources checked)
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "-s"],
                               check=True, capture_output=True, timeout=120)
            except Exception:  # noqa: BLE001 — no toolchain: use stale .so
                if not os.path.exists(_SO_PATH):
                    raise
            lib = ctypes.CDLL(_SO_PATH)
            lib.gather_ragged_u8.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int32]
            lib.gather_ragged_u8.restype = None
            if hasattr(lib, "adjacent_equal_u8"):
                # a stale prebuilt .so (no toolchain to rebuild) may lack
                # the newer symbol; only that feature degrades, not the lib
                lib.adjacent_equal_u8.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32]
                lib.adjacent_equal_u8.restype = None
            _lib = lib
            log.info("native host ops loaded from %s", _SO_PATH)
        except Exception as e:  # noqa: BLE001 — toolchain may be absent
            log.warning("native host ops unavailable (%s); numpy fallback",
                        e)
            _lib = False
            return None
        return _lib


def native_available() -> bool:
    return _load() is not None


def gather_ragged_native(data: np.ndarray, offsets: np.ndarray,
                         perm: np.ndarray
                         ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Multithreaded ragged permute; returns None when the native lib is
    unavailable (caller falls back to numpy)."""
    lib = _load()
    if lib is None:
        return None
    n_out = len(perm)
    lengths = offsets[1:] - offsets[:-1]
    out_offsets = np.zeros(n_out + 1, dtype=np.int64)
    np.cumsum(lengths[perm], out=out_offsets[1:])
    out = np.empty(int(out_offsets[-1]), dtype=np.uint8)
    data = np.ascontiguousarray(data)
    offsets = np.ascontiguousarray(offsets.astype(np.int64))
    perm64 = np.ascontiguousarray(perm.astype(np.int64))
    threads = min(8, os.cpu_count() or 1)
    lib.gather_ragged_u8(
        data.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        perm64.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n_out),
        out_offsets.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(threads))
    return out, out_offsets


def adjacent_equal_native(data: np.ndarray, offsets: np.ndarray,
                          cand: np.ndarray) -> Optional[np.ndarray]:
    """Threaded per-pair memcmp for adjacent-row equality; None when the
    native lib is unavailable (caller falls back to numpy)."""
    lib = _load()
    if lib is None or not hasattr(lib, "adjacent_equal_u8"):
        return None
    data = np.ascontiguousarray(data)
    offsets = np.ascontiguousarray(offsets.astype(np.int64))
    cand64 = np.ascontiguousarray(cand.astype(np.int64))
    out = np.empty(len(cand64), dtype=np.uint8)
    lib.adjacent_equal_u8(
        data.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        cand64.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(len(cand64)),
        out.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(min(8, os.cpu_count() or 1)))
    return out.astype(bool)
