"""ctypes bindings for the native host ops (tez_tpu/native/ragged.cpp).

Auto-builds `libtezhost.so` with g++ on first use (cached); every
caller has a numpy fallback, so a missing toolchain degrades gracefully.

The native sources ship INSIDE the package (`tez_tpu/native/`) so pip
installs get them; when the install dir is read-only (site-packages), the
build happens in a per-user cache dir instead (`TEZ_TPU_CACHE_DIR` or
`~/.cache/tez_tpu`).
"""
from __future__ import annotations

import ctypes
import logging
import os
import shutil
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "native")
_SOURCES = ("ragged.cpp", "spansort.cpp", "shuffle_server.cpp",
            "baseline_proxy.cpp", "Makefile")


def _build_dir() -> str:
    """Where to run make: the package dir when writable, else a user cache
    keyed by version (read-only site-packages installs)."""
    if os.access(_NATIVE_DIR, os.W_OK):
        return _NATIVE_DIR
    from tez_tpu.version import __version__
    cache_root = os.environ.get("TEZ_TPU_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "tez_tpu")
    bdir = os.path.join(cache_root, f"native-{__version__}")
    os.makedirs(bdir, exist_ok=True)
    for fname in _SOURCES:
        src = os.path.join(_NATIVE_DIR, fname)
        dst = os.path.join(bdir, fname)
        if os.path.exists(src) and (
                not os.path.exists(dst)
                or os.path.getmtime(dst) < os.path.getmtime(src)):
            # temp + rename: a concurrent builder's `make` must never see
            # a half-copied source (the Makefile already renames the .so)
            tmp = f"{dst}.{os.getpid()}.tmp"
            shutil.copy2(src, tmp)
            os.replace(tmp, dst)
    return bdir

_lib: "ctypes.CDLL | None | bool" = None   # None=untried, False=unavailable
_lock = threading.Lock()

#: Below this many bytes the thread spawn outweighs the copy.
MIN_NATIVE_BYTES = 1 << 20


def _load() -> "ctypes.CDLL | None":
    global _lib
    if _lib is False:
        return None
    if _lib is not None:
        return _lib
    with _lock:
        if _lib not in (None,):
            return _lib if _lib is not False else None
        try:
            bdir = _build_dir()
            so_path = os.path.join(bdir, "libtezhost.so")
            # make is a no-op when current and rebuilds a stale .so after a
            # source change (the .so is newer-than-sources checked)
            try:
                subprocess.run(["make", "-C", bdir, "-s"],
                               check=True, capture_output=True, timeout=120)
            except Exception:  # noqa: BLE001 — no toolchain: use stale .so
                prebuilt = os.path.join(_NATIVE_DIR, "libtezhost.so")
                if os.path.exists(so_path):
                    pass
                elif os.path.exists(prebuilt):
                    so_path = prebuilt
                else:
                    raise
            lib = ctypes.CDLL(so_path)
            lib.gather_ragged_u8.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int32]
            lib.gather_ragged_u8.restype = None
            if hasattr(lib, "adjacent_equal_u8"):
                # a stale prebuilt .so (no toolchain to rebuild) may lack
                # the newer symbol; only that feature degrades, not the lib
                lib.adjacent_equal_u8.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32]
                lib.adjacent_equal_u8.restype = None
            if hasattr(lib, "tz_wc_create"):
                lib.tz_wc_create.argtypes = []
                lib.tz_wc_create.restype = ctypes.c_void_p
                lib.tz_wc_feed.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                           ctypes.c_int64]
                lib.tz_wc_feed.restype = None
                lib.tz_wc_stats.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                            ctypes.c_void_p]
                lib.tz_wc_stats.restype = None
                lib.tz_wc_emit.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                           ctypes.c_void_p, ctypes.c_void_p]
                lib.tz_wc_emit.restype = None
                lib.tz_wc_destroy.argtypes = [ctypes.c_void_p]
                lib.tz_wc_destroy.restype = None
                lib.hash_sum_i64.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
                lib.hash_sum_i64.restype = ctypes.c_int64
            if hasattr(lib, "tz_split_ws"):
                lib.tz_split_ws.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                    ctypes.c_void_p]
                lib.tz_split_ws.restype = ctypes.c_int64
            if hasattr(lib, "tz_sort_partition_keys"):
                lib.tz_fnv32_partition.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                    ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32]
                lib.tz_fnv32_partition.restype = None
                lib.tz_sort_partition_keys.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32]
                lib.tz_sort_partition_keys.restype = None
            if hasattr(lib, "tz_merge_runs"):
                lib.tz_merge_runs.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
                    ctypes.c_int32]
                lib.tz_merge_runs.restype = None
            if hasattr(lib, "gather_fixed_u8"):
                lib.gather_fixed_u8.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                    ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32]
                lib.gather_fixed_u8.restype = None
            if hasattr(lib, "tz_span_sort_emit"):
                lib.tz_span_sort_emit.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                    ctypes.c_void_p, ctypes.c_int32,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_int32]
                lib.tz_span_sort_emit.restype = ctypes.c_int32
            if hasattr(lib, "tz_merge_emit"):
                lib.tz_merge_emit.argtypes = [
                    ctypes.c_int32,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_int32,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_int32]
                lib.tz_merge_emit.restype = ctypes.c_int32
            if hasattr(lib, "pipelined_sorter_proxy"):
                lib.pipelined_sorter_proxy.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                    ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
                    ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p]
                lib.pipelined_sorter_proxy.restype = ctypes.c_double
            if hasattr(lib, "owc_proxy_v2"):
                # _v2: the combine arg changed the C ABI — a stale prebuilt
                # .so (no-toolchain fallback) must fail the hasattr gate,
                # never be called with the new signature
                lib.owc_proxy_v2.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                    ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p,
                    ctypes.c_int64, ctypes.c_void_p]
                lib.owc_proxy_v2.restype = ctypes.c_double
            _lib = lib
            log.info("native host ops loaded from %s", so_path)
        except Exception as e:  # noqa: BLE001 — toolchain may be absent
            log.warning("native host ops unavailable (%s); numpy fallback",
                        e)
            _lib = False
            return None
        return _lib


def native_available() -> bool:
    return _load() is not None


def gather_ragged_native(data: np.ndarray, offsets: np.ndarray,
                         perm: np.ndarray
                         ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Multithreaded ragged permute; returns None when the native lib is
    unavailable (caller falls back to numpy)."""
    lib = _load()
    if lib is None:
        return None
    n_out = len(perm)
    lengths = offsets[1:] - offsets[:-1]
    out_offsets = np.zeros(n_out + 1, dtype=np.int64)
    np.cumsum(lengths[perm], out=out_offsets[1:])
    out = np.empty(int(out_offsets[-1]), dtype=np.uint8)
    data = np.ascontiguousarray(data)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    perm64 = np.ascontiguousarray(perm, dtype=np.int64)
    threads = min(8, os.cpu_count() or 1)
    lib.gather_ragged_u8(
        data.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        perm64.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n_out),
        out_offsets.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(threads))
    return out, out_offsets


def gather_fixed_native(data: np.ndarray, row_len: int, perm: np.ndarray
                        ) -> Optional[np.ndarray]:
    """Permute fixed-width rows: out[i] = data[perm[i]*row_len:+row_len].
    Skips the per-row offset lookups of the ragged gather (compile-time
    copy sizes for the common serde widths).  None when unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "gather_fixed_u8"):
        return None
    n = len(perm)
    out = np.empty(n * row_len, dtype=np.uint8)
    data = np.ascontiguousarray(data)
    perm64 = np.ascontiguousarray(perm, dtype=np.int64)
    lib.gather_fixed_u8(
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(row_len),
        perm64.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(min(8, os.cpu_count() or 1)))
    return out


def span_sort_emit_native(key_bytes: np.ndarray, key_offsets: np.ndarray,
                          val_bytes: np.ndarray, val_offsets: np.ndarray,
                          num_partitions: int,
                          partitions: Optional[np.ndarray],
                          compute_hash: bool
                          ) -> "Optional[tuple]":
    """Fused producer span sort: partition (optionally fnv32 in C) + stable
    (partition, key) sort + direct materialization of the sorted batch.
    Returns (out_kb, out_ko, out_vb, out_vo, row_index) or None when the
    native lib / symbol is unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "tz_span_sort_emit"):
        return None
    n = len(key_offsets) - 1
    key_bytes = np.ascontiguousarray(key_bytes)
    key_offsets = np.ascontiguousarray(key_offsets, dtype=np.int64)
    val_bytes = np.ascontiguousarray(val_bytes)
    val_offsets = np.ascontiguousarray(val_offsets, dtype=np.int64)
    parts_ptr = None
    if partitions is not None:
        partitions = np.ascontiguousarray(partitions, dtype=np.int32)
        parts_ptr = partitions.ctypes.data_as(ctypes.c_void_p)
    out_kb = np.empty(int(key_offsets[-1]), dtype=np.uint8)
    out_ko = np.empty(n + 1, dtype=np.int64)
    out_vb = np.empty(int(val_offsets[-1]), dtype=np.uint8)
    out_vo = np.empty(n + 1, dtype=np.int64)
    part_counts = np.empty(num_partitions, dtype=np.int64)
    rc = lib.tz_span_sort_emit(
        key_bytes.ctypes.data_as(ctypes.c_void_p),
        key_offsets.ctypes.data_as(ctypes.c_void_p),
        val_bytes.ctypes.data_as(ctypes.c_void_p),
        val_offsets.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n), ctypes.c_int32(num_partitions), parts_ptr,
        ctypes.c_int32(1 if compute_hash else 0),
        out_kb.ctypes.data_as(ctypes.c_void_p),
        out_ko.ctypes.data_as(ctypes.c_void_p),
        out_vb.ctypes.data_as(ctypes.c_void_p),
        out_vo.ctypes.data_as(ctypes.c_void_p),
        None,   # out_parts: derivable from row_index, nobody consumes it
        part_counts.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(min(8, os.cpu_count() or 1)))
    if rc != 0:
        return None
    row_index = np.zeros(num_partitions + 1, dtype=np.int64)
    np.cumsum(part_counts, out=row_index[1:])
    return out_kb, out_ko, out_vb, out_vo, row_index


def merge_emit_native(runs: "list", num_partitions: int
                      ) -> "Optional[tuple]":
    """Fused k-run merge: group-scan each (partition, key)-sorted run,
    k-way merge group heads, emit contiguous segment copies (no concat, no
    row gather).  `runs` is a list of (key_bytes, key_offsets, val_bytes,
    val_offsets, row_index) tuples.  Returns (out_kb, out_ko, out_vb,
    out_vo, row_index) or None when unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "tz_merge_emit"):
        return None
    k = len(runs)
    holders = []   # keep contiguous arrays alive across the call
    kb_ptrs = (ctypes.c_void_p * k)()
    ko_ptrs = (ctypes.c_void_p * k)()
    vb_ptrs = (ctypes.c_void_p * k)()
    vo_ptrs = (ctypes.c_void_p * k)()
    ri_ptrs = (ctypes.c_void_p * k)()
    nrows = np.empty(k, dtype=np.int64)
    total_rows = total_kb = total_vb = 0
    for i, (kb, ko, vb, vo, ri) in enumerate(runs):
        kb = np.ascontiguousarray(kb)
        ko = np.ascontiguousarray(ko, dtype=np.int64)
        vb = np.ascontiguousarray(vb)
        vo = np.ascontiguousarray(vo, dtype=np.int64)
        ri = np.ascontiguousarray(ri, dtype=np.int64)
        holders.extend((kb, ko, vb, vo, ri))
        kb_ptrs[i] = kb.ctypes.data
        ko_ptrs[i] = ko.ctypes.data
        vb_ptrs[i] = vb.ctypes.data
        vo_ptrs[i] = vo.ctypes.data
        ri_ptrs[i] = ri.ctypes.data
        n = len(ko) - 1
        nrows[i] = n
        total_rows += n
        total_kb += int(ko[-1])
        total_vb += int(vo[-1])
    out_kb = np.empty(total_kb, dtype=np.uint8)
    out_ko = np.empty(total_rows + 1, dtype=np.int64)
    out_vb = np.empty(total_vb, dtype=np.uint8)
    out_vo = np.empty(total_rows + 1, dtype=np.int64)
    part_counts = np.empty(num_partitions, dtype=np.int64)
    rc = lib.tz_merge_emit(
        ctypes.c_int32(k), kb_ptrs, ko_ptrs, vb_ptrs, vo_ptrs,
        nrows.ctypes.data_as(ctypes.c_void_p), ri_ptrs,
        ctypes.c_int32(num_partitions),
        out_kb.ctypes.data_as(ctypes.c_void_p),
        out_ko.ctypes.data_as(ctypes.c_void_p),
        out_vb.ctypes.data_as(ctypes.c_void_p),
        out_vo.ctypes.data_as(ctypes.c_void_p),
        None,   # out_parts: derivable from row_index, nobody consumes it
        part_counts.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(min(8, os.cpu_count() or 1)))
    del holders
    if rc != 0:
        return None
    row_index = np.zeros(num_partitions + 1, dtype=np.int64)
    np.cumsum(part_counts, out=row_index[1:])
    return out_kb, out_ko, out_vb, out_vo, row_index


class WordCountAggregator:
    """Fused tokenize + hash-count over byte chunks (native); None-pattern:
    use `create()` and fall back to a numpy tokenizer when it returns None.

    Each `feed()` must be whitespace-complete (line-aligned chunks from the
    text reader), so tokens never span feed boundaries.
    """

    def __init__(self, lib: "ctypes.CDLL"):
        self._lib = lib
        self._h = lib.tz_wc_create()

    @staticmethod
    def create() -> "WordCountAggregator | None":
        lib = _load()
        if lib is None or not hasattr(lib, "tz_wc_create"):
            return None
        return WordCountAggregator(lib)

    def feed(self, chunk: bytes) -> None:
        self._lib.tz_wc_feed(self._h, chunk, len(chunk))

    def emit(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """-> (key_bytes, key_offsets, counts) in first-occurrence order."""
        n_unique = ctypes.c_int64()
        total = ctypes.c_int64()
        self._lib.tz_wc_stats(self._h, ctypes.byref(n_unique),
                              ctypes.byref(total))
        n, tot = n_unique.value, total.value
        key_bytes = np.empty(tot, dtype=np.uint8)
        key_offsets = np.empty(n + 1, dtype=np.int64)
        counts = np.empty(n, dtype=np.int64)
        if n:
            self._lib.tz_wc_emit(
                self._h, key_bytes.ctypes.data_as(ctypes.c_void_p),
                key_offsets.ctypes.data_as(ctypes.c_void_p),
                counts.ctypes.data_as(ctypes.c_void_p))
        else:
            key_offsets[0] = 0
        return key_bytes, key_offsets, counts

    def close(self) -> None:
        if self._h:
            self._lib.tz_wc_destroy(self._h)
            self._h = None

    def __del__(self):  # noqa: D105 — belt-and-braces native cleanup
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def hash_sum_native(key_bytes: np.ndarray, key_offsets: np.ndarray,
                    values: np.ndarray
                    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Sum int64 `values` of equal keys (first-occurrence order): returns
    (first_idx, sums) or None when the native lib is unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "hash_sum_i64"):
        return None
    n = len(values)
    key_bytes = np.ascontiguousarray(key_bytes)
    key_offsets = np.ascontiguousarray(key_offsets, dtype=np.int64)
    values = np.ascontiguousarray(values, dtype=np.int64)
    first_idx = np.empty(n, dtype=np.int64)
    sums = np.empty(n, dtype=np.int64)
    n_unique = lib.hash_sum_i64(
        key_bytes.ctypes.data_as(ctypes.c_void_p),
        key_offsets.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n),
        values.ctypes.data_as(ctypes.c_void_p),
        first_idx.ctypes.data_as(ctypes.c_void_p),
        sums.ctypes.data_as(ctypes.c_void_p))
    return first_idx[:n_unique].copy(), sums[:n_unique].copy()


def pipelined_sorter_proxy(keys: np.ndarray, vals: np.ndarray,
                           num_producers: int, num_partitions: int
                           ) -> "Optional[Tuple[float, np.ndarray, np.ndarray, np.ndarray]]":
    """Run the PipelinedSorter/TezMerger-semantics C++ baseline proxy
    (native/baseline_proxy.cpp; see BASELINE.md) over fixed-width records.

    keys: (n, key_len) u8; vals: (n, val_len) u8.  Returns (wall_seconds,
    merged_keys, merged_vals, per_partition_counts) or None when the
    native lib is unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "pipelined_sorter_proxy"):
        return None
    n, key_len = keys.shape
    val_len = vals.shape[1] if vals.size else 0
    keys = np.ascontiguousarray(keys)
    vals = np.ascontiguousarray(vals)
    out_keys = np.empty_like(keys)
    out_vals = np.empty_like(vals)
    counts = np.zeros(num_partitions, dtype=np.int64)
    secs = lib.pipelined_sorter_proxy(
        keys.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(key_len),
        vals.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(val_len),
        ctypes.c_int64(n), ctypes.c_int32(num_producers),
        ctypes.c_int32(num_partitions),
        out_keys.ctypes.data_as(ctypes.c_void_p),
        out_vals.ctypes.data_as(ctypes.c_void_p),
        counts.ctypes.data_as(ctypes.c_void_p))
    return float(secs), out_keys, out_vals, counts


def split_ws_native(chunk: bytes) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """One-pass whitespace split of a text chunk into compacted ragged
    (word_bytes, word_offsets); None when the native lib is unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "tz_split_ws"):
        return None
    n = len(chunk)
    out_bytes = np.empty(n, dtype=np.uint8)
    out_offsets = np.empty((n + 1) // 2 + 2, dtype=np.int64)
    words = lib.tz_split_ws(chunk, ctypes.c_int64(n),
                            out_bytes.ctypes.data_as(ctypes.c_void_p),
                            out_offsets.ctypes.data_as(ctypes.c_void_p))
    offsets = out_offsets[:words + 1].copy()
    return out_bytes[:int(offsets[-1])].copy(), offsets


def fnv32_partition_native(key_bytes: np.ndarray, key_offsets: np.ndarray,
                           num_partitions: int) -> Optional[np.ndarray]:
    """Threaded 32-bit FNV-1a hash partition over full ragged keys
    (byte-identical to the device kernel and numpy host partitioner);
    None when the native lib is unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "tz_fnv32_partition"):
        return None
    n = len(key_offsets) - 1
    key_bytes = np.ascontiguousarray(key_bytes)
    key_offsets = np.ascontiguousarray(key_offsets, dtype=np.int64)
    parts = np.empty(n, dtype=np.int32)
    lib.tz_fnv32_partition(
        key_bytes.ctypes.data_as(ctypes.c_void_p),
        key_offsets.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n), ctypes.c_int32(num_partitions),
        parts.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(min(8, os.cpu_count() or 1)))
    return parts


def sort_partition_keys_native(key_bytes: np.ndarray,
                               key_offsets: np.ndarray,
                               partitions: Optional[np.ndarray]
                               ) -> Optional[np.ndarray]:
    """Stable sort permutation by (partition, full key bytes) — parallel
    native merge sort over row indices, GIL released for the whole call.
    None when the native lib is unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "tz_sort_partition_keys"):
        return None
    n = len(key_offsets) - 1
    key_bytes = np.ascontiguousarray(key_bytes)
    key_offsets = np.ascontiguousarray(key_offsets, dtype=np.int64)
    parts_ptr = None
    if partitions is not None:
        partitions = np.ascontiguousarray(partitions, dtype=np.int32)
        parts_ptr = partitions.ctypes.data_as(ctypes.c_void_p)
    perm = np.empty(n, dtype=np.int64)
    lib.tz_sort_partition_keys(
        key_bytes.ctypes.data_as(ctypes.c_void_p),
        key_offsets.ctypes.data_as(ctypes.c_void_p),
        parts_ptr, ctypes.c_int64(n),
        perm.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(min(8, os.cpu_count() or 1)))
    return perm


def owc_proxy(text: bytes, num_producers: int, num_partitions: int,
              combine: bool = True) -> "Optional[Tuple[float, bytes]]":
    """Run the full-OrderedWordCount reference-semantics C++ proxy
    (native/baseline_proxy.cpp) over a text corpus: tokenize -> span sort
    (+ combiner when `combine`) -> per-partition heap merge + sum ->
    count-keyed second sort -> merged output lines.  combine=False ships
    every (word, 1) record raw — the spill-bench shape.  Returns
    (wall_seconds, output_bytes) or None when the native lib is
    unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "owc_proxy_v2"):
        return None
    n = len(text)
    # output = unique words + "\t<count>\n" tails: usually far below the
    # input, but a mostly-distinct-short-word corpus can exceed it — grow
    # and retry on the (safe) overflow signal
    cap = max(1 << 20, n + (n >> 2))
    for _attempt in range(3):
        out = ctypes.create_string_buffer(cap)
        out_len = ctypes.c_int64()
        secs = lib.owc_proxy_v2(text, ctypes.c_int64(n),
                             ctypes.c_int32(num_producers),
                             ctypes.c_int32(num_partitions),
                             ctypes.c_int32(1 if combine else 0),
                             out, ctypes.c_int64(cap),
                             ctypes.byref(out_len))
        if secs >= 0:
            return float(secs), out.raw[:out_len.value]
        cap *= 4
    raise RuntimeError("owc_proxy output buffer overflow")


def merge_runs_native(key_bytes: np.ndarray, key_offsets: np.ndarray,
                      partitions: Optional[np.ndarray],
                      run_bounds: np.ndarray) -> Optional[np.ndarray]:
    """Stable merge permutation over the concatenation of k
    (partition, key)-sorted runs — a ladder of in-place merges instead of
    a full re-sort (GIL released).  None when the native lib is
    unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "tz_merge_runs"):
        return None
    key_bytes = np.ascontiguousarray(key_bytes)
    key_offsets = np.ascontiguousarray(key_offsets, dtype=np.int64)
    parts_ptr = None
    if partitions is not None:
        partitions = np.ascontiguousarray(partitions, dtype=np.int32)
        parts_ptr = partitions.ctypes.data_as(ctypes.c_void_p)
    run_bounds = np.ascontiguousarray(run_bounds, dtype=np.int64)
    n = int(run_bounds[-1])
    perm = np.empty(n, dtype=np.int64)
    lib.tz_merge_runs(
        key_bytes.ctypes.data_as(ctypes.c_void_p),
        key_offsets.ctypes.data_as(ctypes.c_void_p),
        parts_ptr,
        run_bounds.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(len(run_bounds) - 1),
        perm.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(min(8, os.cpu_count() or 1)))
    return perm


def owc_proxy_counts(corpus_path: str, num_producers: int,
                     num_partitions: int, combine: bool = True
                     ) -> "Optional[Tuple[float, dict]]":
    """Shared baseline harness for bench.py / spill_bench: run the
    reference-semantics proxy over a corpus FILE and parse its output
    lines into {word(str): count}.  Returns None only when the native lib
    is unavailable; parse errors (corrupt proxy output) RAISE — a wrong
    baseline must never masquerade as an absent one."""
    lib = _load()
    if lib is None or not hasattr(lib, "owc_proxy_v2"):
        return None
    with open(corpus_path, "rb") as fh:
        text = fh.read()
    res = owc_proxy(text, num_producers, num_partitions, combine=combine)
    if res is None:
        return None
    secs, out_bytes = res
    counts: dict = {}
    for line in out_bytes.decode().splitlines():
        w, cnt = line.rsplit("\t", 1)
        counts[w] = counts.get(w, 0) + int(cnt)
    return secs, counts


def adjacent_equal_native(data: np.ndarray, offsets: np.ndarray,
                          cand: np.ndarray) -> Optional[np.ndarray]:
    """Threaded per-pair memcmp for adjacent-row equality; None when the
    native lib is unavailable (caller falls back to numpy)."""
    lib = _load()
    if lib is None or not hasattr(lib, "adjacent_equal_u8"):
        return None
    data = np.ascontiguousarray(data)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    cand64 = np.ascontiguousarray(cand, dtype=np.int64)
    out = np.empty(len(cand64), dtype=np.uint8)
    lib.adjacent_equal_u8(
        data.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        cand64.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(len(cand64)),
        out.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(min(8, os.cpu_count() or 1)))
    return out.astype(bool)
