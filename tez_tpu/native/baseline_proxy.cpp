// PipelinedSorter/TezMerger-semantics baseline proxy.
//
// BASELINE.md's protocol wants the kernel bench compared against the
// reference's own sorter (apache/tez PipelinedSorter.java:75 + the
// TezMerger k-way merge, TezMerger.java:76).  The reference is Java and
// this image ships no JVM, so this file reimplements the reference
// ALGORITHM faithfully in C++ as a clearly-labeled proxy:
//
//   - collect: each producer span computes a partition per record
//     (hash % P) and sorts 16-byte metadata entries by (partition, raw
//     key bytes) — PipelinedSorter's kvmeta quicksort, std::sort here;
//   - merge: per partition, the producers' sorted segments merge through
//     a binary heap keyed on (key bytes, producer index) — TezMerger's
//     segment heap, with producer index as the stable tie-break.
//
// C++ vs Java makes this a CONSERVATIVE baseline (it under-states the
// speedup vs the real JVM implementation).  Producers run sequentially:
// the comparison host executes framework tasks on the same cores, so
// equal total work is the apples-to-apples framing.
//
// The merged key stream is written out so the caller can verify
// byte-identity against the device pipeline's output (the reducer
// byte-identity requirement in BASELINE.json).

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <queue>
#include <vector>

namespace {

inline uint32_t fnv1a32(const uint8_t* p, int64_t len) {
    uint32_t h = 2166136261u;
    for (int64_t i = 0; i < len; i++) {
        h = (h ^ p[i]) * 16777619u;
    }
    return h;
}

}  // namespace

extern "C" {

// Fixed-width records (the kernel bench shape): n keys of key_len bytes,
// n values of val_len bytes.  out_keys receives the merged keys in
// (partition, key) order, n*key_len bytes; out_vals the values riding the
// same permutation (n*val_len); out_counts the per-partition record
// counts.  Returns sort+merge wall-seconds (timed inside, excluding
// buffer setup by the caller).
double pipelined_sorter_proxy(const uint8_t* key_bytes, int64_t key_len,
                              const uint8_t* val_bytes, int64_t val_len,
                              int64_t n, int32_t num_producers,
                              int32_t num_partitions, uint8_t* out_keys,
                              uint8_t* out_vals, int64_t* out_counts) {
    auto t0 = std::chrono::steady_clock::now();

    // --- collect + span sort per producer (PipelinedSorter.sort) ---
    int64_t per = n / num_producers;
    std::vector<std::vector<int64_t>> order(num_producers);
    std::vector<std::vector<int32_t>> parts(num_producers);
    std::vector<int64_t> span_start(num_producers);
    for (int p = 0; p < num_producers; p++) {
        int64_t lo = p * per;
        int64_t hi = (p == num_producers - 1) ? n : lo + per;
        span_start[p] = lo;
        int64_t m = hi - lo;
        parts[p].resize(m);
        order[p].resize(m);
        for (int64_t i = 0; i < m; i++) {
            parts[p][i] = (int32_t)(fnv1a32(key_bytes + (lo + i) * key_len,
                                            key_len) %
                                    (uint32_t)num_partitions);
            order[p][i] = i;
        }
        const int32_t* pp = parts[p].data();
        std::sort(order[p].begin(), order[p].end(),
                  [&](int64_t a, int64_t b) {
                      if (pp[a] != pp[b]) return pp[a] < pp[b];
                      int c = std::memcmp(key_bytes + (lo + a) * key_len,
                                          key_bytes + (lo + b) * key_len,
                                          (size_t)key_len);
                      if (c != 0) return c < 0;
                      return a < b;   // stable (kvmeta order)
                  });
    }

    // --- per-partition segment bounds per producer ---
    // (TezSpillRecord: each spill carries a partition index)
    std::vector<std::vector<int64_t>> bounds(num_producers);
    for (int p = 0; p < num_producers; p++) {
        int64_t m = (int64_t)order[p].size();
        bounds[p].assign(num_partitions + 1, m);
        int32_t prev = -1;
        for (int64_t i = 0; i < m; i++) {
            int32_t c = parts[p][order[p][i]];
            while (prev < c) bounds[p][++prev] = i;
        }
        while (prev < num_partitions) bounds[p][++prev] = m;
    }

    // --- k-way heap merge per partition (TezMerger segment heap) ---
    struct HeapItem {
        const uint8_t* key;
        int32_t producer;
        int64_t pos;
    };
    int64_t out_row = 0;
    for (int32_t c = 0; c < num_partitions; c++) {
        auto cmp = [key_len](const HeapItem& a, const HeapItem& b) {
            int r = std::memcmp(a.key, b.key, (size_t)key_len);
            if (r != 0) return r > 0;            // min-heap on key bytes
            return a.producer > b.producer;      // stable across segments
        };
        std::priority_queue<HeapItem, std::vector<HeapItem>, decltype(cmp)>
            heap(cmp);
        for (int p = 0; p < num_producers; p++) {
            if (bounds[p][c] < bounds[p][c + 1]) {
                int64_t row = span_start[p] + order[p][bounds[p][c]];
                heap.push({key_bytes + row * key_len, p, bounds[p][c]});
            }
        }
        int64_t part_rows = 0;
        while (!heap.empty()) {
            HeapItem it = heap.top();
            heap.pop();
            int64_t row = span_start[it.producer] +
                          order[it.producer][it.pos];
            std::memcpy(out_keys + out_row * key_len,
                        key_bytes + row * key_len, (size_t)key_len);
            if (val_len > 0) {
                std::memcpy(out_vals + out_row * val_len,
                            val_bytes + row * val_len, (size_t)val_len);
            }
            out_row++;
            part_rows++;
            int64_t next = it.pos + 1;
            if (next < bounds[it.producer][c + 1]) {
                int64_t nrow = span_start[it.producer] +
                               order[it.producer][next];
                heap.push({key_bytes + nrow * key_len, it.producer, next});
            }
        }
        out_counts[c] = part_rows;
    }

    auto t1 = std::chrono::steady_clock::now();
    return std::chrono::duration<double>(t1 - t0).count();
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Full OrderedWordCount E2E proxy (reference semantics end to end).
//
// tez-examples OrderedWordCount.java:56 — Tokenizer -> Summation -> Sorter
// over two ordered scatter-gather edges.  The reference machinery this
// reimplements faithfully: per-producer span sort with a sum combiner on
// the sorted stream (PipelinedSorter + combiner), per-consumer segment
// heap merge with grouped summation (TezMerger + ReduceProcessor), a
// second sorted edge keyed on the count, and the final single-task merge
// writing "word\tcount\n" lines.  C++ vs the reference's Java keeps this a
// CONSERVATIVE baseline; producers run sequentially (single host core =
// equal total work framing, same as the kernel proxy above).
// ---------------------------------------------------------------------------

namespace {

inline bool ws(uint8_t c) { return c == 32 || (c >= 9 && c <= 13); }

struct WordEntry {
    const uint8_t* w;
    int32_t len;
    int64_t cnt;
};

inline int word_cmp(const WordEntry& a, const WordEntry& b) {
    int64_t m = a.len < b.len ? a.len : b.len;
    int c = std::memcmp(a.w, b.w, (size_t)m);
    if (c != 0) return c;
    return a.len < b.len ? -1 : (a.len > b.len ? 1 : 0);
}

}  // namespace

extern "C" {

// text/n: the corpus.  M producers (tokenizer tasks), P partitions
// (summation tasks); the sorter stage is one task (the example's shape).
// combine != 0 runs the reference combiner on each sorted span (the
// example's default); combine == 0 ships every (word, 1) record through
// the sort/merge machinery raw — the spill-bench shape.
// out/out_cap receive the final "word\tcount\n" lines; *out_len gets the
// byte count.  Returns wall-seconds for everything past argument setup,
// or -1.0 when out_cap is too small.
double owc_proxy_v2(const uint8_t* text, int64_t n, int32_t num_producers,
                 int32_t num_partitions, int32_t combine, uint8_t* out,
                 int64_t out_cap, int64_t* out_len) {
    auto t0 = std::chrono::steady_clock::now();
    int M = num_producers, P = num_partitions;

    // --- split generation: whitespace-aligned slices (MRInput splits) ---
    std::vector<int64_t> sb(M + 1, 0);
    sb[M] = n;
    for (int i = 1; i < M; i++) {
        int64_t b = n * i / M;
        while (b < n && !ws(text[b])) b++;
        sb[i] = b;
    }
    std::sort(sb.begin(), sb.end());

    // --- tokenizer tasks: tokenize, partition, span sort, combine ---
    std::vector<std::vector<WordEntry>> prod(M);
    std::vector<std::vector<int64_t>> pbounds(M);
    for (int p = 0; p < M; p++) {
        std::vector<WordEntry> words;
        std::vector<int32_t> parts;
        for (int64_t i = sb[p]; i < sb[p + 1];) {
            while (i < sb[p + 1] && ws(text[i])) i++;
            int64_t s = i;
            while (i < sb[p + 1] && !ws(text[i])) i++;
            if (i > s) {
                words.push_back({text + s, (int32_t)(i - s), 1});
                parts.push_back((int32_t)(
                    fnv1a32(text + s, i - s) % (uint32_t)P));
            }
        }
        std::vector<int64_t> order(words.size());
        for (size_t i = 0; i < order.size(); i++) order[i] = (int64_t)i;
        std::sort(order.begin(), order.end(),
                  [&](int64_t a, int64_t b) {
                      if (parts[a] != parts[b]) return parts[a] < parts[b];
                      int c = word_cmp(words[a], words[b]);
                      if (c != 0) return c < 0;
                      return a < b;
                  });
        // combiner on the sorted span stream (PipelinedSorter + combine);
        // combine off = every record ships raw (spill-bench semantics)
        auto& entries = prod[p];
        auto& bounds = pbounds[p];
        bounds.assign(P + 1, 0);
        int32_t prev_part = -1;
        for (size_t i = 0; i < order.size(); i++) {
            const WordEntry& we = words[order[i]];
            int32_t c = parts[order[i]];
            if (!combine || c != prev_part || entries.empty() ||
                word_cmp(entries.back(), we) != 0) {
                while (prev_part < c) bounds[++prev_part] =
                    (int64_t)entries.size();
                entries.push_back(we);
            } else {
                entries.back().cnt++;
            }
        }
        while (prev_part < P) bounds[++prev_part] = (int64_t)entries.size();
    }

    // --- summation tasks: segment heap merge + grouped sum ------------
    struct SegItem { const WordEntry* e; int32_t producer; int64_t pos; };
    std::vector<std::vector<WordEntry>> summed(P);
    for (int32_t c = 0; c < P; c++) {
        auto cmp = [](const SegItem& a, const SegItem& b) {
            int r = word_cmp(*a.e, *b.e);
            if (r != 0) return r > 0;
            return a.producer > b.producer;
        };
        std::priority_queue<SegItem, std::vector<SegItem>, decltype(cmp)>
            heap(cmp);
        for (int p = 0; p < M; p++) {
            if (pbounds[p][c] < pbounds[p][c + 1]) {
                heap.push({&prod[p][pbounds[p][c]], p, pbounds[p][c]});
            }
        }
        auto& outp = summed[c];
        while (!heap.empty()) {
            SegItem it = heap.top();
            heap.pop();
            if (!outp.empty() && word_cmp(outp.back(), *it.e) == 0) {
                outp.back().cnt += it.e->cnt;
            } else {
                outp.push_back(*it.e);
            }
            int64_t next = it.pos + 1;
            if (next < pbounds[it.producer][c + 1]) {
                heap.push({&prod[it.producer][next], it.producer, next});
            }
        }
    }

    // --- second sorted edge: key = count; single sorter task ----------
    std::vector<std::vector<int64_t>> order2(P);
    for (int32_t c = 0; c < P; c++) {
        order2[c].resize(summed[c].size());
        for (size_t i = 0; i < order2[c].size(); i++)
            order2[c][i] = (int64_t)i;
        auto& seg = summed[c];
        std::sort(order2[c].begin(), order2[c].end(),
                  [&](int64_t a, int64_t b) {
                      if (seg[a].cnt != seg[b].cnt)
                          return seg[a].cnt < seg[b].cnt;
                      return a < b;   // stable (arrival order)
                  });
    }
    struct CntItem { int64_t cnt; int32_t producer; int64_t pos; };
    auto cmp2 = [](const CntItem& a, const CntItem& b) {
        if (a.cnt != b.cnt) return a.cnt > b.cnt;   // min-heap on count
        return a.producer > b.producer;
    };
    std::priority_queue<CntItem, std::vector<CntItem>, decltype(cmp2)>
        heap2(cmp2);
    for (int32_t c = 0; c < P; c++) {
        if (!order2[c].empty())
            heap2.push({summed[c][order2[c][0]].cnt, c, 0});
    }
    int64_t pos_out = 0;
    while (!heap2.empty()) {
        CntItem it = heap2.top();
        heap2.pop();
        const WordEntry& e = summed[it.producer][order2[it.producer][it.pos]];
        char tail[32];
        int tn = std::snprintf(tail, sizeof(tail), "\t%lld\n",
                               (long long)e.cnt);
        if (pos_out + e.len + tn > out_cap) return -1.0;
        std::memcpy(out + pos_out, e.w, (size_t)e.len);
        pos_out += e.len;
        std::memcpy(out + pos_out, tail, (size_t)tn);
        pos_out += tn;
        int64_t next = it.pos + 1;
        if (next < (int64_t)order2[it.producer].size()) {
            heap2.push({summed[it.producer][order2[it.producer][next]].cnt,
                        it.producer, next});
        }
    }
    *out_len = pos_out;
    auto t1 = std::chrono::steady_clock::now();
    return std::chrono::duration<double>(t1 - t0).count();
}

}  // extern "C"
