// PipelinedSorter/TezMerger-semantics baseline proxy.
//
// BASELINE.md's protocol wants the kernel bench compared against the
// reference's own sorter (apache/tez PipelinedSorter.java:75 + the
// TezMerger k-way merge, TezMerger.java:76).  The reference is Java and
// this image ships no JVM, so this file reimplements the reference
// ALGORITHM faithfully in C++ as a clearly-labeled proxy:
//
//   - collect: each producer span computes a partition per record
//     (hash % P) and sorts 16-byte metadata entries by (partition, raw
//     key bytes) — PipelinedSorter's kvmeta quicksort, std::sort here;
//   - merge: per partition, the producers' sorted segments merge through
//     a binary heap keyed on (key bytes, producer index) — TezMerger's
//     segment heap, with producer index as the stable tie-break.
//
// C++ vs Java makes this a CONSERVATIVE baseline (it under-states the
// speedup vs the real JVM implementation).  Producers run sequentially:
// the comparison host executes framework tasks on the same cores, so
// equal total work is the apples-to-apples framing.
//
// The merged key stream is written out so the caller can verify
// byte-identity against the device pipeline's output (the reducer
// byte-identity requirement in BASELINE.json).

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

namespace {

inline uint32_t fnv1a32(const uint8_t* p, int64_t len) {
    uint32_t h = 2166136261u;
    for (int64_t i = 0; i < len; i++) {
        h = (h ^ p[i]) * 16777619u;
    }
    return h;
}

}  // namespace

extern "C" {

// Fixed-width records (the kernel bench shape): n keys of key_len bytes,
// n values of val_len bytes.  out_keys receives the merged keys in
// (partition, key) order, n*key_len bytes; out_vals the values riding the
// same permutation (n*val_len); out_counts the per-partition record
// counts.  Returns sort+merge wall-seconds (timed inside, excluding
// buffer setup by the caller).
double pipelined_sorter_proxy(const uint8_t* key_bytes, int64_t key_len,
                              const uint8_t* val_bytes, int64_t val_len,
                              int64_t n, int32_t num_producers,
                              int32_t num_partitions, uint8_t* out_keys,
                              uint8_t* out_vals, int64_t* out_counts) {
    auto t0 = std::chrono::steady_clock::now();

    // --- collect + span sort per producer (PipelinedSorter.sort) ---
    int64_t per = n / num_producers;
    std::vector<std::vector<int64_t>> order(num_producers);
    std::vector<std::vector<int32_t>> parts(num_producers);
    std::vector<int64_t> span_start(num_producers);
    for (int p = 0; p < num_producers; p++) {
        int64_t lo = p * per;
        int64_t hi = (p == num_producers - 1) ? n : lo + per;
        span_start[p] = lo;
        int64_t m = hi - lo;
        parts[p].resize(m);
        order[p].resize(m);
        for (int64_t i = 0; i < m; i++) {
            parts[p][i] = (int32_t)(fnv1a32(key_bytes + (lo + i) * key_len,
                                            key_len) %
                                    (uint32_t)num_partitions);
            order[p][i] = i;
        }
        const int32_t* pp = parts[p].data();
        std::sort(order[p].begin(), order[p].end(),
                  [&](int64_t a, int64_t b) {
                      if (pp[a] != pp[b]) return pp[a] < pp[b];
                      int c = std::memcmp(key_bytes + (lo + a) * key_len,
                                          key_bytes + (lo + b) * key_len,
                                          (size_t)key_len);
                      if (c != 0) return c < 0;
                      return a < b;   // stable (kvmeta order)
                  });
    }

    // --- per-partition segment bounds per producer ---
    // (TezSpillRecord: each spill carries a partition index)
    std::vector<std::vector<int64_t>> bounds(num_producers);
    for (int p = 0; p < num_producers; p++) {
        int64_t m = (int64_t)order[p].size();
        bounds[p].assign(num_partitions + 1, m);
        int32_t prev = -1;
        for (int64_t i = 0; i < m; i++) {
            int32_t c = parts[p][order[p][i]];
            while (prev < c) bounds[p][++prev] = i;
        }
        while (prev < num_partitions) bounds[p][++prev] = m;
    }

    // --- k-way heap merge per partition (TezMerger segment heap) ---
    struct HeapItem {
        const uint8_t* key;
        int32_t producer;
        int64_t pos;
    };
    int64_t out_row = 0;
    for (int32_t c = 0; c < num_partitions; c++) {
        auto cmp = [key_len](const HeapItem& a, const HeapItem& b) {
            int r = std::memcmp(a.key, b.key, (size_t)key_len);
            if (r != 0) return r > 0;            // min-heap on key bytes
            return a.producer > b.producer;      // stable across segments
        };
        std::priority_queue<HeapItem, std::vector<HeapItem>, decltype(cmp)>
            heap(cmp);
        for (int p = 0; p < num_producers; p++) {
            if (bounds[p][c] < bounds[p][c + 1]) {
                int64_t row = span_start[p] + order[p][bounds[p][c]];
                heap.push({key_bytes + row * key_len, p, bounds[p][c]});
            }
        }
        int64_t part_rows = 0;
        while (!heap.empty()) {
            HeapItem it = heap.top();
            heap.pop();
            int64_t row = span_start[it.producer] +
                          order[it.producer][it.pos];
            std::memcpy(out_keys + out_row * key_len,
                        key_bytes + row * key_len, (size_t)key_len);
            if (val_len > 0) {
                std::memcpy(out_vals + out_row * val_len,
                            val_bytes + row * val_len, (size_t)val_len);
            }
            out_row++;
            part_rows++;
            int64_t next = it.pos + 1;
            if (next < bounds[it.producer][c + 1]) {
                int64_t nrow = span_start[it.producer] +
                               order[it.producer][next];
                heap.push({key_bytes + nrow * key_len, it.producer, next});
            }
        }
        out_counts[c] = part_rows;
    }

    auto t1 = std::chrono::steady_clock::now();
    return std::chrono::duration<double>(t1 - t0).count();
}

}  // extern "C"
