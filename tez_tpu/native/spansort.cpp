// Host span sort + run merge, v2 (round 5).
//
// The reference's ordered data plane sorts (partition, key) records with a
// comparison sort over serialized bytes (PipelinedSorter.java:75 sortmaster
// + TezMerger.java:76 merge queue).  This host engine keeps those semantics
// (stable (partition, full key bytes) order, byte-identical output) but is
// shaped for how shuffle keys actually look — short keys with heavy
// duplication (wordcount families: zipfian vocab) — and for cache behavior:
//
//  * items pack the FIRST 12 key bytes into registers ({u64 prefix,
//    u32 prefix2, u32 idx} = 16 bytes): compares never touch key memory
//    unless both keys exceed 12 bytes.  The previous 8-byte prefix fell
//    through to memcmp on nearly every compare for zero-padded numeric
//    keys whose first 8 bytes carry almost no entropy.
//  * duplication-aware fast path: hash (partition, key) -> unique id,
//    comparison-sort ONLY the uniques, then one stable O(n) counting
//    scatter of the records.  A 32k-record sample gates the path so
//    near-unique spans take the direct sort instead.
//
// Exported symbols keep the v1 ABI (tz_sort_partition_keys, tz_merge_runs)
// so ops/native.py needs no change for the sort; gather_fixed_u8 is new.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" void tz_fnv32_partition(const uint8_t*, const int64_t*, int64_t,
                                   int32_t, int32_t*, int32_t);  // ragged.cpp

namespace {

inline uint64_t load_be64(const uint8_t* p) {
    uint64_t v;
    std::memcpy(&v, p, 8);
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
    v = __builtin_bswap64(v);
#endif
    return v;
}

inline uint32_t load_be32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
    v = __builtin_bswap32(v);
#endif
    return v;
}

// Big-endian zero-padded prefixes of the first 12 key bytes: unsigned
// compare of (prefix, prefix2) orders exactly like memcmp of those bytes.
inline void key_prefix12(const uint8_t* p, int64_t len,
                         uint64_t& pre, uint32_t& pre2) {
    if (len >= 12) {
        pre = load_be64(p);
        pre2 = load_be32(p + 8);
        return;
    }
    pre = 0;
    pre2 = 0;
    int64_t m = len < 8 ? len : 8;
    for (int64_t i = 0; i < m; i++) pre |= (uint64_t)p[i] << (56 - 8 * i);
    for (int64_t i = 8; i < len; i++)
        pre2 |= (uint32_t)p[i] << (24 - 8 * (i - 8));
}

struct Item { uint64_t prefix; uint32_t prefix2; uint32_t idx; };

// Total order == stable result: ties on the full key fall to idx.
struct ItemCmp {
    const uint8_t* kb;
    const int64_t* ko;
    bool operator()(const Item& a, const Item& b) const {
        if (a.prefix != b.prefix) return a.prefix < b.prefix;
        if (a.prefix2 != b.prefix2) return a.prefix2 < b.prefix2;
        int64_t la = ko[a.idx + 1] - ko[a.idx];
        int64_t lb = ko[b.idx + 1] - ko[b.idx];
        if (la > 12 && lb > 12) {
            int64_t m = (la < lb ? la : lb) - 12;
            int c = std::memcmp(kb + ko[a.idx] + 12, kb + ko[b.idx] + 12,
                                (size_t)m);
            if (c) return c < 0;
        }
        if (la != lb) return la < lb;
        return a.idx < b.idx;
    }
};

inline uint64_t fnv64(const uint8_t* p, int64_t len) {
    uint64_t h = 1469598103934665603ull;
    for (int64_t i = 0; i < len; i++) {
        h ^= p[i];
        h *= 1099511628211ull;
    }
    return h;
}

// One pass, both hashes: the 64-bit dedup hash and the 32-bit partition
// hash (must stay byte-identical to tz_fnv32_partition / the device
// partitioner).
inline void fnv_both(const uint8_t* p, int64_t len,
                     uint64_t& h64, uint32_t& h32) {
    uint64_t h = 1469598103934665603ull;
    uint32_t g = 2166136261u;
    for (int64_t i = 0; i < len; i++) {
        h ^= p[i];
        h *= 1099511628211ull;
        g ^= p[i];
        g *= 16777619u;
    }
    h64 = h;
    h32 = g;
}

// Uniform row width of a ragged offsets array, or -1.
inline int64_t fixed_width(const int64_t* off, int64_t n) {
    if (n <= 0) return -1;
    int64_t w = off[1] - off[0];
    if (w < 0 || off[n] - off[0] != n * w) return -1;
    for (int64_t i = 1; i < n; i++)
        if (off[i + 1] - off[i] != w) return -1;
    return w;
}

// Compile-time-size row copy for the common serde widths.
inline void copy_row(uint8_t* dst, const uint8_t* src, int64_t w) {
    switch (w) {
    case 8:  std::memcpy(dst, src, 8); break;
    case 12: std::memcpy(dst, src, 12); break;
    case 16: std::memcpy(dst, src, 16); break;
    default: std::memcpy(dst, src, (size_t)w);
    }
}

// ---- duplication-aware machinery -----------------------------------------

// Open-addressing map of (partition, key bytes) -> unique id.  Keys are
// referenced in place (the span's byte arrays outlive the call); no arena.
struct UniqTable {
    struct Entry { uint64_t hash; int64_t rec; int32_t part; int64_t count; };
    std::vector<uint32_t> slots;   // entry index + 1; 0 = empty
    std::vector<Entry> entries;
    uint64_t mask;
    const uint8_t* kb;
    const int64_t* ko;

    UniqTable(const uint8_t* kb_, const int64_t* ko_, int64_t expect)
        : kb(kb_), ko(ko_) {
        size_t cap = 1024;
        while ((int64_t)cap < expect * 2) cap <<= 1;
        slots.assign(cap, 0);
        mask = cap - 1;
    }

    void grow() {
        size_t ns = slots.size() * 2;
        std::vector<uint32_t>(ns, 0).swap(slots);
        mask = ns - 1;
        for (size_t e = 0; e < entries.size(); e++) {
            uint64_t slot = entries[e].hash & mask;
            while (slots[slot]) slot = (slot + 1) & mask;
            slots[slot] = (uint32_t)e + 1;
        }
    }

    inline uint32_t add(int64_t rec, int32_t part) {
        const uint8_t* key = kb + ko[rec];
        int64_t len = ko[rec + 1] - ko[rec];
        uint64_t h = fnv64(key, len) ^
            (0x9E3779B97F4A7C15ull * (uint64_t)(part + 1));
        uint64_t slot = h & mask;
        while (true) {
            uint32_t idx = slots[slot];
            if (idx == 0) break;
            Entry& e = entries[idx - 1];
            int64_t elen = ko[e.rec + 1] - ko[e.rec];
            if (e.hash == h && e.part == part && elen == len &&
                std::memcmp(kb + ko[e.rec], key, (size_t)len) == 0) {
                e.count++;
                return idx - 1;
            }
            slot = (slot + 1) & mask;
        }
        entries.push_back({h, rec, part, 1});
        slots[slot] = (uint32_t)entries.size();
        if (entries.size() * 10 > slots.size() * 7) grow();
        return (uint32_t)entries.size() - 1;
    }

    // Identity by key bytes alone; the partition derives from the key's
    // fnv32 at first insertion (a hash partition is a pure function of
    // the key, so equal keys can never land in different partitions).
    // Saves the separate whole-span fnv32 pass.
    inline uint32_t add_derive(int64_t rec, int32_t num_partitions) {
        const uint8_t* key = kb + ko[rec];
        int64_t len = ko[rec + 1] - ko[rec];
        uint64_t h; uint32_t g;
        fnv_both(key, len, h, g);
        uint64_t slot = h & mask;
        while (true) {
            uint32_t idx = slots[slot];
            if (idx == 0) break;
            Entry& e = entries[idx - 1];
            int64_t elen = ko[e.rec + 1] - ko[e.rec];
            if (e.hash == h && elen == len &&
                std::memcmp(kb + ko[e.rec], key, (size_t)len) == 0) {
                e.count++;
                return idx - 1;
            }
            slot = (slot + 1) & mask;
        }
        entries.push_back({h, rec,
                           (int32_t)(g % (uint32_t)num_partitions), 1});
        slots[slot] = (uint32_t)entries.size();
        if (entries.size() * 10 > slots.size() * 7) grow();
        return (uint32_t)entries.size() - 1;
    }
};

// Sampled uniqueness estimate: distinct 64-bit hashes in the first
// `sample` records (hash collisions only ever UNDER-count, which biases
// toward the dedup path — harmless).  Returns distinct count.
int64_t sample_distinct(const uint8_t* kb, const int64_t* ko,
                        const int32_t* parts, int64_t sample) {
    size_t cap = 1;
    while ((int64_t)cap < sample * 2) cap <<= 1;
    std::vector<uint64_t> set(cap, 0);
    uint64_t mask = cap - 1;
    int64_t distinct = 0;
    for (int64_t i = 0; i < sample; i++) {
        int32_t part = parts ? parts[i] : 0;
        uint64_t h = fnv64(kb + ko[i], ko[i + 1] - ko[i]) ^
            (0x9E3779B97F4A7C15ull * (uint64_t)(part + 1));
        if (h == 0) h = 1;
        uint64_t slot = h & mask;
        while (set[slot] != 0 && set[slot] != h) slot = (slot + 1) & mask;
        if (set[slot] == 0) { set[slot] = h; distinct++; }
    }
    return distinct;
}

// Shared dedup-rank machinery: hash records to uniques (optionally
// deriving the partition from the key's fnv32), sort the uniques by
// (partition, key bytes), and compute per-unique rank + output start
// offsets.  Both the permutation-only sort and the fused emit build on
// this — one copy of the tie-break and table logic.
struct DedupRank {
    UniqTable table;
    std::vector<uint32_t> uids;    // per record -> unique id
    std::vector<Item> items;       // uniques in sorted output order
    std::vector<uint32_t> rank;    // unique id -> sorted position
    std::vector<int64_t> start;    // sorted position -> output row offset

    DedupRank(const uint8_t* kb, const int64_t* ko, const int32_t* parts,
              int64_t n, int64_t expect_uniques,
              int32_t derive_partitions /* 0 = use parts */)
        : table(kb, ko, expect_uniques), uids((size_t)n) {
        if (derive_partitions > 1) {
            for (int64_t i = 0; i < n; i++)
                uids[(size_t)i] = table.add_derive(i, derive_partitions);
        } else {
            for (int64_t i = 0; i < n; i++)
                uids[(size_t)i] = table.add(i, parts ? parts[i] : 0);
        }
        int64_t u = (int64_t)table.entries.size();
        // sort unique entries by (partition, key bytes); all entries are
        // distinct so no stability concern at this level
        items.resize((size_t)u);
        for (int64_t e = 0; e < u; e++) {
            int64_t rec = table.entries[(size_t)e].rec;
            uint64_t pre; uint32_t pre2;
            key_prefix12(kb + ko[rec], ko[rec + 1] - ko[rec], pre, pre2);
            items[(size_t)e] = {pre, pre2, (uint32_t)e};
        }
        ItemCmp base{kb, ko};
        std::sort(items.begin(), items.end(),
                  [&](const Item& a, const Item& b) {
            int32_t pa = table.entries[a.idx].part;
            int32_t pb = table.entries[b.idx].part;
            if (pa != pb) return pa < pb;
            Item ra{a.prefix, a.prefix2, (uint32_t)table.entries[a.idx].rec};
            Item rb{b.prefix, b.prefix2, (uint32_t)table.entries[b.idx].rec};
            return base(ra, rb);
        });
        // rank per unique, output start offset per rank
        start.resize((size_t)u);
        rank.resize((size_t)u);
        int64_t off = 0;
        for (int64_t r = 0; r < u; r++) {
            uint32_t e = items[(size_t)r].idx;
            rank[e] = (uint32_t)r;
            start[(size_t)r] = off;
            off += table.entries[e].count;
        }
    }

    // stable permutation: records scatter to their rank group in
    // original order
    void fill_perm(int64_t n, int64_t* perm) const {
        std::vector<int64_t> cursor(start);
        for (int64_t i = 0; i < n; i++)
            perm[(size_t)cursor[rank[uids[(size_t)i]]]++] = i;
    }
};

// Stable sort permutation via dedup-rank: hash records to uniques, sort
// uniques by (partition, key), counting-scatter records by rank.
void dedup_rank_sort(const uint8_t* kb, const int64_t* ko,
                     const int32_t* parts, int64_t n, int64_t* perm,
                     int64_t expect_uniques) {
    DedupRank dr(kb, ko, parts, n, expect_uniques, 0);
    dr.fill_perm(n, perm);
}

struct Range { int64_t lo, hi; };
struct MJob { int64_t lo, mid, hi; };

template <typename Cmp>
void parallel_sort_ranges(std::vector<Item>& items,
                          const std::vector<int64_t>& pstart,
                          int64_t nparts, int64_t n, int threads,
                          const Cmp& cmp) {
    if (threads == 1 || n < (1 << 15)) {
        for (int64_t p = 0; p < nparts; p++)
            std::sort(items.begin() + pstart[p],
                      items.begin() + pstart[p + 1], cmp);
        return;
    }
    // two-level parallelism: chunk each partition range, sort chunks on a
    // pool, then ladder pairwise inplace_merges (one dominant partition
    // still uses every thread)
    int64_t target = std::max<int64_t>(1 << 15, n / threads / 2 + 1);
    std::vector<std::vector<int64_t>> chunk_bounds((size_t)nparts);
    std::vector<Range> jobs;
    for (int64_t p = 0; p < nparts; p++) {
        int64_t lo = pstart[p], hi = pstart[p + 1];
        int64_t len = hi - lo;
        int64_t k = std::max<int64_t>(1, (len + target - 1) / target);
        auto& cb = chunk_bounds[(size_t)p];
        cb.resize((size_t)k + 1);
        for (int64_t c = 0; c <= k; c++) cb[(size_t)c] = lo + len * c / k;
        for (int64_t c = 0; c < k; c++)
            jobs.push_back({cb[(size_t)c], cb[(size_t)c + 1]});
    }
    {
        std::atomic<size_t> next(0);
        std::vector<std::thread> pool;
        int nt = std::min<int64_t>(threads, (int64_t)jobs.size());
        for (int t = 0; t < nt; t++)
            pool.emplace_back([&]() {
                for (size_t j; (j = next.fetch_add(1)) < jobs.size();)
                    std::sort(items.begin() + jobs[j].lo,
                              items.begin() + jobs[j].hi, cmp);
            });
        for (auto& th : pool) th.join();
    }
    for (int64_t step = 1;; step *= 2) {
        std::vector<MJob> mjobs;
        for (int64_t p = 0; p < nparts; p++) {
            auto& cb = chunk_bounds[(size_t)p];
            int64_t k = (int64_t)cb.size() - 1;
            for (int64_t c = 0; c + step < k; c += 2 * step) {
                int64_t hi_idx = std::min<int64_t>(k, c + 2 * step);
                mjobs.push_back({cb[(size_t)c], cb[(size_t)(c + step)],
                                 cb[(size_t)hi_idx]});
            }
        }
        if (mjobs.empty()) break;
        std::atomic<size_t> next(0);
        std::vector<std::thread> pool;
        int nt = std::min<int64_t>(threads, (int64_t)mjobs.size());
        for (int t = 0; t < nt; t++)
            pool.emplace_back([&]() {
                for (size_t j; (j = next.fetch_add(1)) < mjobs.size();)
                    std::inplace_merge(items.begin() + mjobs[j].lo,
                                       items.begin() + mjobs[j].mid,
                                       items.begin() + mjobs[j].hi, cmp);
            });
        for (auto& th : pool) th.join();
    }
}

}  // namespace

extern "C" {

// Stable sort permutation of rows by (partition, key bytes).  partitions
// may be null (single-partition sort, e.g. run merges).  v2: see header.
void tz_sort_partition_keys(const uint8_t* key_bytes,
                            const int64_t* key_offsets,
                            const int32_t* partitions, int64_t n,
                            int64_t* perm, int32_t n_threads) {
    if (n <= 0) return;
    if (n > 0x7FFFFFFFll - 2) {
        // u32 idx packing would overflow; spans never get near this (the
        // span budget caps records long before 2^31), but stay correct
        std::vector<int64_t> idx((size_t)n);
        for (int64_t i = 0; i < n; i++) idx[(size_t)i] = i;
        std::stable_sort(idx.begin(), idx.end(),
            [&](int64_t a, int64_t b) {
                int32_t pa = partitions ? partitions[a] : 0;
                int32_t pb = partitions ? partitions[b] : 0;
                if (pa != pb) return pa < pb;
                int64_t la = key_offsets[a + 1] - key_offsets[a];
                int64_t lb = key_offsets[b + 1] - key_offsets[b];
                int64_t m = la < lb ? la : lb;
                int c = std::memcmp(key_bytes + key_offsets[a],
                                    key_bytes + key_offsets[b], (size_t)m);
                if (c) return c < 0;
                return la < lb;
            });
        std::memcpy(perm, idx.data(), (size_t)n * 8);
        return;
    }

    // duplication gate: a 32k sample decides dedup-rank vs direct sort
    int64_t sample = n < 32768 ? n : 32768;
    if (n >= 4096) {
        int64_t distinct = sample_distinct(key_bytes, key_offsets,
                                           partitions, sample);
        if (distinct * 2 < sample) {
            int64_t expect = distinct * (n / sample + 1) + 16;
            dedup_rank_sort(key_bytes, key_offsets, partitions, n, perm,
                            expect);
            return;
        }
    }

    // direct path: stable counting sort by partition, then per-partition
    // value sort of packed 16-byte items
    std::vector<Item> items((size_t)n);
    int64_t nparts = 1;
    std::vector<int64_t> pstart;
    if (partitions != nullptr) {
        int32_t maxp = 0;
        for (int64_t i = 0; i < n; i++)
            if (partitions[i] > maxp) maxp = partitions[i];
        nparts = (int64_t)maxp + 1;
        pstart.assign((size_t)nparts + 1, 0);
        for (int64_t i = 0; i < n; i++) pstart[partitions[i] + 1]++;
        for (int64_t p = 0; p < nparts; p++) pstart[p + 1] += pstart[p];
        std::vector<int64_t> cur(pstart.begin(), pstart.end() - 1);
        for (int64_t i = 0; i < n; i++) {
            uint64_t pre; uint32_t pre2;
            key_prefix12(key_bytes + key_offsets[i],
                         key_offsets[i + 1] - key_offsets[i], pre, pre2);
            items[(size_t)cur[partitions[i]]++] = {pre, pre2, (uint32_t)i};
        }
    } else {
        pstart = {0, n};
        for (int64_t i = 0; i < n; i++) {
            uint64_t pre; uint32_t pre2;
            key_prefix12(key_bytes + key_offsets[i],
                         key_offsets[i + 1] - key_offsets[i], pre, pre2);
            items[(size_t)i] = {pre, pre2, (uint32_t)i};
        }
    }
    ItemCmp cmp{key_bytes, key_offsets};
    parallel_sort_ranges(items, pstart, nparts, n,
                         std::max(1, (int)n_threads), cmp);
    for (int64_t i = 0; i < n; i++) perm[i] = items[(size_t)i].idx;
}

// Merge k (partition, key)-sorted runs into one stable permutation.
// Rows are the CONCATENATION of the runs; run_bounds has k+1 entries.
// Equal (partition, key) rows keep concatenation order == run age order
// (MergeQueue semantics) — which a stable full sort also guarantees, so
// the duplication fast path may re-derive the order by dedup-rank.
void tz_merge_runs(const uint8_t* key_bytes, const int64_t* key_offsets,
                   const int32_t* partitions, const int64_t* run_bounds,
                   int32_t num_runs, int64_t* perm, int32_t n_threads) {
    int64_t n = run_bounds[num_runs];
    if (n <= 0) return;
    if (n > 0x7FFFFFFFll - 2) {
        tz_sort_partition_keys(key_bytes, key_offsets, partitions, n, perm,
                               n_threads);
        return;
    }
    // duplication gate (sample the first run's records — representative
    // because every run drew from the same producer stream)
    int64_t sample = n < 32768 ? n : 32768;
    if (n >= 4096) {
        int64_t distinct = sample_distinct(key_bytes, key_offsets,
                                           partitions, sample);
        if (distinct * 2 < sample) {
            int64_t expect = distinct * (n / sample + 1) + 16;
            dedup_rank_sort(key_bytes, key_offsets, partitions, n, perm,
                            expect);
            return;
        }
    }
    struct PCmp {
        const int32_t* parts;
        ItemCmp base;
        bool operator()(const Item& a, const Item& b) const {
            if (parts != nullptr && parts[a.idx] != parts[b.idx])
                return parts[a.idx] < parts[b.idx];
            return base(a, b);
        }
    };
    std::vector<Item> items((size_t)n);
    for (int64_t i = 0; i < n; i++) {
        uint64_t pre; uint32_t pre2;
        key_prefix12(key_bytes + key_offsets[i],
                     key_offsets[i + 1] - key_offsets[i], pre, pre2);
        items[(size_t)i] = {pre, pre2, (uint32_t)i};
    }
    PCmp cmp{partitions, ItemCmp{key_bytes, key_offsets}};
    int threads = std::max(1, (int)n_threads);
    for (int64_t step = 1; step < num_runs; step *= 2) {
        std::vector<MJob> jobs;
        for (int64_t r = 0; r + step < num_runs; r += 2 * step) {
            int64_t hi = std::min<int64_t>(num_runs, r + 2 * step);
            jobs.push_back({run_bounds[r], run_bounds[r + step],
                            run_bounds[hi]});
        }
        int nt = std::min<int64_t>(threads, (int64_t)jobs.size());
        if (nt <= 1 || n < (1 << 15)) {
            for (const MJob& j : jobs)
                std::inplace_merge(items.begin() + j.lo,
                                   items.begin() + j.mid,
                                   items.begin() + j.hi, cmp);
        } else {
            std::atomic<size_t> next(0);
            std::vector<std::thread> pool;
            for (int t = 0; t < nt; t++)
                pool.emplace_back([&]() {
                    for (size_t j; (j = next.fetch_add(1)) < jobs.size();)
                        std::inplace_merge(items.begin() + jobs[j].lo,
                                           items.begin() + jobs[j].mid,
                                           items.begin() + jobs[j].hi, cmp);
                });
            for (auto& th : pool) th.join();
        }
    }
    for (int64_t i = 0; i < n; i++) perm[i] = items[(size_t)i].idx;
}

// Fused producer span sort + materialization (round 5): one call replaces
// fnv-partition, sort-permutation, and the two take() gathers.  Sorted key
// bytes emit as sequential writes (on the dedup path each unique key's
// bytes repeat in place — a single cached source row per group); values
// follow the stable permutation.  Fixed-width rows (the long/word serde
// common case) use compile-time-size copies and vectorized offset fills.
// Semantics identical to tz_sort_partition_keys + gather: stable
// (partition, full key bytes) order, byte-identical output.
//   parts_in    : per-row partitions, or null
//   compute_hash: when parts_in is null — 1 = fnv32(key) % num_partitions
//                 (PipelinedSorter hash-partition parity), 0 = all rows in
//                 partition 0
//   out_parts   : per-row partition of the sorted output, or null to skip
//   part_counts : int64[num_partitions], zeroed and filled here
// Returns 0 on success.
int32_t tz_span_sort_emit(
        const uint8_t* kb, const int64_t* ko,
        const uint8_t* vb, const int64_t* vo,
        int64_t n, int32_t num_partitions, const int32_t* parts_in,
        int32_t compute_hash,
        uint8_t* out_kb, int64_t* out_ko,
        uint8_t* out_vb, int64_t* out_vo,
        int32_t* out_parts, int64_t* part_counts, int32_t n_threads) {
    // mirror the n > INT32_MAX-2 fallback: with num_partitions <= 0 the
    // part_counts buffer is zero-length and every row would emit through
    // part_counts[0] — reject before touching any output buffer
    if (num_partitions <= 0) return -1;
    for (int32_t p = 0; p < num_partitions; p++) part_counts[p] = 0;
    out_ko[0] = 0;
    out_vo[0] = 0;
    if (n <= 0) return 0;
    if (n > 0x7FFFFFFFll - 2) return -1;   // caller falls back to v1 path

    const int32_t* parts = parts_in;
    if (parts != nullptr) {
        // range-check custom partitions: the buffers here are sized
        // num_partitions, so an out-of-range id is heap corruption, not a
        // wrong answer.  Reject and let the caller's fallback path handle
        // (and report) the bad partitioner output.
        for (int64_t i = 0; i < n; i++)
            if (parts[i] < 0 || parts[i] >= num_partitions) return -2;
    }
    bool derive = parts == nullptr && compute_hash && num_partitions > 1;
    int64_t wk = fixed_width(ko, n);
    int64_t wv = fixed_width(vo, n);

    int64_t sample = n < 32768 ? n : 32768;
    int64_t distinct = n >= 4096 ?
        sample_distinct(kb, ko, derive ? nullptr : parts, sample) : sample;

    if (n >= 4096 && distinct * 2 < sample) {
        // ---- dedup-rank path: hash records to uniques (partition derives
        // from the key's own fnv32 — no separate partition pass), sort
        // only the uniques, emit rank groups
        DedupRank dr(kb, ko, parts, n, distinct * (n / sample + 1) + 16,
                     derive ? num_partitions : 0);
        const UniqTable& table = dr.table;
        int64_t u = (int64_t)table.entries.size();
        // keys: each rank's unique bytes repeat count times — sequential
        // writes from one cached source row
        if (wk >= 0) {
            int64_t kpos = 0;
            for (int64_t r = 0; r < u; r++) {
                const UniqTable::Entry& e =
                    table.entries[dr.items[(size_t)r].idx];
                const uint8_t* src = kb + (int64_t)e.rec * wk;
                for (int64_t c = 0; c < e.count; c++) {
                    copy_row(out_kb + kpos, src, wk);
                    kpos += wk;
                }
                part_counts[e.part] += e.count;
            }
            for (int64_t i = 0; i <= n; i++) out_ko[i] = i * wk;
        } else {
            int64_t kpos = 0, row = 0;
            for (int64_t r = 0; r < u; r++) {
                const UniqTable::Entry& e =
                    table.entries[dr.items[(size_t)r].idx];
                const uint8_t* src = kb + ko[e.rec];
                int64_t len = ko[e.rec + 1] - ko[e.rec];
                for (int64_t c = 0; c < e.count; c++) {
                    if (len > 0)
                        std::memcpy(out_kb + kpos, src, (size_t)len);
                    kpos += len;
                    out_ko[++row] = kpos;
                }
                part_counts[e.part] += e.count;
            }
        }
        if (out_parts != nullptr) {
            for (int64_t r = 0; r < u; r++) {
                const UniqTable::Entry& e =
                    table.entries[dr.items[(size_t)r].idx];
                std::fill(out_parts + dr.start[(size_t)r],
                          out_parts + dr.start[(size_t)r] + e.count, e.part);
            }
        }
        // values: stable scatter straight into output slots (no
        // intermediate permutation array for fixed-width values)
        if (wv >= 0) {
            std::vector<int64_t> cursor(dr.start);
            for (int64_t i = 0; i < n; i++) {
                int64_t slot = cursor[dr.rank[dr.uids[(size_t)i]]]++;
                copy_row(out_vb + slot * wv, vb + i * wv, wv);
            }
            for (int64_t i = 0; i <= n; i++) out_vo[i] = i * wv;
        } else {
            std::vector<int64_t> perm((size_t)n);
            dr.fill_perm(n, perm.data());
            int64_t vpos = 0;
            for (int64_t j = 0; j < n; j++) {
                int64_t i = perm[(size_t)j];
                int64_t len = vo[i + 1] - vo[i];
                if (len > 0)
                    std::memcpy(out_vb + vpos, vb + vo[i], (size_t)len);
                vpos += len;
                out_vo[j + 1] = vpos;
            }
        }
        return 0;
    }

    // ---- direct path: counting sort by partition + item sort
    std::vector<int32_t> computed;
    if (derive) {
        computed.resize((size_t)n);
        tz_fnv32_partition(kb, ko, n, num_partitions, computed.data(),
                           n_threads);
        parts = computed.data();
    }
    std::vector<Item> items((size_t)n);
    int64_t nparts = 1;
    std::vector<int64_t> pstart;
    if (parts != nullptr) {
        nparts = num_partitions;
        pstart.assign((size_t)nparts + 1, 0);
        for (int64_t i = 0; i < n; i++) pstart[parts[i] + 1]++;
        for (int64_t p = 0; p < nparts; p++) pstart[p + 1] += pstart[p];
        std::vector<int64_t> cur(pstart.begin(), pstart.end() - 1);
        for (int64_t i = 0; i < n; i++) {
            uint64_t pre; uint32_t pre2;
            key_prefix12(kb + ko[i], ko[i + 1] - ko[i], pre, pre2);
            items[(size_t)cur[parts[i]]++] = {pre, pre2, (uint32_t)i};
        }
        for (int64_t p = 0; p < nparts; p++)
            part_counts[p] = pstart[p + 1] - pstart[p];
    } else {
        pstart = {0, n};
        for (int64_t i = 0; i < n; i++) {
            uint64_t pre; uint32_t pre2;
            key_prefix12(kb + ko[i], ko[i + 1] - ko[i], pre, pre2);
            items[(size_t)i] = {pre, pre2, (uint32_t)i};
        }
        part_counts[0] = n;
    }
    ItemCmp cmp{kb, ko};
    parallel_sort_ranges(items, pstart, nparts, n,
                         std::max(1, (int)n_threads), cmp);

    if (wk >= 0) {
        for (int64_t j = 0; j < n; j++)
            copy_row(out_kb + j * wk, kb + (int64_t)items[(size_t)j].idx * wk,
                     wk);
        for (int64_t i = 0; i <= n; i++) out_ko[i] = i * wk;
    } else {
        int64_t kpos = 0;
        for (int64_t j = 0; j < n; j++) {
            int64_t i = items[(size_t)j].idx;
            int64_t len = ko[i + 1] - ko[i];
            if (len > 0) std::memcpy(out_kb + kpos, kb + ko[i], (size_t)len);
            kpos += len;
            out_ko[j + 1] = kpos;
        }
    }
    if (out_parts != nullptr) {
        if (parts != nullptr) {
            for (int64_t j = 0; j < n; j++)
                out_parts[j] = parts[items[(size_t)j].idx];
        } else {
            std::fill(out_parts, out_parts + n, 0);
        }
    }
    if (wv >= 0) {
        for (int64_t j = 0; j < n; j++)
            copy_row(out_vb + j * wv, vb + (int64_t)items[(size_t)j].idx * wv,
                     wv);
        for (int64_t i = 0; i <= n; i++) out_vo[i] = i * wv;
    } else {
        int64_t vpos = 0;
        for (int64_t j = 0; j < n; j++) {
            int64_t i = items[(size_t)j].idx;
            int64_t len = vo[i + 1] - vo[i];
            if (len > 0) std::memcpy(out_vb + vpos, vb + vo[i], (size_t)len);
            vpos += len;
            out_vo[j + 1] = vpos;
        }
    }
    return 0;
}

// Fused k-run merge + materialization (round 5): the runs are already
// (partition, key)-sorted with equal keys adjacent, so the merge works on
// GROUPS — per run, scan adjacent rows into (partition, key)-groups, then
// k-way merge the group heads and emit each winning group as ONE
// contiguous segment copy from its source run (sequential reads, no
// per-row gather, no concatenation).  Equal (partition, key) groups
// across runs emit in run order == concatenation order (MergeQueue age
// semantics, TezMerger.java:76).
//   row_indices[r] : int64[num_partitions+1] partition bounds of run r
//   part_counts    : int64[num_partitions], zeroed and filled here
// Returns 0 on success.
int32_t tz_merge_emit(
        int32_t num_runs,
        const uint8_t** kbs, const int64_t** kos,
        const uint8_t** vbs, const int64_t** vos,
        const int64_t* nrows, const int64_t** row_indices,
        int32_t num_partitions,
        uint8_t* out_kb, int64_t* out_ko,
        uint8_t* out_vb, int64_t* out_vo,
        int32_t* out_parts, int64_t* part_counts, int32_t n_threads) {
    (void)n_threads;
    for (int32_t p = 0; p < num_partitions; p++) part_counts[p] = 0;
    out_ko[0] = 0;
    out_vo[0] = 0;

    // group scan per run: starts[] row indices where a new (partition,
    // key) group begins; gparts[] the group's partition
    struct RunGroups {
        std::vector<int64_t> starts;   // group start rows, + nrows sentinel
        std::vector<int32_t> gparts;
    };
    std::vector<RunGroups> groups((size_t)num_runs);
    for (int32_t r = 0; r < num_runs; r++) {
        int64_t m = nrows[r];
        auto& g = groups[(size_t)r];
        if (m == 0) { g.starts.push_back(0); continue; }
        const int64_t* ko = kos[r];
        const uint8_t* kb = kbs[r];
        const int64_t* ri = row_indices[r];
        g.starts.reserve(1024);
        g.gparts.reserve(1024);
        for (int32_t p = 0; p < num_partitions; p++) {
            int64_t lo = ri[p], hi = ri[p + 1];
            for (int64_t i = lo; i < hi; i++) {
                if (i == lo) {
                    g.starts.push_back(i);
                    g.gparts.push_back(p);
                    continue;
                }
                int64_t la = ko[i] - ko[i - 1];
                int64_t lb = ko[i + 1] - ko[i];
                if (la != lb ||
                    std::memcmp(kb + ko[i - 1], kb + ko[i],
                                (size_t)lb) != 0) {
                    g.starts.push_back(i);
                    g.gparts.push_back(p);
                }
            }
        }
        g.starts.push_back(m);
    }

    // head state per run: cached (part, prefix12) of the current group key
    struct Head {
        int64_t gi;          // group index
        int32_t part;
        uint64_t pre;
        uint32_t pre2;
    };
    std::vector<Head> heads((size_t)num_runs);
    auto load_head = [&](int32_t r) {
        auto& g = groups[(size_t)r];
        Head& h = heads[(size_t)r];
        if (h.gi >= (int64_t)g.gparts.size()) return;
        int64_t row = g.starts[(size_t)h.gi];
        const int64_t* ko = kos[r];
        key_prefix12(kbs[r] + ko[row], ko[row + 1] - ko[row], h.pre, h.pre2);
        h.part = g.gparts[(size_t)h.gi];
    };
    for (int32_t r = 0; r < num_runs; r++) {
        heads[(size_t)r].gi = 0;
        load_head(r);
    }

    // full-key compare for heads whose prefix12 ties (keys > 12 bytes)
    auto head_less = [&](int32_t a, int32_t b) {
        const Head& ha = heads[(size_t)a];
        const Head& hb = heads[(size_t)b];
        if (ha.part != hb.part) return ha.part < hb.part;
        if (ha.pre != hb.pre) return ha.pre < hb.pre;
        if (ha.pre2 != hb.pre2) return ha.pre2 < hb.pre2;
        int64_t rowa = groups[(size_t)a].starts[(size_t)ha.gi];
        int64_t rowb = groups[(size_t)b].starts[(size_t)hb.gi];
        const int64_t* koa = kos[a];
        const int64_t* kob = kos[b];
        int64_t la = koa[rowa + 1] - koa[rowa];
        int64_t lb = kob[rowb + 1] - kob[rowb];
        if (la > 12 && lb > 12) {
            int64_t m = (la < lb ? la : lb) - 12;
            int c = std::memcmp(kbs[a] + koa[rowa] + 12,
                                kbs[b] + kob[rowb] + 12, (size_t)m);
            if (c) return c < 0;
        }
        if (la != lb) return la < lb;
        return false;   // equal keys: caller keeps lower run index
    };

    // group selection: O(log k) binary min-heap of run indices (linear
    // scan for tiny k, where its constants win).  Equal (partition, key)
    // heads pop in run-index order — the MergeQueue age tie-break.
    auto run_after = [&](int32_t a, int32_t b) {
        // priority_queue order: true when a emits AFTER b
        if (head_less(b, a)) return true;
        if (head_less(a, b)) return false;
        return a > b;
    };
    std::vector<int32_t> heap;
    heap.reserve((size_t)num_runs);
    bool use_heap = num_runs > 4;
    if (use_heap) {
        for (int32_t r = 0; r < num_runs; r++)
            if (heads[(size_t)r].gi <
                (int64_t)groups[(size_t)r].gparts.size())
                heap.push_back(r);
        std::make_heap(heap.begin(), heap.end(), run_after);
    }
    int64_t kpos = 0, vpos = 0, row_out = 0;
    while (true) {
        int32_t best = -1;
        if (use_heap) {
            if (heap.empty()) break;
            std::pop_heap(heap.begin(), heap.end(), run_after);
            best = heap.back();
            heap.pop_back();
        } else {
            for (int32_t r = 0; r < num_runs; r++) {
                if (heads[(size_t)r].gi >=
                    (int64_t)groups[(size_t)r].gparts.size()) continue;
                if (best < 0 || head_less(r, best)) best = r;
            }
            if (best < 0) break;
        }
        auto& g = groups[(size_t)best];
        Head& h = heads[(size_t)best];
        int64_t s = g.starts[(size_t)h.gi];
        int64_t e = g.starts[(size_t)h.gi + 1];
        const int64_t* ko = kos[best];
        const int64_t* vo = vos[best];
        int64_t kbytes = ko[e] - ko[s];
        int64_t vbytes = vo[e] - vo[s];
        std::memcpy(out_kb + kpos, kbs[best] + ko[s], (size_t)kbytes);
        std::memcpy(out_vb + vpos, vbs[best] + vo[s], (size_t)vbytes);
        int64_t kbase = kpos - ko[s];
        int64_t vbase = vpos - vo[s];
        if (out_parts != nullptr)
            std::fill(out_parts + row_out, out_parts + row_out + (e - s),
                      h.part);
        for (int64_t i = s; i < e; i++) {
            out_ko[row_out + 1] = ko[i + 1] + kbase;
            out_vo[row_out + 1] = vo[i + 1] + vbase;
            row_out++;
        }
        part_counts[h.part] += e - s;
        kpos += kbytes;
        vpos += vbytes;
        h.gi++;
        load_head(best);
        if (use_heap &&
            h.gi < (int64_t)groups[(size_t)best].gparts.size()) {
            heap.push_back(best);
            std::push_heap(heap.begin(), heap.end(), run_after);
        }
    }
    return 0;
}

// Permute FIXED-width rows: out[i] = data[perm[i]*row_len : +row_len].
// The ragged gather pays an offset lookup and a length-unknown memcpy per
// row; fixed width makes the copy a compile-time-size move for the common
// serde widths (8/12/16).
void gather_fixed_u8(const uint8_t* data, int64_t row_len,
                     const int64_t* perm, int64_t n, uint8_t* out,
                     int32_t n_threads) {
    if (n <= 0 || row_len <= 0) return;
    int threads = std::max(1, (int)n_threads);
    auto body = [=](int64_t lo, int64_t hi) {
        switch (row_len) {
        case 8:
            for (int64_t i = lo; i < hi; i++)
                std::memcpy(out + i * 8, data + perm[i] * 8, 8);
            break;
        case 12:
            for (int64_t i = lo; i < hi; i++)
                std::memcpy(out + i * 12, data + perm[i] * 12, 12);
            break;
        case 16:
            for (int64_t i = lo; i < hi; i++)
                std::memcpy(out + i * 16, data + perm[i] * 16, 16);
            break;
        default:
            for (int64_t i = lo; i < hi; i++)
                std::memcpy(out + i * row_len, data + perm[i] * row_len,
                            (size_t)row_len);
        }
    };
    if (threads == 1 || n < (1 << 16)) {
        body(0, n);
        return;
    }
    std::vector<std::thread> pool;
    int64_t per = (n + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        int64_t lo = t * per, hi = std::min<int64_t>(n, lo + per);
        if (lo >= hi) break;
        pool.emplace_back(body, lo, hi);
    }
    for (auto& th : pool) th.join();
}

}  // extern "C"
