// Native host ops for the tez_tpu data plane.
//
// The reference's byte-crunching data path is JVM code (SURVEY.md: the
// performance-critical path is plain Java over byte[]); here the device
// kernels do the heavy lifting and the host side only permutes/concatenates
// ragged byte arrays when materializing runs.  That gather is memory-bound
// and single-threaded in numpy (fancy indexing builds an index array of one
// int64 per BYTE); this C++ version does per-row memcpy across threads and
// skips the index materialization entirely.
//
// Build: make -C native   (g++ -O3 -shared; loaded via ctypes, with a numpy
// fallback when the .so is missing).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

// Permute rows of a ragged u8 array.
//   data/offsets     : source (n_src rows; offsets has n_src+1 entries)
//   perm             : n_out row indices into the source
//   out_offsets      : n_out+1 entries, PRECOMPUTED by the caller
//   out_data         : out_offsets[n_out] bytes
void gather_ragged_u8(const uint8_t* data, const int64_t* offsets,
                      const int64_t* perm, int64_t n_out,
                      const int64_t* out_offsets, uint8_t* out_data,
                      int32_t n_threads) {
    if (n_out <= 0) return;
    int threads = std::max(1, (int)n_threads);
    int64_t total = out_offsets[n_out];
    // Partition output rows so each thread copies ~equal BYTES, not rows
    // (row sizes are ragged; equal-row chunks would skew badly).
    std::vector<std::thread> pool;
    pool.reserve(threads);
    int64_t bytes_per_thread = (total + threads - 1) / threads;
    int64_t row = 0;
    for (int t = 0; t < threads && row < n_out; t++) {
        int64_t start_row = row;
        int64_t target = std::min(total, (int64_t)(t + 1) * bytes_per_thread);
        // advance to the first row whose start offset reaches the target
        while (row < n_out && out_offsets[row] < target) row++;
        int64_t end_row = row;
        pool.emplace_back([=]() {
            for (int64_t i = start_row; i < end_row; i++) {
                int64_t src = perm[i];
                int64_t len = offsets[src + 1] - offsets[src];
                if (len > 0) {
                    std::memcpy(out_data + out_offsets[i],
                                data + offsets[src], (size_t)len);
                }
            }
        });
    }
    for (auto& th : pool) th.join();
}

// Concatenate ragged u8 arrays: caller passes flattened descriptor arrays.
void concat_ragged_u8(const uint8_t** datas, const int64_t* sizes,
                      int64_t n_parts, uint8_t* out_data,
                      int32_t n_threads) {
    std::vector<int64_t> starts(n_parts + 1, 0);
    for (int64_t i = 0; i < n_parts; i++) starts[i + 1] = starts[i] + sizes[i];
    int threads = std::max(1, (int)n_threads);
    std::vector<std::thread> pool;
    int64_t per = (n_parts + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        int64_t lo = t * per, hi = std::min<int64_t>(n_parts, lo + per);
        if (lo >= hi) break;
        pool.emplace_back([=, &starts]() {
            for (int64_t i = lo; i < hi; i++) {
                if (sizes[i] > 0)
                    std::memcpy(out_data + starts[i], datas[i],
                                (size_t)sizes[i]);
            }
        });
    }
    for (auto& th : pool) th.join();
}


// Adjacent-row equality over a ragged u8 array: for each candidate index
// cand[j] (caller guarantees rows cand[j] and cand[j]+1 have equal byte
// length), out[j] = 1 iff the two rows' bytes match.  Per-pair memcmp
// across threads — the numpy formulation materializes an int64 index per
// BYTE (8x expansion) on the grouping/combine hot path.
void adjacent_equal_u8(const uint8_t* data, const int64_t* offsets,
                       const int64_t* cand, int64_t n_cand,
                       uint8_t* out, int32_t n_threads) {
    if (n_cand <= 0) return;
    int threads = std::max(1, (int)n_threads);
    std::vector<std::thread> pool;
    int64_t per = (n_cand + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        int64_t lo = t * per, hi = std::min<int64_t>(n_cand, lo + per);
        if (lo >= hi) break;
        pool.emplace_back([=]() {
            for (int64_t j = lo; j < hi; j++) {
                int64_t i = cand[j];
                int64_t len = offsets[i + 1] - offsets[i];
                out[j] = (len == 0) ||
                    std::memcmp(data + offsets[i], data + offsets[i + 1],
                                (size_t)len) == 0;
            }
        });
    }
    for (auto& th : pool) th.join();
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Key partitioning (host engine).  The span sort itself lives in
// spansort.cpp (v2: register-packed 12-byte prefixes + duplication-aware
// dedup-rank fast path).
// ---------------------------------------------------------------------------

extern "C" {

// 32-bit FNV-1a over each full key, mod num_partitions — must stay
// byte-identical to the device kernel and numpy host partitioner.
void tz_fnv32_partition(const uint8_t* key_bytes, const int64_t* key_offsets,
                        int64_t n, int32_t num_partitions, int32_t* parts,
                        int32_t n_threads) {
    if (n <= 0) return;
    int threads = std::max(1, (int)n_threads);
    std::vector<std::thread> pool;
    int64_t per = (n + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        int64_t lo = t * per, hi = std::min<int64_t>(n, lo + per);
        if (lo >= hi) break;
        pool.emplace_back([=]() {
            for (int64_t i = lo; i < hi; i++) {
                uint32_t h = 2166136261u;
                for (int64_t j = key_offsets[i]; j < key_offsets[i + 1]; j++) {
                    h ^= key_bytes[j];
                    h *= 16777619u;
                }
                parts[i] = (int32_t)(h % (uint32_t)num_partitions);
            }
        });
    }
    for (auto& th : pool) th.join();
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Hash aggregation (map-side combine).
//
// The reference runs its combiner AFTER the sort, over each spill
// (PipelinedSorter semantics); on TPU the economics invert — collapsing
// duplicate keys BEFORE the device sort shrinks the expensive step
// (pad/lanes/sort/gather) by the duplication factor.  These helpers give
// the host a C-speed open-addressing hash table for that pre-combine and
// for fused tokenize+count (the WordCount family's entire map task).
// ---------------------------------------------------------------------------

namespace {

inline uint64_t fnv1a(const uint8_t* p, int64_t len) {
    uint64_t h = 1469598103934665603ull;
    for (int64_t i = 0; i < len; i++) {
        h ^= p[i];
        h *= 1099511628211ull;
    }
    return h;
}

// Open-addressing table mapping byte-string keys -> int64 accumulator.
// Keys are appended to an arena on first occurrence; emit order is
// first-occurrence order (deterministic for a given input).
struct HashAgg {
    std::vector<int64_t> table;      // entry index + 1; 0 = empty
    struct Entry { uint64_t hash; int64_t off; int32_t len; int64_t acc; };
    std::vector<Entry> entries;
    std::vector<uint8_t> arena;
    uint64_t mask;

    HashAgg() : table(1 << 12, 0), mask((1 << 12) - 1) {}

    void grow() {
        size_t ns = table.size() * 2;
        std::vector<int64_t>(ns, 0).swap(table);
        mask = ns - 1;
        for (size_t e = 0; e < entries.size(); e++) {
            uint64_t slot = entries[e].hash & mask;
            while (table[slot]) slot = (slot + 1) & mask;
            table[slot] = (int64_t)e + 1;
        }
    }

    void add(const uint8_t* key, int64_t len, int64_t value) {
        uint64_t h = fnv1a(key, len);
        uint64_t slot = h & mask;
        while (true) {
            int64_t idx = table[slot];
            if (idx == 0) break;
            const Entry& e = entries[idx - 1];
            if (e.hash == h && e.len == len &&
                std::memcmp(arena.data() + e.off, key, (size_t)len) == 0) {
                entries[idx - 1].acc += value;
                return;
            }
            slot = (slot + 1) & mask;
        }
        int64_t off = (int64_t)arena.size();
        arena.insert(arena.end(), key, key + len);
        entries.push_back({h, off, (int32_t)len, value});
        table[slot] = (int64_t)entries.size();
        if (entries.size() * 10 > table.size() * 7) grow();
    }
};

}  // namespace

extern "C" {

// --- fused tokenize + count (stateful across feeds) ----------------------
// Contract: each feed() is whitespace-complete (the text reader yields
// line-aligned chunks), so tokens never span feed boundaries.

// bytes.split() whitespace set: space \t \n \v \f \r
static inline bool tz_is_ws(uint8_t c) {
    return c == 32 || (c >= 9 && c <= 13);
}

void* tz_wc_create() { return new HashAgg(); }

void tz_wc_feed(void* handle, const uint8_t* data, int64_t n) {
    HashAgg* agg = (HashAgg*)handle;
    int64_t i = 0;
    while (i < n) {
        while (i < n && tz_is_ws(data[i])) i++;
        int64_t start = i;
        while (i < n && !tz_is_ws(data[i])) i++;
        if (i > start) agg->add(data + start, i - start, 1);
    }
}

void tz_wc_stats(void* handle, int64_t* n_unique, int64_t* total_key_bytes) {
    HashAgg* agg = (HashAgg*)handle;
    *n_unique = (int64_t)agg->entries.size();
    *total_key_bytes = (int64_t)agg->arena.size();
}

// key_offsets: n_unique+1 entries; key_bytes: arena size; counts: n_unique
void tz_wc_emit(void* handle, uint8_t* key_bytes, int64_t* key_offsets,
                int64_t* counts) {
    HashAgg* agg = (HashAgg*)handle;
    std::memcpy(key_bytes, agg->arena.data(), agg->arena.size());
    int64_t off = 0;
    for (size_t e = 0; e < agg->entries.size(); e++) {
        key_offsets[e] = off;
        off += agg->entries[e].len;
        counts[e] = agg->entries[e].acc;
    }
    key_offsets[agg->entries.size()] = off;
}

void tz_wc_destroy(void* handle) { delete (HashAgg*)handle; }

// --- raw whitespace split (no combine): one pass, compacted words --------
// out_bytes: caller-allocated n bytes (worst case: no whitespace);
// out_offsets: caller-allocated (n+1)/2 + 2 entries.  Returns word count.
int64_t tz_split_ws(const uint8_t* data, int64_t n, uint8_t* out_bytes,
                    int64_t* out_offsets) {
    int64_t words = 0, out = 0, i = 0;
    out_offsets[0] = 0;
    while (i < n) {
        while (i < n && tz_is_ws(data[i])) i++;
        int64_t start = i;
        while (i < n && !tz_is_ws(data[i])) i++;
        if (i > start) {
            std::memcpy(out_bytes + out, data + start, (size_t)(i - start));
            out += i - start;
            out_offsets[++words] = out;
        }
    }
    return words;
}

// --- generic pre-sort combine: sum int64 values of equal keys -------------
// first_idx[u] = record index of key u's first occurrence (caller gathers
// the key bytes); sums[u] = total value.  Both sized n by the caller.
// Returns the number of unique keys.
int64_t hash_sum_i64(const uint8_t* key_bytes, const int64_t* key_offsets,
                     int64_t n, const int64_t* values,
                     int64_t* first_idx, int64_t* sums) {
    HashAgg agg;
    // remember first-occurrence record index per unique key: the arena
    // offset uniquely identifies the entry, so track indices alongside
    std::vector<int64_t> firsts;
    firsts.reserve(1024);
    for (int64_t i = 0; i < n; i++) {
        size_t before = agg.entries.size();
        agg.add(key_bytes + key_offsets[i],
                key_offsets[i + 1] - key_offsets[i], values[i]);
        if (agg.entries.size() > before) firsts.push_back(i);
    }
    for (size_t u = 0; u < agg.entries.size(); u++) {
        first_idx[u] = firsts[u];
        sums[u] = agg.entries[u].acc;
    }
    return (int64_t)agg.entries.size();
}

}  // extern "C"
