// Native host ops for the tez_tpu data plane.
//
// The reference's byte-crunching data path is JVM code (SURVEY.md: the
// performance-critical path is plain Java over byte[]); here the device
// kernels do the heavy lifting and the host side only permutes/concatenates
// ragged byte arrays when materializing runs.  That gather is memory-bound
// and single-threaded in numpy (fancy indexing builds an index array of one
// int64 per BYTE); this C++ version does per-row memcpy across threads and
// skips the index materialization entirely.
//
// Build: make -C native   (g++ -O3 -shared; loaded via ctypes, with a numpy
// fallback when the .so is missing).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

// Permute rows of a ragged u8 array.
//   data/offsets     : source (n_src rows; offsets has n_src+1 entries)
//   perm             : n_out row indices into the source
//   out_offsets      : n_out+1 entries, PRECOMPUTED by the caller
//   out_data         : out_offsets[n_out] bytes
void gather_ragged_u8(const uint8_t* data, const int64_t* offsets,
                      const int64_t* perm, int64_t n_out,
                      const int64_t* out_offsets, uint8_t* out_data,
                      int32_t n_threads) {
    if (n_out <= 0) return;
    int threads = std::max(1, (int)n_threads);
    int64_t total = out_offsets[n_out];
    // Partition output rows so each thread copies ~equal BYTES, not rows
    // (row sizes are ragged; equal-row chunks would skew badly).
    std::vector<std::thread> pool;
    pool.reserve(threads);
    int64_t bytes_per_thread = (total + threads - 1) / threads;
    int64_t row = 0;
    for (int t = 0; t < threads && row < n_out; t++) {
        int64_t start_row = row;
        int64_t target = std::min(total, (int64_t)(t + 1) * bytes_per_thread);
        // advance to the first row whose start offset reaches the target
        while (row < n_out && out_offsets[row] < target) row++;
        int64_t end_row = row;
        pool.emplace_back([=]() {
            for (int64_t i = start_row; i < end_row; i++) {
                int64_t src = perm[i];
                int64_t len = offsets[src + 1] - offsets[src];
                if (len > 0) {
                    std::memcpy(out_data + out_offsets[i],
                                data + offsets[src], (size_t)len);
                }
            }
        });
    }
    for (auto& th : pool) th.join();
}

// Concatenate ragged u8 arrays: caller passes flattened descriptor arrays.
void concat_ragged_u8(const uint8_t** datas, const int64_t* sizes,
                      int64_t n_parts, uint8_t* out_data,
                      int32_t n_threads) {
    std::vector<int64_t> starts(n_parts + 1, 0);
    for (int64_t i = 0; i < n_parts; i++) starts[i + 1] = starts[i] + sizes[i];
    int threads = std::max(1, (int)n_threads);
    std::vector<std::thread> pool;
    int64_t per = (n_parts + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        int64_t lo = t * per, hi = std::min<int64_t>(n_parts, lo + per);
        if (lo >= hi) break;
        pool.emplace_back([=, &starts]() {
            for (int64_t i = lo; i < hi; i++) {
                if (sizes[i] > 0)
                    std::memcpy(out_data + starts[i], datas[i],
                                (size_t)sizes[i]);
            }
        });
    }
    for (auto& th : pool) th.join();
}


// Adjacent-row equality over a ragged u8 array: for each candidate index
// cand[j] (caller guarantees rows cand[j] and cand[j]+1 have equal byte
// length), out[j] = 1 iff the two rows' bytes match.  Per-pair memcmp
// across threads — the numpy formulation materializes an int64 index per
// BYTE (8x expansion) on the grouping/combine hot path.
void adjacent_equal_u8(const uint8_t* data, const int64_t* offsets,
                       const int64_t* cand, int64_t n_cand,
                       uint8_t* out, int32_t n_threads) {
    if (n_cand <= 0) return;
    int threads = std::max(1, (int)n_threads);
    std::vector<std::thread> pool;
    int64_t per = (n_cand + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        int64_t lo = t * per, hi = std::min<int64_t>(n_cand, lo + per);
        if (lo >= hi) break;
        pool.emplace_back([=]() {
            for (int64_t j = lo; j < hi; j++) {
                int64_t i = cand[j];
                int64_t len = offsets[i + 1] - offsets[i];
                out[j] = (len == 0) ||
                    std::memcmp(data + offsets[i], data + offsets[i + 1],
                                (size_t)len) == 0;
            }
        });
    }
    for (auto& th : pool) th.join();
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Span sort (host engine): partition + stable sort over ragged keys.
//
// The host twin of the device hash_sort_span kernel and the C-speed
// replacement for the numpy path (pad-to-matrix -> u32 lanes -> 6-key
// lexsort -> host tie-break).  Sorting row indices directly against the
// ragged key bytes needs no padded matrix, resolves ties exactly (full-key
// memcmp), and releases the GIL for the whole call (ctypes), so concurrent
// producer tasks in one process actually overlap — the reference gets this
// for free from JVM threads (PipelinedSorter sortmaster); numpy never does.
// ---------------------------------------------------------------------------

namespace {

// Big-endian zero-padded first-8-bytes prefix: unsigned compare of prefixes
// orders like memcmp of the first 8 bytes.
inline uint64_t key_prefix(const uint8_t* p, int64_t len) {
    uint64_t v = 0;
    int64_t m = len < 8 ? len : 8;
    for (int64_t i = 0; i < m; i++) v |= (uint64_t)p[i] << (56 - 8 * i);
    return v;
}

}  // namespace

extern "C" {

// 32-bit FNV-1a over each full key, mod num_partitions — must stay
// byte-identical to the device kernel and numpy host partitioner.
void tz_fnv32_partition(const uint8_t* key_bytes, const int64_t* key_offsets,
                        int64_t n, int32_t num_partitions, int32_t* parts,
                        int32_t n_threads) {
    if (n <= 0) return;
    int threads = std::max(1, (int)n_threads);
    std::vector<std::thread> pool;
    int64_t per = (n + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        int64_t lo = t * per, hi = std::min<int64_t>(n, lo + per);
        if (lo >= hi) break;
        pool.emplace_back([=]() {
            for (int64_t i = lo; i < hi; i++) {
                uint32_t h = 2166136261u;
                for (int64_t j = key_offsets[i]; j < key_offsets[i + 1]; j++) {
                    h ^= key_bytes[j];
                    h *= 16777619u;
                }
                parts[i] = (int32_t)(h % (uint32_t)num_partitions);
            }
        });
    }
    for (auto& th : pool) th.join();
}

// Stable sort permutation of rows by (partition, key bytes).  partitions
// may be null (single-partition sort, e.g. run merges).
//
// Shape chosen for cache behavior, which dominates on big spans: first a
// stable COUNTING sort by partition (two O(n) passes), then per
// partition a VALUE sort of packed 16-byte {prefix, idx} items — the hot
// comparison touches one contiguous array instead of chasing three
// indirections per compare; full-key compares run only on prefix ties.
// Partition ranges sort across a thread pool (no-op on 1 core, real
// parallelism elsewhere).
void tz_sort_partition_keys(const uint8_t* key_bytes,
                            const int64_t* key_offsets,
                            const int32_t* partitions, int64_t n,
                            int64_t* perm, int32_t n_threads) {
    if (n <= 0) return;
    struct Item { uint64_t prefix; int64_t idx; };
    std::vector<Item> items((size_t)n);

    // partition grouping (stable): count, prefix-sum, scatter
    int64_t nparts = 1;
    std::vector<int64_t> pstart;
    if (partitions != nullptr) {
        int32_t maxp = 0;
        for (int64_t i = 0; i < n; i++)
            if (partitions[i] > maxp) maxp = partitions[i];
        nparts = (int64_t)maxp + 1;
        pstart.assign((size_t)nparts + 1, 0);
        for (int64_t i = 0; i < n; i++) pstart[partitions[i] + 1]++;
        for (int64_t p = 0; p < nparts; p++) pstart[p + 1] += pstart[p];
        std::vector<int64_t> cur(pstart.begin(), pstart.end() - 1);
        for (int64_t i = 0; i < n; i++) {
            items[(size_t)cur[partitions[i]]++] = {
                key_prefix(key_bytes + key_offsets[i],
                           key_offsets[i + 1] - key_offsets[i]), i};
        }
    } else {
        pstart = {0, n};
        for (int64_t i = 0; i < n; i++)
            items[(size_t)i] = {
                key_prefix(key_bytes + key_offsets[i],
                           key_offsets[i + 1] - key_offsets[i]), i};
    }

    auto cmp = [&](const Item& a, const Item& b) {
        if (a.prefix != b.prefix) return a.prefix < b.prefix;
        int64_t la = key_offsets[a.idx + 1] - key_offsets[a.idx];
        int64_t lb = key_offsets[b.idx + 1] - key_offsets[b.idx];
        if (la > 8 && lb > 8) {
            int64_t m = (la < lb ? la : lb) - 8;
            int c = std::memcmp(key_bytes + key_offsets[a.idx] + 8,
                                key_bytes + key_offsets[b.idx] + 8,
                                (size_t)m);
            if (c) return c < 0;
        }
        if (la != lb) return la < lb;
        return a.idx < b.idx;          // total order == stable result
    };
    int threads = std::max(1, (int)n_threads);
    if (threads == 1 || n < (1 << 15)) {
        // below the threshold thread spawn/join costs more than the sort
        for (int64_t p = 0; p < nparts; p++)
            std::sort(items.begin() + pstart[p],
                      items.begin() + pstart[p + 1], cmp);
    } else {
        // two-level parallelism: each partition range splits into
        // ~equal chunks (so ONE dominant partition — or the
        // single-partition run-merge case — still uses every thread),
        // chunks sort on a pool, then each level of pairwise
        // inplace_merges runs on the pool across all partitions.
        struct Range { int64_t lo, hi; };
        int64_t target = std::max<int64_t>(1 << 15,
                                           n / threads / 2 + 1);
        std::vector<std::vector<int64_t>> chunk_bounds((size_t)nparts);
        std::vector<Range> jobs;
        for (int64_t p = 0; p < nparts; p++) {
            int64_t lo = pstart[p], hi = pstart[p + 1];
            int64_t len = hi - lo;
            int64_t k = std::max<int64_t>(1, (len + target - 1) / target);
            auto& cb = chunk_bounds[(size_t)p];
            cb.resize((size_t)k + 1);
            for (int64_t c = 0; c <= k; c++) cb[(size_t)c] = lo + len * c / k;
            for (int64_t c = 0; c < k; c++)
                jobs.push_back({cb[(size_t)c], cb[(size_t)c + 1]});
        }
        auto run_jobs = [&](auto&& fn) {
            std::atomic<size_t> next(0);
            std::vector<std::thread> pool;
            int nt = std::min<int64_t>(threads, (int64_t)jobs.size());
            for (int t = 0; t < nt; t++)
                pool.emplace_back([&]() {
                    for (size_t j; (j = next.fetch_add(1)) < jobs.size();)
                        fn(jobs[j]);
                });
            for (auto& th : pool) th.join();
        };
        run_jobs([&](const Range& r) {
            std::sort(items.begin() + r.lo, items.begin() + r.hi, cmp);
        });
        // merge ladders, one level at a time across every partition
        struct MJob { int64_t lo, mid, hi; };
        for (int64_t step = 1;; step *= 2) {
            std::vector<MJob> mjobs;
            for (int64_t p = 0; p < nparts; p++) {
                auto& cb = chunk_bounds[(size_t)p];
                int64_t k = (int64_t)cb.size() - 1;
                for (int64_t c = 0; c + step < k; c += 2 * step) {
                    int64_t hi_idx = std::min<int64_t>(k, c + 2 * step);
                    mjobs.push_back({cb[(size_t)c], cb[(size_t)(c + step)],
                                     cb[(size_t)hi_idx]});
                }
            }
            if (mjobs.empty()) break;
            std::atomic<size_t> next(0);
            std::vector<std::thread> pool;
            int nt = std::min<int64_t>(threads, (int64_t)mjobs.size());
            for (int t = 0; t < nt; t++)
                pool.emplace_back([&]() {
                    for (size_t j; (j = next.fetch_add(1)) < mjobs.size();)
                        std::inplace_merge(items.begin() + mjobs[j].lo,
                                           items.begin() + mjobs[j].mid,
                                           items.begin() + mjobs[j].hi,
                                           cmp);
                });
            for (auto& th : pool) th.join();
        }
    }
    for (int64_t i = 0; i < n; i++) perm[i] = items[(size_t)i].idx;
}

// Merge k (partition, key)-sorted runs into one stable permutation.
// Rows are the CONCATENATION of the runs; run_bounds has k+1 entries.
// Exploits sortedness: items build in one pass, then a ladder of
// inplace_merges over run segments — O(n log k) with cache-friendly 16-byte
// items instead of a full O(n log n) sort (TezMerger's economics, value
// form).  Equal (partition, key) rows keep concatenation order == run age
// order (MergeQueue semantics).
void tz_merge_runs(const uint8_t* key_bytes, const int64_t* key_offsets,
                   const int32_t* partitions, const int64_t* run_bounds,
                   int32_t num_runs, int64_t* perm, int32_t n_threads) {
    int64_t n = run_bounds[num_runs];
    if (n <= 0) return;
    struct Item { uint64_t prefix; int64_t idx; };
    std::vector<Item> items((size_t)n);
    for (int64_t i = 0; i < n; i++)
        items[(size_t)i] = {key_prefix(key_bytes + key_offsets[i],
                                       key_offsets[i + 1] - key_offsets[i]),
                            i};
    auto cmp = [&](const Item& a, const Item& b) {
        if (partitions != nullptr && partitions[a.idx] != partitions[b.idx])
            return partitions[a.idx] < partitions[b.idx];
        if (a.prefix != b.prefix) return a.prefix < b.prefix;
        int64_t la = key_offsets[a.idx + 1] - key_offsets[a.idx];
        int64_t lb = key_offsets[b.idx + 1] - key_offsets[b.idx];
        if (la > 8 && lb > 8) {
            int64_t m = (la < lb ? la : lb) - 8;
            int c = std::memcmp(key_bytes + key_offsets[a.idx] + 8,
                                key_bytes + key_offsets[b.idx] + 8,
                                (size_t)m);
            if (c) return c < 0;
        }
        if (la != lb) return la < lb;
        return a.idx < b.idx;
    };
    int threads = std::max(1, (int)n_threads);
    for (int64_t step = 1; step < num_runs; step *= 2) {
        // each level's merges touch disjoint segments: run them on a pool
        struct MJob { int64_t lo, mid, hi; };
        std::vector<MJob> jobs;
        for (int64_t r = 0; r + step < num_runs; r += 2 * step) {
            int64_t hi = std::min<int64_t>(num_runs, r + 2 * step);
            jobs.push_back({run_bounds[r], run_bounds[r + step],
                            run_bounds[hi]});
        }
        int nt = std::min<int64_t>(threads, (int64_t)jobs.size());
        if (nt <= 1 || n < (1 << 15)) {
            for (const MJob& j : jobs)
                std::inplace_merge(items.begin() + j.lo,
                                   items.begin() + j.mid,
                                   items.begin() + j.hi, cmp);
        } else {
            std::atomic<size_t> next(0);
            std::vector<std::thread> pool;
            for (int t = 0; t < nt; t++)
                pool.emplace_back([&]() {
                    for (size_t j; (j = next.fetch_add(1)) < jobs.size();)
                        std::inplace_merge(items.begin() + jobs[j].lo,
                                           items.begin() + jobs[j].mid,
                                           items.begin() + jobs[j].hi,
                                           cmp);
                });
            for (auto& th : pool) th.join();
        }
    }
    for (int64_t i = 0; i < n; i++) perm[i] = items[(size_t)i].idx;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Hash aggregation (map-side combine).
//
// The reference runs its combiner AFTER the sort, over each spill
// (PipelinedSorter semantics); on TPU the economics invert — collapsing
// duplicate keys BEFORE the device sort shrinks the expensive step
// (pad/lanes/sort/gather) by the duplication factor.  These helpers give
// the host a C-speed open-addressing hash table for that pre-combine and
// for fused tokenize+count (the WordCount family's entire map task).
// ---------------------------------------------------------------------------

namespace {

inline uint64_t fnv1a(const uint8_t* p, int64_t len) {
    uint64_t h = 1469598103934665603ull;
    for (int64_t i = 0; i < len; i++) {
        h ^= p[i];
        h *= 1099511628211ull;
    }
    return h;
}

// Open-addressing table mapping byte-string keys -> int64 accumulator.
// Keys are appended to an arena on first occurrence; emit order is
// first-occurrence order (deterministic for a given input).
struct HashAgg {
    std::vector<int64_t> table;      // entry index + 1; 0 = empty
    struct Entry { uint64_t hash; int64_t off; int32_t len; int64_t acc; };
    std::vector<Entry> entries;
    std::vector<uint8_t> arena;
    uint64_t mask;

    HashAgg() : table(1 << 12, 0), mask((1 << 12) - 1) {}

    void grow() {
        size_t ns = table.size() * 2;
        std::vector<int64_t>(ns, 0).swap(table);
        mask = ns - 1;
        for (size_t e = 0; e < entries.size(); e++) {
            uint64_t slot = entries[e].hash & mask;
            while (table[slot]) slot = (slot + 1) & mask;
            table[slot] = (int64_t)e + 1;
        }
    }

    void add(const uint8_t* key, int64_t len, int64_t value) {
        uint64_t h = fnv1a(key, len);
        uint64_t slot = h & mask;
        while (true) {
            int64_t idx = table[slot];
            if (idx == 0) break;
            const Entry& e = entries[idx - 1];
            if (e.hash == h && e.len == len &&
                std::memcmp(arena.data() + e.off, key, (size_t)len) == 0) {
                entries[idx - 1].acc += value;
                return;
            }
            slot = (slot + 1) & mask;
        }
        int64_t off = (int64_t)arena.size();
        arena.insert(arena.end(), key, key + len);
        entries.push_back({h, off, (int32_t)len, value});
        table[slot] = (int64_t)entries.size();
        if (entries.size() * 10 > table.size() * 7) grow();
    }
};

}  // namespace

extern "C" {

// --- fused tokenize + count (stateful across feeds) ----------------------
// Contract: each feed() is whitespace-complete (the text reader yields
// line-aligned chunks), so tokens never span feed boundaries.

// bytes.split() whitespace set: space \t \n \v \f \r
static inline bool tz_is_ws(uint8_t c) {
    return c == 32 || (c >= 9 && c <= 13);
}

void* tz_wc_create() { return new HashAgg(); }

void tz_wc_feed(void* handle, const uint8_t* data, int64_t n) {
    HashAgg* agg = (HashAgg*)handle;
    int64_t i = 0;
    while (i < n) {
        while (i < n && tz_is_ws(data[i])) i++;
        int64_t start = i;
        while (i < n && !tz_is_ws(data[i])) i++;
        if (i > start) agg->add(data + start, i - start, 1);
    }
}

void tz_wc_stats(void* handle, int64_t* n_unique, int64_t* total_key_bytes) {
    HashAgg* agg = (HashAgg*)handle;
    *n_unique = (int64_t)agg->entries.size();
    *total_key_bytes = (int64_t)agg->arena.size();
}

// key_offsets: n_unique+1 entries; key_bytes: arena size; counts: n_unique
void tz_wc_emit(void* handle, uint8_t* key_bytes, int64_t* key_offsets,
                int64_t* counts) {
    HashAgg* agg = (HashAgg*)handle;
    std::memcpy(key_bytes, agg->arena.data(), agg->arena.size());
    int64_t off = 0;
    for (size_t e = 0; e < agg->entries.size(); e++) {
        key_offsets[e] = off;
        off += agg->entries[e].len;
        counts[e] = agg->entries[e].acc;
    }
    key_offsets[agg->entries.size()] = off;
}

void tz_wc_destroy(void* handle) { delete (HashAgg*)handle; }

// --- raw whitespace split (no combine): one pass, compacted words --------
// out_bytes: caller-allocated n bytes (worst case: no whitespace);
// out_offsets: caller-allocated (n+1)/2 + 2 entries.  Returns word count.
int64_t tz_split_ws(const uint8_t* data, int64_t n, uint8_t* out_bytes,
                    int64_t* out_offsets) {
    int64_t words = 0, out = 0, i = 0;
    out_offsets[0] = 0;
    while (i < n) {
        while (i < n && tz_is_ws(data[i])) i++;
        int64_t start = i;
        while (i < n && !tz_is_ws(data[i])) i++;
        if (i > start) {
            std::memcpy(out_bytes + out, data + start, (size_t)(i - start));
            out += i - start;
            out_offsets[++words] = out;
        }
    }
    return words;
}

// --- generic pre-sort combine: sum int64 values of equal keys -------------
// first_idx[u] = record index of key u's first occurrence (caller gathers
// the key bytes); sums[u] = total value.  Both sized n by the caller.
// Returns the number of unique keys.
int64_t hash_sum_i64(const uint8_t* key_bytes, const int64_t* key_offsets,
                     int64_t n, const int64_t* values,
                     int64_t* first_idx, int64_t* sums) {
    HashAgg agg;
    // remember first-occurrence record index per unique key: the arena
    // offset uniquely identifies the entry, so track indices alongside
    std::vector<int64_t> firsts;
    firsts.reserve(1024);
    for (int64_t i = 0; i < n; i++) {
        size_t before = agg.entries.size();
        agg.add(key_bytes + key_offsets[i],
                key_offsets[i + 1] - key_offsets[i], values[i]);
        if (agg.entries.size() > before) firsts.push_back(i);
    }
    for (size_t u = 0; u < agg.entries.size(); u++) {
        first_idx[u] = firsts[u];
        sums[u] = agg.entries[u].acc;
    }
    return (int64_t)agg.entries.size();
}

}  // extern "C"
