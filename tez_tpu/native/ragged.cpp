// Native host ops for the tez_tpu data plane.
//
// The reference's byte-crunching data path is JVM code (SURVEY.md: the
// performance-critical path is plain Java over byte[]); here the device
// kernels do the heavy lifting and the host side only permutes/concatenates
// ragged byte arrays when materializing runs.  That gather is memory-bound
// and single-threaded in numpy (fancy indexing builds an index array of one
// int64 per BYTE); this C++ version does per-row memcpy across threads and
// skips the index materialization entirely.
//
// Build: make -C native   (g++ -O3 -shared; loaded via ctypes, with a numpy
// fallback when the .so is missing).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

// Permute rows of a ragged u8 array.
//   data/offsets     : source (n_src rows; offsets has n_src+1 entries)
//   perm             : n_out row indices into the source
//   out_offsets      : n_out+1 entries, PRECOMPUTED by the caller
//   out_data         : out_offsets[n_out] bytes
void gather_ragged_u8(const uint8_t* data, const int64_t* offsets,
                      const int64_t* perm, int64_t n_out,
                      const int64_t* out_offsets, uint8_t* out_data,
                      int32_t n_threads) {
    if (n_out <= 0) return;
    int threads = std::max(1, (int)n_threads);
    int64_t total = out_offsets[n_out];
    // Partition output rows so each thread copies ~equal BYTES, not rows
    // (row sizes are ragged; equal-row chunks would skew badly).
    std::vector<std::thread> pool;
    pool.reserve(threads);
    int64_t bytes_per_thread = (total + threads - 1) / threads;
    int64_t row = 0;
    for (int t = 0; t < threads && row < n_out; t++) {
        int64_t start_row = row;
        int64_t target = std::min(total, (int64_t)(t + 1) * bytes_per_thread);
        // advance to the first row whose start offset reaches the target
        while (row < n_out && out_offsets[row] < target) row++;
        int64_t end_row = row;
        pool.emplace_back([=]() {
            for (int64_t i = start_row; i < end_row; i++) {
                int64_t src = perm[i];
                int64_t len = offsets[src + 1] - offsets[src];
                if (len > 0) {
                    std::memcpy(out_data + out_offsets[i],
                                data + offsets[src], (size_t)len);
                }
            }
        });
    }
    for (auto& th : pool) th.join();
}

// Concatenate ragged u8 arrays: caller passes flattened descriptor arrays.
void concat_ragged_u8(const uint8_t** datas, const int64_t* sizes,
                      int64_t n_parts, uint8_t* out_data,
                      int32_t n_threads) {
    std::vector<int64_t> starts(n_parts + 1, 0);
    for (int64_t i = 0; i < n_parts; i++) starts[i + 1] = starts[i] + sizes[i];
    int threads = std::max(1, (int)n_threads);
    std::vector<std::thread> pool;
    int64_t per = (n_parts + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        int64_t lo = t * per, hi = std::min<int64_t>(n_parts, lo + per);
        if (lo >= hi) break;
        pool.emplace_back([=, &starts]() {
            for (int64_t i = lo; i < hi; i++) {
                if (sizes[i] > 0)
                    std::memcpy(out_data + starts[i], datas[i],
                                (size_t)sizes[i]);
            }
        });
    }
    for (auto& th : pool) th.join();
}


// Adjacent-row equality over a ragged u8 array: for each candidate index
// cand[j] (caller guarantees rows cand[j] and cand[j]+1 have equal byte
// length), out[j] = 1 iff the two rows' bytes match.  Per-pair memcmp
// across threads — the numpy formulation materializes an int64 index per
// BYTE (8x expansion) on the grouping/combine hot path.
void adjacent_equal_u8(const uint8_t* data, const int64_t* offsets,
                       const int64_t* cand, int64_t n_cand,
                       uint8_t* out, int32_t n_threads) {
    if (n_cand <= 0) return;
    int threads = std::max(1, (int)n_threads);
    std::vector<std::thread> pool;
    int64_t per = (n_cand + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
        int64_t lo = t * per, hi = std::min<int64_t>(n_cand, lo + per);
        if (lo >= hi) break;
        pool.emplace_back([=]() {
            for (int64_t j = lo; j < hi; j++) {
                int64_t i = cand[j];
                int64_t len = offsets[i + 1] - offsets[i];
                out[j] = (len == 0) ||
                    std::memcmp(data + offsets[i], data + offsets[i + 1],
                                (size_t)len) == 0;
            }
        });
    }
    for (auto& th : pool) th.join();
}

}  // extern "C"
