// Native shuffle data server: the ShuffleHandler analog in C++.
//
// The reference's bulk data plane is an NM-resident Netty HTTP server with
// job-token HMAC auth and zero-copy sendfile (tez-plugins/tez-aux-services
// ShuffleHandler.java:159, FadvisedFileRegion).  This is its TPU-framework
// twin: a thread-per-connection TCP server speaking the SAME wire protocol
// as tez_tpu/shuffle/server.py (16-byte nonce greeting, length-prefixed
// JSON requests, HMAC-SHA256 over the full canonical request + nonce,
// keep-alive), serving pre-serialized partition blobs from disk via
// sendfile(2) — the hot serving path never copies payload bytes through
// user space, and never touches the Python runtime.
//
// File layout (written by tez_tpu/shuffle/native_server.py FileShuffleStore):
//   <dir>/<hex(path)>_<spill>.data   concatenated single-partition Run blobs
//   <dir>/<hex(path)>_<spill>.index  "TZIX" | u32 P | u64 offsets[P+1]
//
// Build: make -C native (part of libtezhost.so, loaded via ctypes).

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <poll.h>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// SHA-256 + HMAC (self-contained; no OpenSSL headers in this image)
// ---------------------------------------------------------------------------
struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buf_len = 0;

  Sha256() {
    static const uint32_t init[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(h));
  }

  static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void block(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[i * 4]) << 24) | (uint32_t(p[i * 4 + 1]) << 16) |
             (uint32_t(p[i * 4 + 2]) << 8) | uint32_t(p[i * 4 + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + k[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    len += n;
    while (n) {
      size_t take = std::min(n, sizeof(buf) - buf_len);
      memcpy(buf + buf_len, p, take);
      buf_len += take; p += take; n -= take;
      if (buf_len == 64) { block(buf); buf_len = 0; }
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len != 56) update(&zero, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; i++) lb[i] = uint8_t(bits >> (56 - 8 * i));
    update(lb, 8);
    for (int i = 0; i < 8; i++) {
      out[i * 4] = uint8_t(h[i] >> 24);
      out[i * 4 + 1] = uint8_t(h[i] >> 16);
      out[i * 4 + 2] = uint8_t(h[i] >> 8);
      out[i * 4 + 3] = uint8_t(h[i]);
    }
  }
};

void hmac_sha256(const uint8_t* key, size_t key_len, const uint8_t* msg,
                 size_t msg_len, uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (key_len > 64) {
    Sha256 s; s.update(key, key_len); s.final(k);
  } else {
    memcpy(k, key, key_len);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) { ipad[i] = k[i] ^ 0x36; opad[i] = k[i] ^ 0x5c; }
  uint8_t inner[32];
  Sha256 si; si.update(ipad, 64); si.update(msg, msg_len); si.final(inner);
  Sha256 so; so.update(opad, 64); so.update(inner, 32); so.final(out);
}

bool ct_equal(const uint8_t* a, const uint8_t* b, size_t n) {
  uint8_t acc = 0;
  for (size_t i = 0; i < n; i++) acc |= a[i] ^ b[i];
  return acc == 0;
}

// ---------------------------------------------------------------------------
// tiny helpers
// ---------------------------------------------------------------------------
bool read_exact(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r; n -= size_t(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) return false;
    p += r; n -= size_t(r);
  }
  return true;
}

std::string hex(const uint8_t* p, size_t n) {
  static const char* d = "0123456789abcdef";
  std::string s;
  s.reserve(n * 2);
  for (size_t i = 0; i < n; i++) { s += d[p[i] >> 4]; s += d[p[i] & 15]; }
  return s;
}

bool unhex(const std::string& s, std::vector<uint8_t>* out) {
  if (s.size() % 2) return false;
  out->resize(s.size() / 2);
  for (size_t i = 0; i < out->size(); i++) {
    auto nib = [](char c) -> int {
      if (c >= '0' && c <= '9') return c - '0';
      if (c >= 'a' && c <= 'f') return c - 'a' + 10;
      if (c >= 'A' && c <= 'F') return c - 'A' + 10;
      return -1;
    };
    int hi = nib(s[i * 2]), lo = nib(s[i * 2 + 1]);
    if (hi < 0 || lo < 0) return false;
    (*out)[i] = uint8_t((hi << 4) | lo);
  }
  return true;
}

// Minimal JSON field extraction for OUR OWN fixed client format (flat
// object, string/int values).  Anything malformed simply fails auth.
bool json_string(const std::string& j, const char* key, std::string* out) {
  std::string pat = std::string("\"") + key + "\"";
  size_t k = j.find(pat);
  if (k == std::string::npos) return false;
  size_t colon = j.find(':', k + pat.size());
  if (colon == std::string::npos) return false;
  size_t q1 = j.find('"', colon + 1);
  if (q1 == std::string::npos) return false;
  std::string s;
  for (size_t i = q1 + 1; i < j.size(); i++) {
    char c = j[i];
    if (c == '\\') {                     // full JSON escape set
      if (i + 1 >= j.size()) return false;
      char e = j[++i];
      switch (e) {
        case '"': s += '"'; break;
        case '\\': s += '\\'; break;
        case '/': s += '/'; break;
        case 'b': s += '\b'; break;
        case 'f': s += '\f'; break;
        case 'n': s += '\n'; break;
        case 'r': s += '\r'; break;
        case 't': s += '\t'; break;
        case 'u': {
          if (i + 4 >= j.size()) return false;
          auto nib = [](char c) -> int {
            if (c >= '0' && c <= '9') return c - '0';
            if (c >= 'a' && c <= 'f') return c - 'a' + 10;
            if (c >= 'A' && c <= 'F') return c - 'A' + 10;
            return -1;
          };
          int cp = 0;
          for (int k = 1; k <= 4; k++) {
            int v = nib(j[i + k]);
            if (v < 0) return false;
            cp = (cp << 4) | v;
          }
          i += 4;
          // UTF-16 surrogate pair -> code point
          if (cp >= 0xD800 && cp <= 0xDBFF && i + 6 < j.size() &&
              j[i + 1] == '\\' && j[i + 2] == 'u') {
            int lo = 0;
            bool ok2 = true;
            for (int k = 3; k <= 6; k++) {
              int v = nib(j[i + k]);
              if (v < 0) { ok2 = false; break; }
              lo = (lo << 4) | v;
            }
            if (ok2 && lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              i += 6;
            }
          }
          // UTF-8 encode (matches Python's json path bytes)
          if (cp < 0x80) {
            s += char(cp);
          } else if (cp < 0x800) {
            s += char(0xC0 | (cp >> 6));
            s += char(0x80 | (cp & 0x3F));
          } else if (cp < 0x10000) {
            s += char(0xE0 | (cp >> 12));
            s += char(0x80 | ((cp >> 6) & 0x3F));
            s += char(0x80 | (cp & 0x3F));
          } else {
            s += char(0xF0 | (cp >> 18));
            s += char(0x80 | ((cp >> 12) & 0x3F));
            s += char(0x80 | ((cp >> 6) & 0x3F));
            s += char(0x80 | (cp & 0x3F));
          }
          break;
        }
        default: return false;
      }
    } else if (c == '"') {
      *out = s;
      return true;
    } else {
      s += c;
    }
  }
  return false;
}

bool json_int(const std::string& j, const char* key, int64_t* out) {
  std::string pat = std::string("\"") + key + "\"";
  size_t k = j.find(pat);
  if (k == std::string::npos) return false;
  size_t colon = j.find(':', k + pat.size());
  if (colon == std::string::npos) return false;
  size_t i = colon + 1;
  while (i < j.size() && (j[i] == ' ')) i++;
  bool neg = false;
  if (i < j.size() && j[i] == '-') { neg = true; i++; }
  if (i >= j.size() || j[i] < '0' || j[i] > '9') return false;
  int64_t v = 0;
  int digits = 0;
  for (; i < j.size() && j[i] >= '0' && j[i] <= '9'; i++) {
    // parse runs pre-auth: cap at 18 digits so a crafted digit run can't
    // overflow signed int64 (UB) before the HMAC check rejects the request
    if (++digits > 18) return false;
    v = v * 10 + (j[i] - '0');
  }
  *out = neg ? -v : v;
  return true;
}

// ---------------------------------------------------------------------------
// server
// ---------------------------------------------------------------------------
struct Server {
  int listen_fd = -1;
  int port = 0;
  std::string dir;
  std::vector<uint8_t> secret;
  std::atomic<bool> stopping{false};
  std::atomic<uint64_t> bytes_served{0};
  std::atomic<uint64_t> auth_failures{0};
  std::atomic<int64_t> active_connections{0};
  std::thread accept_thread;
};

// Wait (poll) for readability with periodic stop checks, so idle keep-alive
// connections survive but shutdown wakes them within ~200 ms.  poll(2), not
// select: fds can exceed FD_SETSIZE in a thread-per-connection server.
bool wait_readable(Server* srv, int fd) {
  while (!srv->stopping.load()) {
    pollfd pfd{fd, POLLIN, 0};
    int r = poll(&pfd, 1, 200);
    if (r > 0) return (pfd.revents & (POLLIN | POLLHUP)) != 0;
    if (r < 0 && errno != EINTR) return false;
  }
  return false;
}

Server* g_server = nullptr;

void reply_header(int fd, const std::string& body) {
  uint32_t n = uint32_t(body.size());
  uint8_t len[4] = {uint8_t(n), uint8_t(n >> 8), uint8_t(n >> 16),
                    uint8_t(n >> 24)};
  if (!write_all(fd, len, 4)) return;
  write_all(fd, body.data(), body.size());
}

void handle_connection(Server* srv, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  uint8_t nonce[16];
  int ur = open("/dev/urandom", O_RDONLY);
  if (ur < 0 || !read_exact(ur, nonce, sizeof(nonce))) {
    if (ur >= 0) close(ur);
    close(fd);
    return;
  }
  close(ur);
  if (!write_all(fd, nonce, sizeof(nonce))) { close(fd); return; }
  std::string nonce_hex = hex(nonce, sizeof(nonce));

  while (!srv->stopping.load()) {           // keep-alive loop
    if (!wait_readable(srv, fd)) break;     // idle wait, stop-aware
    uint8_t len_raw[4];
    if (!read_exact(fd, len_raw, 4)) break;
    uint32_t req_len = uint32_t(len_raw[0]) | (uint32_t(len_raw[1]) << 8) |
                       (uint32_t(len_raw[2]) << 16) |
                       (uint32_t(len_raw[3]) << 24);
    if (req_len == 0 || req_len > (1u << 16)) break;
    std::string req(req_len, '\0');
    if (!read_exact(fd, req.data(), req_len)) break;

    std::string path, hmac_hex;
    int64_t spill = -1, lo = 0, hi = -1;
    bool ok = json_string(req, "path", &path) &&
              json_string(req, "hmac", &hmac_hex) &&
              json_int(req, "spill", &spill) &&
              json_int(req, "partition_lo", &lo);
    if (ok && !json_int(req, "partition_hi", &hi)) hi = lo + 1;

    // canonical request bytes: path|spill|lo|hi|noncehex
    std::vector<uint8_t> sig;
    bool auth = false;
    if (ok && unhex(hmac_hex, &sig) && sig.size() == 32) {
      char msg[4096];
      int m = snprintf(msg, sizeof(msg), "%s|%lld|%lld|%lld|%s",
                       path.c_str(), static_cast<long long>(spill),
                       static_cast<long long>(lo),
                       static_cast<long long>(hi), nonce_hex.c_str());
      if (m > 0 && size_t(m) < sizeof(msg)) {
        uint8_t want[32];
        hmac_sha256(srv->secret.data(), srv->secret.size(),
                    reinterpret_cast<const uint8_t*>(msg), size_t(m), want);
        auth = ct_equal(want, sig.data(), 32);
      }
    }
    if (!auth) {
      srv->auth_failures.fetch_add(1);
      reply_header(fd, "{\"status\": \"forbidden\"}");
      continue;
    }

    std::string base = srv->dir + "/" +
        hex(reinterpret_cast<const uint8_t*>(path.data()), path.size()) +
        "_" + std::to_string(spill);
    int idx_fd = open((base + ".index").c_str(), O_RDONLY);
    if (idx_fd < 0) { reply_header(fd, "{\"status\": \"not_found\"}"); continue; }
    // The TZIX index is written little-endian (struct '<I'/'<Q' in
    // shuffle/native_server.py); decode byte-wise so a big-endian host
    // reads the same values instead of byte-swapped garbage.
    char magic[4];
    uint8_t np_raw[4];
    uint32_t num_parts = 0;
    bool idx_ok = read_exact(idx_fd, magic, 4) &&
                  memcmp(magic, "TZIX", 4) == 0 &&
                  read_exact(idx_fd, np_raw, 4);
    if (idx_ok) {
      num_parts = uint32_t(np_raw[0]) | (uint32_t(np_raw[1]) << 8) |
                  (uint32_t(np_raw[2]) << 16) | (uint32_t(np_raw[3]) << 24);
      idx_ok = num_parts < (1u << 24);
    }
    std::vector<uint64_t> offs;
    if (idx_ok) {
      std::vector<uint8_t> raw((size_t(num_parts) + 1) * 8);
      idx_ok = read_exact(idx_fd, raw.data(), raw.size());
      if (idx_ok) {
        offs.resize(num_parts + 1);
        for (size_t p = 0; p < offs.size(); p++) {
          uint64_t v = 0;
          for (int b = 7; b >= 0; b--) v = (v << 8) | raw[p * 8 + b];
          offs[p] = v;
        }
      }
    }
    close(idx_fd);
    if (!idx_ok || lo < 0 || hi > int64_t(num_parts) || lo >= hi) {
      reply_header(fd, "{\"status\": \"not_found\"}");
      continue;
    }

    std::string sizes = "[";
    for (int64_t p = lo; p < hi; p++) {
      if (p > lo) sizes += ", ";
      sizes += std::to_string(offs[p + 1] - offs[p]);
    }
    sizes += "]";
    reply_header(fd, "{\"status\": \"ok\", \"sizes\": " + sizes + "}");

    int data_fd = open((base + ".data").c_str(), O_RDONLY);
    if (data_fd < 0) break;                 // index/data mismatch: drop conn
    off_t off = off_t(offs[lo]);
    size_t remaining = size_t(offs[hi] - offs[lo]);
    bool sent = true;
    while (remaining) {
      ssize_t r = sendfile(fd, data_fd, &off, remaining);
      if (r <= 0) { sent = false; break; }
      remaining -= size_t(r);
      srv->bytes_served.fetch_add(uint64_t(r));
    }
    close(data_fd);
    if (!sent) break;
  }
  close(fd);
}

void connection_entry(Server* srv, int fd) {
  handle_connection(srv, fd);
  srv->active_connections.fetch_sub(1);
}

void accept_loop(Server* srv) {
  while (!srv->stopping.load()) {
    int fd = accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (srv->stopping.load()) return;
      usleep(50 * 1000);   // EMFILE etc.: back off, don't spin hot
      continue;
    }
    srv->active_connections.fetch_add(1);
    std::thread(connection_entry, srv, fd).detach();
  }
}

}  // namespace

extern "C" {

// Start the singleton server.  Returns the bound port (>0) or -1.
int tez_shuffle_server_start(const char* dir, const uint8_t* secret,
                             int32_t secret_len, const char* bind_host,
                             int32_t port) {
  if (g_server) return -1;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, bind_host, &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(fd, 128) < 0) {
    close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);

  auto* srv = new Server();
  srv->listen_fd = fd;
  srv->port = ntohs(addr.sin_port);
  srv->dir = dir;
  srv->secret.assign(secret, secret + secret_len);
  srv->accept_thread = std::thread(accept_loop, srv);
  g_server = srv;
  return srv->port;
}

int tez_shuffle_server_port() { return g_server ? g_server->port : -1; }

uint64_t tez_shuffle_server_bytes_served() {
  return g_server ? g_server->bytes_served.load() : 0;
}

uint64_t tez_shuffle_server_auth_failures() {
  return g_server ? g_server->auth_failures.load() : 0;
}

void tez_shuffle_server_stop() {
  Server* srv = g_server;
  if (!srv) return;
  g_server = nullptr;
  srv->stopping.store(true);
  shutdown(srv->listen_fd, SHUT_RDWR);
  close(srv->listen_fd);
  if (srv->accept_thread.joinable()) srv->accept_thread.join();
  // Connection threads observe `stopping` within one 200 ms poll tick when
  // idle; in-flight sendfiles finish their transfer first.  Wait for the
  // active count to drain; if a transfer outlives the grace period, LEAK
  // the Server rather than free memory still in use.
  for (int i = 0; i < 100 && srv->active_connections.load() > 0; i++)
    usleep(100 * 1000);                    // up to 10 s
  if (srv->active_connections.load() == 0) delete srv;
}

}  // extern "C"
