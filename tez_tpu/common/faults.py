"""Deterministic fault-injection plane: named fault points + seeded schedule.

Reference lineage: the reference tests faults at the API layer
(TestInput/TestProcessor config-driven failures); real outages happen at the
infrastructure seams — fetch sockets, spill files, heartbeats, container
launches, journal writes.  This module is a process-global registry of
*named fault points* compiled into those seams.  Production cost is one
module-flag check per point (`if not _armed: return`); nothing else runs
unless a test or the chaos harness installs rules.

Jepsen-style determinism: every rule owns a `random.Random` seeded from the
install seed and the rule's own text, so a given (spec, seed) pair produces
the same fault schedule on every run — `python -m tez_tpu.tools.chaos
--seed N` replays a storm exactly.

Modes per rule:
  fail     raise an exception the first `n` matching fires (n=-1: always)
  pfail    raise with probability `p` per fire (seeded RNG, budget `n`)
  delay    sleep `ms` milliseconds (budget `n`)
  corrupt  flip one payload byte via :func:`corrupt_bytes` (budget `n`)

Rules are installed under a *scope* token (the DAG id — the AM installs
from ``tez.test.fault.*`` conf at submit and clears at DAG finish), so
concurrent tests in one process don't interfere: each scope's rules come
and go atomically and `clear(scope)` removes exactly its own.

Spec grammar (``tez.test.fault.spec``)::

    point:mode[:k=v[,k=v...]][;point:mode:...]

    shuffle.fetch.read:fail:n=2,exc=conn;task.run:delay:ms=3000,match=_00_000000_0

Params: ``n`` (budget, -1 unlimited), ``p`` (pfail probability), ``ms``
(delay), ``exc`` (conn|io|os|timeout|runtime|perm), ``match`` (substring
the fire's detail must contain).
"""
from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

#: Canonical instrumented points (docs/fault_injection.md is generated from
#: this table).  fire() accepts any name — new seams need no central edit —
#: but the chaos storm menu and the docs draw from here.
KNOWN_POINTS: Dict[str, str] = {
    "shuffle.fetch.connect":
        "shuffle/server.py FetchSession connect (TCP dial + nonce)",
    "shuffle.fetch.read":
        "shuffle fetch read: FetchSession.fetch_range and the in-process "
        "local-fetch short circuit (library/inputs.py)",
    "shuffle.serve":
        "shuffle/server.py request serving (server side of a fetch)",
    "shuffle.data":
        "shuffle/service.py payload integrity: corrupt mode round-trips the "
        "served partition through the checksummed Run wire blob",
    "spill.write":
        "ops/runformat.py + ops/sorter.py spill writes (Run.save, "
        "save_run_partitioned, DeviceSorter._store_run)",
    "spill.read":
        "ops/runformat.py spill reads (Run.load, FileRun block reads); "
        "corrupt mode flips stored bytes so the CRC path must catch it",
    "am.heartbeat":
        "am/task_comm.py heartbeat delivery (shared by local and umbilical "
        "paths)",
    "am.heartbeat.monitor":
        "am/heartbeat.py liveness sweep (delay stalls failure detection)",
    "am.umbilical":
        "am/umbilical_server.py method dispatch (detail = method name)",
    "am.container.launch":
        "am/launcher.py runner/container startup",
    "am.recovery.append":
        "am/recovery.py journal append (before the write)",
    "am.recovery.fsync":
        "am/recovery.py journal fsync of summary events",
    "mesh.exchange":
        "parallel/coordinator.py host-level mesh exchange entry (the jitted "
        "SPMD body itself is not instrumentable)",
    "mesh.exchange.delay":
        "parallel/coordinator.py per-device shard readback (detail = "
        "<edge>:round=<r>:device=<d>); delay mode turns one chip into a "
        "readback straggler — the lever chaos uses to prove coded r2 "
        "masks it via the buddy copy; fail mode fails that chip's copy",
    "task.run":
        "runtime/task_runner.py processor invocation (detail = attempt id; "
        "delay mode makes an attempt a straggler, fail mode crashes it)",
    "commit.ledger.fsync":
        "am/recovery.py fsync of a commit-ledger record (DAG_COMMIT_STARTED/"
        "FINISHED/ABORTED) — fail mode crashes the AM between ledger states",
    "commit.publish":
        "io/file_output.py per-part-file publish inside commit_output "
        "(detail = part filename; delay mode holds the commit mid-publish)",
    "fence.stale_epoch":
        "observability point fired wherever a stale-epoch actor is rejected "
        "(task_comm, shuffle service/server, committer publish fence)",
    "fence.stale_window":
        "observability point fired wherever a stale-WINDOW actor is "
        "rejected — the streaming generalization of fence.stale_epoch "
        "(umbilical, shuffle register/push/fetch, store publish)",
    "stream.window.commit":
        "am/streaming.py exactly-once window committer, fired between the "
        "WINDOW_COMMIT_STARTED and WINDOW_COMMIT_FINISHED ledger records "
        "(detail = <stream>@w<window>); fail mode crashes the stream "
        "mid-commit — the chaos --stream-kill lever",
    "stream.ingest":
        "am/streaming.py StreamDriver.ingest (detail = <stream> record "
        "count); delay mode paces the source, fail mode drops the ingest "
        "call with a typed error",
    "device.dispatch.delay":
        "ops/async_stage.py readback completion (detail = span=<id>); delay "
        "mode holds one span's completion while later spans drain past it — "
        "the deterministic out-of-order-completion lever for the async "
        "device pipeline",
    "device.dispatch.hang":
        "ops/async_stage.py device dispatch entry (detail = span=<id>); "
        "delay mode simulates a hung XLA dispatch so the watchdog abandons "
        "the attempt and the span fails over to the host engine",
    "device.dispatch.oom":
        "ops/async_stage.py device dispatch entry and ops/sorter.py split "
        "retries (detail = span=<id>[:split[lo:hi)]); fail mode raises a "
        "RESOURCE_EXHAUSTED-classified error driving the split-then-"
        "fallback ladder and the circuit breaker",
    "device.readback.fail":
        "ops/async_stage.py D2H readback entry (detail = span=<id>); fail "
        "mode crashes the readback worker's attempt so the span re-sorts "
        "through the host engine",
    "shuffle.push.send":
        "shuffle/push.py SpillPusher send attempt (detail = "
        "path/spill -> dest); fail mode kills the eager push so the "
        "consumer must recover through the pull path — the push-storm "
        "chaos lever",
    "shuffle.push.admit":
        "shuffle/push.py PushAdmissionController decision (detail = "
        "source path + nbytes); fail mode turns the decision into a "
        "RETRY-AFTER rejection, delay mode stretches admit_wait",
    "am.admit.shed":
        "am/admission.py AdmissionController decision (detail = "
        "tenant/dag name); fail mode forces the verdict to SHED with "
        "RETRY-AFTER regardless of load — the tenant-storm chaos lever "
        "for exercising client resubmit paths",
    "am.queue.delay":
        "am/admission.py queue consumer drain step (detail = queued "
        "submission id); delay mode holds a parked submission before it "
        "is promoted to submit, stretching queue latency; fail mode "
        "crashes the consumer thread mid-drain (the lossless-admission "
        "ledger regression lever)",
    "am.crash":
        "am/app_master.py DAGAppMaster.crash() entry (detail = "
        "attempt=<n>); fires as the simulated SIGKILL begins — delay mode "
        "widens the kill window deterministically, any raise is swallowed "
        "(the AM is dying regardless).  The --am-kill chaos lever",
    "store.replica.lost":
        "shuffle/service.py consumer-side fetch chain (detail = "
        "path/spill); fail mode declares the PRIMARY copies lost — store "
        "entry and local registration both — so the fetch must "
        "reconstruct from the coded push replica (store.replica.failover "
        "proves no producer re-ran)",
}

_EXC_KINDS = {
    "conn": ConnectionError,
    "io": IOError,
    "os": OSError,
    "timeout": TimeoutError,
    "runtime": RuntimeError,
    "perm": PermissionError,
}

_MODES = ("fail", "pfail", "delay", "corrupt")


class FaultInjected(Exception):
    """Marker mixin never raised directly; see _make_exc."""


@dataclasses.dataclass
class FaultRule:
    point: str
    mode: str                 # fail | pfail | delay | corrupt
    times: int = -1           # fire budget; -1 = unlimited
    prob: float = 1.0         # pfail draw threshold
    delay_ms: float = 0.0
    exc: str = "conn"
    match: str = ""           # substring filter on the fire's detail
    scope: str = ""           # installer token (set by install())
    fired: int = 0
    rng: Optional[random.Random] = None

    def spec(self) -> str:
        parts = [f"{self.point}:{self.mode}"]
        kv = []
        if self.times != -1:
            kv.append(f"n={self.times}")
        if self.mode == "pfail":
            kv.append(f"p={self.prob}")
        if self.mode == "delay":
            kv.append(f"ms={self.delay_ms:g}")
        if self.mode in ("fail", "pfail") and self.exc != "conn":
            kv.append(f"exc={self.exc}")
        if self.match:
            kv.append(f"match={self.match}")
        if kv:
            parts.append(",".join(kv))
        return ":".join(parts)


def parse_spec(spec: str) -> List[FaultRule]:
    """Parse the ``tez.test.fault.spec`` grammar into rules (unseeded)."""
    rules: List[FaultRule] = []
    for chunk in spec.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        fields = chunk.split(":", 2)
        if len(fields) < 2:
            raise ValueError(f"fault rule {chunk!r}: want point:mode[:k=v..]")
        point, mode = fields[0].strip(), fields[1].strip()
        if mode not in _MODES:
            raise ValueError(f"fault rule {chunk!r}: unknown mode {mode!r} "
                             f"(want one of {_MODES})")
        rule = FaultRule(point=point, mode=mode)
        if len(fields) == 3 and fields[2].strip():
            for kv in fields[2].split(","):
                k, _, v = kv.partition("=")
                k, v = k.strip(), v.strip()
                if k == "n":
                    rule.times = int(v)
                elif k == "p":
                    rule.prob = float(v)
                elif k == "ms":
                    rule.delay_ms = float(v)
                elif k == "exc":
                    if v not in _EXC_KINDS:
                        raise ValueError(
                            f"fault rule {chunk!r}: unknown exc {v!r} "
                            f"(want one of {sorted(_EXC_KINDS)})")
                    rule.exc = v
                elif k == "match":
                    rule.match = v
                else:
                    raise ValueError(f"fault rule {chunk!r}: unknown "
                                     f"param {k!r}")
        if rule.mode in ("fail", "pfail", "corrupt", "delay") and \
                rule.times == 0:
            raise ValueError(f"fault rule {chunk!r}: n=0 never fires")
        rules.append(rule)
    return rules


def format_spec(rules: List[FaultRule]) -> str:
    return ";".join(r.spec() for r in rules)


def _seed_rule(rule: FaultRule, seed: int) -> None:
    # derive the per-rule stream from the install seed + the rule's own
    # text via crc32 (never hash(): it is salted per process, which would
    # break cross-run reproducibility)
    h = zlib.crc32(rule.spec().encode("utf-8"))
    rule.rng = random.Random((seed & 0xFFFFFFFF) * 0x9E3779B1 + h)


class FaultPlane:
    """Process-global rule registry; all state mutations are locked.
    Sleeps happen outside the lock."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._scopes: Dict[str, List[FaultRule]] = {}
        #: repro/assertion trail: (point, detail, action) in fire order
        self.journal: List[Tuple[str, str, str]] = []

    # -- installation --------------------------------------------------------
    def install(self, scope: str, rules: List[FaultRule],
                seed: int = 0) -> None:
        global _armed
        for r in rules:
            r.scope = scope
            r.fired = 0
            _seed_rule(r, seed)
        with self._lock:
            self._scopes[scope] = list(rules)
            _armed = True
        log.info("fault plane: scope %s armed with %d rule(s), seed=%d: %s",
                 scope, len(rules), seed, format_spec(rules))

    def clear(self, scope: str) -> None:
        global _armed
        with self._lock:
            self._scopes.pop(scope, None)
            if not self._scopes:
                _armed = False

    def clear_all(self) -> None:
        global _armed
        with self._lock:
            self._scopes.clear()
            self.journal.clear()
            _armed = False

    def rules_snapshot(self) -> List[FaultRule]:
        with self._lock:
            return [r for rules in self._scopes.values() for r in rules]

    # -- firing --------------------------------------------------------------
    def _claim(self, point: str, detail: str,
               modes: Tuple[str, ...]) -> Optional[FaultRule]:
        """Find the first matching rule with budget and consume one fire."""
        with self._lock:
            for rules in self._scopes.values():
                for r in rules:
                    if r.point != point or r.mode not in modes:
                        continue
                    if r.match and r.match not in detail:
                        continue
                    if r.times >= 0 and r.fired >= r.times:
                        continue
                    if r.mode == "pfail":
                        assert r.rng is not None
                        if r.rng.random() >= r.prob:
                            continue
                    r.fired += 1
                    self.journal.append((point, detail, r.mode))
                    return r
        return None

    def fire(self, point: str, detail: str = "") -> None:
        """Raise or sleep according to the first matching armed rule."""
        rule = self._claim(point, detail, ("fail", "pfail", "delay"))
        if rule is None:
            return
        if rule.mode == "delay":
            log.info("fault plane: delaying %s (%s) %.0fms",
                     point, detail, rule.delay_ms)
            time.sleep(rule.delay_ms / 1000.0)
            return
        exc_type = _EXC_KINDS[rule.exc]
        log.info("fault plane: failing %s (%s) with %s",
                 point, detail, exc_type.__name__)
        raise exc_type(f"injected fault at {point} ({detail})")

    def should_corrupt(self, point: str, detail: str = "") -> bool:
        return self._claim(point, detail, ("corrupt",)) is not None

    def corrupt_bytes(self, point: str, detail: str, data: bytes,
                      lo: int = 0) -> bytes:
        """Flip one byte at/after `lo` when a corrupt rule fires; the
        caller's checksum layer must detect the damage."""
        if len(data) <= lo:
            return data
        rule = self._claim(point, detail, ("corrupt",))
        if rule is None:
            return data
        assert rule.rng is not None
        pos = lo + rule.rng.randrange(len(data) - lo)
        log.info("fault plane: corrupting %s (%s) byte %d of %d",
                 point, detail, pos, len(data))
        out = bytearray(data)
        out[pos] ^= 0xFF
        return bytes(out)


_PLANE = FaultPlane()
_armed = False     # module-level fast path: production cost is this check


def plane() -> FaultPlane:
    return _PLANE


def armed() -> bool:
    return _armed


def install(scope: str, rules: List[FaultRule], seed: int = 0) -> None:
    _PLANE.install(scope, rules, seed)


def clear(scope: str) -> None:
    _PLANE.clear(scope)


def clear_all() -> None:
    _PLANE.clear_all()


def install_from_conf(conf, scope: str) -> bool:
    """Arm the plane from ``tez.test.fault.*`` conf keys (AM submit path).
    Returns True when rules were installed."""
    from tez_tpu.common import config as C
    spec = conf.get(C.TEST_FAULT_SPEC)
    if not spec:
        return False
    seed = int(conf.get(C.TEST_FAULT_SEED))
    install(scope, parse_spec(spec), seed=seed)
    return True


def fire(point: str, detail: str = "") -> None:
    if not _armed:
        return
    _PLANE.fire(point, detail)


def should_corrupt(point: str, detail: str = "") -> bool:
    if not _armed:
        return False
    return _PLANE.should_corrupt(point, detail)


def corrupt_bytes(point: str, detail: str, data: bytes,
                  lo: int = 0) -> bytes:
    if not _armed:
        return data
    return _PLANE.corrupt_bytes(point, detail, data, lo=lo)
