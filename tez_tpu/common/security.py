"""Security primitives: job tokens, shuffle-request HMAC, ACLs.

Reference parity: tez-api/.../common/security/{JobTokenSecretManager.java:40,
DAGAccessControls, ACLManager}.java + tez-runtime-library SecureShuffleUtils
(SecureShuffleUtils.java:41 — URL-HMAC of the fetch request with the job
token, reply-hash verification).  ICI transfers are intra-trust-domain; the
HMAC protects the DCN fetch path (SURVEY.md §5.8).
"""
from __future__ import annotations

import hashlib
import hmac
import os
from typing import Dict, Iterable, Optional, Set


class JobTokenSecretManager:
    """Per-app shared secret between orchestrator and shuffle servers."""

    def __init__(self, secret: Optional[bytes] = None):
        self.secret = secret or os.urandom(32)

    def compute_hash(self, msg: bytes) -> bytes:
        return hmac.new(self.secret, msg, hashlib.sha256).digest()

    def verify_hash(self, digest: bytes, msg: bytes) -> bool:
        return hmac.compare_digest(digest, self.compute_hash(msg))


def shuffle_request_msg(path: str, spill_id: int, partition_lo: int,
                        partition_hi: int, nonce: bytes) -> bytes:
    """Canonical fetch-request bytes (SecureShuffleUtils.hashFromString
    analog): covers EVERY request field plus the server's per-connection
    nonce, so a captured request neither authorizes different partitions
    nor replays on a new connection."""
    return (f"{path}|{spill_id}|{partition_lo}|{partition_hi}|"
            f"{nonce.hex()}".encode())


def hash_from_request(secret: JobTokenSecretManager, path: str,
                      spill_id: int, partition_lo: int, partition_hi: int,
                      nonce: bytes) -> bytes:
    return secret.compute_hash(
        shuffle_request_msg(path, spill_id, partition_lo, partition_hi,
                            nonce))


class DAGAccessControls:
    """View/modify user lists; '*' = everyone (reference:
    DAGAccessControls.java)."""

    def __init__(self, view_users: Iterable[str] = ("*",),
                 modify_users: Iterable[str] = ()):
        self.view_users: Set[str] = set(view_users)
        self.modify_users: Set[str] = set(modify_users)


class ACLManager:
    """Reference: ACLManager.java — owner always allowed; '*' wildcard."""

    def __init__(self, owner: str, dag_acls: Optional[DAGAccessControls] = None,
                 enabled: bool = True):
        self.owner = owner
        self.acls = dag_acls or DAGAccessControls()
        self.enabled = enabled

    def check_view_access(self, user: str) -> bool:
        if not self.enabled or user == self.owner:
            return True
        return "*" in self.acls.view_users or user in self.acls.view_users

    def check_modify_access(self, user: str) -> bool:
        if not self.enabled or user == self.owner:
            return True
        return "*" in self.acls.modify_users or user in self.acls.modify_users
