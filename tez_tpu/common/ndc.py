"""Nested diagnostic context: tag log lines with the entity being worked on.

Reference parity: tez-common CallableWithNdc/RunnableWithNdc + the log4j NDC
the reference pushes task-attempt ids through so every log line in a shared
JVM names its attempt.  Python shape: a contextvar stack + a logging.Filter
that exposes it as %(ndc)s, and wrappers that carry the caller's stack onto
executor threads (the CallableWithNdc behavior).
"""
from __future__ import annotations

import contextlib
import contextvars
import functools
import logging
from typing import Any, Callable, Iterator, Tuple

_stack: contextvars.ContextVar[Tuple[str, ...]] = contextvars.ContextVar(
    "tez_ndc", default=())


def push(tag: str) -> contextvars.Token:
    return _stack.set(_stack.get() + (tag,))


def pop(token: contextvars.Token) -> None:
    _stack.reset(token)


def current() -> str:
    return ":".join(_stack.get())


@contextlib.contextmanager
def context(tag: str) -> Iterator[None]:
    token = push(tag)
    try:
        yield
    finally:
        pop(token)


def with_current_ndc(fn: Callable[..., Any]) -> Callable[..., Any]:
    """Capture the caller's NDC stack and re-apply it wherever the callable
    runs (reference: CallableWithNdc.callInternal wraps NDC.inherit)."""
    captured = _stack.get()

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        token = _stack.set(captured)
        try:
            return fn(*args, **kwargs)
        finally:
            _stack.reset(token)

    return wrapper


class NdcFilter(logging.Filter):
    """Makes %(ndc)s available to formatters; '' outside any context."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.ndc = current()
        return True


def install(fmt: str = "%(asctime)s %(levelname)s [%(ndc)s] "
                       "%(name)s: %(message)s") -> None:
    """Attach the NDC filter (and an NDC-aware format) to root handlers."""
    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig()
    for h in root.handlers:
        if not any(isinstance(f, NdcFilter) for f in h.filters):
            h.addFilter(NdcFilter())
            h.setFormatter(logging.Formatter(fmt))
