"""Central event bus for the orchestrator.

Reference parity: tez-common/.../AsyncDispatcher.java:50 (single-threaded typed
event bus; all control-plane state mutation is serialized through it) and
AsyncDispatcherConcurrent.java (hash-sharded variant for event storms).

Design kept from the reference (SURVEY.md §5.2): *all control-plane mutation on
one event loop* — state machines are never locked, they are only touched from
the dispatcher thread.  Two modes:

- ``Dispatcher`` — a background thread draining a queue (production).
- ``DrainDispatcher`` — same bus but manually pumped (``drain()``), giving the
  deterministic unit-test style of the reference's DrainDispatcher.
"""
from __future__ import annotations

import enum
import logging
import queue
import threading
from typing import Any, Callable, Dict, Type

log = logging.getLogger(__name__)


class Event:
    """Base event: subclasses carry an ``event_type`` enum member."""
    __slots__ = ("event_type",)

    def __init__(self, event_type: enum.Enum):
        self.event_type = event_type

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.event_type.name})"


EventHandler = Callable[[Event], None]


class Dispatcher:
    """Typed event bus: handlers register per event-type *enum class*.

    Reference: AsyncDispatcher.register(Class<? extends Enum>, EventHandler).
    """

    def __init__(self, name: str = "dispatcher"):
        self.name = name
        self._handlers: Dict[Type[enum.Enum], EventHandler] = {}
        self._queue: "queue.Queue[Event | None]" = queue.Queue()
        self._thread: threading.Thread | None = None
        self._stopped = threading.Event()
        self._drained = threading.Condition()
        self._in_flight = 0
        self._delivered = 0   # monotonically counts handled events
        self.peak_in_flight = 0   # high-water queue depth (storm metric)
        self.on_error: Callable[[BaseException, Event], None] | None = None

    # -- registration -------------------------------------------------------
    def register(self, event_type_class: Type[enum.Enum], handler: EventHandler) -> None:
        existing = self._handlers.get(event_type_class)
        if existing is not None:
            self._handlers[event_type_class] = _MultiHandler(existing, handler)
        else:
            self._handlers[event_type_class] = handler

    # -- event intake -------------------------------------------------------
    def dispatch(self, event: Event) -> None:
        with self._drained:
            self._in_flight += 1
            if self._in_flight > self.peak_in_flight:
                self.peak_in_flight = self._in_flight
        self._queue.put(event)

    @property
    def event_handler(self) -> EventHandler:
        return self.dispatch

    # -- delivery -----------------------------------------------------------
    def _deliver(self, event: Event) -> None:
        handler = self._handlers.get(type(event.event_type))
        try:
            if handler is None:
                log.warning("%s: no handler for %r", self.name, event)
            else:
                handler(event)
        except BaseException as e:  # noqa: BLE001 — AM error funnel
            log.exception("%s: handler error for %r", self.name, event)
            if self.on_error is not None:
                self.on_error(e, event)
            else:
                raise
        finally:
            with self._drained:
                self._in_flight -= 1
                self._delivered += 1
                if self._in_flight == 0 and self._queue.empty():
                    self._drained.notify_all()

    # -- threaded mode ------------------------------------------------------
    def start(self) -> None:
        assert self._thread is None
        self._stopped.clear()
        self._thread = threading.Thread(target=self._run, name=self.name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stopped.is_set():
            ev = self._queue.get()
            if ev is None:
                break
            self._deliver(ev)

    def stop(self) -> None:
        self._stopped.set()
        self._queue.put(None)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # Abandon undelivered events so await_drained() callers unblock.
        with self._drained:
            dropped = 0
            while True:
                try:
                    if self._queue.get_nowait() is not None:
                        dropped += 1
                except queue.Empty:
                    break
            if dropped:
                log.warning("%s: dropped %d undelivered events on stop",
                            self.name, dropped)
            self._in_flight = 0
            self._drained.notify_all()

    def await_drained(self, timeout: float | None = None) -> bool:
        """Block until every queued event has been handled."""
        with self._drained:
            return self._drained.wait_for(
                lambda: self._in_flight == 0 and self._queue.empty(), timeout)


class DrainDispatcher(Dispatcher):
    """Manually pumped dispatcher for deterministic tests and sync local mode.

    Reference: DrainDispatcher used throughout tez-dag state-machine tests.
    """

    def drain(self) -> int:
        """Deliver queued events until the queue is empty (including events
        enqueued by handlers).  Returns the number delivered."""
        n = 0
        while True:
            try:
                ev = self._queue.get_nowait()
            except queue.Empty:
                return n
            if ev is None:
                continue
            self._deliver(ev)
            n += 1

    def start(self) -> None:  # drained explicitly; no thread
        pass

    def stop(self) -> None:
        pass


class _MultiHandler:
    """Fan-out when two subsystems register for the same event-type class
    (reference: AsyncDispatcher MultiListenerHandler)."""

    def __init__(self, *handlers: EventHandler):
        self.handlers = list(handlers)

    def __call__(self, event: Event) -> None:
        for h in self.handlers:
            h(event)


class ShardedDispatcher(Dispatcher):
    """Hash-sharded event bus for event storms: events partition across N
    worker queues by a shard key so unrelated entities process in parallel
    while per-entity ordering is preserved.

    Reference: tez-common AsyncDispatcherConcurrent.java (used by the AM for
    vertex/task event storms at high task counts).  The shard key defaults
    to the event's entity id attribute when present.
    """

    def __init__(self, name: str = "sharded-dispatcher", num_shards: int = 4):
        super().__init__(name)
        self.num_shards = max(1, num_shards)
        self._shards = [Dispatcher(f"{name}-{i}")
                        for i in range(self.num_shards)]
        for s in self._shards:
            s._handlers = self._handlers   # shared registry
            # forward errors at delivery time so assigning self.on_error
            # after start() still reaches the funnel
            s.on_error = self._forward_error

    def _forward_error(self, exc: BaseException, event: Event) -> None:
        if self.on_error is not None:
            self.on_error(exc, event)
        else:
            raise exc

    def peak_depths(self) -> "list[int]":
        """Per-shard high-water queue depths (storm diagnostics)."""
        return [s.peak_in_flight for s in self._shards]

    def _shard_key(self, event: Event) -> int:
        for attr in ("attempt_id", "task_id", "vertex_id", "dag_id"):
            v = getattr(event, attr, None)
            if v is not None:
                return hash(str(v))
        return 0

    def dispatch(self, event: Event) -> None:
        self._shards[self._shard_key(event) % self.num_shards].dispatch(event)

    def start(self) -> None:
        for s in self._shards:
            s.start()

    def stop(self) -> None:
        for s in self._shards:
            s.stop()

    def await_drained(self, timeout: float | None = None) -> bool:
        """Drained only when TWO consecutive full passes observe every shard
        empty with no deliveries in between — handlers may cascade events
        ACROSS shards, so a single quiet pass has a TOCTOU window.  The
        shared deadline bounds total wait at `timeout`."""
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        prev_gen = -1
        while True:
            for s in self._shards:
                remaining = None if deadline is None else \
                    max(0.0, deadline - _time.monotonic())
                if not s.await_drained(remaining):
                    return False
            gen = sum(sh._delivered for sh in self._shards)
            if gen == prev_gen:
                # nothing was delivered between two fully-drained passes:
                # no cascade can be in flight
                return True
            prev_gen = gen
            if deadline is not None and _time.monotonic() >= deadline:
                return False
