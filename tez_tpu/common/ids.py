"""Typed hierarchical identifiers for every orchestrator entity.

Reference parity: org.apache.tez.dag.records.{TezDAGID,TezVertexID,TezTaskID,
TezTaskAttemptID} (tez-api/src/main/java/org/apache/tez/dag/records/).  The
reference derives IDs from a YARN ApplicationId; here the root is an AppId
string minted by the client/orchestrator.
"""
from __future__ import annotations

import dataclasses
import itertools
import time

_app_seq = itertools.count(1)


def new_app_id(cluster_ts: int | None = None) -> str:
    ts = cluster_ts if cluster_ts is not None else int(time.time())
    return f"app_{ts}_{next(_app_seq):04d}"


@dataclasses.dataclass(frozen=True, order=True)
class DAGId:
    app_id: str
    id: int

    def __str__(self) -> str:
        return f"dag_{self.app_id[4:]}_{self.id}"

    def vertex(self, vid: int) -> "VertexId":
        return VertexId(self, vid)


@dataclasses.dataclass(frozen=True, order=True)
class VertexId:
    dag_id: DAGId
    id: int

    def __str__(self) -> str:
        return f"vertex_{self.dag_id.app_id[4:]}_{self.dag_id.id}_{self.id:02d}"

    def task(self, tid: int) -> "TaskId":
        return TaskId(self, tid)


@dataclasses.dataclass(frozen=True, order=True)
class TaskId:
    vertex_id: VertexId
    id: int

    def __str__(self) -> str:
        return f"task_{str(self.vertex_id)[7:]}_{self.id:06d}"

    def attempt(self, aid: int) -> "TaskAttemptId":
        return TaskAttemptId(self, aid)

    @property
    def dag_id(self) -> DAGId:
        return self.vertex_id.dag_id


@dataclasses.dataclass(frozen=True, order=True)
class TaskAttemptId:
    task_id: TaskId
    id: int

    def __str__(self) -> str:
        return f"attempt_{str(self.task_id)[5:]}_{self.id}"

    @property
    def vertex_id(self) -> VertexId:
        return self.task_id.vertex_id

    @property
    def dag_id(self) -> DAGId:
        return self.task_id.vertex_id.dag_id


@dataclasses.dataclass(frozen=True, order=True)
class ContainerId:
    """An execution slot.  On TPU deployments a 'container' is one runner
    process bound to a TPU host (or a worker thread in local mode)."""
    app_id: str
    id: int

    def __str__(self) -> str:
        return f"container_{self.app_id[4:]}_{self.id:06d}"
