"""Opaque user payloads and entity descriptors.

Reference parity: tez-api/src/main/java/org/apache/tez/dag/api/
{UserPayload,EntityDescriptor,ProcessorDescriptor,InputDescriptor,
OutputDescriptor,...}.java — every pluggable entity is shipped as
(class name, opaque bytes).  Here entities are Python classes addressed by
"module:Class" strings plus a payload that is either raw bytes or any
picklable object (the common case for in-process TPU deployments).
"""
from __future__ import annotations

import dataclasses
import importlib
import pickle
from typing import Any


@dataclasses.dataclass(frozen=True)
class UserPayload:
    """Opaque configuration blob handed to a pluggable entity.

    Reference: UserPayload.java (ByteBuffer + version).
    """
    data: bytes = b""
    version: int = 0

    @staticmethod
    def of(obj: Any) -> "UserPayload":
        if obj is None:
            return UserPayload()
        if isinstance(obj, UserPayload):
            return obj
        if isinstance(obj, bytes):
            return UserPayload(obj)
        return UserPayload(pickle.dumps(obj), version=1)

    def load(self) -> Any:
        if not self.data:
            return None
        if self.version == 1:
            return pickle.loads(self.data)
        return self.data


def _qualname(cls: type) -> str:
    return f"{cls.__module__}:{cls.__qualname__}"


def resolve_class(name: str) -> type:
    mod, _, qual = name.partition(":")
    obj: Any = importlib.import_module(mod)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


@dataclasses.dataclass(frozen=True)
class EntityDescriptor:
    """(class name, payload) pair describing a pluggable entity.

    Reference: EntityDescriptor.java; subclasses mirror the reference's
    ProcessorDescriptor / InputDescriptor / ... type tags.
    """
    class_name: str
    payload: UserPayload = UserPayload()
    history_text: str = ""

    @classmethod
    def create(cls, target: type | str, payload: Any = None,
               history_text: str = "") -> "EntityDescriptor":
        name = target if isinstance(target, str) else _qualname(target)
        return cls(name, UserPayload.of(payload), history_text)

    def instantiate(self, *args: Any, **kw: Any) -> Any:
        return resolve_class(self.class_name)(*args, **kw)

    def with_payload(self, payload: Any) -> "EntityDescriptor":
        return dataclasses.replace(self, payload=UserPayload.of(payload))


class ProcessorDescriptor(EntityDescriptor):
    pass


class InputDescriptor(EntityDescriptor):
    pass


class OutputDescriptor(EntityDescriptor):
    pass


class InputInitializerDescriptor(EntityDescriptor):
    pass


class OutputCommitterDescriptor(EntityDescriptor):
    pass


class VertexManagerPluginDescriptor(EntityDescriptor):
    pass


class EdgeManagerPluginDescriptor(EntityDescriptor):
    pass
