"""Process-global AM attempt-epoch registry: zombie fencing's source of truth.

Reference lineage: the reference fences stale task attempts with the AM
attempt number baked into the YARN container/token identity; a restarted AM
implicitly invalidates its predecessor because the RM kills the old
containers.  In-process and multi-runner deployments here have no RM to do
that killing, so zombie threads of a crashed AM incarnation can keep
running — this registry is how every shared seam (commit arbitration,
umbilical, shuffle registration, output publish) discovers it has been
superseded.

The epoch IS the AM attempt number: monotonically increasing per app across
incarnations.  Every ``DAGAppMaster`` registers ``(app_id, attempt)`` at
construction; components compare their own stamped epoch against
``current(app_id)`` before acting on shared state.

Stamping convention: epoch 0 means "unstamped" (legacy callers, standalone
tests) and is never fenced — fencing only rejects a *known-older* epoch.
"""
from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_current: Dict[str, int] = {}


class EpochFencedError(RuntimeError):
    """An actor from a superseded AM incarnation touched a fenced seam."""


def register(app_id: str, epoch: int) -> int:
    """Record ``epoch`` as a live incarnation of ``app_id``; keeps the max
    (a late-starting old attempt cannot roll the fence back).  Returns the
    current epoch after registration."""
    with _lock:
        cur = max(_current.get(app_id, 0), int(epoch))
        _current[app_id] = cur
        return cur


def current(app_id: str) -> int:
    """The newest registered epoch for ``app_id`` (0 = never registered)."""
    with _lock:
        return _current.get(app_id, 0)


def is_stale(app_id: str, epoch: int) -> bool:
    """True when ``epoch`` is a *known-older* incarnation of ``app_id``.
    Unstamped (<= 0) epochs are never stale."""
    if epoch <= 0:
        return False
    with _lock:
        return epoch < _current.get(app_id, 0)


def reset() -> None:
    """Test hook: drop all registrations (the registry is process-global)."""
    with _lock:
        _current.clear()
