"""Process-global AM attempt-epoch registry: zombie fencing's source of truth.

Reference lineage: the reference fences stale task attempts with the AM
attempt number baked into the YARN container/token identity; a restarted AM
implicitly invalidates its predecessor because the RM kills the old
containers.  In-process and multi-runner deployments here have no RM to do
that killing, so zombie threads of a crashed AM incarnation can keep
running — this registry is how every shared seam (commit arbitration,
umbilical, shuffle registration, output publish) discovers it has been
superseded.

The epoch IS the AM attempt number: monotonically increasing per app across
incarnations.  Every ``DAGAppMaster`` registers ``(app_id, attempt)`` at
construction; components compare their own stamped epoch against
``current(app_id)`` before acting on shared state.

Stamping convention: epoch 0 means "unstamped" (legacy callers, standalone
tests) and is never fenced — fencing only rejects a *known-older* epoch.

Window generalization (streaming mode): the same fence carries a second
coordinate.  A resident streaming DAG processes numbered windows; the open
window id per ``(app_id, stream)`` lives in this registry next to the
attempt epoch, and every seam that fences on epoch also fences on a
*known-older* window.  The pair ``(attempt_epoch, window_id)`` is totally
ordered lexicographically: a zombie from a dead incarnation is caught by
the epoch coordinate, a straggler from a sealed window of the LIVE
incarnation is caught by the window coordinate.  Window id 0 means "batch /
unstamped" and is never fenced, so pre-streaming DAGs behave byte-
identically.
"""
from __future__ import annotations

import threading
from typing import Dict, Tuple

_lock = threading.Lock()
_current: Dict[str, int] = {}
_windows: Dict[Tuple[str, str], int] = {}


class EpochFencedError(RuntimeError):
    """An actor from a superseded AM incarnation touched a fenced seam."""


class WindowFencedError(EpochFencedError):
    """An actor from a superseded *window* touched a fenced seam.

    Subclasses :class:`EpochFencedError` so every existing except-clause
    that absorbs epoch fencing (task runner die-path, shuffle fetch retry
    suppression) absorbs window fencing identically."""


def register(app_id: str, epoch: int) -> int:
    """Record ``epoch`` as a live incarnation of ``app_id``; keeps the max
    (a late-starting old attempt cannot roll the fence back).  Returns the
    current epoch after registration."""
    with _lock:
        cur = max(_current.get(app_id, 0), int(epoch))
        _current[app_id] = cur
        return cur


def current(app_id: str) -> int:
    """The newest registered epoch for ``app_id`` (0 = never registered)."""
    with _lock:
        return _current.get(app_id, 0)


def is_stale(app_id: str, epoch: int) -> bool:
    """True when ``epoch`` is a *known-older* incarnation of ``app_id``.
    Unstamped (<= 0) epochs are never stale."""
    if epoch <= 0:
        return False
    with _lock:
        return epoch < _current.get(app_id, 0)


def register_window(app_id: str, stream: str, window_id: int) -> int:
    """Record ``window_id`` as the open window of ``(app_id, stream)``;
    keeps the max (a replayed older window cannot roll the fence back —
    recovery re-registers the first *uncommitted* window, which is by
    definition >= everything that ever ran).  Returns the open window."""
    with _lock:
        key = (app_id, stream)
        cur = max(_windows.get(key, 0), int(window_id))
        _windows[key] = cur
        return cur


def current_window(app_id: str, stream: str) -> int:
    """The newest registered window for ``(app_id, stream)`` (0 = never)."""
    with _lock:
        return _windows.get((app_id, stream), 0)


def is_stale_window(app_id: str, stream: str, window_id: int) -> bool:
    """True when ``window_id`` is a *known-older* window of the stream.
    Batch / unstamped (<= 0) windows are never stale, and a stream that
    never registered fences nothing."""
    if window_id <= 0 or not stream:
        return False
    with _lock:
        return window_id < _windows.get((app_id, stream), 0)


def reset() -> None:
    """Test hook: drop all registrations (the registry is process-global)."""
    with _lock:
        _current.clear()
        _windows.clear()
