"""Hierarchical counters with limits and aggregation.

Reference parity: tez-api/.../common/counters/{TezCounters,TezCounter,
CounterGroup,TaskCounter,DAGCounter,Limits}.java.  Counters aggregate
task -> vertex -> DAG and double as the profiling surface (SURVEY.md §5.1).
"""
from __future__ import annotations

import enum
import threading
from collections import defaultdict
from typing import Any, Dict, Iterator, Mapping


class CounterLimitExceeded(Exception):
    pass


class Limits:
    """Reference: common/counters/Limits.java (caps configurable via
    tez.counters.max / tez.counters.max.groups, Limits.setConfiguration)."""
    DEFAULT_MAX_COUNTERS = 1200
    DEFAULT_MAX_GROUPS = 500
    DEFAULT_MAX_COUNTER_NAME_LEN = 64
    DEFAULT_MAX_GROUP_NAME_LEN = 256
    MAX_COUNTERS = DEFAULT_MAX_COUNTERS
    MAX_GROUPS = DEFAULT_MAX_GROUPS
    MAX_COUNTER_NAME_LEN = DEFAULT_MAX_COUNTER_NAME_LEN
    MAX_GROUP_NAME_LEN = DEFAULT_MAX_GROUP_NAME_LEN

    @classmethod
    def configure(cls, conf: Any) -> None:
        # always resolve against the pristine defaults so one AM's caps
        # never leak into the next AM in the same process
        try:
            cls.MAX_COUNTERS = int(conf.get("tez.counters.max",
                                            cls.DEFAULT_MAX_COUNTERS))
            cls.MAX_GROUPS = int(conf.get("tez.counters.max.groups",
                                          cls.DEFAULT_MAX_GROUPS))
            cls.MAX_COUNTER_NAME_LEN = int(conf.get(
                "tez.counters.counter-name.max-length",
                cls.DEFAULT_MAX_COUNTER_NAME_LEN))
            cls.MAX_GROUP_NAME_LEN = int(conf.get(
                "tez.counters.group-name.max-length",
                cls.DEFAULT_MAX_GROUP_NAME_LEN))
        except (TypeError, ValueError, AttributeError):
            pass


class TaskCounter(enum.Enum):
    """Reference: TaskCounter.java:26 (the per-IO byte/record/timing counters)."""
    NUM_SPECULATIONS = enum.auto()
    REDUCE_INPUT_GROUPS = enum.auto()
    REDUCE_INPUT_RECORDS = enum.auto()
    REDUCE_OUTPUT_RECORDS = enum.auto()
    # reference-parity entries (TaskCounter.java): INPUT_GROUPS is the
    # deprecated map-side alias, SKIPPED_RECORDS / APPROXIMATE_INPUT_RECORDS
    # exist for analyzer/API compatibility
    INPUT_GROUPS = enum.auto()
    SKIPPED_RECORDS = enum.auto()
    APPROXIMATE_INPUT_RECORDS = enum.auto()
    REDUCE_SKIPPED_GROUPS = enum.auto()
    REDUCE_SKIPPED_RECORDS = enum.auto()
    SPLIT_RAW_BYTES = enum.auto()
    COMBINE_INPUT_RECORDS = enum.auto()
    COMBINE_OUTPUT_RECORDS = enum.auto()
    INPUT_RECORDS_PROCESSED = enum.auto()
    INPUT_SPLIT_LENGTH_BYTES = enum.auto()
    OUTPUT_RECORDS = enum.auto()
    OUTPUT_LARGE_RECORDS = enum.auto()
    OUTPUT_BYTES = enum.auto()
    OUTPUT_BYTES_WITH_OVERHEAD = enum.auto()
    OUTPUT_BYTES_PHYSICAL = enum.auto()
    SPILLED_RECORDS = enum.auto()
    ADDITIONAL_SPILLS_BYTES_WRITTEN = enum.auto()
    ADDITIONAL_SPILLS_BYTES_READ = enum.auto()
    ADDITIONAL_SPILL_COUNT = enum.auto()
    SHUFFLE_CHUNK_COUNT = enum.auto()
    SHUFFLE_BYTES = enum.auto()
    # push-based pipelined shuffle (shuffle/push.py): bytes eagerly pushed
    # into a reducer-side buffer store, and pushes the admission controller
    # (or a dead transport) turned away — rejected spills stay pull-served
    SHUFFLE_PUSH_BYTES = enum.auto()
    SHUFFLE_PUSH_REJECTED = enum.auto()
    SHUFFLE_BYTES_DECOMPRESSED = enum.auto()
    SHUFFLE_BYTES_TO_MEM = enum.auto()
    SHUFFLE_BYTES_TO_DISK = enum.auto()
    SHUFFLE_BYTES_DISK_DIRECT = enum.auto()
    NUM_MEM_TO_DISK_MERGES = enum.auto()
    NUM_DISK_TO_DISK_MERGES = enum.auto()
    SHUFFLE_PHASE_TIME = enum.auto()
    MERGE_PHASE_TIME = enum.auto()
    FIRST_EVENT_RECEIVED = enum.auto()
    LAST_EVENT_RECEIVED = enum.auto()
    NUM_SHUFFLED_INPUTS = enum.auto()
    LOCAL_SHUFFLED_INPUTS = enum.auto()   # same-host handoff (DATA_LOCAL analog)
    NUM_SKIPPED_INPUTS = enum.auto()
    NUM_FAILED_SHUFFLE_INPUTS = enum.auto()
    MERGED_MAP_OUTPUTS = enum.auto()
    GC_TIME_MILLIS = enum.auto()
    CPU_MILLISECONDS = enum.auto()
    WALL_CLOCK_MILLISECONDS = enum.auto()
    PHYSICAL_MEMORY_BYTES = enum.auto()
    VIRTUAL_MEMORY_BYTES = enum.auto()
    COMMITTED_HEAP_BYTES = enum.auto()
    # TPU-specific additions (device data plane profiling)
    DEVICE_SORT_MILLIS = enum.auto()
    DEVICE_MERGE_MILLIS = enum.auto()
    DEVICE_EXCHANGE_MILLIS = enum.auto()
    HBM_BYTES_ALLOCATED = enum.auto()
    HOST_SPILL_BYTES = enum.auto()
    H2D_TRANSFER_BYTES = enum.auto()
    D2H_TRANSFER_BYTES = enum.auto()


# Mesh ICI exchange plane (parallel/coordinator.py): string-named counters
# in their own group — the exchange is an edge-level event, not a per-task
# IO, so it reports through the triggering producer's TezCounters rather
# than the TaskCounter enum.  counter_diff renders these as the `exchange`
# section (efficiency rows are workload-shaped and never flagged; pressure
# rows regress when they GROW — more rounds / more splits means the plane
# started re-rounding or re-partitioning to absorb skew).
MESH_EXCHANGE_GROUP = "MeshExchange"
MESH_EXCHANGE_EFFICIENCY_COUNTERS = (
    "exchange.rows.sent", "exchange.bytes.sent",
    "exchange.coded.duplicate.bytes", "exchange.coded.buddy.wins")
MESH_EXCHANGE_PRESSURE_COUNTERS = ("exchange.rounds", "exchange.splits")


class FileSystemCounter(enum.Enum):
    """Reference: FileSystemCounterGroup (per-FS bytes/ops)."""
    FILE_BYTES_READ = enum.auto()
    FILE_BYTES_WRITTEN = enum.auto()
    FILE_READ_OPS = enum.auto()
    FILE_WRITE_OPS = enum.auto()


class DAGCounter(enum.Enum):
    """Reference: DAGCounter.java."""
    NUM_FAILED_TASKS = enum.auto()
    NUM_KILLED_TASKS = enum.auto()
    NUM_SUCCEEDED_TASKS = enum.auto()
    TOTAL_LAUNCHED_TASKS = enum.auto()
    OTHER_LOCAL_TASKS = enum.auto()
    DATA_LOCAL_TASKS = enum.auto()
    RACK_LOCAL_TASKS = enum.auto()
    AM_CPU_MILLISECONDS = enum.auto()
    AM_GC_TIME_MILLIS = enum.auto()
    NUM_UBER_SUBTASKS = enum.auto()
    TOTAL_CONTAINERS_USED = enum.auto()
    TOTAL_CONTAINER_ALLOCATION_COUNT = enum.auto()
    TOTAL_CONTAINER_REUSE_COUNT = enum.auto()
    NUM_SPECULATIONS = enum.auto()


class TezCounter:
    __slots__ = ("name", "display_name", "value")

    def __init__(self, name: str, display_name: str | None = None, value: int = 0):
        self.name = name
        self.display_name = display_name or name
        self.value = value

    def increment(self, n: int = 1) -> None:
        self.value += n

    def set_value(self, v: int) -> None:
        self.value = v

    def __repr__(self) -> str:
        return f"{self.name}={self.value}"


class CounterGroup:
    def __init__(self, name: str):
        if len(name) > Limits.MAX_GROUP_NAME_LEN:
            name = name[:Limits.MAX_GROUP_NAME_LEN]
        self.name = name
        self._counters: Dict[str, TezCounter] = {}
        self._lock = threading.Lock()

    def __getstate__(self) -> dict:
        state = self.__dict__.copy()
        del state["_lock"]   # locks don't cross the umbilical wire
        return state

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def find_counter(self, name: str, create: bool = True) -> TezCounter:
        # Truncate BEFORE lookup so the dict key and TezCounter.name always
        # agree (names longer than the limit collapse consistently).
        name = name[:Limits.MAX_COUNTER_NAME_LEN]
        c = self._counters.get(name)
        if c is None and create:
            with self._lock:
                c = self._counters.get(name)
                if c is None:
                    if len(self._counters) >= Limits.MAX_COUNTERS:
                        raise CounterLimitExceeded(
                            f"too many counters in {self.name}")
                    c = self._counters[name] = TezCounter(name)
        return c

    def __iter__(self) -> Iterator[TezCounter]:
        return iter(self._counters.values())

    def __len__(self) -> int:
        return len(self._counters)


class TezCounters:
    """Counter registry; enum counters group by enum class name.

    Group/counter *creation* is thread-safe.  Increments are plain
    read-modify-writes: each counter has a single writer (one task thread, or
    the dispatcher thread for vertex/DAG roll-ups) per the control-plane
    single-event-loop rule — mirror of the reference where counters are
    task-local and aggregated centrally.
    """

    def __init__(self) -> None:
        self._groups: Dict[str, CounterGroup] = {}
        self._lock = threading.Lock()

    def __getstate__(self) -> dict:
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def group(self, name: str) -> CounterGroup:
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                if len(self._groups) >= Limits.MAX_GROUPS:
                    raise CounterLimitExceeded("too many counter groups")
                g = self._groups[name] = CounterGroup(name)
            return g

    def find_counter(self, key: "enum.Enum | str", name: str | None = None) -> TezCounter:
        if isinstance(key, enum.Enum):
            return self.group(type(key).__name__).find_counter(key.name)
        assert name is not None
        return self.group(key).find_counter(name)

    def increment(self, key: "enum.Enum | str", n: int = 1) -> None:
        self.find_counter(key).increment(n)

    def aggregate(self, other: "TezCounters") -> None:
        """task->vertex->DAG roll-up (reference: AbstractCounters.incrAllCounters)."""
        for gname, group in other._groups.items():
            mine = self.group(gname)
            for c in group:
                mine.find_counter(c.name).increment(c.value)

    def to_dict(self) -> Dict[str, Dict[str, int]]:
        return {g.name: {c.name: c.value for c in g} for g in self._groups.values()}

    @staticmethod
    def from_dict(d: Mapping[str, Mapping[str, int]]) -> "TezCounters":
        out = TezCounters()
        for gname, counters in d.items():
            g = out.group(gname)
            for cname, v in counters.items():
                g.find_counter(cname).set_value(v)
        return out

    def __iter__(self) -> Iterator[CounterGroup]:
        return iter(self._groups.values())

    def __repr__(self) -> str:
        return f"TezCounters({self.to_dict()!r})"
