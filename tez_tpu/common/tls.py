"""Optional TLS on the DCN socket paths (shuffle fetch + umbilical).

Reference parity: tez-runtime-library http/SSLFactory.java (keystore/
truststore SSL factory used by the fetchers) behind the
`tez.runtime.shuffle.ssl.enable` knob, exercised by
tez-tests TestSecureShuffle.java:70.  Design differences by intent:
PEM files instead of JKS keystores (python `ssl`), ONE knob covers every
DCN socket this framework owns (shuffle server/fetcher AND the AM
umbilical — the reference leaves the umbilical to Hadoop RPC's own
security layer, which does not exist here), and the in-channel HMAC
handshakes stay on — TLS wraps them, it does not replace them.

Config keys (TEZ_TPU_SSL_* env fallback, so a freshly-launched runner
process can dial the AM umbilical before any conf has arrived):

  tez.runtime.shuffle.ssl.enable   bool          TEZ_TPU_SSL_ENABLE=1
  tez.shuffle.ssl.cert.path        PEM cert      TEZ_TPU_SSL_CERT
  tez.shuffle.ssl.key.path         PEM key       TEZ_TPU_SSL_KEY
  tez.shuffle.ssl.ca.path          CA bundle     TEZ_TPU_SSL_CA

Every endpoint (server or client) presents the cert and verifies its
peer against the CA — mutual TLS, which is what a shuffle fleet wants
(any node is both producer and consumer).  Hostname checks are off
(cluster nodes dial raw IPs); the CA is the trust root.
"""
from __future__ import annotations

import os
import ssl
from typing import Any, Dict, Optional

#: conf key -> env fallback
_KEYS = {
    "enable": ("tez.runtime.shuffle.ssl.enable", "TEZ_TPU_SSL_ENABLE"),
    "cert": ("tez.shuffle.ssl.cert.path", "TEZ_TPU_SSL_CERT"),
    "key": ("tez.shuffle.ssl.key.path", "TEZ_TPU_SSL_KEY"),
    "ca": ("tez.shuffle.ssl.ca.path", "TEZ_TPU_SSL_CA"),
}


def _get(conf: Any, name: str) -> Any:
    conf_key, env_key = _KEYS[name]
    v = None
    if conf is not None:
        v = conf.get(conf_key)
    if v in (None, ""):
        v = os.environ.get(env_key)
    return v


def tls_config(conf: Any = None) -> Optional[Dict[str, str]]:
    """-> {cert, key, ca} when TLS is enabled, else None.  Loud on a
    half-configured setup — silently falling back to plaintext would be
    worse than failing."""
    enable = _get(conf, "enable")
    if not enable or str(enable).lower() in ("0", "false", ""):
        return None
    cfg = {name: _get(conf, name) for name in ("cert", "key", "ca")}
    missing = [n for n, v in cfg.items() if not v]
    if missing:
        raise ValueError(
            f"shuffle TLS is enabled but {missing} not configured "
            f"(tez.shuffle.ssl.*.path / TEZ_TPU_SSL_*)")
    for n, path in cfg.items():
        if not os.path.exists(path):
            raise ValueError(f"shuffle TLS {n} file not found: {path}")
    return cfg


def _context(purpose: ssl.Purpose, cfg: Dict[str, str]) -> ssl.SSLContext:
    ctx = ssl.create_default_context(purpose, cafile=cfg["ca"])
    ctx.load_cert_chain(cfg["cert"], cfg["key"])
    ctx.check_hostname = False          # cluster peers dial raw IPs
    ctx.verify_mode = ssl.CERT_REQUIRED  # mutual: both sides verify
    return ctx


def server_context(conf: Any = None) -> Optional[ssl.SSLContext]:
    cfg = tls_config(conf)
    return None if cfg is None else _context(ssl.Purpose.CLIENT_AUTH, cfg)


def client_context(conf: Any = None) -> Optional[ssl.SSLContext]:
    cfg = tls_config(conf)
    return None if cfg is None else _context(ssl.Purpose.SERVER_AUTH, cfg)


def wrap_server_class(server_cls, ssl_context):
    """TCP-server class whose accepted sockets are TLS-terminated (the
    in-channel HMAC handshakes then run inside the encrypted stream);
    passthrough when ssl_context is None.

    The handshake is DEFERRED (do_handshake_on_connect=False): get_request
    runs on the single accept thread, and a stalled or plaintext peer must
    never block accepts for everyone — the handshake happens on the first
    read inside the per-connection handler thread."""
    if ssl_context is None:
        return server_cls

    class _TLSServer(server_cls):
        def get_request(self):
            sock, addr = server_cls.get_request(self)
            return ssl_context.wrap_socket(
                sock, server_side=True,
                do_handshake_on_connect=False), addr

    return _TLSServer


def resolve_conf(getter) -> Dict[str, Any]:
    """Build a TLS conf dict through a caller-supplied `getter(conf_key)`
    (e.g. a runtime context whose config merges edge payloads) — keeps the
    key vocabulary in this module."""
    return {ck: getter(ck) for ck, _env in _KEYS.values()}


def export_env(conf: Any) -> Dict[str, str]:
    """Env block that carries the TLS config into launched runner
    processes (subprocess/pod launchers merge this into the runner env)."""
    cfg = tls_config(conf)
    if cfg is None:
        return {}
    return {"TEZ_TPU_SSL_ENABLE": "1",
            "TEZ_TPU_SSL_CERT": cfg["cert"],
            "TEZ_TPU_SSL_KEY": cfg["key"],
            "TEZ_TPU_SSL_CA": cfg["ca"]}
