"""Declarative state-machine kernel used by DAG/Vertex/Task/Attempt.

Reference parity: Hadoop's StateMachineFactory + tez-dag/.../state/
StateMachineTez.java:27 (state-change callbacks).  Transitions are declared as
a table; hooks may return the next state (multi-arc transitions).
"""
from __future__ import annotations

import enum
import logging
from typing import Any, Callable, Dict, Generic, Iterable, Tuple, TypeVar

log = logging.getLogger(__name__)

S = TypeVar("S", bound=enum.Enum)


class InvalidStateTransition(Exception):
    def __init__(self, state: enum.Enum, event_type: enum.Enum):
        super().__init__(f"invalid event {event_type} at state {state}")
        self.state = state
        self.event_type = event_type


Transition = Callable[[Any, Any], "enum.Enum | None"]


class StateMachineFactory(Generic[S]):
    """Builds immutable transition tables.

    ``add(pre, post, events, hook)`` — single-arc: hook's return ignored.
    ``add_multi(pre, posts, events, hook)`` — multi-arc: hook returns one of
    ``posts``.
    """

    def __init__(self, initial_state: S):
        self.initial_state = initial_state
        self._table: Dict[Tuple[S, enum.Enum], Tuple[Tuple[S, ...], Transition | None]] = {}

    def add(self, pre: S, post: S,
            events: "enum.Enum | Iterable[enum.Enum]",
            hook: Transition | None = None) -> "StateMachineFactory[S]":
        return self.add_multi(pre, (post,), events, hook)

    def add_multi(self, pre: S, posts: Iterable[S],
                  events: "enum.Enum | Iterable[enum.Enum]",
                  hook: Transition | None = None) -> "StateMachineFactory[S]":
        posts = tuple(posts)
        assert len(posts) == 1 or hook is not None, \
            "multi-arc transition requires a hook to pick the post state"
        if isinstance(events, enum.Enum):
            events = [events]
        for ev in events:
            key = (pre, ev)
            assert key not in self._table, f"duplicate transition {key}"
            self._table[key] = (posts, hook)
        return self

    def make(self, entity: Any,
             on_state_change: Callable[[Any, S, S], None] | None = None) -> "StateMachine[S]":
        return StateMachine(self, entity, on_state_change)


class StateMachine(Generic[S]):
    def __init__(self, factory: StateMachineFactory[S], entity: Any,
                 on_state_change: Callable[[Any, S, S], None] | None = None):
        self._factory = factory
        self._entity = entity
        self._state = factory.initial_state
        self._on_state_change = on_state_change

    @property
    def state(self) -> S:
        return self._state

    def force_state(self, state: S) -> None:
        """Recovery-only escape hatch (reference: recovery transitions)."""
        self._state = state

    def handle(self, event: Any) -> S:
        key = (self._state, event.event_type)
        entry = self._factory._table.get(key)
        if entry is None:
            raise InvalidStateTransition(self._state, event.event_type)
        posts, hook = entry
        old = self._state
        if hook is not None:
            ret = hook(self._entity, event)
            if len(posts) == 1:
                new = posts[0]
            else:
                assert ret in posts, f"hook returned {ret}, expected one of {posts}"
                new = ret
        else:
            new = posts[0]
        self._state = new
        if new is not old and self._on_state_change is not None:
            self._on_state_change(self._entity, old, new)
        return new

    def can_handle(self, event_type: enum.Enum) -> bool:
        return (self._state, event_type) in self._factory._table
