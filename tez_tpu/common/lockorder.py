"""Runtime lock-order witness: record actual nested lock acquisitions and
catch order inversions, cross-validating the static lock graph.

Mirrors the :mod:`tez_tpu.common.faults` arm/disarm shape: a process-global
plane, scope tokens so concurrent arms compose, a module-level ``_armed``
flag as the production fast path, and ``install_from_conf`` reading the
``tez.debug.lockorder`` knob on the AM submit path.

While armed, the ``threading.Lock`` / ``RLock`` / ``Condition``
constructors are patched with factories that wrap locks *created from
source files inside the tez_tpu package* (stdlib-internal locks — e.g.
the Condition threading.Event builds — and locks created by tests or by
this module stay raw, which is what keeps the observed edge set a subset
of the static graph built by :mod:`tez_tpu.analysis.lockorder`).  Each
wrapped lock is named from its creation site using the *same scheme the
static analyzer uses*: ``{module}.{Class}.{attr}`` for ``self.X =
threading.Lock()`` inside a method, ``{module}.{var}`` at module level —
so static vs. dynamic comparison is plain set algebra.
``threading.Condition(self.X)`` on an already-wrapped lock is an alias:
the condition acquires under the wrapped lock's own name.

On every acquire with locks already held, the witness records the
held->new edges and checks reverse reachability in the edges observed so
far: acquiring B while holding A after some thread ever ordered B before
A (directly or transitively) is an order violation — the runtime shadow
of the static checker's cycle report.

Limits (by design): locks created *before* arming — import-time module
singletons — are invisible; the witness sees order among locks born
during the armed window (in tests: everything test bodies construct).
Wrappers survive disarm and simply stop recording.
"""
from __future__ import annotations

import dataclasses
import linecache
import logging
import os
import re
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

log = logging.getLogger(__name__)

#: Originals captured at import, before any patching — also used for the
#: witness's own internal lock so it never observes itself.
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_CONDITION = threading.Condition

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SELF_FILE = os.path.abspath(__file__)

#: ``self.attr = ...`` / ``attr = ...`` on a lock-constructing line; the
#: creation frame plus this names the lock like the static analyzer does.
_ASSIGN_RE = re.compile(r"^\s*(?:self\.(\w+)|(\w+))\s*(?::[^=]+)?=\s")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One observed inversion: ``acquired`` taken while ``held`` was held,
    after earlier observations ordered ``acquired`` before ``held``."""
    held: str
    acquired: str
    thread: str
    where: str          # file:line of the inverting acquire

    def render(self) -> str:
        return (f"lock-order inversion: acquired {self.acquired} while "
                f"holding {self.held} (thread {self.thread}, {self.where}); "
                f"prior observations order {self.acquired} before "
                f"{self.held}")


def _defining_class(frame) -> Optional[type]:
    """The class whose method the frame is executing — py3.10 has no
    ``co_qualname``, so scan the receiver's MRO for the class that owns
    this exact code object."""
    self_obj = frame.f_locals.get("self")
    if self_obj is None:
        return None
    code = frame.f_code
    for klass in type(self_obj).__mro__:
        fn = klass.__dict__.get(code.co_name)
        fn = getattr(fn, "__func__", fn)
        if getattr(fn, "__code__", None) is code:
            return klass
    return type(self_obj)


def _creation_name(frame) -> str:
    """Lock name from its creation frame, in static-analyzer notation."""
    fname = os.path.abspath(frame.f_code.co_filename)
    rel = os.path.relpath(fname, _PKG_DIR).replace(os.sep, "/")
    module = rel[:-3] if rel.endswith(".py") else rel
    if module.endswith("/__init__"):
        module = module[: -len("/__init__")]
    module = module.replace("/", ".")
    line = linecache.getline(fname, frame.f_lineno)
    m = _ASSIGN_RE.match(line)
    self_attr = m.group(1) if m else None
    var = m.group(2) if m else None
    if self_attr is not None:
        klass = _defining_class(frame)
        if klass is not None:
            return f"{module}.{klass.__qualname__}.{self_attr}"
        return f"{module}.{self_attr}"
    if var is not None:
        return f"{module}.{var}"
    return f"{module}.<anon@{frame.f_code.co_name}:{frame.f_lineno}>"


def _site_of(frame) -> str:
    # skip the wrapper's own frames (__enter__ -> acquire) so the
    # reported site is the caller's ``with`` statement
    while frame is not None and \
            os.path.abspath(frame.f_code.co_filename) == _SELF_FILE:
        frame = frame.f_back
    if frame is None:
        return "<unknown>"
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"


class LockWitness:
    """Edge/violation accumulator.  A process singleton backs the armed
    plane; tests provoke inversions on private instances (via
    :meth:`wrap`) so deliberate violations never pollute the global
    record the conftest finalizer asserts on."""

    def __init__(self) -> None:
        self._lock = _ORIG_LOCK()
        #: (held, acquired) -> first-observed site
        self._edges: Dict[Tuple[str, str], str] = {}
        self._violations: List[Violation] = []
        self._names: Set[str] = set()
        self._tls = threading.local()

    # -- recording -----------------------------------------------------------
    def note_created(self, name: str) -> None:
        with self._lock:
            self._names.add(name)

    def on_acquired(self, name: str) -> None:
        """Called *after* the real acquire succeeds.  The acquire site is
        resolved — and reachability searched — only when a *new* edge is
        recorded: a cycle is always flagged when its closing edge first
        appears, so re-walking known edges would find nothing new and
        the steady-state nested hot path stays one dict probe per held
        lock."""
        try:
            stack = self._tls.stack
        except AttributeError:
            stack = self._tls.stack = []
        if not stack:                  # common case: no nesting
            stack.append([name, 1])
            return
        for entry in stack:
            if entry[0] == name:       # reentrant (RLock): no new edges
                entry[1] += 1
                return
        held = [entry[0] for entry in stack]
        stack.append([name, 1])
        where = None
        with self._lock:
            for h in held:
                key = (h, name)
                if key in self._edges:
                    continue
                if where is None:      # lazy: only for genuinely new edges
                    where = _site_of(sys._getframe(1))
                if self._reachable(name, h):
                    self._violations.append(Violation(
                        h, name, threading.current_thread().name, where))
                self._edges[key] = where

    def on_released(self, name: str) -> None:
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return
        top = stack[-1]
        if top[0] == name:             # common case: LIFO release
            if top[1] == 1:
                stack.pop()
            else:
                top[1] -= 1
            return
        for i in range(len(stack) - 2, -1, -1):
            if stack[i][0] == name:
                stack[i][1] -= 1
                if stack[i][1] == 0:
                    del stack[i]
                return

    def _reachable(self, src: str, dst: str) -> bool:
        """dst reachable from src over observed edges (caller holds lock)."""
        seen = {src}
        frontier = [src]
        while frontier:
            node = frontier.pop()
            for (a, b) in self._edges:
                if a == node and b not in seen:
                    if b == dst:
                        return True
                    seen.add(b)
                    frontier.append(b)
        return False

    # -- wrapping ------------------------------------------------------------
    def wrap(self, inner, name: str) -> "_WitnessLock":
        self.note_created(name)
        return _WitnessLock(inner, name, self)

    # -- inspection ----------------------------------------------------------
    def edges(self) -> Set[Tuple[str, str]]:
        with self._lock:
            return set(self._edges)

    def edge_sites(self) -> Dict[Tuple[str, str], str]:
        with self._lock:
            return dict(self._edges)

    def violations(self) -> List[Violation]:
        with self._lock:
            return list(self._violations)

    def lock_names(self) -> Set[str]:
        with self._lock:
            return set(self._names)

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._violations.clear()
            self._names.clear()


class _WitnessLock:
    """Wrapper around a real Lock/RLock recording acquisition order.

    Implements the private Condition hooks (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``) so ``threading.Condition(self.X)``
    over a wrapped lock keeps the witness held-stack exact across
    ``wait()``'s release/reacquire cycle.
    """

    __slots__ = ("_inner", "_witness_name", "_witness")

    def __init__(self, inner, name: str, witness: LockWitness) -> None:
        self._inner = inner
        self._witness_name = name
        self._witness = witness

    def __repr__(self) -> str:
        return f"<witnessed {self._inner!r} as {self._witness_name}>"

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got and _armed:
            self._witness.on_acquired(self._witness_name)
        return got

    def release(self) -> None:
        self._inner.release()
        if _armed:
            self._witness.on_released(self._witness_name)

    # inlined acquire/release: with-blocks are the package idiom and the
    # wrapper tax is paid on every one of them
    def __enter__(self):
        self._inner.acquire()
        if _armed:
            self._witness.on_acquired(self._witness_name)
        return self

    def __exit__(self, *exc) -> None:
        self._inner.release()
        if _armed:
            self._witness.on_released(self._witness_name)

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition integration ----------------------------------------------
    def _release_save(self):
        if _armed:
            self._witness.on_released(self._witness_name)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        if _armed:
            self._witness.on_acquired(self._witness_name)

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True


# --------------------------------------------------------------------------
# Constructor patching
# --------------------------------------------------------------------------

def _should_wrap(frame) -> bool:
    fname = os.path.abspath(frame.f_code.co_filename)
    if fname == _SELF_FILE:
        return False
    return fname.startswith(_PKG_DIR + os.sep)


def _lock_factory(*args, **kwargs):
    frame = sys._getframe(1)
    inner = _ORIG_LOCK(*args, **kwargs)
    if not _armed or not _should_wrap(frame):
        return inner
    return _WITNESS.wrap(inner, _creation_name(frame))


def _rlock_factory(*args, **kwargs):
    frame = sys._getframe(1)
    inner = _ORIG_RLOCK(*args, **kwargs)
    if not _armed or not _should_wrap(frame):
        return inner
    return _WITNESS.wrap(inner, _creation_name(frame))


def _condition_factory(lock=None):
    frame = sys._getframe(1)
    if lock is None and _armed and _should_wrap(frame):
        # an anonymous Condition owns its lock: name the hidden RLock
        # after the condition attribute itself, exactly as the static
        # analyzer names ``self.cv = threading.Condition()``
        lock = _WITNESS.wrap(_ORIG_RLOCK(), _creation_name(frame))
    # a wrapped ``lock`` argument needs no new name — the condition
    # acquires through the wrapper, aliasing to the inner lock's name
    return _ORIG_CONDITION(lock)


# --------------------------------------------------------------------------
# Plane arm / disarm (faults.py shape)
# --------------------------------------------------------------------------

_WITNESS = LockWitness()
_armed = False     # module-level fast path, same convention as faults._armed
_scopes: Set[str] = set()
_plane_lock = _ORIG_LOCK()


def witness() -> LockWitness:
    return _WITNESS


def armed() -> bool:
    return _armed


def arm(scope: str = "default") -> None:
    """Arm the witness for ``scope``; the constructor patch installs on
    the first live scope."""
    global _armed
    with _plane_lock:
        first = not _scopes
        _scopes.add(scope)
        if first:
            threading.Lock = _lock_factory
            threading.RLock = _rlock_factory
            threading.Condition = _condition_factory
            _armed = True
            log.info("lock-order witness armed (scope %s)", scope)


def disarm(scope: str = "default") -> None:
    global _armed
    with _plane_lock:
        _scopes.discard(scope)
        if not _scopes and _armed:
            threading.Lock = _ORIG_LOCK
            threading.RLock = _ORIG_RLOCK
            threading.Condition = _ORIG_CONDITION
            _armed = False
            log.info("lock-order witness disarmed")


def clear_all() -> None:
    """Disarm every scope and drop accumulated observations."""
    global _armed
    with _plane_lock:
        _scopes.clear()
        if _armed:
            threading.Lock = _ORIG_LOCK
            threading.RLock = _ORIG_RLOCK
            threading.Condition = _ORIG_CONDITION
            _armed = False
    _WITNESS.reset()


def install_from_conf(conf, scope: str) -> bool:
    """Arm from the ``tez.debug.lockorder`` knob (AM submit path, the
    exact seam faults.install_from_conf uses).  Returns True when armed."""
    from tez_tpu.common import config as C
    if not bool(conf.get(C.DEBUG_LOCKORDER)):
        return False
    arm(scope)
    return True


# -- convenience assertions used by tests and the chaos harness ------------

def check(static_edges: Optional[Set[Tuple[str, str]]] = None,
          static_locks: Optional[Set[str]] = None) -> List[str]:
    """Problems found so far, rendered; empty list = clean.

    With ``static_edges``/``static_locks`` from
    :func:`tez_tpu.analysis.lockorder.build_graph`, also verifies the
    cross-validation contract: every observed edge between locks the
    static pass discovered must appear in the static graph.
    """
    problems = [v.render() for v in _WITNESS.violations()]
    if static_edges is not None and static_locks is not None:
        sites = _WITNESS.edge_sites()
        for (a, b) in sorted(_WITNESS.edges()):
            if a in static_locks and b in static_locks and \
                    (a, b) not in static_edges:
                problems.append(
                    f"witnessed edge missing from static graph: {a} -> {b} "
                    f"(first at {sites[(a, b)]})")
    return problems
