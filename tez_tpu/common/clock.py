"""One shared monotonic clock for cross-plane event correlation.

Every observability plane that timestamps events independently picks its
own axis: spans use ``time.time()`` (epoch seconds, comparable across
processes), stage pipelines use ``time.perf_counter()`` (monotonic,
process-local), and the flight recorder needs integer nanoseconds cheap
enough for a ~O(100ns) append.  This module anchors them to each other:
a single ``(wall, monotonic_ns)`` pair captured at import lets any
monotonic timestamp be projected onto the wall clock (and back), so the
doctor can join flight events with history timestamps and span
start/end times on one axis.

The anchor is deliberately captured once — NTP steps after import skew
the projection, but a *stable* mapping matters more than an exact one:
all intra-process deltas stay exact, and the wall projection is only
used to line flight events up against history/span times recorded in
the same process lifetime.
"""
from __future__ import annotations

import time
from typing import Tuple

#: (epoch seconds, monotonic ns) captured together at import — the one
#: anchor every projection in this process uses.
_ANCHOR: Tuple[float, int] = (time.time(), time.monotonic_ns())


def mono_ns() -> int:
    """Integer monotonic nanoseconds — the flight recorder's time axis."""
    return time.monotonic_ns()


def mono_s() -> float:
    """Monotonic seconds on the shared axis (``mono_ns() / 1e9``).

    The injectable replacement for raw ``time.monotonic()`` in ``am/``
    and ``obs/`` — graftlint's rawtime checker bans the raw call there so
    every duration and series timestamp provably shares this module's
    anchor (wall/mono drift between independently-sampled clocks was
    hand-caught in the PR-12 review; now it is structural)."""
    return time.monotonic_ns() / 1e9


def wall_s() -> float:
    """Epoch seconds — the injectable replacement for raw ``time.time()``
    in ``am/`` and ``obs/`` (see :func:`mono_s`)."""
    return time.time()


def anchor() -> Tuple[float, int]:
    """The process ``(wall_s, mono_ns)`` anchor pair.  Flight dumps embed
    it so an offline reader can project event times onto the wall axis of
    the history journal written by the same process."""
    return _ANCHOR


def mono_to_wall(ns: int, anchor_pair: Tuple[float, int] = None) -> float:
    """Project a monotonic-ns timestamp onto epoch seconds."""
    wall0, mono0 = anchor_pair if anchor_pair is not None else _ANCHOR
    return wall0 + (ns - mono0) / 1e9


def wall_to_mono_ns(wall_s: float,
                    anchor_pair: Tuple[float, int] = None) -> int:
    """Project epoch seconds back onto the monotonic-ns axis."""
    wall0, mono0 = anchor_pair if anchor_pair is not None else _ANCHOR
    return mono0 + int((wall_s - wall0) * 1e9)
