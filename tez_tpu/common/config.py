"""Typed configuration with scope annotations.

Reference parity: tez-api/.../TezConfiguration.java (238 keys,
@ConfigurationScope annotations) and TezRuntimeConfiguration.java (70 runtime
keys filtered into per-IO payloads via the edge config builders).  The design
rule kept from the reference: *runtime config travels inside the edge payload,
not global files* (SURVEY.md §5.6).

TPU-first deltas: memory keys budget HBM instead of JVM heap; sorter/shuffle
keys configure device kernels (span bytes = HBM block size, io factor = k-way
merge width on device).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Iterator, Mapping


_UNSET = object()


class Scope(enum.Enum):
    """Reference: ConfigurationScope.java — where a key may be overridden."""
    AM = "am"
    DAG = "dag"
    VERTEX = "vertex"
    CLIENT = "client"


@dataclasses.dataclass(frozen=True)
class ConfKey:
    name: str
    default: Any
    scope: Scope
    doc: str = ""

    def __call__(self, conf: "TezConfiguration") -> Any:
        return conf.get(self)


_REGISTRY: dict[str, ConfKey] = {}


def _key(name: str, default: Any, scope: Scope, doc: str = "") -> ConfKey:
    k = ConfKey(name, default, scope, doc)
    _REGISTRY[name] = k
    return k


class TezConfiguration(dict):
    """String-keyed config map with typed accessors.

    Mirrors Hadoop `Configuration` usage in the reference but is a plain dict
    so it pickles into payloads cheaply.
    """

    def get_key(self, key: "ConfKey | str", default: Any = _UNSET) -> Any:
        return self.get(key, default)

    def get(self, key: Any, default: Any = _UNSET) -> Any:  # type: ignore[override]
        """Precedence: stored value > caller-supplied default > registered
        ConfKey default > None."""
        if isinstance(key, ConfKey):
            name, reg_default = key.name, key.default
        else:
            name = key
            reg = _REGISTRY.get(key)
            reg_default = reg.default if reg is not None else None
        if name in self:
            return self[name]
        return reg_default if default is _UNSET else default

    def set(self, key: "ConfKey | str", value: Any) -> "TezConfiguration":
        self[key.name if isinstance(key, ConfKey) else key] = value
        return self

    def merged(self, other: Mapping | None) -> "TezConfiguration":
        out = TezConfiguration(self)
        if other:
            out.update(other)
        return out

    def subset(self, prefix: str) -> "TezConfiguration":
        return TezConfiguration(
            {k: v for k, v in self.items() if k.startswith(prefix)})

    @staticmethod
    def registry() -> Iterator[ConfKey]:
        return iter(_REGISTRY.values())


# --------------------------------------------------------------------------
# AM / framework keys (TezConfiguration.java analog)
# --------------------------------------------------------------------------
# registry-compat key superseded by tez.framework.mode  # graftlint: disable=knob-unread
LOCAL_MODE = _key("tez.local.mode", True, Scope.CLIENT,
                  "Run orchestrator in-process (reference: TezConfiguration.TEZ_LOCAL_MODE)")
SESSION_MODE = _key("tez.session.mode", False, Scope.CLIENT,
                    "Keep AM alive across DAGs")
FRAMEWORK_MODE = _key(
    "tez.framework.mode", "local", Scope.CLIENT,
    "'local' = in-process AM; 'remote' = connect to a running AM over "
    "the umbilical wire (client-only key, never shipped into DAG plans)")
AM_ADDRESS = _key(
    "tez.am.address", "", Scope.CLIENT,
    "host:port of the remote AM umbilical endpoint (remote framework "
    "mode; client-only key)")
JOB_TOKEN = _key(
    "tez.job.token", "", Scope.CLIENT,
    "hex-encoded shared job secret authenticating umbilical and shuffle "
    "peers (client-only key, never shipped into DAG plans — see "
    "TezClient._CLIENT_ONLY_KEYS)")
APP_ID = _key(
    "tez.app.id", "", Scope.AM,
    "externally-assigned application id for history/log correlation; "
    "'' = derive one from the submit timestamp")
STAGING_DIR = _key("tez.staging-dir", "/tmp/tez-tpu-staging", Scope.CLIENT)
AM_MAX_APP_ATTEMPTS = _key("tez.am.max.app.attempts", 2, Scope.AM)
TASK_MAX_FAILED_ATTEMPTS = _key("tez.am.task.max.failed.attempts", 4, Scope.VERTEX,
                                "Reference: TezConfiguration.TEZ_AM_TASK_MAX_FAILED_ATTEMPTS")
MAX_ALLOWED_OUTPUT_FAILURES = _key("tez.am.max.allowed.output.failures", 10, Scope.VERTEX)
MAX_ALLOWED_OUTPUT_FAILURES_FRACTION = _key(
    "tez.am.max.allowed.output.failures.fraction", 0.1, Scope.VERTEX)
MAX_ALLOWED_TIME_FOR_READ_ERROR_SEC = _key(
    "tez.am.max.allowed.time-sec.for-read-error", 300, Scope.VERTEX,
    "Output-failure reports persisting past this window fail the source "
    "attempt regardless of counts (consumers stuck too long)")
TASK_RESCHEDULE_HIGHER_PRIORITY = _key(
    "tez.am.task.reschedule.higher.priority", True, Scope.VERTEX,
    "Re-runs after output loss schedule ahead of their vertex's normal "
    "priority (they block live consumers)")
NODE_BLACKLISTING_ENABLED = _key("tez.am.node-blacklisting.enabled", True, Scope.AM)
NODE_BLACKLISTING_FAILURE_THRESHOLD = _key(
    "tez.am.node-blacklisting.ignore-threshold-node-percent", 33, Scope.AM,
    "Blacklists are ignored (nodes FORCED_ACTIVE) above this percent")
NODE_MAX_TASK_FAILURES = _key(
    "tez.am.maxtaskfailures.per.node", 10, Scope.AM,
    "Task-attempt failures on one node before it is blacklisted "
    "(reference: AMNodeImpl)")
AM_CONTAINER_REUSE_ENABLED = _key("tez.am.container.reuse.enabled", True, Scope.AM)
AM_SESSION_MIN_HELD_CONTAINERS = _key("tez.am.session.min.held-containers", 0, Scope.AM)
AM_CONTAINER_IDLE_RELEASE_TIMEOUT_MIN = _key(
    "tez.am.container.idle.release-timeout-min.millis", 5000, Scope.AM)
TASK_HEARTBEAT_TIMEOUT_MS = _key("tez.task.heartbeat.timeout-ms", 300_000, Scope.VERTEX)
# reference-parity key; liveness uses tez.task.heartbeat.timeout-ms  # graftlint: disable=knob-unread
CONTAINER_HEARTBEAT_TIMEOUT_MS = _key("tez.container.heartbeat.timeout-ms", 300_000, Scope.AM)
TASK_PROGRESS_STUCK_INTERVAL_MS = _key("tez.task.progress.stuck.interval-ms", -1, Scope.VERTEX)
SPECULATION_ENABLED = _key("tez.am.speculation.enabled", False, Scope.VERTEX)
SPECULATION_SLOWTASK_THRESHOLD = _key(
    "tez.am.legacy.speculative.slowtask.threshold", 1.0, Scope.VERTEX)
SPECULATION_ESTIMATOR = _key("tez.am.legacy.speculative.estimator.class",
                             "simple_exponential", Scope.VERTEX)
SPECULATION_SMOOTH_LAMBDA_MS = _key(
    "tez.am.legacy.speculative.exponential.smooth.lambda-millis", 30_000,
    Scope.VERTEX,
    "time constant of the exponentially-smoothed progress rate")
SPECULATION_STAGNATED_MS = _key(
    "tez.am.legacy.speculative.exponential.stagnated.millis", 90_000,
    Scope.VERTEX,
    "no progress change for this long marks the attempt stagnated "
    "(estimate becomes infinite)")
SPECULATION_SKIP_INITIALS = _key(
    "tez.am.legacy.speculative.exponential.skip.initials", 8, Scope.VERTEX,
    "progress samples to observe before trusting the smoothed estimate")
SPECULATION_MIN_ALLOWED_TASKS = _key(
    "tez.am.minimum.allowed.speculative.tasks", 10, Scope.VERTEX,
    "floor of the concurrent-speculation cap (reference: "
    "LegacySpeculator.minimumAllowedSpeculativeTasks)")
SPECULATION_PROPORTION_TOTAL = _key(
    "tez.am.proportion.total.tasks.speculatable", 0.01, Scope.VERTEX,
    "cap component: this fraction of ALL tasks may speculate at once")
SPECULATION_PROPORTION_RUNNING = _key(
    "tez.am.proportion.running.tasks.speculatable", 0.1, Scope.VERTEX,
    "cap component: this fraction of RUNNING tasks may speculate at once")
SPECULATION_RETRY_AFTER_NO_SPECULATE_MS = _key(
    "tez.am.soonest.retry.after.no.speculate", 1000, Scope.VERTEX,
    "rescan delay when the last scan launched nothing")
SPECULATION_RETRY_AFTER_SPECULATE_MS = _key(
    "tez.am.soonest.retry.after.speculate", 15_000, Scope.VERTEX,
    "rescan delay after launching a speculation (let it prove itself)")
SPECULATION_SINGLE_TASK_VERTEX_TIMEOUT_MS = _key(
    "tez.am.legacy.speculative.single.task.vertex.timeout", -1, Scope.VERTEX,
    "single-task vertices have no sibling completions to estimate from; "
    "speculate their attempt on this wall-clock timeout instead "
    "(-1 = never, the reference default)")
DAG_RECOVERY_ENABLED = _key("tez.dag.recovery.enabled", True, Scope.AM)
RECOVERY_TRUSTED_STAGING = _key(
    "tez.dag.recovery.trusted-staging", False, Scope.AM,
    "allow pickle-encoded journal payloads during recovery replay (only "
    "safe when the staging dir is writable solely by the framework)")
DAG_RECOVERY_FLUSH_INTERVAL_SECS = _key("tez.dag.recovery.flush.interval.secs", 30, Scope.AM)
AM_EPOCH_FENCING_ENABLED = _key(
    "tez.am.epoch.fencing.enabled", True, Scope.AM,
    "Reject umbilical/commit/shuffle traffic stamped with an older AM "
    "attempt epoch, and stop acting once this AM is itself superseded "
    "(zombie fencing across AM restarts; see docs/recovery.md)")
AM_RECOVERY_QUEUE_REPLAY = _key(
    "tez.am.recovery.queue-replay.enabled", True, Scope.AM,
    "on AM restart, rebuild the admission queue from unresolved "
    "DAG_QUEUED / DAG_REQUEUED_ON_RECOVERY journal records (original "
    "tenant + arrival order preserved; each replay journals a "
    "DAG_REQUEUED_ON_RECOVERY event) — the redeem side of the "
    "lossless-admission contract (docs/recovery.md)")
AM_RECOVERY_REATTACH_RETRIES = _key(
    "tez.am.recovery.reattach.retries", 5, Scope.CLIENT,
    "client re-attach: connection attempts against the captured AM "
    "address before giving up (full-jitter exponential backoff between "
    "tries) — covers the restart window of a crashed AM")
AM_RECOVERY_REATTACH_BACKOFF_MS = _key(
    "tez.am.recovery.reattach.backoff-ms", 200.0, Scope.CLIENT,
    "client re-attach: base of the full-jitter exponential backoff "
    "between connection attempts")
AM_COMMIT_RECOVERY_POLICY = _key(
    "tez.am.commit.recovery.policy", "resume", Scope.AM,
    "What recovery does with a DAG whose commit ledger shows "
    "COMMIT_STARTED without COMMIT_FINISHED/ABORTED: 'resume' re-runs the "
    "idempotent committers and rolls the commit forward; 'fail' keeps the "
    "reference semantics (partial commits fail the DAG)")
AM_HISTORY_LOGGING_ENABLED = _key(
    "tez.am.history.logging.enabled", True, Scope.AM,
    "Master switch for the history logging service (recovery journaling "
    "is unaffected); reference: TEZ_AM_HISTORY_LOGGING_ENABLED")
DAG_HISTORY_LOGGING_ENABLED = _key(
    "tez.dag.history.logging.enabled", True, Scope.DAG,
    "Per-DAG history-logging off switch (set in the DAG conf)")
HISTORY_LOGGING_SERVICE_CLASS = _key(
    "tez.history.logging.service.class",
    "tez_tpu.am.history:InMemoryHistoryLoggingService", Scope.AM)
HISTORY_LOG_DIR = _key("tez.history.logging.log-dir", "", Scope.AM)
AM_NUM_CONTAINERS = _key("tez.am.local.num-containers", 0, Scope.AM,
                         "Local-mode executor slots; 0 = cpu count")
GENERATE_DEBUG_ARTIFACTS = _key("tez.generate.debug.artifacts", False, Scope.DAG)
TEST_FAULT_SPEC = _key(
    "tez.test.fault.spec", "", Scope.DAG,
    "Fault-injection rules armed for this DAG (test/chaos only): "
    "'point:mode[:k=v,..]' rules joined by ';' — modes fail|pfail|delay|"
    "corrupt, params n/p/ms/exc/match.  See tez_tpu.common.faults and "
    "docs/fault_injection.md.  Empty = fault plane disarmed (zero cost)")
TEST_FAULT_SEED = _key(
    "tez.test.fault.seed", 0, Scope.DAG,
    "Seed for the fault plane's deterministic schedule; the same "
    "(spec, seed) pair replays the identical fault storm "
    "(python -m tez_tpu.tools.chaos --seed N prints repro seeds)")
TEST_RAMP_BASE_MS = _key(
    "tez.test.ramp.base-ms", 0.0, Scope.DAG,
    "Base sink latency in ms for the SLO-burn chaos leg's ramp "
    "processor (test/chaos only): each window sleeps base + step x "
    "window_id before committing, so windowed p95 climbs a "
    "deterministic ramp toward the SLO target.  See make "
    "chaos-slo-burn and docs/telemetry.md")
TEST_RAMP_STEP_MS = _key(
    "tez.test.ramp.step-ms", 0.0, Scope.DAG,
    "Per-window latency increment in ms for the SLO-burn chaos leg's "
    "ramp processor (test/chaos only); see tez.test.ramp.base-ms")
DEBUG_LOCKORDER = _key(
    "tez.debug.lockorder", False, Scope.DAG,
    "Arm the runtime lock-order witness for this DAG (test/chaos only): "
    "locks created inside tez_tpu are wrapped to record nested "
    "acquisition edges and flag order inversions, cross-validating the "
    "static graph from tez_tpu.analysis.lockorder (graftlint).  "
    "See docs/static_analysis.md.  Off = zero cost")
TRACE_ENABLED = _key(
    "tez.trace.enabled", False, Scope.DAG,
    "Arm the distributed tracing plane for this DAG: causal spans across "
    "AM submit -> task attempt -> shuffle fetch land in a bounded ring "
    "buffer exportable as Chrome/Perfetto trace_event JSON (GET /trace, "
    "tools/trace_export.py, chaos --trace-out).  Disarmed = single boolean "
    "check per call site, zero allocation (see docs/observability.md)")
TRACE_BUFFER_SPANS = _key(
    "tez.trace.buffer.spans", 32768, Scope.DAG,
    "Ring-buffer capacity of the span plane; oldest spans are evicted "
    "first once full")
OBS_FLIGHT_ENABLED = _key(
    "tez.obs.flight.enabled", False, Scope.DAG,
    "Arm the cross-plane flight recorder for this DAG: a bounded binary "
    "ring journal of span edges, histogram observations, breaker/watchdog "
    "transitions, admission verdicts, store demotions, push admissions and "
    "exchange round plans, snapshottable on demand and auto-dumped on DAG "
    "failure / breaker-open / watchdog fire / admission shed "
    "(tools/doctor.py reads the dumps — see docs/doctor.md).  Disarmed = "
    "single module-flag check per call site, zero allocation")
OBS_FLIGHT_BUFFER_EVENTS = _key(
    "tez.obs.flight.buffer.events", 65536, Scope.DAG,
    "Flight-ring capacity in events (44 bytes each, ~2.8 MiB at the "
    "default); the ring overwrites oldest-first once full")
OBS_FLIGHT_DUMP_DIR = _key(
    "tez.obs.flight.dump.dir", "", Scope.DAG,
    "Directory auto-dump snapshots are written to on DAG failure, "
    "breaker-open, watchdog fire, or admission shed (empty = the "
    "process temp dir)")
OBS_FLIGHT_DUMP_MAX = _key(
    "tez.obs.flight.dump.max", 8, Scope.DAG,
    "Auto-dump budget per arm cycle: at most this many flight snapshots "
    "are written before further triggers are dropped, bounding disk use "
    "under a failure storm")
AM_SLO_SUBMIT_P95_MS = _key(
    "tez.am.slo.submit.p95-ms", 0.0, Scope.AM,
    "Per-tenant SLO target on p95 submit-to-finish DAG latency in ms, "
    "evaluated live from the tenant.<t>.dag.latency histograms; a breach "
    "latches a TENANT_SLO_BREACH history event, bumps slo.breach.* "
    "gauges and surfaces on GET /slo (0 = watchdog off; docs/doctor.md)")
AM_SLO_QUEUE_WAIT_P95_MS = _key(
    "tez.am.slo.queue-wait.p95-ms", 0.0, Scope.AM,
    "Session-wide SLO target on p95 admission queue wait in ms, "
    "evaluated from the am.admit.queue_wait histogram (0 = off)")
AM_SLO_SHED_RATE = _key(
    "tez.am.slo.shed-rate", 0.0, Scope.AM,
    "Per-tenant SLO target on the admission shed fraction "
    "shed/(accepted+shed), e.g. 0.1 breaches past 10% shedding "
    "(0 = off)")
AM_SLO_MIN_COUNT = _key(
    "tez.am.slo.min-count", 3, Scope.AM,
    "Minimum observations (completed DAGs / queue waits / admission "
    "verdicts) before an SLO target is evaluated, so a single outlier "
    "cannot latch a breach")
AM_SLO_WINDOW_P95_MS = _key(
    "tez.am.slo.window.p95-ms", 0.0, Scope.AM,
    "Streaming SLO target on p95 per-window commit latency in ms (cut -> "
    "WINDOW_COMMIT_FINISHED), evaluated live from the stream.window.latency "
    "histogram; a breach latches a TENANT_SLO_BREACH history event under "
    "the stream's tenant and surfaces on GET /slo "
    "(0 = watchdog off; docs/streaming.md)")
METRICS_ENABLED = _key(
    "tez.metrics.enabled", True, Scope.AM,
    "Serve GET /metrics (Prometheus text: counters, latency histograms, "
    "running-task/queued-fetch/epoch gauges) on the AM web UI.  Histogram "
    "recording itself is always on — it is a few bucket increments per "
    "IO-sized operation")
AM_METRICS_SAMPLE_PERIOD_MS = _key(
    "tez.am.metrics.sample-period-ms", 250.0, Scope.AM,
    "Tick period of the live telemetry sampler (am/telemetry.py): every "
    "tick snapshots all histograms, gauges and registered collectors "
    "into the bounded time-series rings that feed GET /metrics.json "
    "windows, burn-rate SLO alerts, GET /doctor/live and graft top.  "
    "The plane is always-on like the flight recorder (one snapshot per "
    "tick off the hot path, inside the 3% armed-overhead budget); "
    "0 disables the sampler thread entirely (docs/telemetry.md)")
AM_METRICS_RING_SAMPLES = _key(
    "tez.am.metrics.ring.samples", 512, Scope.AM,
    "Ring capacity per time series, in samples: ~2 minutes of history at "
    "the default 250 ms period.  The ring evicts oldest-first once full "
    "and counts every eviction (the telemetry accounting surfaced at "
    "GET /metrics.json and flagged by counter_diff on growth)")
AM_METRICS_WINDOW_S = _key(
    "tez.am.metrics.window-s", 10.0, Scope.AM,
    "Default aggregation window for the live surfaces: GET /metrics.json "
    "windowed rate/p50/p95/p99, the continuous doctor's incremental "
    "blame sweep (GET /doctor/live) and graft top all summarize the "
    "last this-many seconds unless the request overrides it")
AM_SLO_BURN_THRESHOLD = _key(
    "tez.am.slo.burn.threshold", 0.85, Scope.AM,
    "Error-budget burn alerting threshold as a fraction of each "
    "tez.am.slo.* target: when a fast-window p95 (or shed rate) crosses "
    "threshold x target the watchdog latches a typed SLO_BURN_ALERT "
    "history event plus a flight MARK — *before* the cumulative "
    "histogram breaches the full target, so a stream trending toward "
    "its SLO pages while there is still budget left.  0 disables burn "
    "evaluation (breach-or-not only, the pre-PR-18 behavior)")
AM_SLO_BURN_FAST_S = _key(
    "tez.am.slo.burn.fast-window-s", 5.0, Scope.AM,
    "Fast burn window in seconds: the trigger window.  A burn alert "
    "latches when this window's p95 crosses threshold x target "
    "(windowed aggregates come from the telemetry sampler's rings, so "
    "the sampler period bounds burn-alert latency)")
AM_SLO_BURN_SLOW_S = _key(
    "tez.am.slo.burn.slow-window-s", 60.0, Scope.AM,
    "Slow burn window in seconds: the clear/hysteresis window.  A "
    "latched burn alert clears only when the slow window's p95 drops "
    "back under threshold x target, so an oscillating stream pages once "
    "per episode instead of once per blip (multi-window burn-rate "
    "evaluation, SRE-workbook style)")
AM_SLO_BURN_MIN_COUNT = _key(
    "tez.am.slo.burn.min-count", 2, Scope.AM,
    "Minimum observations inside the fast window before burn evaluation "
    "runs for a series, so a single slow outlier cannot page")
AM_COMMIT_ALL_OUTPUTS_ON_SUCCESS = _key(
    "tez.am.commit-all-outputs-on-dag-success", True, Scope.DAG,
    "Reference: commit at DAG success vs per-vertex commit (DAGImpl commit modes)")
AM_PREEMPTION_PERCENTAGE = _key("tez.am.preemption.percentage", 10, Scope.AM)
AM_PREEMPTION_HEARTBEATS_BETWEEN = _key(
    "tez.am.preemption.heartbeats-between-preemptions", 3, Scope.AM,
    "Minimum spacing between preemption rounds, in 250 ms AM-heartbeat "
    "periods (reference: TEZ_AM_PREEMPTION_HEARTBEATS_BETWEEN_PREEMPTIONS)")
AM_PREEMPTION_MAX_WAIT_MS = _key(
    "tez.am.preemption.max.wait-time-ms", 60_000, Scope.AM,
    "A top-priority request waiting longer than this forces a preemption "
    "round regardless of pacing")
AM_VERTEX_MAX_TASK_CONCURRENCY = _key(
    "tez.am.vertex.max-task-concurrency", -1, Scope.AM,
    "Cap on simultaneously RUNNING tasks per vertex (-1 = unlimited); "
    "queued work from other vertices fills the skipped slots")
AM_TASK_SCHEDULER_CLASS = _key(
    "tez.am.task.scheduler.class", "local", Scope.AM,
    "'local' (priority heap, unrestricted preemption), 'dag-aware' "
    "(preemption victims restricted to descendants of the waiting "
    "vertices — DagAwareYarnTaskScheduler analog), or module:Class")
AM_CLIENT_HEARTBEAT_TIMEOUT_SECS = _key(
    "tez.am.client.heartbeat.timeout.secs", -1, Scope.AM,
    "Session AM shuts down after this long without any client request "
    "(-1 = never); clients keep sessions alive automatically")
CLIENT_TIMEOUT_MS = _key(
    "tez.client.timeout-ms", 60_000, Scope.CLIENT,
    "Per-RPC socket timeout for remote-AM calls")
SESSION_CLIENT_TIMEOUT_SECS = _key(
    "tez.session.client.timeout.secs", 120, Scope.CLIENT,
    "How long start() retries connecting to a session AM that is still "
    "coming up (reference: TEZ_SESSION_CLIENT_TIMEOUT_SECS)")
CLIENT_ASYNCHRONOUS_STOP = _key(
    "tez.client.asynchronous-stop", True, Scope.CLIENT,
    "Session stop(): fire shutdown_session and return (True, reference "
    "default) vs poll until the AM port closes (False)")
CLIENT_DIAGNOSTICS_WAIT_TIMEOUT_MS = _key(
    "tez.client.diagnostics.wait.timeout-ms", 15_000, Scope.CLIENT,
    "Bound on the synchronous-stop wait for AM exit")
AM_SLEEP_TIME_BEFORE_EXIT_MS = _key(
    "tez.am.sleep.time.before.exit.millis", 0, Scope.AM,
    "Standalone AM lingers this long after session shutdown so clients "
    "can fetch final status (reference: DAGAppMaster exit sleep)")
CLIENT_AM_HEARTBEAT_INTERVAL_SECS = _key(
    "tez.client.am.heartbeat.interval.secs", 5, Scope.CLIENT,
    "Remote-client keepalive ping interval (0 disables); reference: "
    "TezClient.sendAMHeartbeat")
DAG_SCHEDULER_CLASS = _key("tez.am.dag.scheduler.class",
                           "tez_tpu.am.dag_scheduler:DAGSchedulerNaturalOrder", Scope.AM)
THREAD_DUMP_INTERVAL_MS = _key("tez.thread.dump.interval.ms", 0, Scope.VERTEX)
TASK_HBM_BUDGET_BYTES = _key(
    "tez.task.hbm.budget.bytes", 2 << 30, Scope.VERTEX,
    "Per-task HBM budget the MemoryDistributor arbitrates (TPU delta of "
    "the reference's JVM-heap scaling)")
TASK_SCALE_MEMORY_RESERVE_FRACTION = _key(
    "tez.task.scale.memory.reserve-fraction", 0.05, Scope.VERTEX,
    "Budget fraction held back from component grants (reference: "
    "TEZ_TASK_SCALE_MEMORY_RESERVE_FRACTION; smaller here — no JVM "
    "overhead to reserve for)")
TASK_SCALE_MEMORY_RATIOS = _key(
    "tez.task.scale.memory.ratios", "", Scope.VERTEX,
    "'TYPE=WEIGHT,...' oversubscription weights per component type "
    "(reference: WeightedScalingMemoryDistributor ratios); '' = defaults")
TASK_SCALE_MEMORY_ALLOCATOR = _key(
    "tez.task.scale.memory.allocator.class", "weighted", Scope.VERTEX,
    "'weighted' (WeightedScalingMemoryDistributor) or 'uniform' "
    "(ScalingAllocator: every request scales by the same factor)")
TASK_MAX_EVENT_BACKLOG = _key(
    "tez.task.max-event-backlog", 10_000, Scope.VERTEX,
    "Max routed events per heartbeat response; the remainder streams on "
    "later heartbeats (reference: TezTaskAttemptListener maxEventsToGet)")
TASK_AM_HEARTBEAT_INTERVAL_MS = _key(
    "tez.task.am.heartbeat.interval-ms", 50, Scope.VERTEX,
    "TaskReporter heartbeat period (reference: "
    "tez.task.am.heartbeat.interval-ms.max)")
COUNTERS_MAX = _key("tez.counters.max", 1200, Scope.AM,
                    "Counter-per-group cap (Limits.java)")
COUNTERS_MAX_GROUPS = _key("tez.counters.max.groups", 500, Scope.AM,
                           "Counter-group cap (Limits.java)")
COUNTERS_COUNTER_NAME_MAX_LEN = _key(
    "tez.counters.counter-name.max-length", 64, Scope.AM,
    "Counter names truncate to this before lookup (Limits.java)")
COUNTERS_GROUP_NAME_MAX_LEN = _key(
    "tez.counters.group-name.max-length", 256, Scope.AM,
    "Counter-group names truncate to this (Limits.java)")
SHUFFLE_VM_AUTO_PARALLEL = _key(
    "tez.shuffle-vertex-manager.enable.auto-parallel", False, Scope.VERTEX,
    "Let ShuffleVertexManager shrink consumer parallelism from observed "
    "source output size (ShuffleVertexManager.java:78)")
SHUFFLE_VM_MIN_SRC_FRACTION = _key(
    "tez.shuffle-vertex-manager.min-src-fraction", 0.25, Scope.VERTEX,
    "Source-completion fraction at which slow-start begins releasing tasks")
SHUFFLE_VM_MAX_SRC_FRACTION = _key(
    "tez.shuffle-vertex-manager.max-src-fraction", 0.75, Scope.VERTEX,
    "Source-completion fraction at which every consumer task is released")
SHUFFLE_VM_DESIRED_TASK_INPUT_SIZE = _key(
    "tez.shuffle-vertex-manager.desired-task-input-size",
    100 * 1024 * 1024, Scope.VERTEX,
    "Auto-parallelism targets ceil(total/this) consumer tasks")
SHUFFLE_VM_MIN_TASK_PARALLELISM = _key(
    "tez.shuffle-vertex-manager.min-task-parallelism", 1, Scope.VERTEX,
    "Auto-parallelism never shrinks below this")
GROUPING_SPLIT_WAVES = _key(
    "tez.grouping.split-waves", 1.7, Scope.VERTEX,
    "Desired split groups per available slot when vertex parallelism is "
    "unbound (TezSplitGrouper.TEZ_GROUPING_SPLIT_WAVES)")
GROUPING_MIN_SIZE = _key(
    "tez.grouping.min-size", 50 * 1024 * 1024, Scope.VERTEX,
    "Lower bound on average grouped-split size")
GROUPING_MAX_SIZE = _key(
    "tez.grouping.max-size", 1024 * 1024 * 1024, Scope.VERTEX,
    "Upper bound on average grouped-split size")
TASK_JAX_PROFILE_DIR = _key(
    "tez.task.jax-profile.dir", "", Scope.VERTEX,
    "Write a per-task-attempt XLA profiler trace (TensorBoard/Perfetto) "
    "under this dir; '' disables (the TPU-native per-kernel tracing story)")
AM_WEB_ENABLED = _key("tez.am.web.enabled", False, Scope.AM,
                      "Serve the live status endpoint (AMWebController analog)")
AM_WEB_PORT = _key("tez.am.web.port", 0, Scope.AM, "0 = ephemeral")
RUNNER_ENV = _key("tez.am.runner.env", {}, Scope.AM,
                  "Env overrides for runner subprocesses; '' value = unset")
UMBILICAL_BIND_HOST = _key("tez.am.umbilical.bind-host", "127.0.0.1",
                           Scope.AM, "'0.0.0.0' for multi-host deployments")
AM_CONCURRENT_DISPATCHER_SHARDS = _key(
    "tez.am.concurrent.dispatcher.shards", 0, Scope.AM,
    "0 = single dispatcher thread (reference default); N>1 = hash-sharded "
    "concurrent dispatcher for event storms (AsyncDispatcherConcurrent)")
RUNNER_MODE = _key("tez.runner.mode", "threads", Scope.AM,
                   "'threads' (in-process, reference local mode), "
                   "'subprocess' (out-of-process runners over the socket "
                   "umbilical — the TezChild-per-container model), or "
                   "'pods' (external cluster binding: the AM acquires "
                   "runner pods via tez.am.pod-pool.driver.class)")
POD_POOL_DRIVER = _key(
    "tez.am.pod-pool.driver.class", "process", Scope.AM,
    "'process' (process-per-host simulation with the real plugin seam), "
    "'kubernetes' (GKE/k8s pods; needs the kubernetes client), or a "
    "module:Class PodDriver path")
POD_POOL_MAX_PODS = _key("tez.am.pod-pool.max-pods", 0, Scope.AM,
                         "0 = tez.am.local.num-containers")
POD_POOL_ADVERTISE_HOST = _key(
    "tez.am.pod-pool.advertise-host", "127.0.0.1", Scope.AM,
    "AM address handed to launched pods for the umbilical dial-back")
POD_POOL_K8S_NAMESPACE = _key("tez.am.pod-pool.k8s.namespace", "default",
                              Scope.AM)
POD_POOL_K8S_IMAGE = _key("tez.am.pod-pool.k8s.image",
                          "tez-tpu-runner:latest", Scope.AM)
POD_POOL_K8S_POD_TEMPLATE = _key(
    "tez.am.pod-pool.k8s.pod-template", "", Scope.AM,
    "Path to a pod-spec YAML merged under the generated runner pod "
    "(resources, tolerations, TPU node selectors); '' = built-in spec")

# --------------------------------------------------------------------------
# Runtime (per-edge / per-IO) keys (TezRuntimeConfiguration.java analog)
# --------------------------------------------------------------------------
RUNTIME_PREFIX = "tez.runtime."

IO_SORT_MB = _key("tez.runtime.io.sort.mb", 256, Scope.VERTEX,
                  "Device sort span budget (HBM MiB); reference: buffer for PipelinedSorter")
IO_SORT_FACTOR = _key("tez.runtime.io.sort.factor", 64, Scope.VERTEX,
                      "k-way merge width; reference: TezRuntimeConfiguration io.sort.factor")
SORTER_CLASS = _key("tez.runtime.sorter.class", "auto", Scope.VERTEX,
                    "'device' (TPU radix/segmented sort) or 'host' (numpy fallback)")
COMBINER_CLASS = _key("tez.runtime.combiner.class", "", Scope.VERTEX)
SORT_THREADS = _key("tez.runtime.sort.threads", 0, Scope.VERTEX,
                    "Background sortmaster workers (0 = sort spans inline); "
                    "reference: PipelinedSorter sortmaster executor")
PARTITIONER_CLASS = _key("tez.runtime.partitioner.class",
                         "tez_tpu.library.partitioners:HashPartitioner", Scope.VERTEX)
PALLAS_HASH_ENABLED = _key("tez.runtime.tpu.pallas.hash", False, Scope.VERTEX,
                           "Use the Pallas FNV kernel for hash partitioning "
                           "on TPU backends (off until profiled per chip); "
                           "non-TPU backends silently use the XLA path")
PIPELINED_SHUFFLE_ENABLED = _key("tez.runtime.pipelined-shuffle.enabled", False, Scope.VERTEX,
                                 "Emit per-spill DMEs; disables final merge "
                                 "(reference: PipelinedSorter.java:113)")
ENABLE_FINAL_MERGE = _key("tez.runtime.enable.final-merge.in.output", True, Scope.VERTEX)
SHUFFLE_PARALLEL_COPIES = _key("tez.runtime.shuffle.parallel.copies", 8, Scope.VERTEX)
SHUFFLE_BUFFER_FRACTION = _key("tez.runtime.shuffle.fetch.buffer.percent", 0.9, Scope.VERTEX)
SHUFFLE_MEMORY_LIMIT_PERCENT = _key("tez.runtime.shuffle.memory.limit.percent", 0.25, Scope.VERTEX)
SHUFFLE_MERGE_PERCENT = _key("tez.runtime.shuffle.merge.percent", 0.9, Scope.VERTEX)
SHUFFLE_MERGE_BUDGET_MB = _key(
    "tez.runtime.shuffle.merge.budget.mb", 0, Scope.VERTEX,
    "consumer-side fetch/merge memory budget; 0 = use the MemoryDistributor "
    "grant (fetch.buffer.percent x io.sort.mb request)")
# reference-parity key; penalty logic uses the report-window knobs  # graftlint: disable=knob-unread
SHUFFLE_FAILED_CHECK_SINCE_LAST_COMPLETION = _key(
    "tez.runtime.shuffle.failed.check.since-last.completion", True, Scope.VERTEX)
SHUFFLE_FETCH_MAX_TASK_OUTPUT_AT_ONCE = _key(
    "tez.runtime.shuffle.fetch.max.task.output.at.once", 20, Scope.VERTEX)
SHUFFLE_NOTIFY_READERROR = _key("tez.runtime.shuffle.notify.readerror", True, Scope.VERTEX)
SHUFFLE_HOST_PENALTY_BASE_MS = _key(
    "tez.runtime.shuffle.host.penalty.base-ms", 250, Scope.VERTEX,
    "initial penalty-box hold for a failing shuffle host; doubles per "
    "consecutive failure (ShuffleScheduler Penalty/Referee analog)")
SHUFFLE_HOST_PENALTY_CAP_MS = _key(
    "tez.runtime.shuffle.host.penalty.cap-ms", 10_000, Scope.VERTEX)
SHUFFLE_FETCH_ATTEMPTS = _key(
    "tez.runtime.shuffle.fetch.attempts", 4, Scope.VERTEX,
    "connection-level retries per fetch before InputReadErrorEvent")
SHUFFLE_SPECULATIVE_FETCH_WAIT_MS = _key(
    "tez.runtime.shuffle.speculative.fetch.wait-ms", 15_000, Scope.VERTEX,
    "an in-flight fetch older than this gets a duplicate on a fresh "
    "connection; first delivery wins")
SHUFFLE_FETCH_SESSION_TTL_MS = _key(
    "tez.runtime.shuffle.fetch.session.ttl-ms", 30_000, Scope.VERTEX,
    "keep-alive cache for fetch sessions: a healthy per-host connection is "
    "reused across batches and closed after this idle time; open sessions "
    "(cached + in use) never exceed the fetcher pool size; 0 = close after "
    "every batch (the historical behavior)")
SHUFFLE_FETCHER_CLASS = _key(
    "tez.runtime.shuffle.fetcher.class", "", Scope.VERTEX,
    "injectable fetch-session factory (tests: FetcherWithInjectableErrors "
    "analog); empty = TCP keep-alive session")
TPU_MESH_MAX_ROWS_PER_ROUND = _key(
    "tez.runtime.tpu.mesh.max-rows-per-round", 0, Scope.VERTEX,
    "per-edge cap on rows moved per exchange round (skewed partitions run "
    "multi-round above it); 0 = coordinator default "
    "(TEZ_TPU_MESH_MAX_ROWS_PER_ROUND env or 1Mi rows)")
MESH_EXCHANGE_ENGINE = _key(
    "tez.runtime.mesh.exchange.engine", "auto", Scope.VERTEX,
    "ICI collective carrying mesh-exchange edges: 'padded' = fixed "
    "[W, CAP] all_to_all (portable; padding crosses ICI as slack), "
    "'ragged' = ragged_all_to_all (only real rows move; TPU-only, falls "
    "back loudly where the backend lacks the thunk), 'auto' = ragged "
    "when the runtime probe passes, padded otherwise (bit-exact either "
    "way; see docs/exchange.md)")
MESH_EXCHANGE_CODED = _key(
    "tez.runtime.mesh.exchange.coded", "off", Scope.VERTEX,
    "Coded TeraSort-style redundant exchange: 'r2' sends every "
    "partition's rows to its primary device AND one rotation-offset "
    "buddy, and the consumer takes the first complete copy — masks one "
    "slow or faulted chip per exchange at 2x send flops (flops are "
    "cheap, ICI stragglers are not); 'off' = single copy")
MESH_EXCHANGE_SPLIT_AFTER = _key(
    "tez.runtime.mesh.exchange.split.after", 2, Scope.VERTEX,
    "fair-shuffle splitter trigger: after this many CONSECUTIVE "
    "exchanges of a recurring edge with one partition over "
    "max-rows-per-round, hot partitions are re-partitioned across "
    "sub-destinations with a merge-side recombine instead of "
    "re-rounding forever; 0 = never split")
TPU_MESH_MAX_KEY_BYTES = _key(
    "tez.runtime.tpu.mesh.max.key.bytes", 256, Scope.VERTEX,
    "hard cap on key bytes the mesh exchange carries (slot widths "
    "auto-widen to the data below it); bigger records -> host shuffle edge")
TPU_MESH_MAX_VALUE_BYTES = _key(
    "tez.runtime.tpu.mesh.max.value.bytes", 1024, Scope.VERTEX,
    "hard cap on value bytes the mesh exchange carries; bigger records -> "
    "host shuffle edge")
SHUFFLE_SSL_ENABLE = _key(
    "tez.runtime.shuffle.ssl.enable", False, Scope.AM,
    "TLS on every DCN socket (shuffle server/fetcher + AM umbilical); "
    "PEM paths below; in-channel HMAC auth stays on inside the stream "
    "(reference: http/SSLFactory.java + TestSecureShuffle)")
SHUFFLE_SSL_CERT = _key("tez.shuffle.ssl.cert.path", "", Scope.AM,
                        "PEM certificate presented by every endpoint")
SHUFFLE_SSL_KEY = _key("tez.shuffle.ssl.key.path", "", Scope.AM,
                       "PEM private key")
SHUFFLE_SSL_CA = _key("tez.shuffle.ssl.ca.path", "", Scope.AM,
                      "CA bundle both sides verify against (mutual TLS)")
TPU_MESH_EXCHANGE_DEADLINE_SECS = _key(
    "tez.runtime.tpu.mesh.exchange.deadline.secs", 0.0, Scope.VERTEX,
    "straggler defense on the mesh gang barrier: consumers waiting longer "
    "than this for the edge's producers fail the edge actionably (naming "
    "the missing producer task indices) instead of stalling forever; "
    "0 = wait indefinitely (AM task-level failure detection still applies)")
TPU_RESIDENT_KEYS = _key(
    "tez.runtime.tpu.resident.keys", True, Scope.VERTEX,
    "keep sorted key lanes in HBM for downstream device merges "
    "(~(key width + 4) B/row pinned per registered output until DAG "
    "deletion; outside the host memory budgets)")
SHUFFLE_CONNECT_TIMEOUT_MS = _key("tez.runtime.shuffle.connect.timeout", 12_000, Scope.VERTEX)
SHUFFLE_READ_TIMEOUT_MS = _key("tez.runtime.shuffle.read.timeout", 30_000, Scope.VERTEX)
COMPRESS = _key("tez.runtime.compress", False, Scope.VERTEX)
COMPRESS_CODEC = _key("tez.runtime.compress.codec", "zlib", Scope.VERTEX)
KEY_CLASS = _key("tez.runtime.key.class", "bytes", Scope.VERTEX)
VALUE_CLASS = _key("tez.runtime.value.class", "bytes", Scope.VERTEX)
KEY_COMPARATOR_CLASS = _key("tez.runtime.key.comparator.class", "", Scope.VERTEX)
UNORDERED_OUTPUT_BUFFER_SIZE_MB = _key(
    "tez.runtime.unordered.output.buffer.size-mb", 100, Scope.VERTEX)
REPORT_PARTITION_STATS = _key("tez.runtime.report.partition.stats", True, Scope.VERTEX,
                              "Ship per-partition output sizes in VertexManagerEvents "
                              "(feeds auto-parallelism)")
KEY_WIDTH_BYTES = _key("tez.runtime.tpu.key.width.bytes", 16, Scope.VERTEX,
                       "Fixed normalized key width for device radix sort (TPU-specific)")
MESH_VALUE_WIDTH_BYTES = _key(
    "tez.runtime.tpu.mesh.value.width.bytes", 16, Scope.VERTEX,
    "Fixed value lane width for mesh-exchange edges (values are packed "
    "into fixed-width device lanes for the SPMD all-to-all)")
# reference-parity key; span sizing uses hbm budget + bucket ladder  # graftlint: disable=knob-unread
DEVICE_BATCH_RECORDS = _key("tez.runtime.tpu.batch.records", 1 << 20, Scope.VERTEX,
                            "Records per device sort batch (static shape bucket)")
DEVICE_SORT_MIN_RECORDS = _key(
    "tez.runtime.tpu.device.sort.min.records", 1 << 16, Scope.VERTEX,
    "Spans smaller than this sort on host even under the device engine "
    "(dispatch + transfer overhead exceeds the sort); 0 = always device")
SORT_ENGINE_MIN_BYTES = _key(
    "tez.runtime.sort.engine.min-bytes", 1 << 20, Scope.VERTEX,
    "auto-engine floor on a span's total SORT-KEY bytes for the device "
    "path: wide-VALUE spans can clear the record-count bar while carrying "
    "few key bytes, where a device dispatch buys almost no device work; "
    "such spans sort on host.  Only applies when tez.runtime.sorter.class "
    "is 'auto' (an explicit 'device' is never rerouted by width); 0 = off")
SORT_PIPELINE_DEPTH = _key(
    "tez.runtime.sort.pipeline.depth", 2, Scope.VERTEX,
    "async device data plane: max spans past the staging gate at once "
    "(encoded/uploaded/dispatched but not read back).  2 = double "
    "buffering — span k+1 stages while span k is in flight and span k-1 "
    "drains.  0 = synchronous spans.  Only takes effect when the engine "
    "resolves to 'device'")
SORT_PIPELINE_COALESCE_RECORDS = _key(
    "tez.runtime.sort.pipeline.coalesce.records", -1, Scope.VERTEX,
    "span-batching budget for the async device plane: adjacent small "
    "spans coalesce into ONE bucketed dispatch while their total records "
    "fit this budget (amortizes per-dispatch overhead).  -1 = auto "
    "(tez.runtime.tpu.device.sort.min.records), 0 = off")
DEVICE_WATCHDOG_DISPATCH_MS = _key(
    "tez.runtime.device.watchdog.dispatch-ms", 60_000, Scope.VERTEX,
    "deadline for one device dispatch attempt in the async data plane; a "
    "dispatch still in flight past this is abandoned by the watchdog "
    "monitor thread and the span re-sorts through the host engine "
    "(bit-exact).  0 = dispatch unwatched")
DEVICE_WATCHDOG_READBACK_MS = _key(
    "tez.runtime.device.watchdog.readback-ms", 60_000, Scope.VERTEX,
    "deadline for one D2H readback attempt in the async data plane; a "
    "hung readback is abandoned and the span fails over to the host "
    "engine instead of wedging flush().  0 = readback unwatched")
DEVICE_BREAKER_FAILURES = _key(
    "tez.runtime.device.breaker.failures", 3, Scope.VERTEX,
    "consecutive device-attempt failures (watchdog fires, device "
    "exceptions) that trip the sticky per-process circuit breaker; while "
    "open, new spans route straight to the host engine without touching "
    "the device")
DEVICE_BREAKER_COOLDOWN_MS = _key(
    "tez.runtime.device.breaker.cooldown-ms", 5_000, Scope.VERTEX,
    "how long an open device breaker waits before letting ONE probe span "
    "try the device again (half-open); the probe's success re-arms the "
    "device engine, its failure re-opens the breaker for another cooldown")
DEVICE_SPLIT_MIN_BYTES = _key(
    "tez.runtime.device.split.min-bytes", 1 << 20, Scope.VERTEX,
    "floor for OOM-adaptive span splitting: a RESOURCE_EXHAUSTED device "
    "attempt retries on-device with the span halved (recursively) while "
    "the half is still above this many key+value bytes; below it the "
    "span goes to the host engine instead")
MERGE_ENGINE = _key(
    "tez.runtime.merge.engine", "", Scope.VERTEX,
    "engine for the reduce-side merge plane (ShuffleMergeManager / "
    "merge_sorted_runs on the consumer): device|host|auto; '' = follow "
    "tez.runtime.sorter.class.  The device engine merges pre-sorted runs "
    "with the O(N) merge-path ladder over HBM-resident key lanes")
MERGE_ENGINE_MIN_RECORDS = _key(
    "tez.runtime.merge.engine.min-records", 0, Scope.VERTEX,
    "merges smaller than this many records run on host even under the "
    "device merge engine (dispatch + transfer overhead exceeds the merge); "
    "0 = follow tez.runtime.tpu.device.sort.min.records")
MERGE_ASYNC_DEPTH = _key(
    "tez.runtime.merge.async.depth", 2, Scope.VERTEX,
    "async reduce-side merge plane: max background merges past the staging "
    "gate at once (device merge in flight + chunked-run disk write "
    "draining).  2 = double buffering — merge k's disk write overlaps "
    "merge k+1's dispatch, both overlap in-flight fetch commits.  "
    "0 = synchronous background merger (the historical behavior)")
HOST_SPILL_DIR = _key("tez.runtime.tpu.host.spill.dir", "", Scope.VERTEX,
                      "Where device buffers spill when HBM budget is exceeded; "
                      "'' = <staging>/spill")
STORE_ENABLED = _key(
    "tez.runtime.store.enabled", False, Scope.AM,
    "route shuffle outputs through the tiered buffer store "
    "(tez_tpu.store): a reference-counted HBM->host->disk object store "
    "with lease pinning, watermark LRU demotion, and epoch-fenced keys.  "
    "Off = the historical bare-registry data plane")
STORE_DEVICE_CAPACITY_MB = _key(
    "tez.runtime.store.device.capacity-mb", 256, Scope.AM,
    "HBM pool budget for store-resident sorted key lanes; crossing the "
    "high watermark demotes LRU unleased entries to the host tier "
    "(drops their device lanes); 0 = no device tier (lanes drop at "
    "publish)")
STORE_HOST_CAPACITY_MB = _key(
    "tez.runtime.store.host.capacity-mb", 1024, Scope.AM,
    "host-RAM pool budget for store-resident runs; crossing the high "
    "watermark demotes LRU unleased entries to the disk tier "
    "(partition-indexed .prun files)")
STORE_DISK_CAPACITY_MB = _key(
    "tez.runtime.store.disk.capacity-mb", 0, Scope.AM,
    "disk pool budget; only sealed cross-DAG lineage entries are ever "
    "evicted from disk (live DAG outputs are never dropped); "
    "0 = unbounded")
STORE_HIGH_WATERMARK = _key(
    "tez.runtime.store.watermark.high", 0.90, Scope.AM,
    "tier occupancy fraction that triggers LRU demotion")
STORE_LOW_WATERMARK = _key(
    "tez.runtime.store.watermark.low", 0.70, Scope.AM,
    "demotion cascade stops once tier occupancy drops below this "
    "fraction")
STORE_DIR = _key(
    "tez.runtime.store.dir", "", Scope.AM,
    "disk-tier directory for demoted runs and sealed lineage segments; "
    "'' = a per-process temp dir removed on reset")
STORE_LINEAGE_REUSE = _key(
    "tez.runtime.store.lineage.reuse", True, Scope.AM,
    "session mode: committed vertex outputs are sealed under "
    "(vertex spec hash, task index, epoch) lineage keys and served as "
    "cache hits to identical recurring DAGs — the producer task "
    "republishes the stored runs instead of recomputing.  Only "
    "meaningful when the store is enabled")
PUSH_ENABLED = _key(
    "tez.runtime.shuffle.push.enabled", False, Scope.VERTEX,
    "push-based pipelined shuffle: producers ship every pipelined spill "
    "eagerly into the reducer-side buffer store mid-map-wave (same-host "
    "publishes are zero-copy; remote spills ride the shuffle server's "
    "push verb), consumers start in ingest mode, and the merge lane "
    "merges pushed arrivals early.  Implies pipelined spill emission.  "
    "The pull path stays registered as the correctness backstop, so a "
    "dead pusher or a rejected push never loses data.  Off = the "
    "historical pull-only shuffle")
PUSH_THREADS = _key(
    "tez.runtime.shuffle.push.threads", 2, Scope.VERTEX,
    "async pusher thread-pool size per producer task")
PUSH_RETRIES = _key(
    "tez.runtime.shuffle.push.retries", 3, Scope.VERTEX,
    "send attempts per pushed spill (full-jitter exponential backoff "
    "between tries, honoring the admission controller's retry-after "
    "hint); exhausting them abandons the push to the pull backstop")
PUSH_INFLIGHT_LIMIT_MB = _key(
    "tez.runtime.shuffle.push.inflight-limit-mb", 64, Scope.VERTEX,
    "per-destination cap on queued + in-flight pushed bytes; a producer "
    "spilling faster than its reducers admit blocks at submit (map-side "
    "backpressure) instead of ballooning the push queue")
PUSH_SOURCE_QUOTA_MB = _key(
    "tez.runtime.shuffle.push.source-quota-mb", 256, Scope.VERTEX,
    "admission controller: max pushed bytes one source attempt may hold "
    "resident in this host's store; beyond it pushes are rejected with "
    "RETRY-AFTER (the source's spills stay pull-served) so a single "
    "hot mapper cannot crowd out the wave")
PUSH_ADMIT_WATERMARK = _key(
    "tez.runtime.shuffle.push.admit-watermark", 0.85, Scope.VERTEX,
    "admission controller: reject pushes once the store's host tier "
    "would exceed this occupancy fraction — deliberately below the "
    "store's own high watermark so eager pushes never trigger the "
    "demotion cascade that pull-registered data would ride")
PUSH_RETRY_AFTER_MS = _key(
    "tez.runtime.shuffle.push.retry-after-ms", 50.0, Scope.VERTEX,
    "retry-after hint attached to admission rejections; the pusher "
    "sleeps at least this long (plus jittered backoff) before retrying")
PUSH_START_FRACTION = _key(
    "tez.runtime.shuffle.push.start-fraction", 0.05, Scope.VERTEX,
    "map-wave/merge-wave co-scheduling: with push enabled, consumer "
    "tasks of scatter-gather edges are ALL released once this fraction "
    "of source tasks has finished (ingest mode) instead of riding the "
    "slow-start [min, max] ramp — reducers sit ingesting pushed spills "
    "while the map wave is still running")
PUSH_EAGER_MERGE_THRESHOLD = _key(
    "tez.runtime.shuffle.push.eager-merge-threshold", 0.5, Scope.VERTEX,
    "with push enabled, the consumer's background merger starts a "
    "mem->disk merge once committed memory crosses this fraction of the "
    "merge budget (instead of only at tez.runtime.shuffle.merge.percent) "
    "so merge work overlaps the map wave; 0 disables early merging")
PUSH_REPLICAS = _key(
    "tez.runtime.shuffle.push.replicas", 1, Scope.VERTEX,
    "copies of each pushed spill landed in the store: 1 = primary only "
    "(historical behavior); 2 = every push also lands on the coded-buddy "
    "replica key, and a consumer whose primary store entry is lost fails "
    "over to the buddy instead of re-running the producer (Coded "
    "TeraSort-style recovery-without-recomputation; the "
    "store.replica.{bytes,failover} counters account for it — "
    "docs/recovery.md, docs/push_shuffle.md)")
DAG_TENANT = _key(
    "tez.dag.tenant", "", Scope.DAG,
    "tenant id stamped onto the DAG plan at submit (and onto every "
    "TaskSpec of the DAG): the unit of admission caps, fair-share "
    "weighting, store byte quotas, and result-cache governance in the "
    "multi-tenant session AM (docs/multitenancy.md); '' = the anonymous "
    "default tenant")
AM_SESSION_MAX_CONCURRENT_DAGS = _key(
    "tez.am.session.max-concurrent-dags", 1, Scope.AM,
    "resident session AM: how many DAGs may run concurrently; submits "
    "beyond it enter the bounded FIFO admission queue.  1 = the "
    "historical one-DAG-at-a-time session (but queued, not rejected)")
AM_SESSION_QUEUE_SIZE = _key(
    "tez.am.session.queue-size", 8, Scope.AM,
    "bounded FIFO admission queue behind the concurrency cap; a submit "
    "arriving with the queue full is shed with a typed RETRY-AFTER "
    "verdict instead of waiting unboundedly")
AM_SESSION_TENANT_MAX_INFLIGHT = _key(
    "tez.am.session.tenant.max-inflight", 0, Scope.AM,
    "per-tenant cap on running + queued DAGs; a tenant at its cap has "
    "further submits shed with RETRY-AFTER so one tenant cannot occupy "
    "the whole queue.  0 = unlimited")
AM_SESSION_SHED_RETRY_AFTER_MS = _key(
    "tez.am.session.shed.retry-after-ms", 500.0, Scope.AM,
    "retry-after hint attached to admission shed verdicts; clients "
    "sleep at least this long (plus full-jitter backoff) before "
    "resubmitting (TezClient.submit_dag_with_retry)")
AM_SESSION_ADMIT_STORE_WATERMARK = _key(
    "tez.am.session.admit.store-watermark", 0.95, Scope.AM,
    "admission pressure gate: with the buffer store enabled, a submit "
    "finding the host tier beyond this occupancy fraction first asks "
    "the store to relieve pressure (relieve_host_pressure) and is shed "
    "if occupancy stays above the gate — the control-plane analog of "
    "the push-shuffle admit watermark")
AM_SESSION_TENANT_WEIGHTS = _key(
    "tez.am.session.tenant.weights", "", Scope.AM,
    "weighted fair-share across tenants as 'tenantA=3,tenantB=1'; the "
    "task scheduler's deficit round-robin grants slots (and thereby the "
    "async device lanes the tasks drive) proportionally to weight.  "
    "Unlisted tenants weigh 1; '' = all tenants equal")
AM_SESSION_FAIR_SHARE = _key(
    "tez.am.session.fair-share", True, Scope.AM,
    "deficit round-robin tenant fair-share at the task-scheduler "
    "allocation point; off = pure priority-heap order across all "
    "tenants (the historical single-tenant behavior)")
STORE_TENANT_DEVICE_QUOTA_MB = _key(
    "tez.runtime.store.quota.device-mb", 0, Scope.AM,
    "per-tenant cap on device(HBM)-tier resident store bytes; a publish "
    "that would cross it lands on the host tier instead (lanes drop), "
    "so one tenant cannot monopolize HBM.  0 = unlimited")
STORE_TENANT_HOST_QUOTA_MB = _key(
    "tez.runtime.store.quota.host-mb", 0, Scope.AM,
    "per-tenant cap on host-tier resident store bytes; a publish over "
    "quota is refused (StoreQuotaExceeded) and the producer falls back "
    "to its own spill files — isolation, not correctness.  "
    "0 = unlimited")
STORE_TENANT_DISK_QUOTA_MB = _key(
    "tez.runtime.store.quota.disk-mb", 0, Scope.AM,
    "per-tenant cap on disk-tier resident store bytes (demoted runs + "
    "sealed lineage); crossing it evicts that tenant's stalest sealed "
    "lineage entries first.  0 = unlimited")
STORE_RESULT_CACHE_TTL_SECS = _key(
    "tez.runtime.store.quota.result-cache.ttl-secs", 0.0, Scope.AM,
    "governed result cache: sealed lineage entries older than this are "
    "expired (not served, and reaped by the next quota sweep) so "
    "recurring tenants re-derive stale results.  0 = no expiry")
STORE_RESULT_CACHE_MB = _key(
    "tez.runtime.store.quota.result-cache-mb", 0, Scope.AM,
    "per-tenant byte cap on sealed result-cache (lineage) entries; "
    "sealing beyond it evicts that tenant's least-recently-hit sealed "
    "entries.  0 = unlimited")
STORE_RESULT_CACHE_ADMIT = _key(
    "tez.runtime.store.quota.result-cache.admit", "always", Scope.AM,
    "result-cache admission policy at seal time: 'always' seals every "
    "committed lineage-tagged output, 'second-use' seals only lineage "
    "keys already observed once this session (scan-resistant), 'never' "
    "disables sealing (lineage reuse off for quota purposes)")
STREAM_ID = _key(
    "tez.runtime.stream.id", "", Scope.DAG,
    "streaming mode: stream identity stamped onto every per-window DAG "
    "plan (and every TaskSpec) by the window driver; the key of the "
    "(attempt_epoch, window_id) fence registry and the marker recovery "
    "uses to hand window DAGs back to the driver instead of resubmitting "
    "them.  '' = batch DAG (docs/streaming.md)")
STREAM_WINDOW_ID = _key(
    "tez.runtime.stream.window-id", 0, Scope.DAG,
    "streaming mode: the numbered window a per-window DAG computes, "
    "stamped by the window driver; rides every TaskSpec/heartbeat/"
    "shuffle-register/push/store-publish as the second fence coordinate. "
    "0 = batch (never fenced; pre-streaming semantics)")
STREAM_WINDOW_COUNT = _key(
    "tez.runtime.stream.window.count", 100, Scope.AM,
    "count-based window cut: the source seals the open window after this "
    "many ingested records (punctuation, if configured, can cut earlier)")
STREAM_WINDOW_PUNCTUATION = _key(
    "tez.runtime.stream.window.punctuation", "", Scope.AM,
    "punctuation-based window cut: ingesting a record whose key equals "
    "this token seals the open window (the punctuation record itself is "
    "not part of any window).  '' = count-based cuts only")
STREAM_MAX_LAG = _key(
    "tez.runtime.stream.max-lag", 4, Scope.AM,
    "backpressure bound on windows cut but not yet committed: ingest() "
    "blocks (source pacing) once the lag reaches this many windows, "
    "journaling one typed WINDOW_LAGGING event per lag episode and "
    "observing stream.window.lag — bounded lag, never OOM or silent "
    "drop (docs/streaming.md)")
STREAM_INGEST_POLL_MS = _key(
    "tez.runtime.stream.ingest.poll-ms", 10.0, Scope.AM,
    "poll interval of a backpressured ingest() while it waits for the "
    "window lag to drop back under tez.runtime.stream.max-lag")
STREAM_WINDOW_TIMEOUT_SECS = _key(
    "tez.runtime.stream.window.timeout-secs", 120.0, Scope.AM,
    "per-window DAG completion deadline; a window that neither succeeds "
    "nor fails inside it aborts the window (WINDOW_COMMIT_ABORTED) and "
    "fails the stream rather than stalling ingest forever")
STREAM_INPUT = _key(
    "tez.runtime.stream.input", "", Scope.DAG,
    "spool file of the sealed window a per-window DAG reads (CRC-framed "
    "record journal under <staging>/stream/<stream>/); stamped by the "
    "window driver, read by StreamWindowSourceProcessor")
STREAM_OUTPUT_DIR = _key(
    "tez.runtime.stream.output-dir", "", Scope.DAG,
    "directory per-window results land in: the sink writes "
    ".w<N>.<part>.tmp files and the driver's exactly-once committer "
    "renames them to w<N>.part<i> between WINDOW_COMMIT_STARTED and "
    "WINDOW_COMMIT_FINISHED ledger records")

# -- relational query layer (tez_tpu/query, docs/query.md) ------------------

QUERY_BROADCAST_MAX_MB = _key(
    "tez.query.broadcast.max-mb", 32.0, Scope.DAG,
    "planner join-strategy threshold: when the estimated (or, on a "
    "replanned run, observed) build-side size fits under this many MB "
    "the join lowers to a broadcast hash join (one-to-all "
    "UnorderedKVEdge); otherwise to a repartition sort-merge join "
    "(two scatter-gather ordered edges)")
QUERY_JOIN_STRATEGY = _key(
    "tez.query.join.strategy", "auto", Scope.DAG,
    "force the join lowering: 'auto' = pick by stats vs "
    "tez.query.broadcast.max-mb, 'broadcast' / 'repartition' = always "
    "that physical strategy (test/bench override; also what a "
    "PlanFeedback replan pins per node)")
QUERY_REDUCERS = _key(
    "tez.query.reducers", 2, Scope.DAG,
    "downstream parallelism of every query exchange (repartition "
    "join, aggregate, window); a skew replan may raise it per node up "
    "to tez.query.replan.max-reducers")
QUERY_SCAN_SPLITS = _key(
    "tez.query.scan.splits", 2, Scope.DAG,
    "desired text splits (and so task parallelism) of each scan stage")
QUERY_STATS_DIR = _key(
    "tez.query.stats.dir", "", Scope.DAG,
    "side-channel directory where query processors drop per-task "
    "qstats JSON (records/bytes emitted per exchange partition); the "
    "QuerySession aggregates them into the per-node partition-size "
    "histograms PlanFeedback replans from.  '' = stats collection off")
QUERY_OPERATOR_TAG = _key(
    "tez.query.operator", "", Scope.VERTEX,
    "planner-set vertex tag naming the logical plan operator this "
    "vertex executes (e.g. 'hash_join(o_custkey)@a1b2c3d4e5f6'); rides "
    "vertex conf so history events, flight dumps, and the lineage "
    "fingerprint all attribute back to the operator")
QUERY_REPLAN_ENABLED = _key(
    "tez.query.replan.enabled", True, Scope.CLIENT,
    "adaptive re-optimization: after each query run the session feeds "
    "the doctor's per-plane blame and the observed qstats histograms "
    "into PlanFeedback; the next run of the same logical node may flip "
    "join strategy or raise reducer parallelism, journaling one typed "
    "QUERY_REPLANNED summary event per decision")
QUERY_REPLAN_SKEW_FACTOR = _key(
    "tez.query.replan.skew-factor", 4.0, Scope.CLIENT,
    "replan trigger: an exchange whose largest observed partition "
    "exceeds this multiple of the mean size of the other partitions is "
    "skewed — the next plan doubles that node's reducer count (up to "
    "tez.query.replan.max-reducers)")
QUERY_REPLAN_MAX_REDUCERS = _key(
    "tez.query.replan.max-reducers", 8, Scope.CLIENT,
    "ceiling a skew replan may raise a query exchange's parallelism to")


def runtime_conf_subset(conf: Mapping) -> "TezConfiguration":
    """Filter the runtime keys into an edge payload (reference: edge config
    builders serialize only TezRuntimeConfiguration keys into UserPayload)."""
    return TezConfiguration(conf).subset(RUNTIME_PREFIX)
