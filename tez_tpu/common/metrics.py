"""Latency histograms + gauges + Prometheus text rendering.

Reference parity: the reference profiles through counters alone
(SURVEY.md §5.1); MR-era *_PHASE_TIME counters record totals but no
distribution.  This module adds log-bucketed latency histograms with two
sinks per observation:

1. a cheap process-global registry (lock-striped per histogram) that the
   AM web /metrics endpoint scrapes live, and
2. optionally the caller's ``TezCounters`` — each bucket becomes a counter
   named ``LE_<bound>`` inside group ``LatencyHistogram.<name>`` so the
   existing task -> vertex -> DAG ``TezCounters.aggregate()`` roll-up sums
   histograms with zero new aggregation code, and histograms survive in
   history dumps for tools/counter_diff.py.

Buckets are powers of two in milliseconds (1ms .. ~65s, plus +Inf), the
usual shape for RPC/IO latencies: fine where fetches live (1-64ms), coarse
where only order-of-magnitude matters.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Mapping, Optional, Tuple

from tez_tpu.obs import flight as _flight

# Upper bounds of the finite buckets, in milliseconds: 1, 2, 4 ... 65536.
BUCKET_BOUNDS_MS: Tuple[float, ...] = tuple(float(1 << i) for i in range(17))
NUM_BUCKETS = len(BUCKET_BOUNDS_MS) + 1          # + overflow (+Inf)

# TezCounters integration: group "LatencyHistogram.<name>" holding
# LE_1 .. LE_65536, LE_INF, COUNT, SUM_US.
HIST_GROUP_PREFIX = "LatencyHistogram."
_BUCKET_COUNTER_NAMES: Tuple[str, ...] = tuple(
    f"LE_{int(b)}" for b in BUCKET_BOUNDS_MS) + ("LE_INF",)


def bucket_index(ms: float) -> int:
    """Index of the first bucket whose bound >= ms (bit_length == log2)."""
    if ms <= 1.0:
        return 0
    i = int(ms - 1e-9).bit_length()
    return i if i < len(BUCKET_BOUNDS_MS) else len(BUCKET_BOUNDS_MS)


class Histogram:
    """Fixed-bucket latency histogram; thread-safe."""

    __slots__ = ("name", "counts", "count", "sum_ms", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self.counts = [0] * NUM_BUCKETS
        self.count = 0
        self.sum_ms = 0.0
        self._lock = threading.Lock()

    def observe(self, ms: float) -> None:
        with self._lock:
            self.counts[bucket_index(ms)] += 1
            self.count += 1
            self.sum_ms += ms

    def snapshot(self) -> "Histogram":
        with self._lock:
            out = Histogram(self.name)
            out.counts = list(self.counts)
            out.count = self.count
            out.sum_ms = self.sum_ms
            return out

    def quantile(self, q: float) -> float:
        return quantile_from_buckets(self.counts, q)

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "counts": list(self.counts),
                "count": self.count, "sum_ms": self.sum_ms}


def quantile_from_buckets(counts: List[int], q: float) -> float:
    """Estimate a quantile from per-bucket counts by linear interpolation
    inside the winning bucket.  Overflow observations report the last
    finite bound (a floor, same convention as Prometheus +Inf)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    seen = 0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if seen + c >= rank:
            if i >= len(BUCKET_BOUNDS_MS):          # +Inf bucket
                return BUCKET_BOUNDS_MS[-1]
            lo = BUCKET_BOUNDS_MS[i - 1] if i > 0 else 0.0
            hi = BUCKET_BOUNDS_MS[i]
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        seen += c
    return BUCKET_BOUNDS_MS[-1]


def max_bound_from_buckets(counts: List[int]) -> float:
    """Upper bound of the highest occupied bucket (0 when empty)."""
    for i in range(len(counts) - 1, -1, -1):
        if counts[i] > 0:
            return (BUCKET_BOUNDS_MS[i] if i < len(BUCKET_BOUNDS_MS)
                    else float("inf"))
    return 0.0


# Histogram families pre-registered on every registry (re)start so the
# /metrics scrape always exposes the full set — zero-count until observed —
# and dashboards don't grow holes when a run happens not to spill or commit.
WELL_KNOWN_HISTOGRAMS = ("shuffle.fetch.rtt", "spill.write", "shuffle.merge",
                         "am.heartbeat.rtt", "device.sort",
                         "commit.ledger.fsync",
                         # async device pipeline stages (ops/async_stage.py):
                         # host encode, H2D staging, dispatch->host-visible
                         # latency, D2H readback
                         "device.encode", "device.h2d",
                         "device.dispatch_wait", "device.d2h",
                         # reduce-side device merge latency: merge-path
                         # ladder dispatches (ops/sorter.py merge_sorted_runs)
                         # and the async merge lane's dispatch->host-visible
                         # wait (library/merge_manager.py)
                         "device.merge",
                         # host-engine failover re-sorts (failure
                         # containment, ops/async_stage.py)
                         "device.failover.host_sort",
                         # in-process local-fetch short circuit latency
                         # (shuffle/scheduler.py store/registry fast path)
                         "shuffle.fetch.short_circuit",
                         # tiered buffer store (tez_tpu/store): publish
                         # admission, leased fetch, and watermark demotion
                         # (host->disk spill happens inside the demote timer)
                         "store.publish", "store.fetch", "store.demote",
                         # push shuffle (shuffle/push.py): one eager push
                         # round trip (same-host publish or remote push
                         # verb) and the pusher's total admission wait
                         # (retry-after backoff before accept/give-up)
                         "shuffle.push.rtt", "shuffle.push.admit_wait",
                         # mesh ICI exchange (parallel/coordinator.py): one
                         # exchange round end-to-end — placement, SPMD
                         # dispatch, per-device readback (coded: first
                         # complete copy), decode
                         "mesh.exchange.round",
                         # session admission (am/admission.py): how long a
                         # QUEUE-verdict submission parks before the consumer
                         # promotes it to a running DAG
                         "am.admit.queue_wait",
                         # flight recorder (obs/flight.py): one snapshot
                         # serialize + atomic write when a dump trigger
                         # (DAG failure, breaker-open, watchdog, shed) fires
                         "obs.flight.dump",
                         # streaming mode (am/streaming.py): per-window
                         # cut->commit latency, and the window lag the
                         # backpressure gate observed while pacing the
                         # source (unit: windows, not ms)
                         "stream.window.latency", "stream.window.lag")


class MetricsRegistry:
    """Process-global histograms + gauges for the live /metrics scrape."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hist: Dict[str, Histogram] = {
            n: Histogram(n) for n in WELL_KNOWN_HISTOGRAMS}
        self._gauges: Dict[str, float] = {}

    def histogram(self, name: str) -> Histogram:
        h = self._hist.get(name)
        if h is None:
            with self._lock:
                h = self._hist.get(name)
                if h is None:
                    h = self._hist[name] = Histogram(name)
        return h

    def histograms(self) -> Dict[str, Histogram]:
        with self._lock:
            return {k: v.snapshot() for k, v in self._hist.items()}

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def reset(self) -> None:
        with self._lock:
            self._hist = {n: Histogram(n) for n in WELL_KNOWN_HISTOGRAMS}
            self._gauges.clear()


_REG = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REG


def set_gauge(name: str, value: float) -> None:
    _REG.set_gauge(name, value)


def observe(name: str, ms: float, counters: Any = None) -> None:
    """Record one latency observation.

    Always lands in the process-global registry; when ``counters`` (a
    TezCounters) is given, also lands in the LatencyHistogram.<name>
    bucket counters so the value aggregates task -> vertex -> DAG.
    """
    _REG.histogram(name).observe(ms)
    if _flight.armed():
        _flight.record(_flight.COUNTER, name, a=int(ms * 1000.0))
    if counters is not None:
        g = counters.group(HIST_GROUP_PREFIX + name)
        g.find_counter(_BUCKET_COUNTER_NAMES[bucket_index(ms)]).increment(1)
        g.find_counter("COUNT").increment(1)
        g.find_counter("SUM_US").increment(int(ms * 1000.0))


@contextmanager
def timer(name: str, counters: Any = None) -> Iterator[None]:
    """Time a block and observe() its duration in milliseconds."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        observe(name, (time.perf_counter() - t0) * 1000.0, counters)


# --------------------------------------------------------------------------
# Reading histograms back out of counter dumps (history JSONL / to_dict)
# --------------------------------------------------------------------------

def histograms_from_counters(
        counters_dict: Mapping[str, Mapping[str, int]]
) -> Dict[str, Dict[str, Any]]:
    """Decode LatencyHistogram.* counter groups from a TezCounters.to_dict
    (or history dump) back into {name: {counts, count, sum_us, p50, p95,
    max_ms}} summaries."""
    out: Dict[str, Dict[str, Any]] = {}
    for gname, cs in counters_dict.items():
        if not gname.startswith(HIST_GROUP_PREFIX):
            continue
        name = gname[len(HIST_GROUP_PREFIX):]
        counts = [int(cs.get(b, 0)) for b in _BUCKET_COUNTER_NAMES]
        out[name] = {
            "counts": counts,
            "count": int(cs.get("COUNT", sum(counts))),
            "sum_us": int(cs.get("SUM_US", 0)),
            "p50": quantile_from_buckets(counts, 0.50),
            "p95": quantile_from_buckets(counts, 0.95),
            "max_ms": max_bound_from_buckets(counts),
        }
    return out


# --------------------------------------------------------------------------
# Prometheus text exposition (version 0.0.4)
# --------------------------------------------------------------------------

def _sanitize(name: str) -> str:
    out = "".join(ch if (ch.isalnum() or ch == "_") else "_" for ch in name)
    return out if not out[:1].isdigit() else "_" + out


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def render_prometheus(
        histograms: Mapping[str, Histogram],
        gauges: Mapping[str, float],
        counters_dict: Optional[Mapping[str, Mapping[str, int]]] = None,
) -> str:
    """Render the standard text exposition format.  Histograms emit
    cumulative le-labelled buckets (Prometheus semantics) even though the
    internal representation is per-bucket."""
    lines: List[str] = []
    for name in sorted(histograms):
        h = histograms[name]
        metric = f"tez_latency_{_sanitize(name)}_ms"
        lines.append(f"# HELP {metric} latency histogram for {name}")
        lines.append(f"# TYPE {metric} histogram")
        cum = 0
        for i, bound in enumerate(BUCKET_BOUNDS_MS):
            cum += h.counts[i]
            lines.append(f'{metric}_bucket{{le="{bound:g}"}} {cum}')
        cum += h.counts[-1]
        lines.append(f'{metric}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{metric}_sum {h.sum_ms:g}")
        lines.append(f"{metric}_count {h.count}")
    for name in sorted(gauges):
        metric = f"tez_{_sanitize(name)}"
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {gauges[name]:g}")
    if counters_dict:
        lines.append("# HELP tez_counter Tez counter value")
        lines.append("# TYPE tez_counter gauge")
        for gname in sorted(counters_dict):
            if gname.startswith(HIST_GROUP_PREFIX):
                continue          # already rendered as histograms above
            for cname in sorted(counters_dict[gname]):
                lines.append(
                    f'tez_counter{{group="{_escape_label(gname)}",'
                    f'name="{_escape_label(cname)}"}} '
                    f"{counters_dict[gname][cname]}")
    return "\n".join(lines) + "\n"
