"""Low-overhead distributed tracing plane: causal spans across AM/runtime/shuffle.

Reference parity: there is no tracing subsystem in Apache Tez itself — the
reference profiling surface is counters plus ATS history (SURVEY.md §5.1).
This module supplies the span substrate the history plane cannot: causal
links across threads and seams (DAG submit -> TaskSpec -> task body ->
umbilical -> shuffle fetch), with per-event timestamps fine enough to see a
single penalty-box hold or fence rejection.

Design rules (mirroring common/faults.py):

- Process-global plane, armed per-DAG via ``install_from_conf(conf, scope)``
  from the AM submit path and released in ``on_dag_finished``.  Arming is
  reference-counted by scope; the span buffer SURVIVES disarm so post-run
  exporters (chaos --trace-out, GET /trace) can read it.
- Single-boolean disarmed fast path: every entry point checks the module
  flag ``_armed`` first and returns a shared no-op singleton, so a
  production run that never arms tracing pays one attribute load per call
  and allocates nothing.
- Bounded in-memory ring buffer (``collections.deque(maxlen=...)``) —
  a runaway DAG evicts its oldest spans instead of eating the heap.

Carrier format is W3C trace-context shaped (``00-<trace_id>-<span_id>-01``)
so the strings stamped into TaskSpec / heartbeats stay greppable and could
interop with a real OTLP exporter later.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, NamedTuple, Optional, Tuple

from tez_tpu.obs import flight as _flight

DEFAULT_BUFFER_SPANS = 32768

_armed = False          # single-boolean fast path (see common/faults.py)
_TLS = threading.local()


# --------------------------------------------------------------------------
# Trace context + carrier
# --------------------------------------------------------------------------

class TraceContext(NamedTuple):
    """Immutable causal coordinate: which trace, and which span is parent."""
    trace_id: str
    span_id: str

    def carrier(self) -> str:
        """W3C traceparent-style wire string for TaskSpec/heartbeat fields."""
        return f"00-{self.trace_id}-{self.span_id}-01"


def parse_carrier(s: Optional[str]) -> Optional[TraceContext]:
    """Parse a carrier string; malformed/empty carriers yield None (the
    receiver simply starts a fresh root trace — never an error)."""
    if not s:
        return None
    parts = s.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return TraceContext(parts[1], parts[2])


def _gen_trace_id() -> str:
    return os.urandom(16).hex()


def _gen_span_id() -> str:
    return os.urandom(8).hex()


# --------------------------------------------------------------------------
# Spans
# --------------------------------------------------------------------------

class Span:
    """One timed unit of work.  start/end are epoch seconds (time.time) so
    spans recorded on different threads/processes align on one axis."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id",
                 "start", "end", "args", "events", "thread", "_recorded")

    def __init__(self, name: str, cat: str, trace_id: str,
                 parent_id: Optional[str], args: Dict[str, Any]) -> None:
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = _gen_span_id()
        self.parent_id = parent_id
        self.start = time.time()
        self.end: Optional[float] = None
        self.args = args
        self.events: List[Tuple[float, str, Dict[str, Any]]] = []
        self.thread = threading.current_thread().name
        self._recorded = False

    # -- annotation -------------------------------------------------------
    def annotate(self, **kv: Any) -> "Span":
        self.args.update(kv)
        return self

    def event(self, name: str, **attrs: Any) -> None:
        """Timestamped point annotation inside this span (fault firings,
        fence rejections, penalty-box holds...)."""
        self.events.append((time.time(), name, attrs))

    # -- lifecycle --------------------------------------------------------
    @property
    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else time.time()) - self.start

    def finish(self, error: Optional[BaseException] = None) -> None:
        if self._recorded:
            return
        self._recorded = True
        self.end = time.time()
        if error is not None:
            self.args["error"] = f"{type(error).__name__}: {error}"
        _PLANE.record(self)
        if _flight.armed():
            _flight.span_edge(self.name, self.start, self.end - self.start,
                              cat=self.cat)

    # -- context-manager protocol (pushes onto the thread-local stack) ----
    def __enter__(self) -> "Span":
        _stack().append(self)
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        self.finish(error=exc if isinstance(exc, BaseException) else None)
        return False

    def __repr__(self) -> str:  # debugging aid only
        return (f"Span({self.name!r}, trace={self.trace_id[:8]}, "
                f"span={self.span_id}, parent={self.parent_id}, "
                f"dur={self.duration * 1000:.2f}ms)")


class _NoopSpan:
    """Shared disarmed singleton: every method is a no-op and ``with``
    support returns the same object, so the disarmed path allocates zero
    objects per call."""

    __slots__ = ()

    name = ""
    trace_id = ""
    span_id = ""
    parent_id = None
    start = 0.0
    end = 0.0
    duration = 0.0
    context = None
    events: List[Any] = []
    args: Dict[str, Any] = {}

    def annotate(self, **kv: Any) -> "_NoopSpan":
        return self

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def finish(self, error: Optional[BaseException] = None) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


def _stack() -> List[Span]:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def _resolve_parent(parent: Any) -> Tuple[str, Optional[str]]:
    """Return (trace_id, parent_span_id) honoring: explicit parent >
    thread-local current span > thread-attached ambient context > new root."""
    if parent is None:
        st = _stack()
        if st:
            ctx = st[-1].context
            return ctx.trace_id, ctx.span_id
        ambient = getattr(_TLS, "ambient", None)
        if ambient is not None:
            return ambient.trace_id, ambient.span_id
        return _gen_trace_id(), None
    if isinstance(parent, Span):
        return parent.trace_id, parent.span_id
    if isinstance(parent, TraceContext):
        return parent.trace_id, parent.span_id
    if isinstance(parent, str):
        ctx = parse_carrier(parent)
        if ctx is not None:
            return ctx.trace_id, ctx.span_id
        return _gen_trace_id(), None
    raise TypeError(f"unsupported span parent: {parent!r}")


# --------------------------------------------------------------------------
# Public span API
# --------------------------------------------------------------------------

def span(name: str, cat: str = "", parent: Any = None, **args: Any):
    """Start a span intended for ``with`` use on the current thread:
    it becomes the thread's current span until the block exits."""
    if not _armed:
        return NOOP_SPAN
    trace_id, parent_id = _resolve_parent(parent)
    return Span(name, cat, trace_id, parent_id, args)


def start_span(name: str, cat: str = "", parent: Any = None, **args: Any):
    """Start a span WITHOUT touching the thread-local stack — for
    long-lived / cross-thread spans (e.g. the DAG root span the AM holds
    open until on_dag_finished).  Caller must invoke .finish()."""
    if not _armed:
        return NOOP_SPAN
    trace_id, parent_id = _resolve_parent(parent)
    return Span(name, cat, trace_id, parent_id, args)


def event(name: str, parent: Any = None, **attrs: Any) -> None:
    """Record a point event.  Attached to the current span when one is
    active on this thread; otherwise recorded as a standalone zero-duration
    span (the common case for fence rejections and penalty-box holds that
    fire on dispatcher/fetcher threads)."""
    if not _armed:
        return
    st = _stack()
    if parent is None and st:
        st[-1].event(name, **attrs)
        return
    trace_id, parent_id = _resolve_parent(parent)
    sp = Span(name, "instant", trace_id, parent_id, dict(attrs))
    sp.finish()


def current_span() -> Optional[Span]:
    if not _armed:
        return None
    st = _stack()
    return st[-1] if st else None


def current_context() -> Optional[TraceContext]:
    """The causal coordinate a child started *now* on this thread would
    inherit — current span, else the thread-attached ambient context."""
    st = _stack()
    if st:
        return st[-1].context
    return getattr(_TLS, "ambient", None)


def current_carrier() -> str:
    ctx = current_context()
    return ctx.carrier() if ctx is not None else ""


@contextmanager
def attached(parent: Any) -> Iterator[Optional[TraceContext]]:
    """Attach an ambient trace context to this thread for the duration of
    the block: spans started with no explicit parent and no active span
    will parent under it.  ``parent`` may be a carrier string, TraceContext,
    or Span; falsy/unparseable values attach nothing (no-op)."""
    ctx: Optional[TraceContext] = None
    if isinstance(parent, TraceContext):
        ctx = parent
    elif isinstance(parent, Span):
        ctx = parent.context
    elif isinstance(parent, str):
        ctx = parse_carrier(parent)
    prev = getattr(_TLS, "ambient", None)
    _TLS.ambient = ctx if ctx is not None else prev
    try:
        yield ctx
    finally:
        _TLS.ambient = prev


# --------------------------------------------------------------------------
# The plane (arming + ring buffer)
# --------------------------------------------------------------------------

class TracePlane:
    """Scope-refcounted arming + bounded span ring buffer."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._scopes: set = set()
        self._buf: Optional[deque] = None

    def install(self, scope: str,
                capacity: int = DEFAULT_BUFFER_SPANS) -> None:
        global _armed
        with self._lock:
            self._scopes.add(scope)
            if self._buf is None or (self._buf.maxlen or 0) != capacity:
                old = list(self._buf) if self._buf is not None else []
                self._buf = deque(old, maxlen=max(1, int(capacity)))
            _armed = True

    def clear(self, scope: str) -> None:
        """Release one scope.  The buffer is deliberately retained so
        post-run exporters can still read the spans."""
        global _armed
        with self._lock:
            self._scopes.discard(scope)
            if not self._scopes:
                _armed = False

    def clear_all(self) -> None:
        global _armed
        with self._lock:
            self._scopes.clear()
            self._buf = None
            _armed = False

    def record(self, sp: Span) -> None:
        buf = self._buf
        if buf is not None:
            buf.append(sp)       # deque.append with maxlen is atomic

    def snapshot(self) -> List[Span]:
        buf = self._buf
        return list(buf) if buf is not None else []

    @property
    def scopes(self) -> set:
        with self._lock:
            return set(self._scopes)


_PLANE = TracePlane()


def plane() -> TracePlane:
    return _PLANE


def armed() -> bool:
    return _armed


def arm(scope: str = "manual",
        capacity: int = DEFAULT_BUFFER_SPANS) -> None:
    _PLANE.install(scope, capacity)


def clear(scope: str) -> None:
    _PLANE.clear(scope)


def clear_all() -> None:
    _PLANE.clear_all()


def snapshot() -> List[Span]:
    return _PLANE.snapshot()


def install_from_conf(conf: Any, scope: str) -> bool:
    """Arm the plane for one DAG when ``tez.trace.enabled`` is set.
    Mirrors faults.install_from_conf: called from app_master.submit_dag
    with scope=str(dag_id); the matching clear() happens in
    on_dag_finished."""
    from tez_tpu.common import config as C
    enabled = conf.get(C.TRACE_ENABLED)
    if not (enabled is True or str(enabled) == "True"):
        return False
    capacity = int(conf.get(C.TRACE_BUFFER_SPANS))
    _PLANE.install(scope, capacity)
    return True
