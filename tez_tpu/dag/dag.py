"""Client-side DAG construction DSL.

Reference parity: tez-api/.../dag/api/DAG.java:90 (addVertex:138, addEdge:287,
verify:574, createDag:844), Vertex.java:131, Edge.java, VertexGroup /
GroupInputEdge (DAG.java:315).  verify() keeps the reference semantics:
duplicate names rejected at add time, unknown vertices at addEdge time,
cycle detection at build time (disconnected components allowed, with a
warning), illegal output-vertex-as-edge-source checks.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, Iterable, List, Optional, Sequence

from tez_tpu.common.payload import (EntityDescriptor, InputDescriptor,
                                    InputInitializerDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor,
                                    VertexManagerPluginDescriptor)
from tez_tpu.dag.edge_property import (DataMovementType, EdgeProperty,
                                       SchedulingType)
from tez_tpu.dag.plan import (DAGPlan, EdgePlan, GroupInputEdgePlan,
                              LeafOutputSpec, RootInputSpec, VertexGroupPlan,
                              VertexPlan)


LOG = logging.getLogger(__name__)


class TezUncheckedException(Exception):
    """Reference: org.apache.tez.dag.api.TezUncheckedException."""


class DataSourceDescriptor:
    """Reference: DataSourceDescriptor.java — a root input + optional
    AM-side initializer."""

    def __init__(self, input_descriptor: InputDescriptor,
                 initializer: Optional[InputInitializerDescriptor] = None,
                 parallelism: int = -1,
                 events: Sequence[Any] = ()):
        self.input_descriptor = input_descriptor
        self.initializer = initializer
        self.parallelism = parallelism
        self.events = tuple(events)

    @staticmethod
    def create(input_descriptor: InputDescriptor,
               initializer: Optional[InputInitializerDescriptor] = None,
               parallelism: int = -1) -> "DataSourceDescriptor":
        return DataSourceDescriptor(input_descriptor, initializer, parallelism)


class DataSinkDescriptor:
    """Reference: DataSinkDescriptor.java — a leaf output + optional committer."""

    def __init__(self, output_descriptor: OutputDescriptor,
                 committer: Optional[OutputCommitterDescriptor] = None):
        self.output_descriptor = output_descriptor
        self.committer = committer

    @staticmethod
    def create(output_descriptor: OutputDescriptor,
               committer: Optional[OutputCommitterDescriptor] = None
               ) -> "DataSinkDescriptor":
        return DataSinkDescriptor(output_descriptor, committer)


class Vertex:
    """Reference: Vertex.java:131 (Vertex.create)."""

    def __init__(self, name: str, processor: ProcessorDescriptor,
                 parallelism: int = -1):
        if not name or name != name.strip():
            raise TezUncheckedException(f"illegal vertex name {name!r}")
        if parallelism < -1 or parallelism == 0:
            raise TezUncheckedException(
                f"parallelism must be -1 (determined at runtime) or > 0: {parallelism}")
        self.name = name
        self.processor = processor
        self.parallelism = parallelism
        self.data_sources: Dict[str, DataSourceDescriptor] = {}
        self.data_sinks: Dict[str, DataSinkDescriptor] = {}
        self.vertex_manager: Optional[VertexManagerPluginDescriptor] = None
        self.conf: Dict[str, Any] = {}
        self.task_resource_mb = 0
        self.locality_hints: tuple = ()
        self._in_edges: List["Edge"] = []
        self._out_edges: List["Edge"] = []
        self._group_inputs: List[str] = []

    @staticmethod
    def create(name: str, processor: ProcessorDescriptor,
               parallelism: int = -1) -> "Vertex":
        return Vertex(name, processor, parallelism)

    def add_data_source(self, name: str, source: DataSourceDescriptor) -> "Vertex":
        if name in self.data_sources:
            raise TezUncheckedException(f"duplicate data source {name}")
        self.data_sources[name] = source
        return self

    def add_data_sink(self, name: str, sink: DataSinkDescriptor) -> "Vertex":
        if name in self.data_sinks:
            raise TezUncheckedException(f"duplicate data sink {name}")
        self.data_sinks[name] = sink
        return self

    def set_vertex_manager_plugin(
            self, desc: VertexManagerPluginDescriptor) -> "Vertex":
        self.vertex_manager = desc
        return self

    def set_conf(self, key: str, value: Any) -> "Vertex":
        self.conf[key] = value
        return self

    def __repr__(self) -> str:
        return f"Vertex({self.name}, parallelism={self.parallelism})"


class Edge:
    """Reference: Edge.java (api)."""

    def __init__(self, input_vertex: Vertex, output_vertex: Vertex,
                 edge_property: EdgeProperty):
        self.input_vertex = input_vertex    # producer
        self.output_vertex = output_vertex  # consumer
        self.edge_property = edge_property

    @staticmethod
    def create(input_vertex: Vertex, output_vertex: Vertex,
               edge_property: EdgeProperty) -> "Edge":
        return Edge(input_vertex, output_vertex, edge_property)

    @property
    def id(self) -> str:
        return f"{self.input_vertex.name}->{self.output_vertex.name}"

    def __repr__(self) -> str:
        return f"Edge({self.id}, {self.edge_property.data_movement_type.name})"


class VertexGroup:
    """Reference: VertexGroup (DAG.java:315) — alias for a set of vertices
    whose outputs feed one consumer through a merged input."""

    def __init__(self, name: str, members: Sequence[Vertex]):
        if len(members) < 2:
            raise TezUncheckedException("vertex group needs >= 2 members")
        self.name = name
        self.members = list(members)
        self.outputs: Dict[str, DataSinkDescriptor] = {}

    def add_data_sink(self, name: str, sink: DataSinkDescriptor) -> "VertexGroup":
        self.outputs[name] = sink
        for v in self.members:
            v.add_data_sink(name, sink)
        return self


class GroupInputEdge:
    """Reference: GroupInputEdge.java — group -> vertex edge with a merged
    input combining the per-member inputs."""

    def __init__(self, group: VertexGroup, output_vertex: Vertex,
                 edge_property: EdgeProperty, merged_input: EntityDescriptor):
        self.group = group
        self.output_vertex = output_vertex
        self.edge_property = edge_property
        self.merged_input = merged_input

    @staticmethod
    def create(group: VertexGroup, output_vertex: Vertex,
               edge_property: EdgeProperty,
               merged_input: EntityDescriptor) -> "GroupInputEdge":
        return GroupInputEdge(group, output_vertex, edge_property, merged_input)


class DAG:
    """Reference: DAG.java:90."""

    def __init__(self, name: str):
        if not name:
            raise TezUncheckedException("DAG needs a name")
        self.name = name
        self.vertices: Dict[str, Vertex] = {}
        self.edges: List[Edge] = []
        self.vertex_groups: Dict[str, VertexGroup] = {}
        self.group_edges: List[GroupInputEdge] = []
        self.conf: Dict[str, Any] = {}
        self.credentials: Dict[str, bytes] = {}

    @staticmethod
    def create(name: str) -> "DAG":
        return DAG(name)

    # -- construction -------------------------------------------------------
    def add_vertex(self, vertex: Vertex) -> "DAG":
        """Reference: DAG.addVertex:138 — duplicate names rejected."""
        if vertex.name in self.vertices:
            raise TezUncheckedException(f"duplicate vertex name {vertex.name}")
        if vertex.name in self.vertex_groups:
            raise TezUncheckedException(
                f"vertex name clashes with group {vertex.name}")
        self.vertices[vertex.name] = vertex
        return self

    def add_edge(self, edge: Edge) -> "DAG":
        """Reference: DAG.addEdge:287 — both endpoints must already exist;
        at most one edge per (src, dst) pair (the reference's VertexImpl keys
        source vertices by name, so a second edge would be unreachable)."""
        for v in (edge.input_vertex, edge.output_vertex):
            if self.vertices.get(v.name) is not v:
                raise TezUncheckedException(
                    f"vertex {v.name} not part of DAG {self.name}")
        if any(e.id == edge.id for e in self.edges):
            raise TezUncheckedException(f"duplicate edge {edge.id}")
        edge.input_vertex._out_edges.append(edge)
        edge.output_vertex._in_edges.append(edge)
        self.edges.append(edge)
        return self

    def create_vertex_group(self, name: str,
                            members: Sequence[Vertex]) -> VertexGroup:
        if name in self.vertex_groups or name in self.vertices:
            raise TezUncheckedException(f"duplicate group name {name}")
        for v in members:
            if self.vertices.get(v.name) is not v:
                raise TezUncheckedException(
                    f"group member {v.name} not part of DAG")
        group = VertexGroup(name, members)
        self.vertex_groups[name] = group
        return group

    def add_group_edge(self, edge: GroupInputEdge) -> "DAG":
        if self.vertex_groups.get(edge.group.name) is not edge.group:
            raise TezUncheckedException("group not part of DAG")
        if self.vertices.get(edge.output_vertex.name) is not edge.output_vertex:
            raise TezUncheckedException("output vertex not part of DAG")
        self.group_edges.append(edge)
        return self

    def set_conf(self, key: str, value: Any) -> "DAG":
        self.conf[key] = value
        return self

    # -- validation (DAG.verify:574) ----------------------------------------
    def verify(self) -> List[str]:
        """Topological check: rejects cycles, warns on disconnected
        sub-graphs; validates edge properties.  Returns topo order."""
        if not self.vertices:
            raise TezUncheckedException("empty DAG")

        adj: Dict[str, List[str]] = {v: [] for v in self.vertices}
        radj: Dict[str, List[str]] = {v: [] for v in self.vertices}
        all_edges: List[tuple] = [
            (e.input_vertex.name, e.output_vertex.name, e.edge_property)
            for e in self.edges
        ]
        for ge in self.group_edges:
            for m in ge.group.members:
                all_edges.append((m.name, ge.output_vertex.name, ge.edge_property))

        for src, dst, prop in all_edges:
            if src == dst:
                raise TezUncheckedException(f"self-edge on {src}")
            # ONE_TO_ONE requires equal (or runtime-determined) parallelism
            if prop.data_movement_type is DataMovementType.ONE_TO_ONE:
                sp = self.vertices[src].parallelism
                dp = self.vertices[dst].parallelism
                if sp != -1 and dp != -1 and sp != dp:
                    raise TezUncheckedException(
                        f"ONE_TO_ONE edge {src}->{dst} with unequal parallelism "
                        f"{sp} vs {dp}")
            adj[src].append(dst)
            radj[dst].append(src)

        # Kahn topo sort; leftover nodes => cycle (DAG.java checkCycles)
        indeg = {v: len(radj[v]) for v in self.vertices}
        order = [v for v in self.vertices if indeg[v] == 0]
        i = 0
        while i < len(order):
            for w in adj[order[i]]:
                indeg[w] -= 1
                if indeg[w] == 0:
                    order.append(w)
            i += 1
        if len(order) != len(self.vertices):
            cyclic = sorted(v for v in self.vertices if indeg[v] > 0)
            raise TezUncheckedException(f"DAG contains a cycle through {cyclic}")

        # Disconnect check: every vertex reachable in the undirected sense
        # from vertex 0.  The reference runs disconnected component sets as
        # one DAG (DAG.java:574 verify only rejects cycles/dups — e.g.
        # tez-tests TwoLevelsFailingDAG is four disconnected pairs), so
        # this only WARNS; single fully-orphaned vertices are still legal.
        if len(self.vertices) > 1:
            seen: set = set()
            stack = [next(iter(self.vertices))]
            und: Dict[str, set] = {v: set() for v in self.vertices}
            for src, dst, _ in all_edges:
                und[src].add(dst)
                und[dst].add(src)
            while stack:
                v = stack.pop()
                if v in seen:
                    continue
                seen.add(v)
                stack.extend(und[v] - seen)
            if len(seen) != len(self.vertices):
                orphans = sorted(set(self.vertices) - seen)
                LOG.warning("DAG %s has disconnected components "
                            "(vertices %s not connected to %s)", self.name,
                            orphans, sorted(seen))
        return order

    # -- plan build (DAG.createDag:844) -------------------------------------
    def create_dag_plan(self, conf: Optional[Dict[str, Any]] = None) -> DAGPlan:
        self.verify()
        dag_conf = dict(conf or {})
        dag_conf.update(self.conf)

        edge_plans = tuple(
            EdgePlan(e.id, e.input_vertex.name, e.output_vertex.name,
                     e.edge_property) for e in self.edges)
        group_edge_plans = []
        synth_edges: List[EdgePlan] = []
        for ge in self.group_edges:
            gid = f"{ge.group.name}->{ge.output_vertex.name}"
            group_edge_plans.append(GroupInputEdgePlan(
                gid, ge.group.name, ge.output_vertex.name, ge.edge_property,
                ge.merged_input))
            # Materialize one concrete edge per member (reference expands
            # group edges into member edges inside DAGImpl).
            for m in ge.group.members:
                eid = f"{m.name}->{ge.output_vertex.name}#group:{ge.group.name}"
                synth_edges.append(EdgePlan(eid, m.name,
                                            ge.output_vertex.name,
                                            ge.edge_property))

        all_edge_plans = edge_plans + tuple(synth_edges)
        by_in: Dict[str, List[str]] = {v: [] for v in self.vertices}
        by_out: Dict[str, List[str]] = {v: [] for v in self.vertices}
        for ep in all_edge_plans:
            by_out[ep.input_vertex].append(ep.id)
            by_in[ep.output_vertex].append(ep.id)

        vertex_plans = []
        for v in self.vertices.values():
            vertex_plans.append(VertexPlan(
                name=v.name,
                processor=v.processor,
                parallelism=v.parallelism,
                vertex_manager=v.vertex_manager,
                root_inputs=tuple(
                    RootInputSpec(n, s.input_descriptor, s.initializer,
                                  s.parallelism, s.events)
                    for n, s in v.data_sources.items()),
                leaf_outputs=tuple(
                    LeafOutputSpec(n, s.output_descriptor, s.committer)
                    for n, s in v.data_sinks.items()),
                in_edge_ids=tuple(by_in[v.name]),
                out_edge_ids=tuple(by_out[v.name]),
                conf=dict(v.conf),
                task_resource_mb=v.task_resource_mb,
                locality_hints=v.locality_hints,
            ))

        return DAGPlan(
            name=self.name,
            vertices=tuple(vertex_plans),
            edges=all_edge_plans,
            vertex_groups=tuple(
                VertexGroupPlan(g.name, tuple(m.name for m in g.members),
                                tuple(g.outputs))
                for g in self.vertex_groups.values()),
            group_edges=tuple(group_edge_plans),
            dag_conf=dag_conf,
            credentials=dict(self.credentials),
            tenant=str(dag_conf.get("tez.dag.tenant", "") or ""),
        )
