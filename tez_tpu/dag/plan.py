"""Serializable DAG plan — the wire format a client ships to the orchestrator.

Reference parity: tez-api/src/main/proto/DAGApiRecords.proto (DAGPlan,
VertexPlan, EdgePlan, ConfigurationProto...) built by DAG.createDag
(DAG.java:844).  Plain frozen dataclasses serialized with pickle; structure
mirrors the proto so recovery/history can persist and reload plans.
"""
from __future__ import annotations

import dataclasses
import pickle
from typing import Any, Dict, List, Optional, Tuple

from tez_tpu.common.payload import (EntityDescriptor, InputDescriptor,
                                    InputInitializerDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor,
                                    ProcessorDescriptor,
                                    VertexManagerPluginDescriptor)
from tez_tpu.dag.edge_property import EdgeProperty


@dataclasses.dataclass(frozen=True)
class RootInputSpec:
    """A data source attached to a vertex (reference: DataSourceDescriptor +
    RootInputLeafOutputProto)."""
    name: str
    input_descriptor: InputDescriptor
    initializer_descriptor: Optional[InputInitializerDescriptor] = None
    # If the client already knows parallelism (e.g. pre-computed splits):
    parallelism: int = -1
    events: Tuple[Any, ...] = ()   # pre-serialized InputDataInformationEvents


@dataclasses.dataclass(frozen=True)
class LeafOutputSpec:
    """A data sink attached to a vertex (reference: DataSinkDescriptor)."""
    name: str
    output_descriptor: OutputDescriptor
    committer_descriptor: Optional[OutputCommitterDescriptor] = None


@dataclasses.dataclass(frozen=True)
class VertexPlan:
    name: str
    processor: ProcessorDescriptor
    parallelism: int
    vertex_manager: Optional[VertexManagerPluginDescriptor]
    root_inputs: Tuple[RootInputSpec, ...]
    leaf_outputs: Tuple[LeafOutputSpec, ...]
    in_edge_ids: Tuple[str, ...]
    out_edge_ids: Tuple[str, ...]
    conf: Dict[str, Any]
    task_resource_mb: int = 0
    locality_hints: Tuple[Tuple[str, ...], ...] = ()


@dataclasses.dataclass(frozen=True)
class EdgePlan:
    id: str
    input_vertex: str     # producer
    output_vertex: str    # consumer
    edge_property: EdgeProperty


@dataclasses.dataclass(frozen=True)
class VertexGroupPlan:
    name: str
    members: Tuple[str, ...]
    outputs: Tuple[str, ...]          # shared leaf-output names


@dataclasses.dataclass(frozen=True)
class GroupInputEdgePlan:
    id: str
    group_name: str
    output_vertex: str
    edge_property: EdgeProperty
    merged_input: EntityDescriptor


@dataclasses.dataclass(frozen=True)
class DAGPlan:
    name: str
    vertices: Tuple[VertexPlan, ...]
    edges: Tuple[EdgePlan, ...]
    vertex_groups: Tuple[VertexGroupPlan, ...] = ()
    group_edges: Tuple[GroupInputEdgePlan, ...] = ()
    dag_conf: Dict[str, Any] = dataclasses.field(default_factory=dict)
    credentials: Dict[str, bytes] = dataclasses.field(default_factory=dict)
    #: tenant id for multi-tenant session AMs (admission caps, fair-share,
    #: store quotas — docs/multitenancy.md); "" = the anonymous tenant.
    #: Populated from ``tez.dag.tenant`` by DAG.create_dag_plan.
    tenant: str = ""

    def vertex(self, name: str) -> VertexPlan:
        for v in self.vertices:
            if v.name == name:
                return v
        raise KeyError(name)

    def edge(self, edge_id: str) -> EdgePlan:
        for e in self.edges:
            if e.id == edge_id:
                return e
        raise KeyError(edge_id)

    def serialize(self) -> bytes:
        return pickle.dumps(self)

    @staticmethod
    def deserialize(data: bytes) -> "DAGPlan":
        plan = pickle.loads(data)
        assert isinstance(plan, DAGPlan)
        return plan
