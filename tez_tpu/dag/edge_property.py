"""Edge semantics: declarative data-movement types.

Reference parity: tez-api/.../dag/api/EdgeProperty.java:35 —
DataMovementType (:44-66), DataSourceType (:71), SchedulingType (:96),
ConcurrentEdgeTriggerType (:114).

TPU mapping (SURVEY.md §2.9): SCATTER_GATHER -> XLA all-to-all over ICI
intra-slice (DCN object fetch inter-slice); BROADCAST -> all-gather /
replicated buffer; ONE_TO_ONE -> pointwise sharding with affinity;
CUSTOM -> EdgeManagerPlugin routing (cartesian product, fair shuffle).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from tez_tpu.common.payload import (EdgeManagerPluginDescriptor,
                                    InputDescriptor, OutputDescriptor)


class DataMovementType(enum.Enum):
    ONE_TO_ONE = "one_to_one"          # src task i -> dst task i
    BROADCAST = "broadcast"            # every src output -> all dst tasks
    SCATTER_GATHER = "scatter_gather"  # src partitions shard across dst tasks
    CUSTOM = "custom"                  # EdgeManagerPlugin decides


class DataSourceType(enum.Enum):
    PERSISTED = "persisted"            # survives task exit (host-RAM/SSD copy on TPU)
    PERSISTED_RELIABLE = "persisted_reliable"  # survives host loss (object store)
    EPHEMERAL = "ephemeral"            # HBM only; consumer must run concurrently


class SchedulingType(enum.Enum):
    SEQUENTIAL = "sequential"          # dst may start after src starts producing
    CONCURRENT = "concurrent"          # gang-schedule src+dst together


class ConcurrentEdgeTriggerType(enum.Enum):
    SOURCE_VERTEX_CONFIGURED = "source_vertex_configured"
    SOURCE_TASK_STARTED = "source_task_started"


@dataclasses.dataclass(frozen=True)
class EdgeProperty:
    data_movement_type: DataMovementType
    data_source_type: DataSourceType
    scheduling_type: SchedulingType
    edge_source: OutputDescriptor
    edge_destination: InputDescriptor
    edge_manager_descriptor: Optional[EdgeManagerPluginDescriptor] = None
    concurrent_trigger: ConcurrentEdgeTriggerType = (
        ConcurrentEdgeTriggerType.SOURCE_VERTEX_CONFIGURED)

    @staticmethod
    def create(data_movement_type: DataMovementType,
               data_source_type: DataSourceType,
               scheduling_type: SchedulingType,
               edge_source: OutputDescriptor,
               edge_destination: InputDescriptor) -> "EdgeProperty":
        assert data_movement_type is not DataMovementType.CUSTOM, \
            "use create_custom for CUSTOM edges"
        return EdgeProperty(data_movement_type, data_source_type,
                            scheduling_type, edge_source, edge_destination)

    @staticmethod
    def create_custom(edge_manager: EdgeManagerPluginDescriptor,
                      data_source_type: DataSourceType,
                      edge_source: OutputDescriptor,
                      edge_destination: InputDescriptor,
                      scheduling_type: SchedulingType = SchedulingType.SEQUENTIAL
                      ) -> "EdgeProperty":
        return EdgeProperty(DataMovementType.CUSTOM, data_source_type,
                            scheduling_type, edge_source, edge_destination,
                            edge_manager_descriptor=edge_manager)
