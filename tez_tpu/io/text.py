"""Root input for text files: splits + line records.

Reference parity: tez-mapreduce MRInput.java:87 (HDFS splits -> records) +
MRInputAMSplitGenerator.java:61 (AM-side split computation -> events +
parallelism) + TezSplitGrouper.java:43 (group splits to a target wave count).
Local filesystem instead of HDFS; splits are newline-aligned byte ranges.
"""
from __future__ import annotations

import dataclasses
import glob as globlib
import os
from typing import Any, Iterator, List, Sequence, Tuple

from tez_tpu.api.events import InputDataInformationEvent, TezAPIEvent
from tez_tpu.api.initializer import (InputConfigureVertexTasksEvent,
                                     InputInitializer)
from tez_tpu.api.runtime import KeyValueReader, LogicalInput, Reader
from tez_tpu.common.counters import FileSystemCounter, TaskCounter


@dataclasses.dataclass(frozen=True)
class FileSplit:
    path: str
    start: int
    length: int


def compute_splits(paths: Sequence[str], desired_splits: int,
                   min_split_bytes: int = 64 * 1024) -> List[FileSplit]:
    """Byte-range splits over the input files (newline alignment is handled
    at read time: a split starts after its first newline unless at offset 0,
    and reads through the record straddling its end — standard InputFormat
    semantics)."""
    files = []
    for p in paths:
        matches = sorted(globlib.glob(p)) if any(c in p for c in "*?[") else [p]
        for m in matches:
            if os.path.isdir(m):
                files.extend(sorted(
                    os.path.join(m, f) for f in os.listdir(m)
                    if os.path.isfile(os.path.join(m, f))))
            else:
                files.append(m)
    total = sum(os.path.getsize(f) for f in files)
    if total == 0 or desired_splits <= 0:
        return [FileSplit(f, 0, os.path.getsize(f)) for f in files]
    target = max(min_split_bytes, total // desired_splits)
    splits: List[FileSplit] = []
    for f in files:
        size = os.path.getsize(f)
        pos = 0
        while pos < size:
            length = min(target, size - pos)
            # avoid tiny trailing splits (< half target merges into last)
            if size - (pos + length) < target // 2:
                length = size - pos
            splits.append(FileSplit(f, pos, length))
            pos += length
    return splits


def group_splits(splits: List[FileSplit], target_count: int
                 ) -> List[List[FileSplit]]:
    """TezSplitGrouper analog: coalesce splits to ~target_count groups
    (locality is moot on local FS, so greedy size-balanced grouping)."""
    if target_count <= 0 or len(splits) <= target_count:
        return [[s] for s in splits]
    groups: List[List[FileSplit]] = [[] for _ in range(target_count)]
    sizes = [0] * target_count
    for s in sorted(splits, key=lambda s: -s.length):
        i = sizes.index(min(sizes))
        groups[i].append(s)
        sizes[i] += s.length
    return [g for g in groups if g]


class TextSplitGenerator(InputInitializer):
    """AM-side initializer: payload {"paths": [...], "desired_splits": N or
    -1 (use vertex parallelism or one wave of slots)}."""

    def initialize(self) -> List[Any]:
        payload = self.context.user_payload.load() or {}
        paths = payload.get("paths", [])
        desired = payload.get("desired_splits", -1)
        if desired <= 0:
            desired = self.context.num_tasks
        if desired <= 0:
            desired = max(1, self.context.get_total_available_resource())
        splits = compute_splits(paths, desired,
                                payload.get("min_split_bytes", 64 * 1024))
        groups = group_splits(splits, desired)
        if self.context.num_tasks > 0:
            # fixed vertex parallelism: every task needs exactly one split
            # event (possibly empty) or it would wait forever
            while len(groups) < self.context.num_tasks:
                groups.append([])
            groups = groups[:self.context.num_tasks] if \
                len(groups) <= self.context.num_tasks else \
                self._fold(groups, self.context.num_tasks)
        events: List[Any] = [
            InputConfigureVertexTasksEvent(num_tasks=len(groups))]
        for i, group in enumerate(groups):
            events.append(InputDataInformationEvent(
                source_index=i, user_payload=group, target_index=i))
        return events

    @staticmethod
    def _fold(groups: List[List[FileSplit]], n: int) -> List[List[FileSplit]]:
        out: List[List[FileSplit]] = [[] for _ in range(n)]
        for i, g in enumerate(groups):
            out[i % n].extend(g)
        return out


class _LineReader(KeyValueReader):
    """Yields (byte offset, line bytes) per record — TextInputFormat parity."""

    def __init__(self, splits: Sequence[FileSplit], context: Any):
        self.splits = splits
        self.context = context

    def iter_chunks(self, chunk_bytes: int = 8 << 20
                    ) -> Iterator[bytes]:
        """Vectorization-friendly reader: yields large line-aligned byte
        chunks covering exactly this reader's splits (same boundary
        semantics as line iteration: a split owns lines STARTING in
        (start, end]).  Batch-first processors (e.g. the vectorized
        tokenizer) consume these instead of per-record lines — the
        TPU-native answer to the reference's per-record hot loop."""
        bytes_read = self.context.counters.find_counter(
            FileSystemCounter.FILE_BYTES_READ)
        read_ops = self.context.counters.find_counter(
            FileSystemCounter.FILE_READ_OPS)
        for split in self.splits:
            with open(split.path, "rb") as fh:
                read_ops.increment()
                fh.seek(split.start)
                pos = split.start
                if split.start > 0:
                    skipped = fh.readline()  # partial record owned by prev
                    pos += len(skipped)
                    bytes_read.increment(len(skipped))
                end = split.start + split.length
                while pos <= end:
                    want = min(chunk_bytes, end - pos + 1)
                    chunk = fh.read(want)
                    if not chunk:
                        break
                    if not chunk.endswith(b"\n"):
                        # extend to the line boundary (the line STARTING at
                        # or before `end` belongs to this split in full)
                        tail = fh.readline()
                        chunk += tail
                    pos += len(chunk)
                    bytes_read.increment(len(chunk))
                    self.context.notify_progress()
                    yield chunk

    def __iter__(self) -> Iterator[Tuple[int, bytes]]:
        # counters update incrementally inside the loop (a consumer may stop
        # early, closing the generator — a post-loop epilogue would be
        # skipped entirely; and re-iteration must not double-count)
        records = self.context.counters.find_counter(
            TaskCounter.INPUT_RECORDS_PROCESSED)
        bytes_read = self.context.counters.find_counter(
            FileSystemCounter.FILE_BYTES_READ)
        read_ops = self.context.counters.find_counter(
            FileSystemCounter.FILE_READ_OPS)
        n = 0
        for split in self.splits:
            with open(split.path, "rb") as fh:
                read_ops.increment()
                fh.seek(split.start)
                pos = split.start
                if split.start > 0:
                    skipped = fh.readline()  # partial record owned by prev
                    pos += len(skipped)
                    bytes_read.increment(len(skipped))
                end = split.start + split.length
                # a line STARTING exactly at `end` belongs to this split
                # (the next split discards its first line since start > 0) —
                # LineRecordReader boundary semantics
                while pos <= end:
                    line = fh.readline()
                    if not line:
                        break
                    yield pos, line.rstrip(b"\r\n")
                    pos += len(line)
                    bytes_read.increment(len(line))   # ACTUAL bytes consumed
                    records.increment()
                    n += 1
                    if (n & 0x3FFF) == 0:
                        self.context.notify_progress()


class TextInput(LogicalInput):
    """Task-side root input: reads the splits delivered by the initializer
    (or directly from payload {"paths": [...]} with no initializer)."""

    def initialize(self) -> List[TezAPIEvent]:
        self._splits: List[FileSplit] = []
        self._has_split_event = False
        payload = self.context.user_payload.load() or {}
        if isinstance(payload, dict) and payload.get("static_splits"):
            self._splits = list(payload["static_splits"])
            self._has_split_event = True
        return []

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        for ev in events:
            if isinstance(ev, InputDataInformationEvent):
                self._splits.extend(ev.user_payload or [])
                self._has_split_event = True
                total = sum(s.length for s in ev.user_payload or [])
                self.context.counters.increment(
                    TaskCounter.INPUT_SPLIT_LENGTH_BYTES, total)

    def get_reader(self) -> Reader:
        import time
        deadline = time.time() + 60
        while not self._has_split_event:
            if time.time() > deadline:
                raise TimeoutError("no split event received")
            time.sleep(0.01)
            self.context.notify_progress()
        return _LineReader(self._splits, self.context)

    def close(self) -> List[TezAPIEvent]:
        return []
