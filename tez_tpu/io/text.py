"""Text root input: the stock line-record instance of the format SPI.

Reference parity: tez-mapreduce MRInput.java:87 (splits -> records) +
MRInputAMSplitGenerator.java:61 + TezSplitGrouper.java:43.  The generic
machinery (FileSplit, split computation/grouping, the format-driven input +
initializer) lives in tez_tpu.io.formats; this module keeps the historical
``tez_tpu.io.text:TextInput`` / ``TextSplitGenerator`` descriptor names as
thin text-format bindings (the format defaults to "text" when the payload
names none).
"""
from __future__ import annotations

from tez_tpu.io.formats import (FileSplit, MRInput,  # noqa: F401 — re-
                                MRSplitGenerator,    # exported compat names
                                _LineReader, compute_splits, group_splits)


class TextSplitGenerator(MRSplitGenerator):
    """AM-side initializer: payload {"paths": [...], "desired_splits": N or
    -1 (use vertex parallelism or one wave of slots)}."""


class TextInput(MRInput):
    """Task-side root input: reads the splits delivered by the initializer
    (or directly from payload {"static_splits": [...]})."""
