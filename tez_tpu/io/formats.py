"""Pluggable input formats: the InputFormat/RecordReader SPI.

Reference parity: tez-mapreduce MRInput.java:87 — MRInput runs ARBITRARY
mapred/mapreduce InputFormats behind one input class, with split metadata
delivered via events from the AM-side split generator
(MRInputAMSplitGenerator.java:61); MultiMRInput exposes one reader per
split instead of a fused stream.  Here the format is a small SPI —
``compute_splits`` (how files chop into ranges) + ``open`` (how a range
becomes records) — selected by registry shorthand or ``module:Class`` path
in the descriptor payload.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import dataclasses
import glob as globlib

from tez_tpu.api.events import InputDataInformationEvent, TezAPIEvent
from tez_tpu.api.initializer import (InputConfigureVertexTasksEvent,
                                     InputInitializer)
from tez_tpu.api.runtime import KeyValueReader, LogicalInput, Reader
from tez_tpu.common.counters import FileSystemCounter, TaskCounter


@dataclasses.dataclass(frozen=True)
class FileSplit:
    path: str
    start: int
    length: int


def compute_splits(paths: Sequence[str], desired_splits: int,
                   min_split_bytes: int = 64 * 1024) -> List[FileSplit]:
    """Byte-range splits over the input files (record alignment is each
    format's job: text aligns at read time, fixed-width realigns split
    boundaries — standard InputFormat semantics)."""
    files = []
    for p in paths:
        matches = sorted(globlib.glob(p)) if any(c in p for c in "*?[") \
            else [p]
        for m in matches:
            if os.path.isdir(m):
                files.extend(sorted(
                    os.path.join(m, f) for f in os.listdir(m)
                    if os.path.isfile(os.path.join(m, f))))
            else:
                files.append(m)
    total = sum(os.path.getsize(f) for f in files)
    if total == 0 or desired_splits <= 0:
        return [FileSplit(f, 0, os.path.getsize(f)) for f in files]
    target = max(min_split_bytes, total // desired_splits)
    splits: List[FileSplit] = []
    for f in files:
        size = os.path.getsize(f)
        pos = 0
        while pos < size:
            length = min(target, size - pos)
            # avoid tiny trailing splits (< half target merges into last)
            if size - (pos + length) < target // 2:
                length = size - pos
            splits.append(FileSplit(f, pos, length))
            pos += length
    return splits


def group_splits(splits: List[FileSplit], target_count: int
                 ) -> List[List[FileSplit]]:
    """TezSplitGrouper analog: coalesce splits to ~target_count groups
    (locality is moot on local FS, so greedy size-balanced grouping)."""
    if target_count <= 0 or len(splits) <= target_count:
        return [[s] for s in splits]
    groups: List[List[FileSplit]] = [[] for _ in range(target_count)]
    sizes = [0] * target_count
    for s in sorted(splits, key=lambda s: -s.length):
        i = sizes.index(min(sizes))
        groups[i].append(s)
        sizes[i] += s.length
    return [g for g in groups if g]


class _LineReader(KeyValueReader):
    """Yields (byte offset, line bytes) per record — TextInputFormat parity."""

    def __init__(self, splits: Sequence[FileSplit], context: Any):
        self.splits = splits
        self.context = context

    def iter_chunks(self, chunk_bytes: int = 8 << 20
                    ) -> Iterator[bytes]:
        """Vectorization-friendly reader: yields large line-aligned byte
        chunks covering exactly this reader's splits (same boundary
        semantics as line iteration: a split owns lines STARTING in
        (start, end]).  Batch-first processors (e.g. the vectorized
        tokenizer) consume these instead of per-record lines — the
        TPU-native answer to the reference's per-record hot loop."""
        bytes_read = self.context.counters.find_counter(
            FileSystemCounter.FILE_BYTES_READ)
        read_ops = self.context.counters.find_counter(
            FileSystemCounter.FILE_READ_OPS)
        for split in self.splits:
            with open(split.path, "rb") as fh:
                read_ops.increment()
                fh.seek(split.start)
                pos = split.start
                if split.start > 0:
                    skipped = fh.readline()  # partial record owned by prev
                    pos += len(skipped)
                    bytes_read.increment(len(skipped))
                end = split.start + split.length
                while pos <= end:
                    want = min(chunk_bytes, end - pos + 1)
                    chunk = fh.read(want)
                    if not chunk:
                        break
                    if not chunk.endswith(b"\n"):
                        # extend to the line boundary (the line STARTING at
                        # or before `end` belongs to this split in full)
                        tail = fh.readline()
                        chunk += tail
                    pos += len(chunk)
                    bytes_read.increment(len(chunk))
                    self.context.notify_progress()
                    yield chunk

    def __iter__(self) -> Iterator[Tuple[int, bytes]]:
        # counters update incrementally inside the loop (a consumer may stop
        # early, closing the generator — a post-loop epilogue would be
        # skipped entirely; and re-iteration must not double-count)
        records = self.context.counters.find_counter(
            TaskCounter.INPUT_RECORDS_PROCESSED)
        bytes_read = self.context.counters.find_counter(
            FileSystemCounter.FILE_BYTES_READ)
        read_ops = self.context.counters.find_counter(
            FileSystemCounter.FILE_READ_OPS)
        n = 0
        for split in self.splits:
            with open(split.path, "rb") as fh:
                read_ops.increment()
                fh.seek(split.start)
                pos = split.start
                if split.start > 0:
                    skipped = fh.readline()  # partial record owned by prev
                    pos += len(skipped)
                    bytes_read.increment(len(skipped))
                end = split.start + split.length
                # a line STARTING exactly at `end` belongs to this split
                # (the next split discards its first line since start > 0) —
                # LineRecordReader boundary semantics
                while pos <= end:
                    line = fh.readline()
                    if not line:
                        break
                    yield pos, line.rstrip(b"\r\n")
                    pos += len(line)
                    bytes_read.increment(len(line))  # ACTUAL bytes consumed
                    records.increment()
                    n += 1
                    if (n & 0x3FFF) == 0:
                        self.context.notify_progress()


class InputFormat:
    """SPI: how paths become splits and splits become (key, value) records.

    Implementations are instantiated per task/initializer with the
    descriptor's ``format_params`` dict."""

    def __init__(self, params: Optional[Dict[str, Any]] = None):
        self.params = params or {}

    def compute_splits(self, paths: Sequence[str], desired: int,
                       min_split_bytes: int = 64 * 1024) -> List[FileSplit]:
        return compute_splits(paths, desired, min_split_bytes)

    def open(self, splits: Sequence[FileSplit],
             context: Any) -> KeyValueReader:
        raise NotImplementedError


class TextFormat(InputFormat):
    """(byte offset, line) records — TextInputFormat parity."""

    def open(self, splits: Sequence[FileSplit],
             context: Any) -> KeyValueReader:
        return _LineReader(splits, context)


class _FixedWidthReader(KeyValueReader):
    def __init__(self, splits: Sequence[FileSplit], context: Any,
                 key_bytes: int, value_bytes: int):
        self.splits = splits
        self.context = context
        self.key_bytes = key_bytes
        self.value_bytes = value_bytes

    def __iter__(self) -> Iterator[Tuple[bytes, bytes]]:
        rec = self.key_bytes + self.value_bytes
        records = self.context.counters.find_counter(
            TaskCounter.INPUT_RECORDS_PROCESSED)
        bytes_read = self.context.counters.find_counter(
            FileSystemCounter.FILE_BYTES_READ)
        read_ops = self.context.counters.find_counter(
            FileSystemCounter.FILE_READ_OPS)
        n = 0
        for split in self.splits:
            with open(split.path, "rb") as fh:
                read_ops.increment()
                fh.seek(split.start)
                remaining = split.length
                # whole records per read; at least one even when a single
                # record exceeds the 8 MiB read granule
                granule = max(rec, (8 << 20) // rec * rec)
                while remaining >= rec:
                    chunk = fh.read(min(remaining, granule))
                    if not chunk:
                        break
                    bytes_read.increment(len(chunk))
                    remaining -= len(chunk)
                    for off in range(0, len(chunk) - rec + 1, rec):
                        yield (chunk[off:off + self.key_bytes],
                               chunk[off + self.key_bytes:off + rec])
                        records.increment()
                        n += 1
                        if (n & 0x3FFF) == 0:
                            self.context.notify_progress()


class FixedWidthKVFormat(InputFormat):
    """Binary records of ``key_bytes`` + ``value_bytes`` fixed-width bytes;
    splits are record-aligned so no record straddles a boundary (the
    second stock format VERDICT r1 item 9 asks for)."""

    def _widths(self) -> Tuple[int, int]:
        kb = int(self.params.get("key_bytes", 8))
        vb = int(self.params.get("value_bytes", 8))
        if kb <= 0 or vb < 0:
            raise ValueError(f"bad fixed-width record: key_bytes={kb}, "
                             f"value_bytes={vb}")
        return kb, vb

    def _rec(self) -> int:
        return sum(self._widths())

    def compute_splits(self, paths: Sequence[str], desired: int,
                       min_split_bytes: int = 64 * 1024) -> List[FileSplit]:
        rec = self._rec()
        raw = compute_splits(paths, desired, min_split_bytes)
        files: Dict[str, int] = {}
        out: List[FileSplit] = []
        for s in raw:
            if s.path not in files:
                files[s.path] = os.path.getsize(s.path)
            size = files[s.path]
            usable = size // rec * rec       # trailing partial record dropped
            start = (s.start + rec - 1) // rec * rec
            end = min(usable, (s.start + s.length + rec - 1) // rec * rec)
            if s.start + s.length >= size:
                end = usable                 # last split absorbs the tail
            if end > start:
                out.append(FileSplit(s.path, start, end - start))
        return out

    def open(self, splits: Sequence[FileSplit],
             context: Any) -> KeyValueReader:
        kb, vb = self._widths()   # validated even on the static_splits path
        return _FixedWidthReader(splits, context, kb, vb)


_REGISTRY = {
    "text": TextFormat,
    "fixed": FixedWidthKVFormat,
}


def resolve_format(name: str, params: Optional[Dict[str, Any]] = None
                   ) -> InputFormat:
    cls = _REGISTRY.get(name)
    if cls is None:
        from tez_tpu.common.payload import resolve_class
        cls = resolve_class(name)
    return cls(params)


class MRSplitGenerator(InputInitializer):
    """AM-side, format-driven split computation -> events + parallelism
    (MRInputAMSplitGenerator.java:61 analog).  Payload: {"paths": [...],
    "desired_splits": N or -1, "format": name-or-class, "format_params":
    {...}, "min_split_bytes": N}."""

    def initialize(self) -> List[Any]:
        payload = self.context.user_payload.load() or {}
        conf = getattr(self.context, "conf", None) or {}

        def knob(key: str, default: Any) -> Any:
            # payload overrides conf overrides default (the edge-payload
            # precedence rule)
            return payload.get(key, conf.get(key, default))

        fmt = resolve_format(payload.get("format", "text"),
                             payload.get("format_params"))
        desired = payload.get("desired_splits", -1)
        if desired <= 0:
            desired = self.context.num_tasks
        wave_path = desired <= 0   # neither payload nor parallelism set it
        if desired <= 0:
            # unbound parallelism: waves x available slots, with the group
            # count clamped so the average grouped-split size stays inside
            # [tez.grouping.min-size, tez.grouping.max-size]
            # (TezSplitGrouper.java:43 wave/size semantics)
            waves = float(knob("tez.grouping.split-waves", 1.7))
            desired = max(1, int(
                self.context.get_total_available_resource() * waves))
        min_split = payload.get("min_split_bytes", 64 * 1024)
        splits = fmt.compute_splits(payload.get("paths", []), desired,
                                    min_split)
        total_bytes = sum(s.length for s in splits)
        min_sz = int(knob("tez.grouping.min-size", 50 * 1024 * 1024))
        max_sz = int(knob("tez.grouping.max-size", 1024 ** 3))
        # size clamp applies ONLY on the wave path: an explicit
        # desired_splits (payload) or fixed vertex parallelism wins
        if wave_path and total_bytes > 0:
            cap = max(1, total_bytes // max(1, min_sz))     # avg >= min-size
            floor = -(-total_bytes // max(1, max_sz))       # avg <= max-size
            clamped = max(min(desired, cap), floor)
            if clamped > len(splits):
                # need finer splits than the wave count produced
                splits = fmt.compute_splits(payload.get("paths", []),
                                            clamped, min_split)
            desired = clamped
        groups = group_splits(splits, desired)
        if self.context.num_tasks > 0:
            # fixed vertex parallelism: every task needs exactly one split
            # event (possibly empty) or it would wait forever
            while len(groups) < self.context.num_tasks:
                groups.append([])
            if len(groups) > self.context.num_tasks:
                folded: List[List[FileSplit]] = [
                    [] for _ in range(self.context.num_tasks)]
                for i, g in enumerate(groups):
                    folded[i % self.context.num_tasks].extend(g)
                groups = folded
        events: List[Any] = [
            InputConfigureVertexTasksEvent(num_tasks=len(groups))]
        for i, group in enumerate(groups):
            events.append(InputDataInformationEvent(
                source_index=i, user_payload=group, target_index=i))
        return events


class MRInput(LogicalInput):
    """Format-driven root input (MRInput.java:87 analog): payload
    {"format": name-or-class, "format_params": {...}} with splits delivered
    by MRSplitGenerator events (or inline via "static_splits")."""

    def initialize(self) -> List[TezAPIEvent]:
        payload = self.context.user_payload.load() or {}
        if not isinstance(payload, dict):
            payload = {}
        self._format = resolve_format(payload.get("format", "text"),
                                      payload.get("format_params"))
        self._splits: List[FileSplit] = []
        self._has_split_event = False
        if payload.get("static_splits"):
            self._splits = list(payload["static_splits"])
            self._has_split_event = True
        return []

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        for ev in events:
            if isinstance(ev, InputDataInformationEvent):
                self._splits.extend(ev.user_payload or [])
                self._has_split_event = True
                total = sum(s.length for s in ev.user_payload or [])
                self.context.counters.increment(
                    TaskCounter.INPUT_SPLIT_LENGTH_BYTES, total)

    def _wait_splits(self) -> None:
        import time
        deadline = time.time() + 60
        while not self._has_split_event:
            if time.time() > deadline:
                raise TimeoutError("no split event received")
            time.sleep(0.01)
            self.context.notify_progress()

    def get_reader(self) -> Reader:
        self._wait_splits()
        return self._format.open(self._splits, self.context)

    def close(self) -> List[TezAPIEvent]:
        return []


class MultiMRInput(MRInput):
    """One reader PER split (reference: MultiMRInput.java) — consumers that
    need split boundaries (e.g. per-file joins, sorted-run inputs) iterate
    ``get_key_value_readers()`` instead of one fused stream."""

    def get_key_value_readers(self) -> List[KeyValueReader]:
        self._wait_splits()
        return [self._format.open([s], self.context) for s in self._splits]

    def get_reader(self) -> Reader:
        readers = self.get_key_value_readers()

        class _Chained(KeyValueReader):
            def __iter__(self):
                for r in readers:
                    yield from r

        return _Chained()
