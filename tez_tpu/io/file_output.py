"""Leaf output writing part files with a rename-on-commit committer.

Reference parity: tez-mapreduce MROutput.java:88 + MROutputCommitter (wraps
FileOutputCommitter: write to a temporary attempt dir, commit renames into
the final output dir).
"""
from __future__ import annotations

import os
import shutil
from typing import Any, List, Sequence

from tez_tpu.api.events import TezAPIEvent
from tez_tpu.api.initializer import OutputCommitter
from tez_tpu.api.runtime import KeyValueWriter, LogicalOutput, Writer
from tez_tpu.common import epoch as epoch_registry
from tez_tpu.common import faults
from tez_tpu.common.counters import FileSystemCounter, TaskCounter
from tez_tpu.common.epoch import EpochFencedError
from tez_tpu.ops.serde import get_serde

TMP_SUBDIR = "_temporary"
#: Publish journal inside the tmp tree: each part filename is appended (and
#: fsync'd) BEFORE its rename into the output dir, so abort after a partial
#: commit can un-publish exactly the files that made it out.
PUBLISH_MANIFEST = "_publish_manifest"


class _PartWriter(KeyValueWriter):
    def __init__(self, path: str, key_serde: Any, val_serde: Any,
                 context: Any, sep: bytes = b"\t"):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._fh = open(path, "wb")
        self.key_serde = key_serde
        self.val_serde = val_serde
        self.context = context
        self.sep = sep
        # hot path: cache the counter objects once (group+name lookup per
        # record is pure dictionary churn)
        self._records_ctr = context.counters.find_counter(
            TaskCounter.OUTPUT_RECORDS)
        self._bytes_ctr = context.counters.find_counter(
            FileSystemCounter.FILE_BYTES_WRITTEN)

    def write(self, key: Any, value: Any) -> None:
        k = self.key_serde.to_bytes(key)
        v = self.val_serde.to_bytes(value)
        self._fh.write(k + self.sep + v + b"\n")
        self._records_ctr.increment()
        self._bytes_ctr.increment(len(k) + len(self.sep) + len(v) + 1)

    def write_raw(self, data: bytes, n_records: int) -> None:
        """Pre-formatted record bytes (separators/newlines included) from a
        vectorized consumer — one write call for the whole block."""
        self._fh.write(data)
        self._records_ctr.increment(n_records)
        self._bytes_ctr.increment(len(data))

    def close(self) -> None:
        self._fh.close()
        self.context.counters.increment(FileSystemCounter.FILE_WRITE_OPS)


class FileOutput(LogicalOutput):
    """Payload: {"path": output dir, "key_serde": .., "value_serde": ..,
    "separator": "\\t"}.  Writes part-{task:05d} under a temporary attempt
    dir; the committer publishes them."""

    def initialize(self) -> List[TezAPIEvent]:
        payload = self.context.user_payload.load() or {}
        self.out_dir = payload["path"]
        self.key_serde = get_serde(payload.get("key_serde", "text"))
        self.val_serde = get_serde(payload.get("value_serde", "text"))
        self.sep = payload.get("separator", "\t").encode()
        attempt = self.context.task_attempt_id
        self.tmp_path = os.path.join(
            self.out_dir, TMP_SUBDIR, str(attempt),
            f"part-{self.context.task_index:05d}")
        self._writer: _PartWriter | None = None
        return []

    def get_writer(self) -> Writer:
        if self._writer is None:
            self._writer = _PartWriter(self.tmp_path, self.key_serde,
                                       self.val_serde, self.context, self.sep)
        return self._writer

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        pass

    def close(self) -> List[TezAPIEvent]:
        if self._writer is not None:
            self._writer.close()
            # task-level commit: AM arbitration picks exactly one live
            # attempt per task (speculation / retry safety); losers leave
            # their file in the attempt dir, cleaned by the committer
            if self.context.can_commit():
                committed = os.path.join(self.out_dir, TMP_SUBDIR,
                                         "committed",
                                         os.path.basename(self.tmp_path))
                os.makedirs(os.path.dirname(committed), exist_ok=True)
                os.replace(self.tmp_path, committed)
        return []


class FileOutputCommitter(OutputCommitter):
    """Publishes committed part files to the output dir.

    Idempotent and resumable: re-entering commit_output after a crash (the
    recovery roll-forward path) publishes only what is still staged, and a
    crash at any point leaves a state this committer can finish or that
    abort_output can fully roll back.  Every publish is (1) preceded by an
    epoch fence check — a committer owned by a superseded AM incarnation
    must not touch the output — and (2) journaled to the publish manifest
    before the rename, so abort can un-publish a partial commit."""

    def initialize(self) -> None:
        payload = self.context.user_payload.load() or {}
        self.out_dir = payload["path"]

    def setup_output(self) -> None:
        os.makedirs(os.path.join(self.out_dir, TMP_SUBDIR), exist_ok=True)

    def _fence(self, detail: str) -> None:
        app_id = str(getattr(self.context, "app_id", "") or "")
        my_epoch = int(getattr(self.context, "am_epoch", 0) or 0)
        if my_epoch > 0 and epoch_registry.is_stale(app_id, my_epoch):
            faults.fire("fence.stale_epoch", detail=f"commit.publish {detail}")
            raise EpochFencedError(
                f"committer epoch {my_epoch} superseded by "
                f"{epoch_registry.current(app_id)}; refusing to publish "
                f"{detail}")

    def commit_output(self) -> None:
        tmp = os.path.join(self.out_dir, TMP_SUBDIR)
        success = os.path.join(self.out_dir, "_SUCCESS")
        if not os.path.isdir(tmp):
            # tmp tree already gone: a prior incarnation finished publishing
            # and was interrupted at (or after) the _SUCCESS marker — roll
            # forward by (re)writing the marker, nothing else to do
            self._fence("_SUCCESS")
            with open(success, "w"):
                pass
            return
        committed = os.path.join(tmp, "committed")
        if os.path.isdir(committed):
            with open(os.path.join(tmp, PUBLISH_MANIFEST), "a") as mf:
                for f in sorted(os.listdir(committed)):
                    # fault point FIRST (delay mode parks the commit right
                    # here), so a zombie held mid-commit re-checks the fence
                    # when it wakes
                    faults.fire("commit.publish", detail=f)
                    self._fence(f)
                    mf.write(f + "\n")
                    mf.flush()
                    os.fsync(mf.fileno())
                    os.replace(os.path.join(committed, f),
                               os.path.join(self.out_dir, f))
        self._fence("_SUCCESS")
        shutil.rmtree(tmp, ignore_errors=True)
        with open(success, "w"):
            pass

    def abort_output(self, final_state: str) -> None:
        """Roll back a (possibly partial) commit: un-publish every file the
        manifest records, then remove the whole tmp tree.  Idempotent — a
        re-entrant abort (recovery re-runs it after a crash mid-abort) finds
        progressively less to do.  A fully-committed output (tmp gone) is
        left intact: there is nothing staged left to roll back."""
        tmp = os.path.join(self.out_dir, TMP_SUBDIR)
        if not os.path.isdir(tmp):
            return
        manifest = os.path.join(tmp, PUBLISH_MANIFEST)
        if os.path.exists(manifest):
            with open(manifest) as fh:
                for line in fh:
                    name = line.strip()
                    if not name:
                        continue
                    try:
                        os.remove(os.path.join(self.out_dir, name))
                    except FileNotFoundError:
                        pass   # crash between manifest append and rename
        try:
            os.remove(os.path.join(self.out_dir, "_SUCCESS"))
        except FileNotFoundError:
            pass   # a partial commit never reached the marker
        shutil.rmtree(tmp, ignore_errors=True)
