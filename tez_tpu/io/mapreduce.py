"""MapReduce compatibility layer: run map/reduce-style user code on DAGs.

Reference parity: tez-mapreduce (MapProcessor.java:403 / ReduceProcessor.java
:369 running real Mapper/Reducer code on Tez, plus the client shim that
translates MR jobs into 2-vertex DAGs).  User functions are plain Python:

    def mapper(key, value) -> iterable[(k, v)]
    def reducer(key, values) -> iterable[(k, v)]

`simple_mr_dag` builds the canonical map->reduce DAG over text input /
file output with a sorted scatter-gather edge in between.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from tez_tpu.api.runtime import (KeyValueReader, KeyValuesReader,
                                 LogicalInput, LogicalOutput)
from tez_tpu.common.payload import (InputDescriptor,
                                    InputInitializerDescriptor,
                                    OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, DataSourceDescriptor,
                             Edge, Vertex)
from tez_tpu.library.conf import OrderedPartitionedKVEdgeConfig
from tez_tpu.library.processors import SimpleProcessor

MapFn = Callable[[Any, Any], Iterable[Tuple[Any, Any]]]
ReduceFn = Callable[[Any, Iterable[Any]], Iterable[Tuple[Any, Any]]]


def _resolve_fn(payload: dict, key: str) -> Callable:
    from tez_tpu.common.payload import resolve_class
    target = payload[key]
    if callable(target):
        return target
    return resolve_class(target)


class MapProcessor(SimpleProcessor):
    """Drives the user map function over every (key, value) of every input;
    emits to every non-leaf output (reference: MapProcessor.java)."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        payload = self.context.user_payload.load() or {}
        mapper: MapFn = _resolve_fn(payload, "map_fn")
        writers = [o.get_writer() for o in outputs.values()]
        for inp in inputs.values():
            reader = inp.get_reader()
            if isinstance(reader, KeyValuesReader):
                items = ((k, v) for k, vs in reader for v in vs)
            else:
                items = iter(reader)
            for k, v in items:
                for ok, ov in mapper(k, v):
                    for w in writers:
                        w.write(ok, ov)


class ReduceProcessor(SimpleProcessor):
    """Drives the user reduce function over grouped input (reference:
    ReduceProcessor.java)."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        payload = self.context.user_payload.load() or {}
        reducer: ReduceFn = _resolve_fn(payload, "reduce_fn")
        writers = [o.get_writer() for o in outputs.values()]
        for inp in inputs.values():
            reader = inp.get_reader()
            if isinstance(reader, KeyValuesReader):
                groups = iter(reader)
            else:  # unordered input: group in memory
                acc: Dict[Any, list] = {}
                for k, v in reader:
                    acc.setdefault(k, []).append(v)
                groups = iter(sorted(acc.items()))
            from tez_tpu.common.counters import TaskCounter
            out_records = self.context.counters.find_counter(
                TaskCounter.REDUCE_OUTPUT_RECORDS)
            for k, vs in groups:
                for ok, ov in reducer(k, vs):
                    out_records.increment()
                    for w in writers:
                        w.write(ok, ov)


def simple_mr_dag(name: str, input_paths, output_path: str,
                  map_fn: str, reduce_fn: str,
                  num_mappers: int = -1, num_reducers: int = 2,
                  key_serde: str = "bytes", value_serde: str = "bytes",
                  intermediate_serdes: Tuple[str, str] = ("bytes", "bytes"),
                  combiner: str = "",
                  input_format: str = "text",
                  format_params: Optional[dict] = None,
                  multi_input: bool = False) -> DAG:
    """The YARNRunner-analog translation: one map vertex over format-driven
    splits (io/formats.py SPI — "text", "fixed", or a module:Class path;
    reference: MRInput.java:87 arbitrary InputFormats), one reduce vertex
    over a sorted shuffle, file-committed output.  multi_input swaps in the
    MultiMRInput analog (one reader per split).  map_fn/reduce_fn are
    "module:callable" strings (must be importable in runner processes)."""
    return mr_chain_dag(name, input_paths, output_path, map_fn,
                        reduce_fns=[reduce_fn], num_mappers=num_mappers,
                        num_reducers=num_reducers, key_serde=key_serde,
                        value_serde=value_serde,
                        stage_serdes=[intermediate_serdes],
                        combiner=combiner, input_format=input_format,
                        format_params=format_params,
                        multi_input=multi_input)


def _map_vertex(map_fn: str, input_paths, num_mappers: int,
                input_format: str, format_params: Optional[dict],
                multi_input: bool) -> Vertex:
    """The map vertex + its MRInput data source (shared by the conf
    translation and the programmatic builders)."""
    input_cls = "tez_tpu.io.formats:MultiMRInput" if multi_input \
        else "tez_tpu.io.formats:MRInput"
    mapper = Vertex.create("map", ProcessorDescriptor.create(
        MapProcessor, payload={"map_fn": map_fn}), num_mappers)
    mapper.add_data_source("input", DataSourceDescriptor.create(
        InputDescriptor.create(input_cls,
                               payload={"format": input_format,
                                        "format_params": format_params}),
        InputInitializerDescriptor.create(
            "tez_tpu.io.formats:MRSplitGenerator",
            payload={"paths": list(input_paths),
                     "desired_splits": num_mappers,
                     "format": input_format,
                     "format_params": format_params})))
    return mapper


def _file_sink(output_path: str, key_serde: str,
               value_serde: str) -> DataSinkDescriptor:
    return DataSinkDescriptor.create(
        OutputDescriptor.create(
            "tez_tpu.io.file_output:FileOutput",
            payload={"path": output_path, "key_serde": key_serde,
                     "value_serde": value_serde}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": output_path}))


#: Hadoop Writable / format class names -> native serde / format names.
#: Native names pass through, so a conf can mix both vocabularies.
_WRITABLE_TO_SERDE = {
    "org.apache.hadoop.io.Text": "text",
    "org.apache.hadoop.io.LongWritable": "long",
    "org.apache.hadoop.io.IntWritable": "long",
    "org.apache.hadoop.io.BytesWritable": "bytes",
    "org.apache.hadoop.io.NullWritable": "bytes",
}
_FORMAT_TO_NATIVE = {
    "org.apache.hadoop.mapreduce.lib.input.TextInputFormat": "text",
    "org.apache.hadoop.mapred.TextInputFormat": "text",
    "org.apache.hadoop.mapreduce.lib.input.KeyValueTextInputFormat": "text",
}


def _job_conf(conf: dict, new_key: str, old_key: str, default=None):
    """mapreduce.* wins over the legacy mapred.* alias (YARNRunner's
    dual-vocabulary conf handling)."""
    if new_key in conf:
        return conf[new_key]
    if old_key in conf:
        return conf[old_key]
    return default


def _serde_for(cls_name: Optional[str], default: str = "bytes") -> str:
    if not cls_name:
        return default
    return _WRITABLE_TO_SERDE.get(cls_name, cls_name)


def mr_job_to_dag(job_conf: dict) -> DAG:
    """Translate an MR JOB CONF into a DAG — the YARNRunner seam
    (reference: tez-mapreduce/.../client/YARNRunner.java translating a
    submitted MR job's Configuration into a 2-vertex Tez DAG;
    MRRuntimeProtos.proto carries the conf to the runtime).

    Honored keys (mapreduce.* with legacy mapred.* aliases):
      job name        mapreduce.job.name            / mapred.job.name
      mapper          mapreduce.job.map.class       / mapred.mapper.class
      reducer         mapreduce.job.reduce.class    / mapred.reducer.class
      combiner        mapreduce.job.combine.class   / mapred.combiner.class
      map count hint  mapreduce.job.maps            / mapred.map.tasks
      reduce count    mapreduce.job.reduces         / mapred.reduce.tasks
      input paths     mapreduce.input.fileinputformat.inputdir
                                                    / mapred.input.dir
      output path     mapreduce.output.fileoutputformat.outputdir
                                                    / mapred.output.dir
      input format    mapreduce.job.inputformat.class
                                                    / mapred.input.format.class
      map out K/V     mapreduce.map.output.key.class / .value.class
      job out K/V     mapreduce.job.output.key.class / .value.class

    Mapper/reducer/combiner classes are "module:callable" paths (the
    Python analog of class names); Hadoop Writable and TextInputFormat
    class names map onto native serdes/formats, and native names pass
    through.  mapreduce.job.reduces=0 builds the map-only DAG (mapper
    commits straight to the output), exactly like the reference."""
    g = lambda nk, ok, d=None: _job_conf(job_conf, nk, ok, d)  # noqa: E731
    name = g("mapreduce.job.name", "mapred.job.name", "mr-job")
    map_fn = g("mapreduce.job.map.class", "mapred.mapper.class")
    if not map_fn:
        raise ValueError(
            "job conf has no mapper (mapreduce.job.map.class)")
    reduce_fn = g("mapreduce.job.reduce.class", "mapred.reducer.class")
    combiner = g("mapreduce.job.combine.class", "mapred.combiner.class", "")
    num_maps = int(g("mapreduce.job.maps", "mapred.map.tasks", -1))
    num_reduces = int(g("mapreduce.job.reduces", "mapred.reduce.tasks", 1))
    inputs = g("mapreduce.input.fileinputformat.inputdir",
               "mapred.input.dir")
    output = g("mapreduce.output.fileoutputformat.outputdir",
               "mapred.output.dir")
    if not inputs or not output:
        raise ValueError("job conf needs input dir(s) and an output dir")
    input_paths = [p.strip() for p in str(inputs).split(",") if p.strip()]
    in_fmt = g("mapreduce.job.inputformat.class",
               "mapred.input.format.class", "text")
    in_fmt = _FORMAT_TO_NATIVE.get(in_fmt, in_fmt)
    out_k = _serde_for(g("mapreduce.job.output.key.class",
                         "mapred.output.key.class"))
    out_v = _serde_for(g("mapreduce.job.output.value.class",
                         "mapred.output.value.class"))
    # Hadoop semantics: map-output classes DEFAULT to the job output
    # classes when unset (JobConf.getMapOutputKeyClass)
    mid_k = _serde_for(g("mapreduce.map.output.key.class",
                         "mapred.mapoutput.key.class"), default=out_k)
    mid_v = _serde_for(g("mapreduce.map.output.value.class",
                         "mapred.mapoutput.value.class"), default=out_v)

    if num_reduces <= 0:
        # map-only job: the mapper commits straight to the sink
        mapper = _map_vertex(map_fn, input_paths, num_maps, in_fmt, None,
                             multi_input=False)
        mapper.add_data_sink("output", _file_sink(output, out_k, out_v))
        return DAG.create(name).add_vertex(mapper)

    if not reduce_fn:
        raise ValueError(
            f"job conf sets {num_reduces} reduces but no reducer class")
    return simple_mr_dag(
        name, input_paths, output, map_fn, reduce_fn,
        num_mappers=num_maps, num_reducers=num_reduces,
        key_serde=out_k, value_serde=out_v,
        intermediate_serdes=(mid_k, mid_v),
        combiner=combiner, input_format=in_fmt)


def mr_chain_dag(name: str, input_paths, output_path: str,
                 map_fn: str, reduce_fns, num_mappers: int = -1,
                 num_reducers=2,
                 key_serde: str = "bytes", value_serde: str = "bytes",
                 stage_serdes=None,
                 combiner: str = "",
                 input_format: str = "text",
                 format_params: Optional[dict] = None,
                 multi_input: bool = False) -> DAG:
    """Chained-job translation (MRR): one map vertex plus N reduce stages —
    map -> r1 -> ... -> rN — each stage joined by its own sorted
    scatter-gather edge, the last stage file-committed.

    Reference role: the MR client shim translating a SEQUENCE of dependent
    MR jobs into one DAG (YARNRunner-style; the canonical MRR workloads are
    tez-tests TestOrderedWordCount.java / MRRSleepJob.java).

    reduce_fns: list of "module:callable" strings, one per stage.
    num_reducers: int (same for all stages) or list per stage.
    stage_serdes: per-EDGE (key, value) serde names, len(reduce_fns)
    entries; defaults to ("bytes", "bytes") everywhere.
    """
    if not reduce_fns:
        raise ValueError("mr_chain_dag needs at least one reduce stage")
    n_stages = len(reduce_fns)
    if isinstance(num_reducers, int):
        num_reducers = [num_reducers] * n_stages
    if len(num_reducers) != n_stages:
        raise ValueError(f"num_reducers: want {n_stages} entries")
    stage_serdes = stage_serdes or [("bytes", "bytes")] * n_stages
    if len(stage_serdes) != n_stages:
        raise ValueError(f"stage_serdes: want {n_stages} entries")

    mapper = _map_vertex(map_fn, input_paths, num_mappers, input_format,
                         format_params, multi_input)
    dag = DAG.create(name).add_vertex(mapper)
    upstream = mapper
    for i, (fn, par, serdes) in enumerate(
            zip(reduce_fns, num_reducers, stage_serdes)):
        last = i == n_stages - 1
        reducer = Vertex.create(
            f"reduce{i + 1}" if n_stages > 1 else "reduce",
            ProcessorDescriptor.create(ReduceProcessor,
                                       payload={"reduce_fn": fn}), par)
        if last:
            reducer.add_data_sink("output", _file_sink(
                output_path, key_serde, value_serde))
        builder = OrderedPartitionedKVEdgeConfig.new_builder(*serdes)
        if combiner and i == 0:
            builder.set_combiner(combiner)   # map-side combine only
        dag.add_vertex(reducer)
        dag.add_edge(Edge.create(
            upstream, reducer,
            builder.build().create_default_edge_property()))
        upstream = reducer
    return dag
