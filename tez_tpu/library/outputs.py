"""Stock outputs: the sorted-shuffle producer side.

Reference parity: tez-runtime-library/.../library/output/
OrderedPartitionedKVOutput.java (sorter selection :151, getWriter :168,
close :189 -> DME events via ShuffleUtils.generateEventOnSpill) — the sorter
behind it is the TPU DeviceSorter instead of PipelinedSorter.
"""
from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional, Sequence

from tez_tpu.api.events import (CompositeDataMovementEvent, ShufflePayload,
                                TezAPIEvent, VertexManagerEvent,
                                pack_empty_partitions)
from tez_tpu.api.runtime import KeyValuesWriter, LogicalOutput, Writer
from tez_tpu.common.counters import TaskCounter
from tez_tpu.ops.runformat import Run
from tez_tpu.ops.serde import get_serde
from tez_tpu.ops.sorter import DeviceSorter, sum_long_combiner
from tez_tpu.shuffle.service import local_shuffle_service

log = logging.getLogger(__name__)

_COMBINERS = {"sum_long": sum_long_combiner}


from tez_tpu.library.util import conf_get as _conf_get  # noqa: E402


def output_path_component(context: Any) -> str:
    # leading DAG id segment enables per-DAG deletion tracking (reference:
    # DeletionTracker / DagDeleteRunnable cleanup of finished DAGs' shuffle
    # data)
    return f"{context.task_attempt_id.dag_id}/{context.task_attempt_id}/" \
           f"{context.destination_vertex_name}"


class _SorterWriter(KeyValuesWriter):
    def __init__(self, sorter: DeviceSorter, key_serde: Any, val_serde: Any,
                 context: Any, partition_fn: Any = None,
                 num_partitions: int = 1):
        self.sorter = sorter
        self.key_serde = key_serde
        self.val_serde = val_serde
        self.context = context
        self.partition_fn = partition_fn
        self.num_partitions = num_partitions
        self._n = 0
        # resolved once: find_counter locks the registry per call
        self._out_bytes_ctr = context.counters.find_counter(
            TaskCounter.OUTPUT_BYTES)

    def write(self, key: Any, value: Any) -> None:
        # a custom Partitioner sees the LOGICAL key/value (pre-serde),
        # matching the reference Partitioner.getPartition contract
        partition = None
        if self.partition_fn is not None:
            partition = int(self.partition_fn(key, value,
                                              self.num_partitions))
        k = self.key_serde.to_bytes(key)
        v = self.val_serde.to_bytes(value)
        self.sorter.write(k, v, partition=partition)
        self._out_bytes_ctr.increment(len(k) + len(v))
        self._n += 1
        if (self._n & 0x3FFF) == 0:
            self.context.notify_progress()   # liveness + kill check

    @property
    def supports_batch(self) -> bool:
        """True when write_batch() will be accepted — batch-first consumers
        probe this BEFORE consuming their reader, so an unsupported config
        (custom Partitioner) falls back to write() instead of failing the
        task mid-stream."""
        return self.partition_fn is None

    def write_batch(self, batch: Any) -> None:
        """Batch-first write path: a KVBatch of PRE-SERIALIZED records goes
        straight to the sorter (no per-record Python).  Only valid with the
        stock hash partitioner — a custom Partitioner sees logical records
        and must use write()."""
        if self.partition_fn is not None:
            raise ValueError("write_batch requires the stock hash "
                             "partitioner (custom Partitioner sees logical "
                             "records)")
        self.sorter.write_batch(batch)
        self.context.counters.increment(TaskCounter.OUTPUT_BYTES,
                                        batch.nbytes)
        self.context.notify_progress()


class OrderedPartitionedKVOutput(LogicalOutput):
    """Sorted, partitioned output feeding OrderedGroupedKVInput."""

    def initialize(self) -> List[TezAPIEvent]:
        ctx = self.context
        sort_mb = int(_conf_get(ctx, "tez.runtime.io.sort.mb", 256))
        self._pipelined = bool(_conf_get(
            ctx, "tez.runtime.pipelined-shuffle.enabled", False))
        # push-based shuffle rides the pipelined spill stream (one eager
        # push per finished spill), so enabling push implies pipelined
        self._push_enabled = bool(_conf_get(
            ctx, "tez.runtime.shuffle.push.enabled", False))
        self._pipelined = self._pipelined or self._push_enabled
        key_width = int(_conf_get(ctx, "tez.runtime.tpu.key.width.bytes", 16))
        combiner_name = _conf_get(ctx, "tez.runtime.combiner.class", "")
        spill_dir = _conf_get(ctx, "tez.runtime.tpu.host.spill.dir", "") or \
            os.path.join(ctx.work_dirs[0], "spill")
        self.key_serde = get_serde(_conf_get(ctx, "tez.runtime.key.class",
                                             "bytes"))
        self.val_serde = get_serde(_conf_get(ctx, "tez.runtime.value.class",
                                             "bytes"))
        engine = _conf_get(ctx, "tez.runtime.sorter.class", "auto")
        merge_factor = int(_conf_get(ctx, "tez.runtime.io.sort.factor", 64))
        sort_threads = int(_conf_get(ctx, "tez.runtime.sort.threads", 0))
        partitioner_cls = _conf_get(ctx, "tez.runtime.partitioner.class",
                                    "tez_tpu.library.partitioners:"
                                    "HashPartitioner")
        self.partition_fn = None
        if partitioner_cls != ("tez_tpu.library.partitioners:"
                               "HashPartitioner"):
            from tez_tpu.common.payload import resolve_class
            self.partition_fn = resolve_class(partitioner_cls)().get_partition
        from tez_tpu.library.comparators import load_comparator
        spill_codec = None
        if _conf_get(ctx, "tez.runtime.compress", False):
            spill_codec = _conf_get(ctx, "tez.runtime.compress.codec", "zlib")
            from tez_tpu.ops.runformat import resolve_codec
            resolve_codec(spill_codec)   # loud error on unknown/unavailable
            # codecs at initialize() — silently-off compression is worse
        self.sorter = DeviceSorter(
            num_partitions=self.num_physical_outputs,
            key_width=key_width,
            span_budget_bytes=sort_mb << 20,
            spill_dir=spill_dir,
            counters=ctx.counters,
            combiner=_COMBINERS.get(combiner_name),
            engine=engine,
            sort_threads=sort_threads,
            merge_factor=merge_factor,
            key_normalizer=load_comparator(ctx),
            spill_codec=spill_codec,
            resident_keys=bool(_conf_get(
                ctx, "tez.runtime.tpu.resident.keys", True)),
            device_min_records=int(_conf_get(
                ctx, "tez.runtime.tpu.device.sort.min.records", 1 << 16)),
            engine_min_bytes=int(_conf_get(
                ctx, "tez.runtime.sort.engine.min-bytes", 1 << 20)),
            # async double-buffered device plane; DeviceSorter keeps it off
            # unless the engine resolves to 'device'.  Spill / pipelined-
            # shuffle emission hooks the completion callback (on_spill runs
            # from the pipeline's readback workers, out of order but with
            # correct spill ids) instead of blocking the collector.
            pipeline_depth=int(_conf_get(
                ctx, "tez.runtime.sort.pipeline.depth", 2)),
            pipeline_coalesce_records=int(_conf_get(
                ctx, "tez.runtime.sort.pipeline.coalesce.records", -1)),
            # failure containment for the async plane: watchdog deadlines,
            # host-engine failover breaker, OOM split floor
            watchdog_dispatch_ms=float(_conf_get(
                ctx, "tez.runtime.device.watchdog.dispatch-ms", 60_000)),
            watchdog_readback_ms=float(_conf_get(
                ctx, "tez.runtime.device.watchdog.readback-ms", 60_000)),
            breaker_failures=int(_conf_get(
                ctx, "tez.runtime.device.breaker.failures", 3)),
            breaker_cooldown_ms=float(_conf_get(
                ctx, "tez.runtime.device.breaker.cooldown-ms", 5_000)),
            split_min_bytes=int(_conf_get(
                ctx, "tez.runtime.device.split.min-bytes", 1 << 20)),
        )
        ctx.request_initial_memory(sort_mb << 20, None,
                           component_type="PARTITIONED_SORTED_OUTPUT")
        self._spills_sent = 0
        if self._pipelined:
            self.sorter.on_spill = self._ship_spill
        self.service = local_shuffle_service()
        self.host = ctx.get_service_provider_metadata("shuffle") or \
            {"host": "local", "port": 0}
        # tiered buffer store: runners create their own process store from
        # the task conf (in-process mode finds the AM's); outputs publish
        # with a lineage tag so a later identical DAG can reuse them
        from tez_tpu.store import ensure_store
        merged: Dict[str, Any] = dict(ctx.conf)
        payload = ctx.user_payload.load()
        if isinstance(payload, dict):
            merged.update(payload)
        ensure_store(merged)
        self._lineage = ""
        self._reused = False
        self._reuse_ready = False
        if not self._pipelined and bool(_conf_get(
                ctx, "tez.runtime.store.lineage.reuse", True)):
            from tez_tpu.store.lineage import task_lineage
            self._lineage = task_lineage(
                getattr(ctx, "lineage", ""), ctx.task_index,
                ctx.destination_vertex_name)
        self._pusher = None
        if self._push_enabled:
            from tez_tpu.shuffle.push import SpillPusher
            self._pusher = SpillPusher(
                self.service,
                threads=int(_conf_get(
                    ctx, "tez.runtime.shuffle.push.threads", 2)),
                retries=int(_conf_get(
                    ctx, "tez.runtime.shuffle.push.retries", 3)),
                inflight_limit_bytes=int(float(_conf_get(
                    ctx, "tez.runtime.shuffle.push.inflight-limit-mb",
                    64)) * (1 << 20)),
                counters=ctx.counters,
                epoch=getattr(ctx, "am_epoch", 0),
                app_id=getattr(ctx, "app_id", ""),
                tenant=getattr(ctx, "tenant", ""),
                replicas=int(_conf_get(
                    ctx, "tez.runtime.shuffle.push.replicas", 1)),
                window_id=getattr(ctx, "window_id", 0),
                stream=getattr(ctx, "stream", ""))
        store = self.service.buffer_store()
        if self._lineage and store is not None:
            # a non-pipelined output seals exactly one run (spill -1);
            # anything else means a partial/incompatible seal — recompute
            self._reuse_ready = store.lineage_spills(
                self._lineage, app_id=getattr(ctx, "app_id", "")) == [-1]
        return []

    # -- cross-DAG output reuse (session mode) -------------------------------
    def reuse_available(self) -> bool:
        """True when the store holds this task's sealed output from an
        identical earlier DAG — the runner may then skip the processor and
        publish_reused() instead of recomputing."""
        return self._reuse_ready

    def publish_reused(self) -> List[TezAPIEvent]:
        """Alias the sealed lineage run under this attempt's path (zero
        copy) and emit the same DME/VM events a fresh sort would."""
        store = self.service.buffer_store()
        ctx = self.context
        path = output_path_component(ctx)
        store.republish_lineage(self._lineage, path,
                                epoch=getattr(ctx, "am_epoch", 0),
                                app_id=getattr(ctx, "app_id", ""),
                                counters=ctx.counters,
                                window_id=getattr(ctx, "window_id", 0),
                                stream=getattr(ctx, "stream", ""))
        run = store.get(path, -1)
        ctx.counters.increment(TaskCounter.OUTPUT_BYTES_PHYSICAL, run.nbytes)
        ctx.counters.find_counter("ShuffleStore",
                                  "store.reuse.outputs").increment(1)
        self._reused = True
        return self._events_for_run(run, -1, True)

    def get_writer(self) -> Writer:
        return _SorterWriter(self.sorter, self.key_serde, self.val_serde,
                             self.context, partition_fn=self.partition_fn,
                             num_partitions=self.num_physical_outputs)

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        pass

    # -- event generation (ShuffleUtils.generateEventOnSpill analog) ---------
    def _events_for_run(self, run: Run, spill_id: int,
                        last: bool) -> List[TezAPIEvent]:
        payload = ShufflePayload(
            host=self.host["host"], port=self.host["port"],
            path_component=output_path_component(self.context),
            empty_partitions=pack_empty_partitions(
                run.empty_partition_flags()),
            spill_id=spill_id if self._pipelined else -1,
            last_event=last)
        from tez_tpu.common import config as C
        total = run.nbytes
        vm_payload: Dict[str, Any] = {"output_size": total}
        if _conf_get(self.context, C.REPORT_PARTITION_STATS.name,
                     C.REPORT_PARTITION_STATS.default):
            # per-partition sizes feed auto-parallelism / fair-shuffle;
            # deployments with huge partition counts can turn the detail
            # off and keep only the total (reference knob)
            vm_payload["partition_sizes"] = [
                run.partition_nbytes(p) for p in range(run.num_partitions)]
        return [
            CompositeDataMovementEvent(0, run.num_partitions, payload),
            VertexManagerEvent(
                target_vertex_name=self.context.destination_vertex_name,
                user_payload=vm_payload),
        ]

    def _ship_spill(self, run: Run, spill_id: int) -> None:
        # spill-scale pipelined spans go to disk as partition-indexed files
        # and register disk-backed: RAM stays bounded, same-host consumers
        # merge disk-direct off the span file, and there is NO producer
        # final merge at all (the pipelined point, reference:
        # tez.runtime.pipelined-shuffle.enabled -> one event per spill)
        sorter = self.sorter
        ctr = self.context.counters
        push = self._pusher is not None
        # _store_run convention: every shipped span counts as spilled
        ctr.increment(TaskCounter.SPILLED_RECORDS, run.batch.num_records)
        if sorter.spill_dir is not None and run.nbytes >= (1 << 20) and \
                not self.service.has_store() and \
                not (push and self.service.buffer_store() is not None):
            # (with push + a buffer store, the store's watermark demotion
            # is the bounded disk path and admission is the backpressure —
            # a pspill here would re-serialize every spill for nothing)
            # (with a write-through store attached the store's own file IS
            # the disk copy — writing a pspill too would double the I/O)
            import uuid as _uuid
            from tez_tpu.ops.runformat import (FileRun,
                                               save_run_partitioned)
            path = os.path.join(sorter.spill_dir,
                                f"pspill_{_uuid.uuid4().hex}.prun")
            save_run_partitioned(run, path, codec=sorter.spill_codec)
            written = os.path.getsize(path)
            ctr.increment(TaskCounter.ADDITIONAL_SPILLS_BYTES_WRITTEN,
                          written)
            ctr.increment(TaskCounter.ADDITIONAL_SPILL_COUNT)
            ctr.increment(TaskCounter.HOST_SPILL_BYTES, written)
            run = FileRun(path)
        path = output_path_component(self.context)
        # push mode: the SYNCHRONOUS bare-registry register below is the
        # pull backstop (events never race a missing key; a dead pusher
        # never loses data) — the async push then aliases the same run
        # into the reducer-side store, zero copy
        self.service.register(path, spill_id,
                              run, epoch=getattr(self.context, "am_epoch", 0),
                              app_id=getattr(self.context, "app_id", ""),
                              lineage=self._lineage,
                              tenant=getattr(self.context, "tenant", ""),
                              counters=self.context.counters,
                              use_store=not push,
                              window_id=getattr(self.context,
                                                "window_id", 0),
                              stream=getattr(self.context, "stream", ""))
        # last=False; close() sends the final marker
        self.context.send_events(self._events_for_run(run, spill_id, False))
        self._spills_sent += 1
        self.context.counters.increment(TaskCounter.SHUFFLE_CHUNK_COUNT)
        if push:
            self._pusher.submit(path, spill_id, run,
                                host=self.host["host"],
                                port=self.host["port"])

    def close(self) -> List[TezAPIEvent]:
        if self._reused:
            # publish_reused() already registered + announced the output;
            # flushing the (empty) sorter would clobber the reused run
            return []
        final_run = self.sorter.flush_run()
        if self._pusher is not None:
            # drain: every queued push lands (or exhausts retries into the
            # pull backstop) before the task reports DONE, so push
            # counters are settled and the final marker is truthful
            self._pusher.close()
        if self._pipelined:
            # final empty marker event with last_event=True for completeness
            payload = ShufflePayload(
                host=self.host["host"], port=self.host["port"],
                path_component=output_path_component(self.context),
                empty_partitions=pack_empty_partitions(
                    [True] * self.num_physical_outputs),
                spill_id=self._spills_sent, last_event=True)
            self.service.register(output_path_component(self.context),
                                  self._spills_sent,
                                  _empty_run(self.num_physical_outputs),
                                  epoch=getattr(self.context, "am_epoch", 0),
                                  app_id=getattr(self.context, "app_id", ""),
                                  counters=self.context.counters,
                                  window_id=getattr(self.context,
                                                    "window_id", 0),
                                  stream=getattr(self.context, "stream", ""))
            return [CompositeDataMovementEvent(0, self.num_physical_outputs,
                                               payload)]
        assert final_run is not None
        self.service.register(output_path_component(self.context), -1,
                              final_run,
                              epoch=getattr(self.context, "am_epoch", 0),
                              app_id=getattr(self.context, "app_id", ""),
                              lineage=self._lineage,
                              tenant=getattr(self.context, "tenant", ""),
                              counters=self.context.counters,
                              window_id=getattr(self.context,
                                                "window_id", 0),
                              stream=getattr(self.context, "stream", ""))
        self.context.counters.increment(
            TaskCounter.OUTPUT_BYTES_PHYSICAL, final_run.nbytes)
        return self._events_for_run(final_run, -1, True)


def _empty_run(num_partitions: int):
    import numpy as np
    from tez_tpu.ops.runformat import KVBatch
    return Run(KVBatch.empty(), np.zeros(num_partitions + 1, dtype=np.int64))
