"""Fault-injectable Input/Processor/Output test doubles.

Reference parity: tez-tests/.../test/{TestInput,TestProcessor,TestOutput}.java
(config-driven failures by task/attempt index, SURVEY.md §4 item 4).  Payload
dict keys:
  do_fail: bool                 fail in run/read
  failing_task_indices: [int]   which tasks fail ([-1] = all)
  failing_upto_attempt: int     fail attempts <= this number (then succeed)
  fatal: bool                   report a FATAL failure
  sleep_ms: int                 delay before acting
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Sequence

from tez_tpu.api.events import TezAPIEvent
from tez_tpu.api.runtime import (KeyValueReader, LogicalIOProcessor,
                                 LogicalInput, LogicalOutput, Reader, Writer)


class _FailPolicy:
    def __init__(self, context: Any):
        payload = context.user_payload.load() or {}
        self.payload = payload if isinstance(payload, dict) else {}
        self.context = context

    def should_fail(self) -> bool:
        if not self.payload.get("do_fail"):
            return False
        tasks = self.payload.get("failing_task_indices", [-1])
        if -1 not in tasks and self.context.task_index not in tasks:
            return False
        upto = self.payload.get("failing_upto_attempt", 10**9)
        return self.context.task_attempt_number <= upto

    @property
    def fatal(self) -> bool:
        return bool(self.payload.get("fatal"))

    def sleep(self) -> None:
        ms = self.payload.get("sleep_ms", 0)
        if ms:
            time.sleep(ms / 1000.0)


class _EmptyReader(KeyValueReader):
    def __iter__(self):
        return iter(())


class TestInput(LogicalInput):
    def initialize(self) -> List[TezAPIEvent]:
        self._policy = _FailPolicy(self.context)
        return []

    def get_reader(self) -> Reader:
        self._policy.sleep()
        if self._policy.should_fail():
            if self._policy.fatal:
                self.context.fatal_error(None, "TestInput fatal failure")
            raise RuntimeError(
                f"TestInput failing task={self.context.task_index} "
                f"attempt={self.context.task_attempt_number}")
        return _EmptyReader()

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        pass

    def close(self) -> List[TezAPIEvent]:
        return []


class _NullWriter(Writer):
    def write(self, key: Any, value: Any) -> None:
        pass


class TestOutput(LogicalOutput):
    def initialize(self) -> List[TezAPIEvent]:
        self._policy = _FailPolicy(self.context)
        return []

    def get_writer(self) -> Writer:
        return _NullWriter()

    def handle_events(self, events: Sequence[TezAPIEvent]) -> None:
        pass

    def close(self) -> List[TezAPIEvent]:
        if self._policy.should_fail():
            raise RuntimeError("TestOutput failing at close")
        return []


class TestProcessor(LogicalIOProcessor):
    def initialize(self) -> None:
        self._policy = _FailPolicy(self.context)

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        self._policy.sleep()
        # drive inputs so TestInput failures fire
        for inp in inputs.values():
            reader = inp.get_reader()
            if isinstance(reader, KeyValueReader):
                for _ in reader:
                    pass
        if self._policy.should_fail():
            if self._policy.fatal:
                self.context.fatal_error(None, "TestProcessor fatal failure")
                raise RuntimeError("fatal TestProcessor failure")
            raise RuntimeError(
                f"TestProcessor failing task={self.context.task_index} "
                f"attempt={self.context.task_attempt_number}")

    def close(self) -> None:
        pass


class FlakyFetchOrderedInput(LogicalInput):
    """OrderedGroupedKVInput wrapper that injects a fetch failure on the
    first event delivery of attempt 0 of configured tasks (reference:
    FetcherWithInjectableErrors + FetcherErrorTestingConfig).

    Payload: {"failing_fetch_task_indices": [ints] (default [0]),
    "inject_delay_ms": int (default 0) — hold the failure report back so the
    cluster reaches a chosen state first (e.g. all slots occupied)}.
    """

    def __new__(cls, context, num_physical_inputs):
        from tez_tpu.library.inputs import OrderedGroupedKVInput

        class _Impl(OrderedGroupedKVInput):
            def initialize(self):
                payload = self.context.user_payload.load() or {}
                if not isinstance(payload, dict):
                    payload = {}
                self._failing_tasks = payload.get(
                    "failing_fetch_task_indices", [0])
                self._inject_delay = payload.get("inject_delay_ms", 0) / 1e3
                self._injected = False
                return super().initialize()

            def handle_events(self, events):
                from tez_tpu.api.events import (
                    CompositeRoutedDataMovementEvent, DataMovementEvent,
                    InputReadErrorEvent)
                passthrough = []
                for ev in events:
                    if (not self._injected
                            and self.context.task_attempt_number == 0
                            and self.context.task_index in self._failing_tasks
                            and isinstance(ev,
                                           (CompositeRoutedDataMovementEvent,
                                            DataMovementEvent))):
                        self._injected = True
                        if self._inject_delay:
                            time.sleep(self._inject_delay)
                        slot = getattr(ev, "target_index_start",
                                       getattr(ev, "target_index", 0))
                        self.context.send_events([InputReadErrorEvent(
                            diagnostics="injected fetch failure",
                            index=slot, version=ev.version,
                            is_local_fetch=True)])
                        continue   # drop just this event: its fetch "failed"
                    passthrough.append(ev)
                if passthrough:
                    super().handle_events(passthrough)

        return _Impl(context, num_physical_inputs)


class ScriptedFetchSession:
    """Injectable fetch-session factory for tez.runtime.shuffle.fetcher.class
    (reference: FetcherWithInjectableErrors — scripted fetch failures behind
    the real seam).  Serves from the in-process shuffle service regardless of
    host so no TCP server is needed; class-level script controls failures.

    Script (class attributes, reset per test):
      fail_remaining — first N fetches raise ConnectionError
      sessions / fetch_log — observability for coalescing assertions
    """

    fail_remaining = 0
    sessions: list = []
    fetch_log: list = []

    @classmethod
    def reset(cls, fail_remaining: int = 0) -> None:
        cls.fail_remaining = fail_remaining
        cls.sessions = []
        cls.fetch_log = []

    def __init__(self, host: str, port: int):
        type(self).sessions.append(self)
        self.host, self.port = host, port

    def fetch(self, path: str, spill: int, partition: int):
        cls = type(self)
        cls.fetch_log.append((self.host, path, spill, partition))
        if cls.fail_remaining > 0:
            cls.fail_remaining -= 1
            raise ConnectionError("scripted fetch failure")
        from tez_tpu.shuffle.service import local_shuffle_service
        return local_shuffle_service().fetch_partition(path, spill,
                                                       partition)

    def close(self) -> None:
        pass
