"""Stock processors.

Reference parity: tez-runtime-library/.../library/processor/
{SimpleProcessor,SleepProcessor}.java.
"""
from __future__ import annotations

import time
from typing import Dict

from tez_tpu.api.runtime import LogicalIOProcessor, LogicalInput, LogicalOutput


class SimpleProcessor(LogicalIOProcessor):
    """Base for processors that just need run(); IOs are started by the
    framework (reference: SimpleProcessor.java)."""

    def initialize(self) -> None:
        pass

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        pass

    def close(self) -> None:
        pass


class SleepProcessor(SimpleProcessor):
    """Sleeps for payload-configured ms; used by tests and pre-warm
    (reference: SleepProcessor.java)."""

    def run(self, inputs: Dict[str, LogicalInput],
            outputs: Dict[str, LogicalOutput]) -> None:
        payload = self.context.user_payload.load() or {}
        ms = payload.get("sleep_ms", 1) if isinstance(payload, dict) else 1
        time.sleep(ms / 1000.0)
