"""Consumer-side bounded-memory shuffle merge (the MergeManager analog).

Reference parity: tez-runtime-library/.../common/shuffle/orderedgrouped/
MergeManager.java:83 — `reserve()` admission with stall (:404), the
commitMemory >= mergeThreshold mem->disk merge trigger (:387), the on-disk
merge cascade, and a final merge over leftover memory + disk segments —
re-thought for this framework's vectorized data plane:

- Fetched batches are already partition-sorted runs (the producer ships
  sorted slices), so a "mem->disk merge" is one vectorized k-way merge of
  the committed batches written out as a block-chunked sorted file
  (ops.runformat.ChunkedRunWriter), and the DISK admission target just
  streams the oversized batch to its own chunked file — no record-at-a-time
  byte crunching anywhere.
- The final merge is vectorized + in-RAM when everything fits the budget
  (the common case, byte-for-byte the old fast path), and otherwise a
  streaming heap-merge over block-buffered disk runs whose resident set is
  one block per run — a partition far larger than host RAM reduces with
  peak memory ~ budget + num_runs * block_bytes.

Equal keys across different source runs emerge in run-arrival order (the
reference's MergeQueue makes the same arrival-dependent choice; within one
source the producer's sorted order is preserved exactly).
"""
from __future__ import annotations

import logging
import os
import threading
import uuid
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tez_tpu.common.counters import TaskCounter, TezCounters
from tez_tpu.ops.block_merge import iter_merged_blocks
from tez_tpu.ops.runformat import (ChunkedRunWriter, KVBatch, Run,
                                   iter_chunked_run)
from tez_tpu.ops.sorter import merge_sorted_runs, normalize_batch_keys

log = logging.getLogger(__name__)


def _as_run(batch: KVBatch) -> Run:
    return Run(batch, np.array([0, batch.num_records], dtype=np.int64))


class _FileSource:
    """Disk-direct shuffle source: one partition of a producer's
    partition-indexed output file, merged straight off the producer's disk
    (LocalDiskFetchedInput analog) — never copied into this consumer's
    memory budget or spill dir."""

    __slots__ = ("path", "partition", "nbytes")

    def __init__(self, path: str, partition: int, nbytes: int):
        self.path = path
        self.partition = partition
        self.nbytes = nbytes


class ShuffleMergeManager:
    """Admission + background mem->disk merging for one consumer input.

    Thread model: fetch threads call `commit()` (which may stall on the
    memory budget); one background merger thread frees memory by merging
    committed batches to disk; `finish()` joins the merger and hands back
    either a fully-merged in-RAM batch or a streaming plan.
    """

    def __init__(self, counters: TezCounters, budget_bytes: int,
                 spill_dir: str,
                 key_width: int = 16,
                 engine: str = "device",
                 device_min_records: "int | None" = None,
                 merge_factor: int = 64,
                 merge_threshold: float = 0.9,
                 max_single_fraction: float = 0.25,
                 key_normalizer: Optional[Callable[[bytes], bytes]] = None,
                 codec: Optional[str] = None,
                 block_records: int = 65536):
        self.counters = counters
        self.budget = int(budget_bytes)
        self.spill_dir = spill_dir
        self.key_width = key_width
        from tez_tpu.ops.sorter import resolve_engine
        self.engine = resolve_engine(engine)
        from tez_tpu.ops.sorter import DEVICE_SORT_MIN_RECORDS
        self.device_min_records = DEVICE_SORT_MIN_RECORDS \
            if device_min_records is None else device_min_records
        self.merge_factor = max(2, merge_factor)
        self.merge_threshold = merge_threshold
        self.max_single = int(self.budget * max_single_fraction) \
            if self.budget > 0 else 0
        self.key_normalizer = key_normalizer
        self.codec = codec
        self.block_records = block_records

        self.lock = threading.Condition()
        # committed in-memory batches: (slot, seq, batch) — slot-major
        # order keeps the no-spill final merge byte-identical to the
        # historical slot-ordered merge; seq is global arrival order
        self._mem: List[Tuple[int, int, KVBatch]] = []
        self._mem_bytes = 0
        self._seq = 0
        self._disk_runs: List[str] = []          # chunked run paths, by age
        self._disk_slots: set = set()            # slots with data on disk
        # disk-direct sources (producer-owned files; never merged by the
        # background merger — they cost no memory and no consumer disk)
        self._file_sources: List[Tuple[int, int, _FileSource]] = []
        self._merging: List[Tuple[int, int, KVBatch]] = []  # claimed by merger
        self._stalled = 0                        # fetchers waiting in commit
        self._slot_gen: dict = {}                # slot -> reset generation
        self._mem_to_disk = 0
        self._disk_to_disk = 0
        self.peak_mem_bytes = 0
        self._poisoned: Optional[str] = None
        self._closed = False
        self._error: Optional[BaseException] = None
        self._merger: Optional[threading.Thread] = None
        if self.budget > 0:
            self._merger = threading.Thread(target=self._merge_loop,
                                            daemon=True,
                                            name="shuffle-merger")
            self._merger.start()

    # ------------------------------------------------------------- admission
    def slot_generation(self, slot: int) -> int:
        """Current reset-generation of a slot.  Fetchers capture this BEFORE
        fetching and pass it to commit(): a commit whose generation is stale
        (the slot reset mid-fetch) is dropped instead of stored, so a new
        producer attempt's data can never be discarded by the old attempt's
        late-arriving fetch."""
        with self.lock:
            return self._slot_gen.get(slot, 0)

    def commit(self, slot: int, batch: KVBatch, generation: int = 0) -> bool:
        """Account a fetched (sorted) batch.  MEM target when it fits the
        budget — stalling while the merger frees memory (reserve():404
        semantics) — DISK target for oversized batches (maxSingleShuffleLimit
        analog): streamed straight to its own chunked run.  Returns False if
        the batch was dropped as stale (slot reset since `generation`)."""
        if self.budget <= 0:
            with self.lock:
                if self._slot_gen.get(slot, 0) != generation:
                    return False
                self._mem.append((slot, self._seq, batch))
                self._seq += 1
                self._mem_bytes += batch.nbytes
                self.peak_mem_bytes = max(self.peak_mem_bytes, self._mem_bytes)
            self.counters.increment(TaskCounter.SHUFFLE_BYTES_TO_MEM,
                                    batch.nbytes)
            return True
        if batch.nbytes > self.max_single:
            path = self._write_chunked([_as_run(batch)])
            with self.lock:
                if self._slot_gen.get(slot, 0) != generation:
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    return False
                self._disk_runs.append(path)
                self._disk_slots.add(slot)
            self.counters.increment(TaskCounter.SHUFFLE_BYTES_TO_DISK,
                                    batch.nbytes)
            return True
        with self.lock:
            while self._mem_bytes + batch.nbytes > self.budget and \
                    self._error is None and self._poisoned is None:
                if not self._mem and not self._merging:
                    # nothing the merger could free: the batch itself is
                    # what's over budget (many stalled fetchers, tiny
                    # budget).  Fall through and admit anyway — peak memory
                    # then exceeds the budget by at most one sub-max_single
                    # batch, which beats deadlocking the fetch forever.
                    break
                self._stalled += 1           # merger merges on our behalf
                self.lock.notify_all()
                try:
                    self.lock.wait(0.1)
                finally:
                    self._stalled -= 1
            self._raise_if_broken()
            if self._slot_gen.get(slot, 0) != generation:
                return False
            self._mem.append((slot, self._seq, batch))
            self._seq += 1
            self._mem_bytes += batch.nbytes
            self.peak_mem_bytes = max(self.peak_mem_bytes, self._mem_bytes)
            if self._mem_bytes >= self.budget * self.merge_threshold:
                self.lock.notify_all()
        self.counters.increment(TaskCounter.SHUFFLE_BYTES_TO_MEM, batch.nbytes)
        return True

    def commit_local_file(self, slot: int, path: str, partition: int,
                          nbytes: int, generation: int = 0) -> bool:
        """Admit a disk-direct source (same-host producer's partition-
        indexed file).  Costs no memory budget and no consumer disk; the
        blocks stream from the producer's file at merge time.  Returns
        False if dropped as stale (slot reset since `generation`)."""
        with self.lock:
            if self._slot_gen.get(slot, 0) != generation:
                return False
            self._file_sources.append(
                (slot, self._seq, _FileSource(path, partition, nbytes)))
            self._seq += 1
        return True

    def on_slot_reset(self, slot: int) -> List[KVBatch]:
        """A producer is re-running.  The slot's generation bumps (so
        in-flight fetches of the old attempt drop at commit), its in-memory
        batches are discarded (and returned for accounting); if the slot's
        data already merged to disk — or is mid-merge right now — the state
        is unrecoverable in place: poison, so the consumer attempt fails
        loudly and re-runs with fresh fetches (the reference's
        too-many-failures consumer-kill escape hatch)."""
        with self.lock:
            self._slot_gen[slot] = self._slot_gen.get(slot, 0) + 1
            if slot in self._disk_slots or \
                    any(s == slot for s, _, _ in self._merging):
                self._poisoned = (
                    f"slot {slot} re-ran after its data merged to disk; "
                    f"consumer must re-fetch from scratch")
                self.lock.notify_all()
                return []
            dropped = [b for s, _, b in self._mem if s == slot]
            self._mem = [(s, q, b) for s, q, b in self._mem if s != slot]
            self._mem_bytes -= sum(b.nbytes for b in dropped)
            # disk-direct sources are never folded into shared merge files:
            # dropping the slot's entries is a complete undo
            self._file_sources = [t for t in self._file_sources
                                  if t[0] != slot]
            self.lock.notify_all()
            return dropped

    def _raise_if_broken(self) -> None:
        if self._error is not None:
            raise RuntimeError("shuffle merger failed") from self._error
        if self._poisoned is not None:
            raise RuntimeError(f"shuffle merge state lost: {self._poisoned}")

    # ------------------------------------------------------- background merge
    def _mem_merge_due(self) -> bool:
        """Under lock: committed memory crossed the merge threshold, OR a
        fetcher is stalled on admission and there is anything at all to
        free (without the second clause a batch that doesn't fit the
        remaining budget while memory sits below the threshold would stall
        its fetcher forever)."""
        if not self._mem:
            return False
        return self._mem_bytes >= self.budget * self.merge_threshold or \
            self._stalled > 0

    def _merge_loop(self) -> None:
        while True:
            with self.lock:
                while not self._closed and self._poisoned is None and \
                        not self._mem_merge_due() and \
                        len(self._disk_runs) < self.merge_factor:
                    self.lock.wait(0.2)
                if self._closed or self._poisoned is not None:
                    return
                work = None
                if self._mem_merge_due():
                    # CLAIM the batches: they leave _mem (so a concurrent
                    # slot reset can't silently mutate the working set) but
                    # stay accounted in _mem_bytes until the write lands
                    work = ("mem", list(self._mem))
                    self._merging = list(self._mem)
                    self._mem = []
                elif len(self._disk_runs) >= self.merge_factor:
                    work = ("disk", self._disk_runs[:self.merge_factor])
            try:
                if work[0] == "mem":
                    self._do_mem_to_disk(work[1])
                else:
                    self._do_disk_to_disk(work[1])
            except BaseException as e:  # noqa: BLE001 — surface to callers
                with self.lock:
                    self._error = e
                    self.lock.notify_all()
                return

    def _do_mem_to_disk(self, items: List[Tuple[int, int, KVBatch]]) -> None:
        items = sorted(items)               # slot-major, then arrival
        runs = [_as_run(b) for _, _, b in items if b.num_records > 0]
        merged = merge_sorted_runs(runs, 1, self.key_width,
                                   engine=self.engine,
                                   device_min_records=self.device_min_records,
                                   merge_factor=self.merge_factor,
                                   key_normalizer=self.key_normalizer) \
            if runs else _as_run(KVBatch.empty())
        path = self._write_chunked([merged])
        freed = sum(b.nbytes for _, _, b in items)
        with self.lock:
            self._merging = []
            if self._poisoned is not None:
                # a claimed slot reset mid-merge: the written file contains
                # stale data — discard it; the consumer attempt re-runs
                try:
                    os.remove(path)
                except OSError:
                    pass
                self.lock.notify_all()
                return
            self._disk_slots.update(s for s, _, _ in items)
            self._mem_bytes -= freed
            self._disk_runs.append(path)
            self._mem_to_disk += 1
            self.lock.notify_all()
        self.counters.increment(TaskCounter.NUM_MEM_TO_DISK_MERGES)

    def _do_disk_to_disk(self, paths: List[str]) -> None:
        out = self._stream_merge_to_disk(paths)
        with self.lock:
            # replace the merged inputs with the result, keeping age order
            i = self._disk_runs.index(paths[0])
            self._disk_runs = [p for p in self._disk_runs if p not in paths]
            self._disk_runs.insert(i, out)
            self._disk_to_disk += 1
            self.lock.notify_all()
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass
        self.counters.increment(TaskCounter.NUM_DISK_TO_DISK_MERGES)

    # ------------------------------------------------------------ disk I/O
    def _write_chunked(self, runs: Sequence[Run]) -> str:
        path = os.path.join(self.spill_dir,
                            f"mmerge_{uuid.uuid4().hex}.crun")
        w = ChunkedRunWriter(path, codec=self.codec,
                             block_records=self.block_records)
        for r in runs:
            w.append(r.batch)
        w.close()
        self.counters.increment(TaskCounter.ADDITIONAL_SPILLS_BYTES_WRITTEN,
                                w.bytes_written)
        return path

    def _block_iter(self, source) -> Iterator[KVBatch]:
        """Sorted KVBatch blocks from a chunked run path, a disk-direct
        file source, or an in-RAM batch; resident memory is one block at a
        time for the disk shapes."""
        if isinstance(source, str):
            return iter_chunked_run(source)
        if isinstance(source, _FileSource):
            from tez_tpu.ops.runformat import FileRun
            return FileRun(source.path).iter_partition_blocks(
                source.partition)
        return iter([source])

    def _merged_block_iter(self, sources: Sequence) -> Iterator[KVBatch]:
        """Blockwise vectorized k-way merge over paths/batches (age order =
        source order, so equal keys keep the reference MergeQueue's
        arrival-order semantics)."""
        return iter_merged_blocks(
            [self._block_iter(s) for s in sources], self.key_width,
            engine=self.engine, key_normalizer=self.key_normalizer,
            merge_factor=self.merge_factor,
            device_min_records=self.device_min_records)

    def _stream_merge_to_disk(self, paths: List[str]) -> str:
        out_path = os.path.join(self.spill_dir,
                                f"mmerge_{uuid.uuid4().hex}.crun")
        w = ChunkedRunWriter(out_path, codec=self.codec,
                             block_records=self.block_records)
        for block in self._merged_block_iter(paths):
            w.append(block)
        w.close()
        self.counters.increment(TaskCounter.ADDITIONAL_SPILLS_BYTES_WRITTEN,
                                w.bytes_written)
        return out_path

    # ------------------------------------------------------------- finish
    def finish(self) -> "MergedResult":
        """Join the merger; decide in-RAM vs streaming final merge."""
        with self.lock:
            self._closed = True
            self.lock.notify_all()
        if self._merger is not None:
            self._merger.join(timeout=300)
        with self.lock:
            self._raise_if_broken()
            mem = sorted(self._mem)
            disk = list(self._disk_runs)
            # no byte-size filter: empty PARTITIONS never commit (gated by
            # the producer's row-count flags), and a committed source whose
            # records are all zero-length pairs still carries rows
            file_entries = sorted(self._file_sources)
        files = [fs for _, _, fs in file_entries]
        file_bytes = sum(fs.nbytes for fs in files)
        if files and self.budget > 0 and not disk and \
                file_bytes + self._mem_bytes <= \
                self.budget * self.merge_threshold:
            # small disk-direct inputs: cheaper to materialize and take the
            # in-RAM merged-batch path than to stream; slot-major order is
            # preserved by merging them into the mem list under their real
            # (slot, seq) keys
            from tez_tpu.ops.runformat import FileRun
            for s, q, fs in file_entries:
                batch = FileRun(fs.path).partition(fs.partition)
                if batch.num_records > 0:
                    mem.append((s, q, batch))
            mem.sort(key=lambda t: t[:2])
            files = []
        if not disk and not files:
            runs = [_as_run(b) for _, _, b in mem if b.num_records > 0]
            if not runs:
                return MergedResult(batch=KVBatch.empty())
            merged = runs[0] if len(runs) == 1 else merge_sorted_runs(
                runs, 1, self.key_width, counters=self.counters,
                engine=self.engine, merge_factor=self.merge_factor,
                device_min_records=self.device_min_records,
                key_normalizer=self.key_normalizer)
            return MergedResult(batch=merged.batch)
        # leftover memory becomes one more (bounded) sorted segment
        mem_runs = [_as_run(b) for _, _, b in mem if b.num_records > 0]
        mem_seg: Optional[KVBatch] = None
        if mem_runs:
            mem_seg = merge_sorted_runs(
                mem_runs, 1, self.key_width, counters=self.counters,
                engine=self.engine, merge_factor=self.merge_factor,
                device_min_records=self.device_min_records,
                key_normalizer=self.key_normalizer).batch
        return MergedResult(stream=_StreamPlan(self, disk + files, mem_seg))

    def cleanup(self) -> None:
        with self.lock:
            self._closed = True
            self.lock.notify_all()
            paths = list(self._disk_runs)
            self._disk_runs = []
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass


class _StreamPlan:
    """Re-iterable streaming merge over disk runs + the leftover mem segment
    (disk blocks re-read on every iteration; memory stays bounded)."""

    def __init__(self, mm: ShuffleMergeManager, disk: List[str],
                 mem_seg: Optional[KVBatch]):
        self.mm = mm
        self.disk = disk
        self.mem_seg = mem_seg

    def _sources(self) -> List[Any]:
        sources: List[Any] = list(self.disk)
        if self.mem_seg is not None:
            sources.append(self.mem_seg)
        return sources

    def iter_batches(self) -> Iterator[KVBatch]:
        """Globally-sorted merged blocks (the vectorized consumer path)."""
        return self.mm._merged_block_iter(self._sources())

    def iter_records(self) -> Iterator[Tuple[bytes, bytes, bytes]]:
        """Per-record view for generic consumers, built on the blockwise
        merge (one normalization pass per block, not per comparison)."""
        norm = self.mm.key_normalizer
        for batch in self.iter_batches():
            if norm is not None:
                nb, no = normalize_batch_keys(batch, norm)
                for i in range(batch.num_records):
                    yield (nb[no[i]:no[i + 1]].tobytes(), batch.key(i),
                           batch.value(i))
            else:
                for i in range(batch.num_records):
                    k = batch.key(i)
                    yield (k, k, batch.value(i))


class MergedResult:
    """Either a fully-merged in-RAM batch or a streaming merge plan."""

    def __init__(self, batch: Optional[KVBatch] = None,
                 stream: Optional[_StreamPlan] = None):
        self.batch = batch
        self.stream = stream

    @property
    def is_streaming(self) -> bool:
        return self.stream is not None
